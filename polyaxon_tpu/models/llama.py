"""Llama-3-style decoder-only transformer (the flagship JAXJob model).

Target of the BASELINE north star [B]: "Llama-3-8B, FSDP over ICI on
v5e-64". TPU-first construction:

- stacked layer params + ``lax.scan`` body → one compiled block,
  remat-able per layer (``jax.checkpoint`` policies map to the spec's
  ``remat`` knob);
- GQA attention (RoPE, fp32 softmax) through ``ops.attention`` so the
  impl can swap xla ↔ Pallas flash ↔ ring (context parallel);
- bf16 activations/compute, fp32 master weights, fp32 loss;
- logical axes on every param so FSDP/TP/CP rule tables place them
  (``parallel.sharding``).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from polyaxon_tpu.models.common import (
    Batch,
    _embed_rows,
    _w,
    ModelDef,
    Variables,
    chunked_lm_loss,
    lm_logits,
    rms_norm,
    rope,
    sample_logits,
    scaled_init,
    shift_right,
    truncated_normal_init,
)
from polyaxon_tpu.ops.attention import dot_product_attention


SEQ2SEQ = False  # serving contract: the prompt is continued in place


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14_336
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    # Llama-3.1-style context-extension scaling (common.rope_frequencies):
    # {"factor", "low_freq_factor", "high_freq_factor",
    #  "original_max_position_embeddings"} or None.
    rope_scaling: Optional[dict] = None
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    # Gemma-convention knobs (all default to the llama convention):
    # norm gains stored as deltas applied as (1 + w); tanh-approx GeGLU
    # instead of SwiGLU; embeddings scaled by sqrt(dim) on read.
    norm_offset: float = 0.0
    mlp_activation: str = "silu"  # silu | gelu_tanh
    scale_embeddings: bool = False
    # Sliding-window (Mistral-style) causal attention: each position
    # attends to its last `sliding_window` tokens. None = full causal.
    sliding_window: Optional[int] = None
    dtype: Any = jnp.bfloat16
    remat: str = "none"  # none | full | dots (checkpoint policy per layer)
    attention_impl: str = "xla"  # xla | flash | ring | ulysses
    # Paged decode attention: "auto" = the Pallas page-streaming kernel
    # on real TPU (ops/paged_attention.py), gather+masked-softmax
    # elsewhere; "gather" / "pallas" force one.
    paged_attention_impl: str = "auto"
    # Flash-kernel tuning (runtime keys flow here via model_overrides):
    # fwd tile sizes and backward implementation ("pallas" | "xla").
    # None = the kernel's own defaults (512 fwd tiles; pallas bwd on
    # real TPU); "auto" = trace-time VMEM-budget pick (flash.auto_blocks).
    # Sweepable per-run from bench.py; setting one with a non-flash
    # attention_impl is an error.
    flash_block_q: Optional[int | str] = None
    flash_block_k: Optional[int | str] = None
    flash_bwd_impl: Optional[str] = None
    # Chunked lm-head loss slab length (peak HBM holds [B, chunk, V]
    # fp32); sweepable alongside the flash tiles.
    loss_chunk: int = 256
    # Vocab-chunk length for QUANTIZED decode logits (common.lm_logits:
    # the scan structure that keeps int8 on decode-loop carries).
    # Bigger chunks = fewer, larger matmuls per step; sweepable on chip
    # via bench_decode --lm-chunk. Ignored for unquantized heads.
    lm_logits_chunk: int = 4096
    # Pipeline parallelism over the `pp` mesh axis (parallel/pipeline.py):
    # >1 splits the layer stack into that many ppermute-chained stages.
    pipeline_stages: int = 1
    pipeline_microbatches: int = 4
    # Double-buffered schedule (parallel/pipeline.py): each tick's
    # stage→stage ppermute carries the PREVIOUS tick's output, so the
    # hop overlaps stage compute. Per-microbatch outputs are identical
    # to the single-buffered schedule; the knob exists for parity
    # drills and as an escape hatch.
    pipeline_double_buffer: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


# Named configs. llama3_8b matches the Llama-3-8B architecture; the
# smaller ones are proxies for single-chip benchmarking and tests.
_LLAMA31_SCALING = {
    "factor": 8.0, "low_freq_factor": 1.0, "high_freq_factor": 4.0,
    "original_max_position_embeddings": 8192,
}

CONFIGS: dict[str, LlamaConfig] = {
    "llama3_8b": LlamaConfig(),
    # Llama-3.1 8B: 128k context via scaled RoPE (public rope_scaling rule).
    "llama31_8b": LlamaConfig(max_seq_len=131_072,
                              rope_scaling=_LLAMA31_SCALING),
    # Mistral-7B architecture: sliding-window attention, 32k context.
    "mistral_7b": LlamaConfig(
        vocab_size=32_000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        ffn_dim=14_336, max_seq_len=32_768, rope_theta=10_000.0,
        sliding_window=4096,
    ),
    "llama3_1b": LlamaConfig(
        vocab_size=128_256, dim=2048, n_layers=16, n_heads=32, n_kv_heads=8,
        ffn_dim=8192, max_seq_len=8192,
    ),
    "llama_200m": LlamaConfig(
        vocab_size=32_000, dim=1024, n_layers=12, n_heads=16, n_kv_heads=8,
        ffn_dim=2816, max_seq_len=2048, rope_theta=10_000.0,
    ),
    # Llama-3-vocab small model: the speculative DRAFT for llama3_*
    # targets (drafting requires an identical token space; the other
    # small configs carry the 32k vocab).
    "llama3_draft_200m": LlamaConfig(
        vocab_size=128_256, dim=768, n_layers=10, n_heads=12, n_kv_heads=4,
        ffn_dim=2048, max_seq_len=8192,
    ),
    "llama_tiny": LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq_len=128, rope_theta=10_000.0,
    ),
    # Tied-embeddings variant (Gemma/Qwen-small convention: lm_head IS
    # embed.T): exercises the transposed head path everywhere —
    # training loss, decode logits, and the quantized serving branch
    # where the [V, D] table must stay int8 on decode-loop carries.
    "llama_tiny_tied": LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq_len=128, rope_theta=10_000.0,
        tie_embeddings=True,
    ),
    # Gemma-2B architecture (public config): MQA (1 kv head), GeGLU,
    # (1+w) norms, sqrt(dim)-scaled embeddings, tied head, 256k vocab,
    # rms_norm_eps 1e-6 (the llama default 1e-5 deviates from the
    # published config — ADVICE r5).
    # head_dim = dim / n_heads = 256, matching the published value.
    "gemma_2b": LlamaConfig(
        vocab_size=256_000, dim=2048, n_layers=18, n_heads=8, n_kv_heads=1,
        ffn_dim=16_384, max_seq_len=8192, rope_theta=10_000.0,
        tie_embeddings=True, norm_offset=1.0, mlp_activation="gelu_tanh",
        scale_embeddings=True, norm_eps=1e-6,
    ),
    "gemma_tiny": LlamaConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=1,
        ffn_dim=128, max_seq_len=128, rope_theta=10_000.0,
        tie_embeddings=True, norm_offset=1.0, mlp_activation="gelu_tanh",
        scale_embeddings=True, norm_eps=1e-6,
    ),
}


def init(cfg: LlamaConfig, rng: jax.Array) -> Variables:
    keys = jax.random.split(rng, 10)
    L, D, F = cfg.n_layers, cfg.dim, cfg.ffn_dim
    H, KV, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    # Identity-at-init norm gains: weight w applies as (norm_offset + w),
    # so llama (offset 0) initializes ones, Gemma (offset 1) zeros.
    gain = jnp.full((L, D), 1.0 - cfg.norm_offset)
    params = {
        "embed": truncated_normal_init(keys[0], (cfg.vocab_size, D)),
        "layers": {
            "attn_norm": gain,
            "wq": scaled_init(keys[1], (L, D, H * Hd), fan_in=D),
            "wk": scaled_init(keys[2], (L, D, KV * Hd), fan_in=D),
            "wv": scaled_init(keys[3], (L, D, KV * Hd), fan_in=D),
            "wo": scaled_init(keys[4], (L, H * Hd, D), fan_in=H * Hd),
            "mlp_norm": gain,
            "w_gate": scaled_init(keys[5], (L, D, F), fan_in=D),
            "w_up": scaled_init(keys[6], (L, D, F), fan_in=D),
            "w_down": scaled_init(keys[7], (L, F, D), fan_in=F),
        },
        "final_norm": jnp.full((D,), 1.0 - cfg.norm_offset),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = truncated_normal_init(keys[8], (D, cfg.vocab_size))
    return {"params": params, "state": {}}


def logical_axes(cfg: LlamaConfig) -> Variables:
    params = {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "embed"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", "embed"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": ("embed",),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = ("embed", "vocab")
    return {"params": params, "state": {}}


_rope = rope  # shared impl (models.common.rope)


def _norm(cfg, x: jax.Array, weight: jax.Array) -> jax.Array:
    """Config-routed rms_norm: llama weights apply as w, Gemma-style
    as (1 + w) (cfg.norm_offset). getattr keeps the shared attention
    kernels usable from moe/t5 configs that carry no offset."""
    return rms_norm(x, weight, cfg.norm_eps,
                    offset=getattr(cfg, "norm_offset", 0.0))


def _act(cfg):
    """MLP gate activation: SwiGLU (silu) or Gemma's tanh-approx GeGLU."""
    kind = getattr(cfg, "mlp_activation", "silu")
    if kind == "silu":
        return jax.nn.silu
    if kind == "gelu_tanh":
        return functools.partial(jax.nn.gelu, approximate=True)
    raise ValueError(f"unknown mlp_activation `{kind}`")


def _embed(cfg, params: dict, tokens: jax.Array, dt) -> jax.Array:
    """Embedding read with the optional Gemma sqrt(dim) scaling —
    every forward/decode path reads through here so the convention
    cannot diverge between prefill and decode."""
    x = _embed_rows(params["embed"], tokens, dt)
    if getattr(cfg, "scale_embeddings", False):
        x = x * jnp.asarray(cfg.dim ** 0.5, dt)
    return x


def _mlp(cfg, x: jax.Array, layer: dict) -> jax.Array:
    """The gated-MLP residual block (norm → act(gate)·up → down),
    shared by the training layer and every decode flavour so the
    convention can never desync between them (this block was
    previously copy-pasted five times)."""
    dt = cfg.dtype
    h = _norm(cfg, x, layer["mlp_norm"])
    gate = _act(cfg)(h @ _w(layer["w_gate"], dt))
    up = h @ _w(layer["w_up"], dt)
    return x + (gate * up) @ _w(layer["w_down"], dt)


def _layer(cfg: LlamaConfig, x: jax.Array, layer: dict, positions: jax.Array,
           segment_ids: Optional[jax.Array] = None) -> jax.Array:
    B, S, D = x.shape
    H, KV, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    dt = cfg.dtype

    h = _norm(cfg, x, layer["attn_norm"])
    q = (h @ _w(layer["wq"], dt)).reshape(B, S, H, Hd)
    k = (h @ _w(layer["wk"], dt)).reshape(B, S, KV, Hd)
    v = (h @ _w(layer["wv"], dt)).reshape(B, S, KV, Hd)
    q = _rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
    k = _rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
    # dot_product_attention owns the impl support matrix (xla and flash
    # both handle packed segment_ids; ring/ulysses raise).
    attn = dot_product_attention(q, k, v, causal=True,
                                 impl=cfg.attention_impl,
                                 segment_ids=segment_ids,
                                 window=cfg.sliding_window,
                                 block_q=cfg.flash_block_q,
                                 block_k=cfg.flash_block_k,
                                 bwd_impl=cfg.flash_bwd_impl)
    x = x + attn.reshape(B, S, H * Hd) @ _w(layer["wo"], dt)

    x = _mlp(cfg, x, layer)
    return x


def _layer_body(cfg: LlamaConfig):
    body = functools.partial(_layer, cfg)
    if cfg.remat == "full":
        body = jax.checkpoint(body, static_argnums=())
    elif cfg.remat == "dots":
        body = jax.checkpoint(
            body, policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
        )
    return body


def _pipelined_layers(cfg: LlamaConfig, body, layer_params, x: jax.Array) -> jax.Array:
    """Run the layer stack as a `pp` pipeline (parallel/pipeline.py).

    Assumes contiguous positions 0..S-1 (the pretraining case): each
    microbatch rebuilds them locally instead of threading them through
    the ppermute chain.
    """
    from polyaxon_tpu.ops.ring import ambient_mesh
    from polyaxon_tpu.parallel.pipeline import pipeline_forward, stack_stages

    mesh = ambient_mesh()
    if mesh is None or "pp" not in mesh.axis_names:
        raise ValueError(
            f"pipeline_stages={cfg.pipeline_stages} needs a mesh with a "
            "`pp` axis in context (`with mesh:`)")
    stacked = stack_stages(layer_params, cfg.pipeline_stages)

    def stage_fn(local_layers, x_mb):
        mb, S, _ = x_mb.shape
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (mb, S))

        def scan_body(carry, layer):
            return body(carry, layer, positions), None

        out, _ = jax.lax.scan(scan_body, x_mb, local_layers)
        return out

    return pipeline_forward(
        mesh, stage_fn, stacked, x,
        n_microbatches=cfg.pipeline_microbatches,
        double_buffer=cfg.pipeline_double_buffer)


def segment_starts(segment_ids: jax.Array) -> jax.Array:
    """Boolean [..., S] marking the first position of each segment."""
    return jnp.concatenate(
        [jnp.ones_like(segment_ids[..., :1], dtype=bool),
         segment_ids[..., 1:] != segment_ids[..., :-1]], axis=-1)


def segment_positions(segment_ids: jax.Array) -> jax.Array:
    """Within-segment positions for packed rows: [0,0,0,1,1] → [0,1,2,0,1]."""
    S = segment_ids.shape[-1]
    idx = jnp.arange(S, dtype=jnp.int32)
    starts = jax.lax.cummax(jnp.where(segment_starts(segment_ids), idx, 0),
                            axis=segment_ids.ndim - 1)
    return idx - starts


def hidden_states(
    cfg: LlamaConfig,
    params: dict,
    tokens: jax.Array,  # [B, S] int32 input ids
    positions: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,  # [B, S] packed-sequence ids
) -> jax.Array:
    """Token ids → final-norm hidden states [B, S, D] (compute dtype).

    ``segment_ids`` enables packed-sequence pretraining: attention is
    restricted within each segment and RoPE positions restart per
    segment (derived automatically unless ``positions`` is given).
    """
    dt = cfg.dtype
    B, S = tokens.shape
    if cfg.pipeline_stages > 1 and (positions is not None
                                    or segment_ids is not None):
        raise ValueError(
            "the pipelined path assumes contiguous positions 0..S-1 and "
            "cannot honor explicit `positions`/`segment_ids` (packed "
            "sequences / decode offsets); use pipeline_stages=1 for those")
    if positions is None:
        if segment_ids is not None:
            positions = segment_positions(segment_ids)
        else:
            positions = jnp.broadcast_to(
                jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    x = _embed(cfg, params, tokens, dt)

    body = _layer_body(cfg)

    if cfg.pipeline_stages > 1:
        x = _pipelined_layers(cfg, body, params["layers"], x)
    else:
        def scan_body(carry, layer_params):
            return body(carry, layer_params, positions, segment_ids), None

        x, _ = jax.lax.scan(scan_body, x, params["layers"])
    return _norm(cfg, x, params["final_norm"])


def lm_head(cfg: LlamaConfig, params: dict) -> jax.Array:
    """Materialized head table — for OUT-OF-LOOP callers only (prefill,
    training forward). Decode loops must go through ``decode_logits``:
    a quantized table dequantized here is loop-invariant, so XLA
    hoists the full-precision [D, V] table onto the loop carry
    (ADVICE r4 #1; see common.lm_logits)."""
    w = params["embed"] if cfg.tie_embeddings else params["lm_head"]
    if hasattr(w, "dequantize"):
        # Unwrap at consumption (same contract as _w): callers sit
        # inside jit, so the convert+scale fuses into the logits
        # matmul's operand read and int8 stays the HBM format.
        w = w.dequantize()
    return w.T if cfg.tie_embeddings else w


def decode_logits(cfg: LlamaConfig, params: dict, x: jax.Array) -> jax.Array:
    """Hidden states [..., D] → fp32 logits [..., V], safe inside
    decode loops (common.lm_logits keeps a quantized head int8 on the
    loop carry via chunked consumption)."""
    w = params["embed"] if cfg.tie_embeddings else params["lm_head"]
    return lm_logits(x, w, cfg.dtype, transpose=cfg.tie_embeddings,
                     chunk=cfg.lm_logits_chunk)


def forward(
    cfg: LlamaConfig,
    params: dict,
    tokens: jax.Array,  # [B, S] int32 input ids
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """Token ids → logits [B, S, vocab]."""
    x = hidden_states(cfg, params, tokens, positions)
    # fp32 logits: the MXU matmul stays bf16; accumulate/softmax in fp32.
    return (x @ lm_head(cfg, params).astype(cfg.dtype)).astype(jnp.float32)


# ---------------------------------------------------------------- decode
def cache_len(cfg: LlamaConfig, max_len: int) -> int:
    """KV-cache length: with a sliding window the cache is a ring buffer
    of `sliding_window` slots (bounded memory for long generations);
    otherwise the full sequence length."""
    if cfg.sliding_window is not None:
        return min(max_len, cfg.sliding_window)
    return max_len


def init_cache(cfg: LlamaConfig, batch: int, max_len: int) -> dict:
    """KV cache [L, B, C, KV, Hd] per tensor (C = cache_len), compute dtype."""
    C = cache_len(cfg, max_len)
    shape = (cfg.n_layers, batch, C, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def decode_step(
    cfg: LlamaConfig,
    params: dict,
    cache: dict,
    tokens: jax.Array,  # [B] int32 current-position token ids
    pos: jax.Array,  # scalar int32 position being written
) -> tuple[jax.Array, dict]:
    """One autoregressive step: returns (logits [B, V] fp32, new cache).

    The cache is addressed as a ring buffer: slot ``pos % C``. With a
    full-length cache this is plain positional indexing; with a
    sliding-window cache (C == window) old entries are overwritten in
    place, so memory stays O(window) for arbitrarily long generations.

    A scalar position is the all-rows-in-lockstep special case of
    ``decode_step_ragged`` — one body, no duplicated decode math.
    """
    B = tokens.shape[0]
    return decode_step_ragged(
        cfg, params, cache, tokens,
        jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,)))


def ragged_cache_coords(pos: jax.Array, C: int):
    """Per-row ring-buffer addressing shared by every cached decode
    path (llama + moe): for rows at positions ``pos`` ([B], -1 = idle)
    over a C-slot ring cache, returns (positions [B,1] for RoPE,
    slot [B] to write, valid [B,1,1,C] attention mask). Slot s holds
    position pos - ((pos - s) mod C) after this write; negative =
    never written. A sliding window needs no extra mask: C <= window
    by cache_len(), so every live slot is inside the band by
    construction."""
    pos_safe = jnp.maximum(pos, 0)
    slot = jnp.mod(pos_safe, C)  # [B]
    delta = jnp.mod(pos_safe[:, None] - jnp.arange(C)[None, :], C)  # [B, C]
    stored = pos_safe[:, None] - delta
    valid = ((stored >= 0) & (pos[:, None] >= 0))[:, None, None, :]
    return pos_safe[:, None], slot, valid


def cached_attn_step(cfg, layer: dict, x: jax.Array, k_cache: jax.Array,
                     v_cache: jax.Array, positions: jax.Array,
                     slot: jax.Array, valid: jax.Array):
    """One cached-attention sublayer for ragged decode — the shared
    QKV/RoPE/cache-write/masked-softmax kernel both decoder families
    (llama dense MLP, moe expert FFN) build their decode steps on.
    ``cfg`` needs n_heads/n_kv_heads/head_dim/dtype/norm_eps/rope_*.
    Returns (x after the attention residual, new k_cache, new v_cache).
    """
    from polyaxon_tpu.ops.attention import repeat_kv

    dt = cfg.dtype
    B = x.shape[0]
    H, KV, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    n_rep = H // KV
    rows = jnp.arange(B)

    h = _norm(cfg, x, layer["attn_norm"])
    q = (h @ _w(layer["wq"], dt)).reshape(B, 1, H, Hd)
    k = (h @ _w(layer["wk"], dt)).reshape(B, 1, KV, Hd)
    v = (h @ _w(layer["wv"], dt)).reshape(B, 1, KV, Hd)
    scaling = getattr(cfg, "rope_scaling", None)
    q = _rope(q, positions, cfg.rope_theta, scaling)
    k = _rope(k, positions, cfg.rope_theta, scaling)
    k_cache = k_cache.at[rows, slot].set(k[:, 0])
    v_cache = v_cache.at[rows, slot].set(v[:, 0])

    keys = repeat_kv(k_cache, n_rep)
    vals = repeat_kv(v_cache, n_rep)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, keys).astype(jnp.float32)
    logits = logits * (Hd ** -0.5)
    logits = jnp.where(valid, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(dt)
    attn = jnp.einsum("bhqk,bkhd->bqhd", probs, vals)
    return x + attn.reshape(B, 1, H * Hd) @ _w(layer["wo"], dt), \
        k_cache, v_cache


def decode_step_ragged(
    cfg: LlamaConfig,
    params: dict,
    cache: dict,
    tokens: jax.Array,  # [B] int32 current-position token ids
    pos: jax.Array,  # [B] int32 per-row position being written (-1 = idle)
) -> tuple[jax.Array, dict]:
    """One autoregressive step with PER-ROW positions — the kernel under
    continuous batching (serving/batching.py), where each cache slot
    holds a different request at its own depth. Same ring-buffer cache
    semantics as ``decode_step``, addressed per row; idle rows
    (``pos < 0``) write only their own slot-0 entry (overwritten by the
    next admission's prefill insert) and their outputs are ignored by
    the engine. A row at position p matches ``decode_step`` at scalar
    position p exactly."""
    dt = cfg.dtype
    C = cache["k"].shape[2]
    positions, slot, valid = ragged_cache_coords(pos, C)
    x = _embed(cfg, params, tokens, dt)[:, None, :]  # [B, 1, D]

    def layer_step(x, inputs):
        layer, k_cache, v_cache = inputs  # caches [B, C, KV, Hd]
        x, k_cache, v_cache = cached_attn_step(
            cfg, layer, x, k_cache, v_cache, positions, slot, valid)
        x = _mlp(cfg, x, layer)
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (params["layers"], cache["k"], cache["v"]))
    x = _norm(cfg, x, params["final_norm"])
    logits = decode_logits(cfg, params, x[:, 0])
    return logits, {"k": new_k, "v": new_v}


def _prompt_pass(cfg: LlamaConfig, params: dict, prompt: jax.Array):
    """The shared causal prompt sweep: one batched pass over [B, P]
    token ids → (final hidden x [B, P, D], k_all, v_all [L, B, P, KV,
    Hd]). Both prefill flavours (ring-buffer assembly below, raw-KV
    paged insert) build on this one body so the prompt math can never
    diverge between the dense and paged engines."""
    dt = cfg.dtype
    B, P = prompt.shape
    H, KV, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    positions = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None], (B, P))
    x = _embed(cfg, params, prompt, dt)

    def layer_step(x, layer):
        h = _norm(cfg, x, layer["attn_norm"])
        q = (h @ _w(layer["wq"], dt)).reshape(B, P, H, Hd)
        k = (h @ _w(layer["wk"], dt)).reshape(B, P, KV, Hd)
        v = (h @ _w(layer["wv"], dt)).reshape(B, P, KV, Hd)
        q = _rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
        k = _rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
        attn = dot_product_attention(q, k, v, causal=True,
                                     impl=cfg.attention_impl,
                                     window=cfg.sliding_window,
                                     block_q=cfg.flash_block_q,
                                     block_k=cfg.flash_block_k,
                                     bwd_impl=cfg.flash_bwd_impl)
        x = x + attn.reshape(B, P, H * Hd) @ _w(layer["wo"], dt)
        x = _mlp(cfg, x, layer)
        return x, (k, v)

    x, (k_all, v_all) = jax.lax.scan(layer_step, x, params["layers"])
    return x, k_all, v_all


def prefill(
    cfg: LlamaConfig,
    params: dict,
    prompt: jax.Array,  # [B, P] int32
    max_len: int,
) -> tuple[jax.Array, dict]:
    """One batched causal pass over the prompt, filling the KV cache:
    returns (last-position logits [B, V] fp32, cache). O(1) layer sweeps
    instead of P sequential decode steps."""
    dt = cfg.dtype
    B, P = prompt.shape
    Hd = cfg.head_dim
    x, k_all, v_all = _prompt_pass(cfg, params, prompt)
    # Ring-buffer cache assembly: position p lands in slot p % C. With a
    # full-length cache that is the identity; with a sliding-window ring
    # only the last C prompt positions are kept (older ones can never be
    # attended again).
    C = cache_len(cfg, max_len)
    if cfg.sliding_window is None and P > max_len:
        raise ValueError(
            f"prompt length {P} exceeds cache length {max_len} "
            "(full attention cannot drop prompt positions)")
    if cfg.sliding_window is not None and C < min(P, cfg.sliding_window):
        raise ValueError(
            f"cache length {C} (max_len {max_len}) cannot hold the last "
            f"min(P={P}, window={cfg.sliding_window}) prompt positions "
            "that remain attendable — raise max_len")
    keep = min(P, C)
    if P <= C:
        # Common no-wrap case (slots are 0..P-1): cheap pad, no scatter.
        pad = ((0, 0), (0, 0), (0, C - P), (0, 0), (0, 0))
        cache = {"k": jnp.pad(k_all, pad), "v": jnp.pad(v_all, pad)}
    else:
        pos_kept = jnp.arange(P - keep, P)
        slots = jnp.mod(pos_kept, C)
        zeros = jnp.zeros(
            (cfg.n_layers, B, C, cfg.n_kv_heads, Hd), dtype=k_all.dtype)
        cache = {
            "k": zeros.at[:, :, slots].set(k_all[:, :, P - keep:]),
            "v": zeros.at[:, :, slots].set(v_all[:, :, P - keep:]),
        }
    x = _norm(cfg, x, params["final_norm"])
    logits = (x[:, -1] @ lm_head(cfg, params).astype(dt)).astype(jnp.float32)
    return logits, cache


# ------------------------------------------- continuous batching surface
# Hooks the slot-pool engine (serving/batching.py) drives; moe reuses
# these verbatim (same decoder cache shape and admission semantics).
def cb_validate(cfg, prompt_len: int, max_new: int, max_len: int) -> None:
    """Decoder-only budget rule: prompt and generation share the cache."""
    if prompt_len + max_new > max_len:
        raise ValueError(
            f"prompt {prompt_len} + max_new_tokens {max_new} exceeds "
            f"max_len {max_len}")


def cb_admission(prompt: list) -> tuple:
    """(start position, first decode token, prefill tokens): the last
    prompt token is the first decode input; the rest prefill the cache
    (none for single-token prompts)."""
    return (len(prompt) - 1, prompt[-1],
            list(prompt[:-1]) if len(prompt) > 1 else None)


def cb_init_cache(cfg, slots: int, max_len: int) -> dict:
    return init_cache(cfg, slots, max_len)


def cb_prefill(cfg, params: dict, prompt: jax.Array, max_len: int) -> dict:
    _, cache = prefill(cfg, params, prompt, max_len)
    return cache


def insert_cache_row(cache: dict, row: dict, b) -> dict:
    return {
        key: jax.lax.dynamic_update_slice(
            cache[key], row[key], (0, b, 0, 0, 0))
        for key in ("k", "v")
    }


# ------------------------------------------------- speculative decoding
def decode_chunk(
    cfg: LlamaConfig,
    params: dict,
    cache: dict,  # full-length cache: slot == position (C == max_len)
    tokens: jax.Array,  # [B, c] int32 — c tokens per row
    pos0: jax.Array,  # [B] int32 — position of tokens[:, 0] per row
) -> tuple[jax.Array, dict]:
    """Cached forward over a SHORT chunk of c tokens per row (the
    speculative-decoding verify step): writes their KV at positions
    pos0..pos0+c-1 and returns logits [B, c, V] — logits[:, i] predicts
    position pos0+i+1. Requires a full-length cache (slot == position;
    no ring wrap, no sliding window), which is what makes acceptance
    rollback-free: stale entries beyond the accepted prefix sit at
    positions the next chunk rewrites before anything attends them."""
    if cfg.sliding_window is not None:
        raise ValueError("speculative decode_chunk requires a full-length "
                         "cache (no sliding_window)")
    dt = cfg.dtype
    B, c = tokens.shape
    C = cache["k"].shape[2]
    positions = pos0[:, None] + jnp.arange(c)[None, :]  # [B, c]
    x = _embed(cfg, params, tokens, dt)  # [B, c, D]

    cols = jnp.arange(C)[None, None, :]  # [1, 1, C]
    # Column j visible to the query at position p iff j <= p: unwritten
    # slots sit at positions > p by the slot==position invariant.
    valid = (cols <= positions[:, :, None])[:, None]  # [B, 1, c, C]

    def layer_step(x, inputs):
        layer, k_cache, v_cache = inputs  # caches [B, C, KV, Hd]
        x, k_cache, v_cache = chunk_attn_step(
            cfg, layer, x, k_cache, v_cache, positions, valid)
        x = _mlp(cfg, x, layer)
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (params["layers"], cache["k"], cache["v"]))
    x = _norm(cfg, x, params["final_norm"])
    logits = decode_logits(cfg, params, x)
    return logits, {"k": new_k, "v": new_v}


def chunk_attn_step(cfg, layer: dict, x: jax.Array, k_cache: jax.Array,
                    v_cache: jax.Array, positions: jax.Array,
                    valid: jax.Array):
    """One cached-attention sublayer for a c-token chunk (the
    speculative-verify analogue of ``cached_attn_step``) — shared by
    both decoder families' ``decode_chunk``. ``positions`` [B, c],
    ``valid`` [B, 1, c, C]; writes slot == position."""
    from polyaxon_tpu.ops.attention import repeat_kv

    dt = cfg.dtype
    B, c = positions.shape
    H, KV, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    n_rep = H // KV
    rows = jnp.arange(B)
    scaling = getattr(cfg, "rope_scaling", None)

    h = _norm(cfg, x, layer["attn_norm"])
    q = (h @ _w(layer["wq"], dt)).reshape(B, c, H, Hd)
    k = (h @ _w(layer["wk"], dt)).reshape(B, c, KV, Hd)
    v = (h @ _w(layer["wv"], dt)).reshape(B, c, KV, Hd)
    q = _rope(q, positions, cfg.rope_theta, scaling)
    k = _rope(k, positions, cfg.rope_theta, scaling)
    k_cache = k_cache.at[rows[:, None], positions].set(k)
    v_cache = v_cache.at[rows[:, None], positions].set(v)
    keys = repeat_kv(k_cache, n_rep)
    vals = repeat_kv(v_cache, n_rep)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, keys).astype(jnp.float32)
    s = s * (Hd ** -0.5)
    s = jnp.where(valid, s, -1e30)
    probs = jax.nn.softmax(s, axis=-1).astype(dt)
    attn = jnp.einsum("bhqk,bkhd->bqhd", probs, vals)
    return x + attn.reshape(B, c, H * Hd) @ _w(layer["wo"], dt), \
        k_cache, v_cache


# ------------------------------------------------- paged KV decode surface
# vLLM-style paged attention, TPU-first: the KV cache is a shared pool
# of fixed-size pages ([L, P, page, KV, Hd]) addressed through per-row
# block tables, so serving memory scales with tokens actually held, not
# slots x max_len reservations (the allocator lives in serving/paged.py;
# the reference orchestrator has no serving path at all — net-new
# surface, SURVEY.md §2). Page 0 is scratch: idle rows and unallocated
# coordinates write there, and masks keep it unread.

def paged_init_cache(cfg: LlamaConfig, n_pages: int, page_size: int) -> dict:
    if cfg.sliding_window is not None:
        raise ValueError(
            "paged KV does not support sliding_window yet — the ring "
            "buffer already bounds that cache; use kv='dense'")
    shape = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def paged_attn_step(cfg, layer: dict, x: jax.Array, k_pages: jax.Array,
                    v_pages: jax.Array, positions: jax.Array,
                    write_page: jax.Array, write_off: jax.Array,
                    tables: jax.Array, valid: jax.Array):
    """Paged analogue of ``cached_attn_step``: writes this step's K/V
    into each row's current page slot and attends over the row's pages
    gathered via its block table. ``tables`` [B, maxp] (-1 = not
    allocated, clamped to scratch page 0 for the gather), ``valid``
    [B, 1, 1, maxp*page] masks real positions."""
    from polyaxon_tpu.ops.attention import repeat_kv

    dt = cfg.dtype
    B = x.shape[0]
    H, KV, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    n_rep = H // KV
    page = k_pages.shape[2]

    h = _norm(cfg, x, layer["attn_norm"])
    q = (h @ _w(layer["wq"], dt)).reshape(B, 1, H, Hd)
    k = (h @ _w(layer["wk"], dt)).reshape(B, 1, KV, Hd)
    v = (h @ _w(layer["wv"], dt)).reshape(B, 1, KV, Hd)
    scaling = getattr(cfg, "rope_scaling", None)
    q = _rope(q, positions, cfg.rope_theta, scaling)
    k = _rope(k, positions, cfg.rope_theta, scaling)
    k_pages = k_pages.at[write_page, write_off].set(k[:, 0])
    v_pages = v_pages.at[write_page, write_off].set(v[:, 0])

    impl = getattr(cfg, "paged_attention_impl", "gather")
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "gather"
    if impl == "pallas":
        # Stream pages straight from the pool (skipping holes and
        # pages past pos) instead of materializing the gather — see
        # ops/paged_attention.py. `pos` is recovered from the RoPE
        # positions + the valid mask's idle bit.
        from polyaxon_tpu.ops.paged_attention import paged_decode_attention

        live = valid[:, 0, 0, :].any(axis=-1)  # [B] — idle rows all-False
        pos_vec = jnp.where(live, positions[:, 0], -1)
        attn = paged_decode_attention(
            q[:, 0].reshape(B, H, Hd), k_pages, v_pages, tables,
            pos_vec).astype(dt)[:, None]
    else:
        gathered = jnp.maximum(tables, 0)  # [B, maxp] — scratch for holes
        keys = k_pages[gathered].reshape(B, -1, KV, Hd)  # [B, maxp*page, .]
        vals = v_pages[gathered].reshape(B, -1, KV, Hd)
        keys = repeat_kv(keys, n_rep)
        vals = repeat_kv(vals, n_rep)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, keys).astype(jnp.float32)
        logits = logits * (Hd ** -0.5)
        logits = jnp.where(valid, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(dt)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, vals)
    return x + attn.reshape(B, 1, H * Hd) @ _w(layer["wo"], dt), \
        k_pages, v_pages


def paged_coords(pos: jax.Array, tables: jax.Array, page: int):
    """Shared paged addressing: per-row positions [B] (-1 = idle) +
    block tables [B, maxp] → (positions [B,1] for RoPE, write_page [B],
    write_off [B], attention mask [B,1,1,maxp*page]). Idle/unallocated
    writes land on scratch page 0; the mask admits exactly positions
    0..pos through allocated pages."""
    B, maxp = tables.shape
    pos_safe = jnp.maximum(pos, 0)
    rows = jnp.arange(B)
    write_page = jnp.where(
        pos >= 0, tables[rows, pos_safe // page], 0)
    write_page = jnp.maximum(write_page, 0)  # unallocated → scratch
    write_off = pos_safe % page
    j = jnp.arange(maxp * page)[None, :]  # global position per column
    allocated = jnp.repeat(tables >= 0, page, axis=1)  # [B, maxp*page]
    valid = ((j <= pos_safe[:, None]) & (pos[:, None] >= 0)
             & allocated)[:, None, None, :]
    return pos_safe[:, None], write_page, write_off, valid


def decode_step_paged(
    cfg: LlamaConfig,
    params: dict,
    cache: dict,  # {"k"/"v": [L, P, page, KV, Hd]}
    tokens: jax.Array,  # [B] int32
    pos: jax.Array,  # [B] int32 per-row position being written (-1 idle)
    tables: jax.Array,  # [B, maxp] int32 page ids (-1 = unallocated)
) -> tuple[jax.Array, dict]:
    """`decode_step_ragged` over the paged pool: a row at position p
    with pages covering 0..p matches the dense ragged step at p exactly
    (parity-tested)."""
    dt = cfg.dtype
    page = cache["k"].shape[2]
    positions, write_page, write_off, valid = paged_coords(pos, tables, page)
    x = _embed(cfg, params, tokens, dt)[:, None, :]

    def layer_step(x, inputs):
        layer, k_pages, v_pages = inputs
        x, k_pages, v_pages = paged_attn_step(
            cfg, layer, x, k_pages, v_pages, positions,
            write_page, write_off, tables, valid)
        x = _mlp(cfg, x, layer)
        return x, (k_pages, v_pages)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (params["layers"], cache["k"], cache["v"]))
    x = _norm(cfg, x, params["final_norm"])
    logits = decode_logits(cfg, params, x[:, 0])
    return logits, {"k": new_k, "v": new_v}


def paged_prefill_kv(cfg: LlamaConfig, params: dict, prompt: jax.Array):
    """Prompt pass returning raw per-position KV (no ring assembly):
    (k_all, v_all) [L, P, KV, Hd] for a single row [1, P] — the paged
    insert scatters these into the row's pages. Same ``_prompt_pass``
    body as ``prefill``, so the engines cannot diverge."""
    _, k_all, v_all = _prompt_pass(cfg, params, prompt)
    return k_all[:, 0], v_all[:, 0]  # [L, P, KV, Hd]


def paged_insert_prefill(cache: dict, k_all: jax.Array, v_all: jax.Array,
                         page_ids: jax.Array, page_size: int) -> dict:
    """Scatter a prefilled row's KV ([L, P, KV, Hd]) into its allocated
    pages. ``page_ids`` [maxp] int32 (-1 padding beyond the row's
    pages; positions < P always map into real ids)."""
    P = k_all.shape[1]
    t = jnp.arange(P)
    pidx = jnp.maximum(page_ids[t // page_size], 0)
    off = t % page_size
    return {
        "k": cache["k"].at[:, pidx, off].set(k_all),
        "v": cache["v"].at[:, pidx, off].set(v_all),
    }


def suffix_attn_step(cfg, layer: dict, x: jax.Array, k_prefix: jax.Array,
                     v_prefix: jax.Array, positions: jax.Array,
                     valid: jax.Array):
    """One attention sublayer for a prefill SUFFIX [B, S] whose prefix
    KV already exists (radix-cache hit): queries at absolute positions
    ``positions`` attend [prefix; suffix]. ``k_prefix``/``v_prefix``
    [B, Mpad, KV, Hd] were written by a completed prefill, so they are
    already roped at their absolute positions — only the suffix K gets
    roped here. ``valid`` [B, 1, S, Mpad+S] masks prefix padding and
    keeps the suffix causal. Returns (x, k_suffix, v_suffix)."""
    from polyaxon_tpu.ops.attention import repeat_kv

    dt = cfg.dtype
    B, S = positions.shape
    H, KV, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    n_rep = H // KV
    scaling = getattr(cfg, "rope_scaling", None)

    h = _norm(cfg, x, layer["attn_norm"])
    q = (h @ _w(layer["wq"], dt)).reshape(B, S, H, Hd)
    k = (h @ _w(layer["wk"], dt)).reshape(B, S, KV, Hd)
    v = (h @ _w(layer["wv"], dt)).reshape(B, S, KV, Hd)
    q = _rope(q, positions, cfg.rope_theta, scaling)
    k = _rope(k, positions, cfg.rope_theta, scaling)
    keys = repeat_kv(jnp.concatenate([k_prefix, k], axis=1), n_rep)
    vals = repeat_kv(jnp.concatenate([v_prefix, v], axis=1), n_rep)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, keys).astype(jnp.float32)
    s = s * (Hd ** -0.5)
    s = jnp.where(valid, s, -1e30)
    probs = jax.nn.softmax(s, axis=-1).astype(dt)
    attn = jnp.einsum("bhqk,bkhd->bqhd", probs, vals)
    return x + attn.reshape(B, S, H * Hd) @ _w(layer["wo"], dt), k, v


def _suffix_mask(S: int, m_pad: int, m: jax.Array) -> jax.Array:
    """[1, 1, S, m_pad+S] validity for a suffix prefill: prefix column
    j is real iff j < m (traced scalar — the gather pads to whole
    pages), suffix columns are causal."""
    pref_ok = jnp.broadcast_to(
        jnp.arange(m_pad, dtype=jnp.int32)[None, :] < m, (S, m_pad))
    tri = jnp.tril(jnp.ones((S, S), bool))
    return jnp.concatenate([pref_ok, tri], axis=1)[None, None]


def paged_prefill_suffix_kv(cfg: LlamaConfig, params: dict,
                            suffix: jax.Array, k_prefix: jax.Array,
                            v_prefix: jax.Array, m: jax.Array):
    """Prefill only the NOVEL tail of a prompt whose first ``m`` tokens
    hit the radix prefix cache: ``suffix`` [1, S] holds the token ids at
    absolute positions m..m+S-1, ``k_prefix``/``v_prefix`` [L, Mpad, KV,
    Hd] are the matched pages gathered in chain order (Mpad = whole
    pages ≥ m; columns past m are masked, not read). Returns (k_suf,
    v_suf) [L, S, KV, Hd] for ``paged_insert_suffix`` — compute is
    O(S·(m+S)) instead of the full O(P²) recompute."""
    dt = cfg.dtype
    B, S = suffix.shape
    m_pad = k_prefix.shape[1]
    positions = jnp.broadcast_to(
        m + jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    valid = _suffix_mask(S, m_pad, m)
    x = _embed(cfg, params, suffix, dt)

    def layer_step(x, inputs):
        layer, kp, vp = inputs
        x, k, v = suffix_attn_step(
            cfg, layer, x, kp[None], vp[None], positions, valid)
        x = _mlp(cfg, x, layer)
        return x, (k, v)

    _, (k_all, v_all) = jax.lax.scan(
        layer_step, x, (params["layers"], k_prefix, v_prefix))
    return k_all[:, 0], v_all[:, 0]  # [L, S, KV, Hd]


def paged_insert_suffix(cache: dict, k_suf: jax.Array, v_suf: jax.Array,
                        page_ids: jax.Array, start: jax.Array,
                        page_size: int,
                        real_len: Optional[jax.Array] = None) -> dict:
    """Scatter suffix KV ([L, S, KV, Hd]) into the row's pages at
    absolute positions start..start+S-1 (``start`` traced int32 — the
    cached-token count varies per admission without recompiling).

    ``real_len`` (traced int32) supports BUCKETED suffixes: positions
    at or past it are padding whose KV is garbage — they are routed to
    scratch page 0 (never allocated, never read; serving/paged.py), so
    a padded suffix writes exactly the same real pages as the unpadded
    one. Without it every position is real (the pre-bucketing shape).
    The page lookup clips explicitly: a padded tail can index past the
    row's block table, and the gather's implicit clamp would otherwise
    land on the table's LAST entry — a real page."""
    S = k_suf.shape[1]
    idx = jnp.arange(S)
    t = start + idx
    slot = jnp.minimum(t // page_size, page_ids.shape[0] - 1)
    pidx = jnp.maximum(page_ids[slot], 0)
    if real_len is not None:
        pidx = jnp.where(idx < real_len, pidx, 0)
    off = t % page_size
    return {
        "k": cache["k"].at[:, pidx, off].set(k_suf),
        "v": cache["v"].at[:, pidx, off].set(v_suf),
    }


def generate(
    cfg: LlamaConfig,
    params: dict,
    prompt: jax.Array,  # [B, P] int32
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_p: float = 1.0,
    top_k: int = 0,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Greedy (temperature 0) or sampled continuation: [B, max_new].

    ``temperature``/``top_p``/``top_k`` may be traced scalars (the
    serving path passes them as jitted arguments so sweeping knobs
    reuses one executable); the greedy/sampling choice itself is
    static — a Python float 0.0 selects greedy, anything else selects
    sampling. ``top_p``/``top_k`` filter inside the compiled loop
    (models/common.py sample_logits) — no host round-trip.
    """
    B, P = prompt.shape
    sampling = isinstance(temperature, jax.Array) or temperature > 0
    if sampling and rng is None:
        raise ValueError("sampling (temperature > 0) needs an rng key")
    rng = rng if rng is not None else jax.random.key(0)

    logits, cache = prefill(cfg, params, prompt, P + max_new_tokens)

    def sample(logits, key):
        if sampling:
            return sample_logits(logits, key, temperature, top_p, top_k)
        return jnp.argmax(logits, axis=-1)

    def decode_loop(carry, t):
        cache, logits, key = carry
        key, sub = jax.random.split(key)
        token = sample(logits, sub).astype(jnp.int32)
        logits, cache = decode_step(cfg, params, cache, token, P + t)
        return (cache, logits, key), token

    (_, logits, _), tokens = jax.lax.scan(
        decode_loop, (cache, logits, rng), jnp.arange(max_new_tokens))
    return tokens.T  # [B, max_new]


def apply(
    cfg: LlamaConfig,
    variables: Variables,
    batch: Batch,
    train: bool = True,
    rng: Optional[jax.Array] = None,
):
    tokens = batch["tokens"]
    inputs = shift_right(tokens)
    segments = batch.get("segments")
    if segments is not None:
        # Packed sequences: each segment starts from BOS (no token leaks
        # across the boundary), attention is segment-restricted, and
        # RoPE restarts — every segment trains exactly like an unpacked
        # sequence of its own.
        inputs = jnp.where(segment_starts(segments),
                           jnp.zeros_like(inputs), inputs)
    # Chunked lm-head loss: the [B, S, V] fp32 logits tensor is never
    # materialized (common.chunked_lm_loss) — the dominant HBM saving at
    # pretraining shapes.
    x = hidden_states(cfg, variables["params"], inputs, segment_ids=segments)
    head = lm_head(cfg, variables["params"]).astype(cfg.dtype)
    loss, acc = chunked_lm_loss(x, head, tokens, batch.get("mask"),
                                chunk=cfg.loss_chunk)
    return loss, {"loss": loss, "accuracy": acc}, variables["state"]


def model_def(name: str, **overrides) -> ModelDef:
    cfg = dataclasses.replace(CONFIGS[name], **overrides)
    return ModelDef(
        name=name,
        init=functools.partial(init, cfg),
        apply=functools.partial(apply, cfg),
        logical_axes=functools.partial(logical_axes, cfg),
        unit="tokens",
    )
