"""Planted blocking call under a lock (golden: lock-blocking-call)."""
import threading
import time

_mutex = threading.Lock()


def slow_update():
    with _mutex:
        time.sleep(0.5)
        return 1
