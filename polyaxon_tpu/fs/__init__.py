from polyaxon_tpu.fs.store import (
    FsspecStore,
    LocalStore,
    MemoryStore,
    Store,
    StoreError,
    get_store,
    register_store,
)

__all__ = [
    "FsspecStore",
    "LocalStore",
    "MemoryStore",
    "Store",
    "StoreError",
    "get_store",
    "register_store",
]
