"""Version-portable manual-collective entry points.

The manual schedules (ring/ulysses attention, SPMD pipeline, ragged MoE
dispatch) were written against the modern ``jax.shard_map`` partial-
manual API (``axis_names=``/``check_vma=``). Older jaxlibs ship only
``jax.experimental.shard_map.shard_map`` — and on the jaxlib pinned in
this image the partial-manual mode (``auto=`` nonempty) CHECK-aborts
inside the SPMD partitioner (``spmd_partitioner.cc: IsManualSubgroup``
mismatch, reproduced on the 8-device CPU mesh 2026-08-04). So this shim
normalizes everything onto the one mode that works everywhere: **full
manual** over the whole mesh, with every axis a tensor is actually
sharded over named explicitly in its specs.

The consequence callers must honor: an axis left out of a spec is
*replicated* into the body (a full-manual shard_map all-gathers over
it), not left to GSPMD. Schedules that take batch-sharded activations
therefore name the batch axes in their specs — see ``batch_axes_in``.
The communication audit (``polyaxon_tpu/perf``) counts exactly the
collectives this choice produces, so a spec that silently gathers the
batch shows up as an all-gather regression in the budget gate.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh

__all__ = ["shard_map", "axis_size", "batch_axes_in",
           "tpu_compiler_params"]

# Mesh axes that carry the batch dimension of activations (the rule
# tables map logical "batch" onto these — parallel/sharding.py).
_BATCH_AXES = ("dp", "fsdp")


def axis_size(axis_name: str) -> int:
    """Size of a bound manual axis (``jax.lax.axis_size`` is newer than
    some supported jaxlibs; ``psum(1)`` over the axis is the portable
    spelling and folds to a compile-time constant)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def batch_axes_in(mesh: Mesh):
    """The nontrivial batch-carrying mesh axes, as a PartitionSpec entry
    (None / a name / a tuple of names). Manual schedules put this on the
    batch dim of their specs so a full-manual shard_map keeps the batch
    sharded instead of gathering it — the audit showed the replicated
    spelling costs 4 extra all-gathers + dp-redundant attention compute
    per step on a dp2xcp4 mesh (docs/performance.md)."""
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    axes = tuple(a for a in _BATCH_AXES if shape.get(a, 1) > 1)
    if not axes:
        return None
    return axes if len(axes) > 1 else axes[0]


def tpu_compiler_params(pltpu, **kwargs):
    """Mosaic compiler params across the pallas-TPU rename
    (``CompilerParams`` on modern jax, ``TPUCompilerParams`` before)."""
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


def shard_map(f, *, mesh, in_specs, out_specs,
              axis_names: Optional[set] = None, check_vma: bool = False):
    """``jax.shard_map`` with the signature the schedules were written
    against, lowered onto whichever API this jax ships.

    ``axis_names`` is accepted for source fidelity but NOT honored as
    partial-manual on old jaxlibs (see module docstring): the body
    always runs full-manual, so collectives over any mesh axis are
    legal, and specs are the single source of placement truth.
    """
    if hasattr(jax, "shard_map"):
        kwargs = {}
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma,
                             **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
