"""Blocked flash attention as a Pallas TPU kernel.

TPU-first design (pallas_guide.md): the forward pass tiles Q into
``block_q`` × head_dim VMEM blocks and streams K/V blocks through the
innermost (sequential) grid dimension, keeping the online-softmax
running max/denominator and the output accumulator in f32 VMEM scratch
— O(S) memory instead of the O(S²) logits tensor, with every matmul on
the MXU (``preferred_element_type=f32``). Causal blocks strictly above
the diagonal are skipped with ``pl.when`` (no wasted MXU cycles), and
GQA is handled in the K/V index maps (kv head = q head // n_rep) so
grouped heads are never materialized ``n_rep`` times in HBM.

The backward pass under ``jax.custom_vjp`` has two implementations:

- **Pallas** (default on real TPU): the FlashAttention-2 split — a
  dk/dv kernel gridded over K/V blocks that streams Q blocks (GQA
  groups accumulate onto their shared kv head inside VMEM scratch, so
  dk/dv never materialize per-q-head), and a dq kernel gridded like
  the forward. Both recompute P from the saved logsumexp residual,
  keep every matmul on the MXU in f32 accumulation, and skip causal /
  out-of-window blocks with ``pl.when``.
- **Chunked XLA** (CPU test mesh, non-tiling shapes, and the parity
  reference): recomputes attention probabilities one K/V block at a
  time from the same residual, so it also never materializes S×S.

The reference delegates attention entirely to user frameworks
(SURVEY.md §2b: no model math in-repo); this kernel is owned surface.
"""

from __future__ import annotations

import functools
import json
import os
from typing import Optional

import jax
import jax.numpy as jnp

from polyaxon_tpu.parallel import compat
from jax.experimental import pallas as pl

try:  # pltpu only imports cleanly where libtpu/mosaic is present
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30
LANES = 128  # TPU lane width: scratch vectors are kept lane-broadcast


def _default_interpret() -> bool:
    return jax.default_backend() != "tpu"


def pick_block(seq: int, preferred: int) -> int:
    """Largest power-of-two block <= preferred that divides seq (the
    shared tiling rule — also used by models.common.chunked_lm_loss)."""
    block = min(preferred, seq)
    while block > 1 and seq % block:
        block //= 2
    return block


_pick_block = pick_block  # internal alias

# Per-core VMEM is ~128 MiB on v5e/v4; the budget leaves headroom for
# Mosaic's double-buffered input pipelining and the bwd kernels' extra
# accumulators (dk/dv scratch ≈ the fwd footprint again).
VMEM_BUDGET = 48 * 2**20


def _tile_bytes(bq: int, bk: int, d: int) -> int:
    """Estimated fwd-kernel VMEM residency for one grid cell: bf16 Q
    tile + double-buffered bf16 K/V streams + f32 scores + f32 output
    accumulator + lane-broadcast m/l scratch."""
    return (bq * d * 2          # q tile (bf16)
            + 2 * 2 * bk * d * 2  # k + v, double-buffered (bf16)
            + bq * bk * 4       # scores (f32)
            + bq * d * 4        # o accumulator (f32)
            + 2 * bq * LANES * 4)  # m / l scratch (f32)


# Committed per-device-kind tile picks from the AOT topology probe
# (perf/aot.py flash_pick): each entry is a tile set Mosaic actually
# compiled for that chip, i.e. VMEM-fit EVIDENCE rather than the
# heuristic's estimate. Keyed by `jax.Device.device_kind`.
FLASH_TILES_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "perf", "flash_tiles.json")


@functools.lru_cache(maxsize=1)
def _committed_tile_picks() -> dict:
    try:
        with open(FLASH_TILES_PATH) as fh:
            table = json.load(fh)
    except (OSError, ValueError):  # uncommitted/corrupt: heuristic only
        return {}
    return {k: v for k, v in table.items() if not k.startswith("_")}


def auto_blocks(seq_q: int, seq_k: int, head_dim: int,
                *, vmem_budget: int = VMEM_BUDGET,
                device_kind: Optional[str] = None) -> tuple[int, int]:
    """Trace-time (block_q, block_k) choice keyed on (seq, head_dim,
    VMEM budget) — VERDICT r4 item 3's staged MFU lever. Larger tiles
    amortize the online-softmax rescale and grid overhead (fewer
    passes over the K/V stream per Q tile) but must leave VMEM room
    for pipelining; the historical fixed 512x512 default is kept as
    the FLOOR of preference order so auto never picks worse than the
    measured r3/r4 configuration, and 1024-tiles are tried first where
    the budget allows (small head_dim). Shapes that don't tile fall
    back through ``pick_block`` exactly as explicit sizes do.

    ``device_kind`` (ISSUE 12): a chip with a committed pick in
    ``perf/flash_tiles.json`` uses that compile-validated tile set
    first — still subject to the same seq-tiling and VMEM-budget
    screens, so a probed pick can never select tiles the budget math
    or the shape would reject."""
    pick = _committed_tile_picks().get(device_kind or "")
    if pick:
        bq, bk = int(pick["block_q"]), int(pick["block_k"])
        if _tile_bytes(bq, bk, head_dim) <= vmem_budget:
            got_q = _pick_block(seq_q, bq)
            got_k = _pick_block(seq_k, bk)
            if got_q == min(bq, seq_q) and got_k == min(bk, seq_k):
                return got_q, got_k
    for bq in (1024, 512, 256, 128):
        for bk in (1024, 512, 256, 128):
            if bk > bq * 2:
                continue  # tall score tiles win nothing; skip extremes
            if _tile_bytes(bq, bk, head_dim) <= vmem_budget:
                got_q = _pick_block(seq_q, bq)
                got_k = _pick_block(seq_k, bk)
                if got_q == min(bq, seq_q) and got_k == min(bk, seq_k):
                    return got_q, got_k
    return _pick_block(seq_q, 512), _pick_block(seq_k, 512)


def _block_visible(qi, ki, block_q: int, block_k: int, causal: bool,
                   window: int):
    """Whether block (qi, ki) contributes at all — the grid-skip
    predicate shared by the fwd and both bwd kernels. Causal blocks
    strictly above the diagonal contribute nothing; with a sliding
    window, blocks entirely below the band neither."""
    if not causal:
        return True
    visible = qi * block_q + block_q > ki * block_k
    if window:
        in_band = ki * block_k + block_k > qi * block_q - (window - 1)
        visible = jnp.logical_and(visible, in_band)
    return visible


def _block_mask(qi, ki, block_q: int, block_k: int, causal: bool,
                window: int, qseg_ref, kseg_ref):
    """The in-block [block_q, block_k] validity mask (or None when the
    whole block is valid) — single source of truth for the causal
    triangle, window band, and packed-segment masking used identically
    by all three kernels."""
    mask = None
    if causal:
        rows = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = rows >= cols
        if window:
            mask &= rows - cols < window
    if qseg_ref is not None:
        seg = qseg_ref[0][:, None] == kseg_ref[0][None, :]
        mask = seg if mask is None else mask & seg
    return mask


def _fwd_kernel(
    q_ref,  # [1, 1, block_q, D]
    k_ref,  # [1, 1, block_k, D]
    v_ref,  # [1, 1, block_k, D]
    *rest,  # [qseg [1,block_q], kseg [1,block_k] when use_segments,]
            # o [1,1,block_q,D], lse [1,1,block_q,1],
            # acc/m/l VMEM scratch
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    window: int,  # 0 = unbounded
    use_segments: bool,
):
    if use_segments:
        qseg_ref, kseg_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
        qseg_ref = kseg_ref = None
    qi, ki = pl.program_id(2), pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(_block_visible(qi, ki, block_q, block_k, causal, window))
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s *= scale  # [block_q, block_k]

        mask = _block_mask(qi, ki, block_q, block_k, causal, window,
                           qseg_ref, kseg_ref)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, :1]  # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if mask is not None:
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)  # [block_q, 1]
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)

        v = v_ref[0, 0]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_ref[:, :1] + jnp.log(l_safe)).astype(lse_ref.dtype)


def _flash_fwd_pallas(
    q: jax.Array,  # [B, H, Sq, D]
    k: jax.Array,  # [B, KV, Sk, D]
    v: jax.Array,
    segments,  # [B, Sq] int32 or None (packed-sequence ids)
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    interpret: bool,
    window: int = 0,
) -> tuple[jax.Array, jax.Array]:
    b, h, sq, d = q.shape
    kv = k.shape[1]
    sk = k.shape[2]
    n_rep = h // kv
    grid = (b, h, sq // block_q, sk // block_k)

    use_segments = segments is not None

    kernel = functools.partial(
        _fwd_kernel, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, window=window, use_segments=use_segments,
    )
    compiler_params = None
    if pltpu is not None and not interpret:
        compiler_params = compat.tpu_compiler_params(
            pltpu,
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        )
    scratch = [
        pltpu.VMEM((block_q, d), jnp.float32),
        pltpu.VMEM((block_q, LANES), jnp.float32),
        pltpu.VMEM((block_q, LANES), jnp.float32),
    ]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec(
                (1, 1, block_k, d),
                lambda b_, h_, qi, ki, n_rep=n_rep: (b_, h_ // n_rep, ki, 0),
            ),
            pl.BlockSpec(
                (1, 1, block_k, d),
                lambda b_, h_, qi, ki, n_rep=n_rep: (b_, h_ // n_rep, ki, 0),
            ),
        ] + ([
            pl.BlockSpec((1, block_q), lambda b_, h_, qi, ki: (b_, qi)),
            pl.BlockSpec((1, block_k), lambda b_, h_, qi, ki: (b_, ki)),
        ] if use_segments else []),
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        scratch_shapes=scratch,
        compiler_params=compiler_params,
        interpret=interpret,
    )(q, k, v, *([segments.astype(jnp.int32)] * 2 if use_segments else []))
    return o, lse[..., 0]


def _flash_bwd_xla(
    causal: bool,
    scale: float,
    block_k: int,
    window: int,
    res,
    do: jax.Array,
    dlse: jax.Array,  # [B,H,Sq] cotangent of the lse output
):
    """Chunked recompute backward: O(Sq·block_k) live logits."""
    q, k, v, segments, o, lse = res  # q,o: [B,H,Sq,D]; lse: [B,H,Sq]
    b, h, sq, dh = q.shape
    kv = k.shape[1]
    sk = k.shape[2]
    n_rep = h // kv
    n_blocks = sk // block_k

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [B,H,Sq]
    rows = jnp.arange(sq)

    # [n_blocks, B, KV, block_k, D] views of K/V for the scan.
    k_blocks = jnp.moveaxis(k.reshape(b, kv, n_blocks, block_k, dh), 2, 0)
    v_blocks = jnp.moveaxis(v.reshape(b, kv, n_blocks, block_k, dh), 2, 0)

    # With a sliding window only q rows in [kb, kb + block_k + window)
    # can touch key block kb — restrict the recompute to that span so the
    # backward, like the forward, does O(S·window) work instead of O(S²).
    span = min(sq, block_k + window) if (causal and window) else sq

    def body(dq_acc, inputs):
        ki, kj, vj = inputs  # kj/vj: [B, KV, block_k, D]
        # GQA: expand kv heads to q heads for this block only.
        kj_h = jnp.repeat(kj, n_rep, axis=1) if n_rep > 1 else kj
        vj_h = jnp.repeat(vj, n_rep, axis=1) if n_rep > 1 else vj
        if span < sq:
            start = jnp.clip(ki * block_k, 0, sq - span)
            q_b = jax.lax.dynamic_slice_in_dim(q, start, span, axis=2)
            do_b = jax.lax.dynamic_slice_in_dim(do, start, span, axis=2)
            delta_b = jax.lax.dynamic_slice_in_dim(delta, start, span, axis=2)
            lse_b = jax.lax.dynamic_slice_in_dim(lse, start, span, axis=2)
            dlse_b = jax.lax.dynamic_slice_in_dim(dlse, start, span, axis=2)
            rows_b = start + jnp.arange(span)
        else:
            q_b, do_b, delta_b, lse_b, rows_b = q, do, delta, lse, rows
            dlse_b = dlse
        if segments is not None:
            seg_k = jax.lax.dynamic_slice_in_dim(
                segments, ki * block_k, block_k, axis=1)  # [B, block_k]
            seg_q = (jax.lax.dynamic_slice_in_dim(segments, start, span, axis=1)
                     if span < sq else segments)  # [B, span]
        s = (
            jnp.einsum(
                "bhqd,bhkd->bhqk", q_b, kj_h, preferred_element_type=jnp.float32
            )
            * scale
        )
        mask = None  # broadcastable [B?, 1, span, block_k]
        if causal:
            cols = ki * block_k + jnp.arange(block_k)
            mask = (rows_b[:, None] >= cols[None, :])[None, None]
            if window:
                mask &= (rows_b[:, None] - cols[None, :] < window)[None, None]
        if segments is not None:
            seg_mask = (seg_q[:, :, None] == seg_k[:, None, :])[:, None]
            mask = seg_mask if mask is None else mask & seg_mask
        if mask is not None:
            p = jnp.where(mask, jnp.exp(s - lse_b[..., None]), 0.0)
        else:
            p = jnp.exp(s - lse_b[..., None])
        dv_h = jnp.einsum(
            "bhqk,bhqd->bhkd", p.astype(do.dtype), do_b,
            preferred_element_type=jnp.float32,
        )
        dp = jnp.einsum(
            "bhqd,bhkd->bhqk", do_b, vj_h, preferred_element_type=jnp.float32
        )
        # d lse/d s_j = p_j, so the lse cotangent enters ds additively.
        ds = p * (dp - delta_b[..., None] + dlse_b[..., None]) * scale
        dk_h = jnp.einsum(
            "bhqk,bhqd->bhkd", ds.astype(q.dtype), q_b,
            preferred_element_type=jnp.float32,
        )
        dq_contrib = jnp.einsum(
            "bhqk,bhkd->bhqd", ds.astype(q.dtype), kj_h,
            preferred_element_type=jnp.float32,
        )
        if span < sq:
            cur = jax.lax.dynamic_slice_in_dim(dq_acc, start, span, axis=2)
            dq_acc = jax.lax.dynamic_update_slice_in_dim(
                dq_acc, cur + dq_contrib, start, axis=2)
        else:
            dq_acc = dq_acc + dq_contrib
        if n_rep > 1:  # fold grouped q-heads back onto their kv head
            dk_h = dk_h.reshape(b, kv, n_rep, block_k, dh).sum(axis=2)
            dv_h = dv_h.reshape(b, kv, n_rep, block_k, dh).sum(axis=2)
        return dq_acc, (dk_h, dv_h)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        body, dq0, (jnp.arange(n_blocks), k_blocks, v_blocks)
    )
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(b, kv, sk, dh)
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(b, kv, sk, dh)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _bwd_dkdv_kernel(
    q_ref,      # [1, 1, block_q, D]   (q head = kv*n_rep + r)
    k_ref,      # [1, 1, block_k, D]
    v_ref,      # [1, 1, block_k, D]
    do_ref,     # [1, 1, block_q, D]
    delta_ref,  # [1, 1, block_q, 1]
    lse_ref,    # [1, 1, block_q, 1]
    dlse_ref,   # [1, 1, block_q, 1]  cotangent of the lse output
    *rest,      # [qseg [1,block_q], kseg [1,block_k] when use_segments,]
                # dk [1,1,block_k,D], dv [1,1,block_k,D], scratch x2
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    window: int,
    use_segments: bool,
):
    if use_segments:
        qseg_ref, kseg_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
        qseg_ref = kseg_ref = None
    ki = pl.program_id(2)
    r, qi = pl.program_id(3), pl.program_id(4)
    n_rep, n_q = pl.num_programs(3), pl.num_programs(4)

    @pl.when(jnp.logical_and(r == 0, qi == 0))
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(_block_visible(qi, ki, block_q, block_k, causal, window))
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]

        mask = _block_mask(qi, ki, block_q, block_k, causal, window,
                           qseg_ref, kseg_ref)
        p = jnp.exp(s - lse_ref[0, 0])  # lse block: [block_q, 1]
        if mask is not None:
            p = jnp.where(mask, p, 0.0)

        do = do_ref[0, 0]
        dv_acc[:] += jax.lax.dot_general(  # p^T @ do → [block_k, D]
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(  # do @ v^T → [block_q, block_k]
            do, v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # d lse/d s_j = p_j, so an lse cotangent enters ds additively.
        ds = p * (dp - delta_ref[0, 0] + dlse_ref[0, 0]) * scale
        dk_acc[:] += jax.lax.dot_general(  # ds^T @ q → [block_k, D]
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jnp.logical_and(r == n_rep - 1, qi == n_q - 1))
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(
    q_ref,      # [1, 1, block_q, D]
    k_ref,      # [1, 1, block_k, D]
    v_ref,      # [1, 1, block_k, D]
    do_ref,     # [1, 1, block_q, D]
    delta_ref,  # [1, 1, block_q, 1]
    lse_ref,    # [1, 1, block_q, 1]
    dlse_ref,   # [1, 1, block_q, 1]  cotangent of the lse output
    *rest,      # [qseg, kseg when use_segments,] dq, dq_acc scratch
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    window: int,
    use_segments: bool,
):
    if use_segments:
        qseg_ref, kseg_ref, dq_ref, dq_acc = rest
    else:
        dq_ref, dq_acc = rest
        qseg_ref = kseg_ref = None
    qi, ki = pl.program_id(2), pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(_block_visible(qi, ki, block_q, block_k, causal, window))
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale

        mask = _block_mask(qi, ki, block_q, block_k, causal, window,
                           qseg_ref, kseg_ref)
        p = jnp.exp(s - lse_ref[0, 0])
        if mask is not None:
            p = jnp.where(mask, p, 0.0)

        do = do_ref[0, 0]
        dp = jax.lax.dot_general(
            do, v_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0, 0] + dlse_ref[0, 0]) * scale
        dq_acc[:] += jax.lax.dot_general(  # ds @ k → [block_q, D]
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == n_k - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd_pallas(
    causal: bool,
    scale: float,
    block_q: int,
    block_k: int,
    window: int,
    interpret: bool,
    res,
    do: jax.Array,
    dlse: jax.Array,  # [B,H,Sq] cotangent of the lse output
):
    """FlashAttention-2 backward as two Pallas kernels (see module
    docstring). Gradients accumulate in f32 VMEM scratch; dk/dv for a
    GQA group accumulate onto the shared kv head inside the kernel, so
    per-q-head dk/dv tensors are never materialized in HBM."""
    q, k, v, segments, o, lse = res  # q,o: [B,H,Sq,D]; lse: [B,H,Sq]
    b, h, sq, d = q.shape
    kv = k.shape[1]
    sk = k.shape[2]
    n_rep = h // kv

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [B,H,Sq,1]
    lse4 = lse[..., None]  # [B,H,Sq,1]
    dlse4 = dlse.astype(jnp.float32)[..., None]  # [B,H,Sq,1]
    use_segments = segments is not None
    seg_args = ([segments.astype(jnp.int32)] * 2) if use_segments else []

    n_q, n_k = sq // block_q, sk // block_k
    common = dict(causal=causal, scale=scale, block_q=block_q,
                  block_k=block_k, window=window, use_segments=use_segments)

    def cparams(n_parallel: int, n_arbitrary: int):
        if pltpu is None or interpret:
            return None
        return compat.tpu_compiler_params(
            pltpu, dimension_semantics=("parallel",) * n_parallel
            + ("arbitrary",) * n_arbitrary)

    # dk/dv: grid (b, kv, k_block, group_rep, q_block); the two inner
    # dims revisit the same (b, kv, k_block) output block, so the
    # accumulators live in scratch and are written once at the end.
    dkdv_grid = (b, kv, n_k, n_rep, n_q)
    qmap = lambda b_, kvh, ki, r, qi, n=n_rep: (b_, kvh * n + r, qi, 0)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, **common),
        grid=dkdv_grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), qmap),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, kvh, ki, r, qi: (b_, kvh, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, kvh, ki, r, qi: (b_, kvh, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d), qmap),
            pl.BlockSpec((1, 1, block_q, 1), qmap),
            pl.BlockSpec((1, 1, block_q, 1), qmap),
            pl.BlockSpec((1, 1, block_q, 1), qmap),
        ] + ([
            pl.BlockSpec((1, block_q), lambda b_, kvh, ki, r, qi: (b_, qi)),
            pl.BlockSpec((1, block_k), lambda b_, kvh, ki, r, qi: (b_, ki)),
        ] if use_segments else []),
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, kvh, ki, r, qi: (b_, kvh, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, kvh, ki, r, qi: (b_, kvh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, kv, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b, kv, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=cparams(3, 2),
        interpret=interpret,
    )(q, k, v, do, delta, lse4, dlse4, *seg_args)

    # dq: gridded like the forward, accumulating over k blocks.
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(b, h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, qi, ki, n=n_rep: (b_, h_ // n, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda b_, h_, qi, ki, n=n_rep: (b_, h_ // n, ki, 0)),
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        ] + ([
            pl.BlockSpec((1, block_q), lambda b_, h_, qi, ki: (b_, qi)),
            pl.BlockSpec((1, block_k), lambda b_, h_, qi, ki: (b_, ki)),
        ] if use_segments else []),
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((b, h, sq, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=cparams(3, 1),
        interpret=interpret,
    )(q, k, v, do, delta, lse4, dlse4, *seg_args)[0]
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash(q, k, v, segments, causal, scale, block_q, block_k, interpret,
           window, bwd_impl):
    """Returns (o, lse). Differentiable in both outputs — an lse
    cotangent (ring attention's online merge uses lse) enters the bwd
    as an additive term in ds. Callers that only need o discard lse;
    its cotangent is then structurally zero."""
    return _flash_fwd_pallas(q, k, v, segments, causal, scale, block_q,
                             block_k, interpret, window)


def _flash_fwd_rule(q, k, v, segments, causal, scale, block_q, block_k,
                    interpret, window, bwd_impl):
    o, lse = _flash_fwd_pallas(q, k, v, segments, causal, scale, block_q,
                               block_k, interpret, window)
    return (o, lse), (q, k, v, segments, o, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret, window,
                    bwd_impl, res, cts):
    do, dlse = cts
    if bwd_impl == "pallas":
        # Smaller default tiles than the fwd: the bwd keeps three
        # [block_q, block_k] f32 intermediates (s, p, ds) plus two
        # accumulators live in VMEM at once.
        bq = pick_block(res[0].shape[2], min(block_q, 256))
        bk = pick_block(res[1].shape[2], min(block_k, 256))
        return _flash_bwd_pallas(causal, scale, bq, bk, window, interpret,
                                 res, do, dlse) + (None,)
    return _flash_bwd_xla(causal, scale, block_k, window, res, do,
                          dlse) + (None,)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, KV, D]
    v: jax.Array,
    *,
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    block_q: int | str = 512,  # tile size, or "auto" (auto_blocks)
    block_k: int | str = 512,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,
    segment_ids: Optional[jax.Array] = None,  # [B, S] packed-sequence ids
    bwd_impl: Optional[str] = None,  # "pallas" | "xla"; None = auto
) -> jax.Array:
    """Flash attention over [B, S, H, D] layouts with GQA support.

    ``window``: sliding-window (Mistral-style) causal attention — each
    query attends to its last ``window`` positions; K/V blocks entirely
    outside the band are skipped, so compute is O(S·window).

    ``segment_ids``: packed sequences — attention is additionally
    restricted to equal segment ids (requires Sq == Sk).

    Falls back to the einsum reference (``ops.attention.xla_attention``)
    when shapes don't tile (seq not divisible into >=128 blocks, or
    head_dim not lane-aligned) — callers never need to special-case.
    """
    return flash_attention_with_lse(
        q, k, v, causal=causal, softmax_scale=softmax_scale,
        block_q=block_q, block_k=block_k, interpret=interpret,
        window=window, segment_ids=segment_ids, bwd_impl=bwd_impl)[0]


def flash_attention_with_lse(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, KV, D]
    v: jax.Array,
    *,
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    block_q: int | str = 512,  # tile size, or "auto" (auto_blocks)
    block_k: int | str = 512,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,
    segment_ids: Optional[jax.Array] = None,
    bwd_impl: Optional[str] = None,
) -> tuple[jax.Array, jax.Array]:
    """``flash_attention`` that also returns the row logsumexp
    ``[B, H, Sq]`` (f32) — the residual ring attention needs to merge
    per-block partial attentions exactly. Differentiable in both
    outputs (the lse cotangent flows through the bwd kernels). Same
    fallback rule: non-tiling shapes use the einsum reference, which
    also returns lse."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kv = k.shape[2]
    if h % kv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {kv}")
    if window is not None and (window < 1 or not causal):
        raise ValueError("window must be >= 1 and requires causal attention")
    if segment_ids is not None and sq != sk:
        raise ValueError(
            f"segment_ids requires Sq == Sk, got {sq} vs {sk}")
    if bwd_impl not in (None, "pallas", "xla"):
        # Validate before the shape-based fallback so a typo can't ride
        # silently on non-tiling shapes.
        raise ValueError(f"unknown bwd_impl `{bwd_impl}`")
    if block_q == "auto" or block_k == "auto":
        # Trace-time auto-pick keyed on (seq, head_dim, VMEM budget) —
        # sweepable against the fixed default (VERDICT r4 item 3). On a
        # real TPU backend the committed per-chip pick table is
        # consulted first (compile-validated tiles beat the estimate).
        kind = (jax.devices()[0].device_kind
                if jax.default_backend() == "tpu" else None)
        abq, abk = auto_blocks(sq, sk, d, device_kind=kind)
        block_q = abq if block_q == "auto" else block_q
        block_k = abk if block_k == "auto" else block_k
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    if pltpu is None or bq < 128 or bk < 128 or (d % 128 and d != 64):
        from polyaxon_tpu.ops.attention import xla_attention_with_lse

        return xla_attention_with_lse(
            q, k, v, causal=causal, softmax_scale=softmax_scale,
            window=window, segment_ids=segment_ids)
    if interpret is None:
        interpret = _default_interpret()
    if bwd_impl is None:
        # Pallas bwd on real TPU; the chunked-XLA bwd is faster than an
        # interpreted Pallas kernel on the CPU test mesh.
        bwd_impl = "xla" if interpret else "pallas"
    scale = softmax_scale if softmax_scale is not None else d**-0.5

    # Kernel layout: heads-major [B, H, S, D] so (seq, head_dim) is the
    # trailing (sublane, lane) tile.
    qT = q.transpose(0, 2, 1, 3)
    kT = k.transpose(0, 2, 1, 3)
    vT = v.transpose(0, 2, 1, 3)
    o, lse = _flash(qT, kT, vT, segment_ids, causal, scale, bq, bk,
                    interpret, window or 0, bwd_impl)
    return o.transpose(0, 2, 1, 3), lse
