"""JAXJob runtime end-to-end tests on the virtual mesh: train, learn,
checkpoint, resume — the §7 'minimum end-to-end slice' compute half."""

import os

import jax
import numpy as np
import pytest

from polyaxon_tpu.polyflow import V1JAXJob
from polyaxon_tpu.runtime import RuntimeConfig, run_jaxjob
from polyaxon_tpu.runtime import data as data_lib


def tiny_job(steps=10, **runtime_extra):
    runtime = {
        "model": "llama_tiny",
        "dataset": "lm_synthetic",
        "steps": steps,
        "optimizer": "adamw",
        "learning_rate": 1e-3,
        "batch_size": 2,
        "seq_len": 32,
        "log_every": 2,
        **runtime_extra,
    }
    return V1JAXJob.from_dict(
        {"kind": "jaxjob", "mesh": {"axes": {"dp": 2, "fsdp": 4}}, "runtime": runtime}
    )


class TestData:
    def test_synthetic_datasets_shapes(self):
        it = data_lib.get_dataset("lm_synthetic", batch_size=4, seq_len=16, vocab_size=100)
        batch = next(it)
        assert batch["tokens"].shape == (4, 16)
        it = data_lib.get_dataset("mnist_synthetic", batch_size=4)
        batch = next(it)
        assert batch["image"].shape == (4, 28, 28, 1)
        it = data_lib.get_dataset("mlm_synthetic", batch_size=2, seq_len=16)
        batch = next(it)
        assert (batch["labels"] >= 0).sum() > 0

    def test_deterministic_by_seed(self):
        a = next(data_lib.get_dataset("lm_synthetic", batch_size=2, seq_len=8, seed=3))
        b = next(data_lib.get_dataset("lm_synthetic", batch_size=2, seq_len=8, seed=3))
        np.testing.assert_array_equal(a["tokens"], b["tokens"])

    def test_unknown_dataset(self):
        with pytest.raises(ValueError):
            data_lib.get_dataset("nope", batch_size=1)


class TestRuntimeConfig:
    def test_model_overrides_filtering(self):
        from polyaxon_tpu.models.llama import LlamaConfig

        cfg = RuntimeConfig.model_validate(
            {"model": "llama_tiny", "seq_len": 64, "remat": "full", "bogus_knob": 1}
        )
        overrides = cfg.model_overrides(LlamaConfig)
        assert overrides["max_seq_len"] == 64
        assert overrides["remat"] == "full"
        assert "bogus_knob" not in overrides


class TestTrainLoop:
    def test_loss_decreases(self, cpu_devices):
        result = run_jaxjob(tiny_job(steps=30, dataset="mnist_synthetic", model="mnist_cnn",
                                     batch_size=16, learning_rate=3e-3))
        assert result.steps == 30
        assert result.final_metrics["loss"] < 2.0  # from ~2.3 at init
        assert result.throughput > 0

    def test_metrics_callback(self, cpu_devices):
        seen = []
        run_jaxjob(tiny_job(steps=6), on_metrics=lambda s, m: seen.append((s, m)))
        assert seen and all("loss" in m for _, m in seen)

    def test_metrics_self_report_throughput_and_tflops(self, cpu_devices):
        """Every emission carries the MFU self-report (VERDICT r2 item
        4): tokens/sec + step time always; achieved TFLOPs/chip for
        families with a FLOPs derivation (llama); mfu only when the
        chip's peak is known — absent on the CPU mesh, never wrong."""
        seen = []
        run_jaxjob(tiny_job(steps=6),
                   on_metrics=lambda s, m: seen.append(m))
        assert seen
        for m in seen:
            assert m["tokens_per_sec"] > 0
            assert m["step_time_ms"] > 0
            assert m["tflops_per_sec_per_chip"] > 0  # llama_tiny derives
            assert "mfu" not in m  # cpu device_kind has no peak entry

    def test_grad_accumulation_matches_full_batch(self, cpu_devices):
        """k microbatches accumulated in-step must produce the same
        update as one full-batch step (mean-of-grads == grad-of-mean for
        per-position-mean LM loss over equal-sized microbatches)."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from polyaxon_tpu.models import llama
        from polyaxon_tpu.parallel import build_mesh, rules_for_mesh
        from polyaxon_tpu.runtime.config import RuntimeConfig
        from polyaxon_tpu.runtime.optim import build_optimizer
        from polyaxon_tpu.runtime.step import build_init, build_train_step

        mesh = build_mesh(axes={"dp": 8})
        rules = rules_for_mesh(mesh)
        model_def = llama.model_def("llama_tiny")
        # SGD: updates are linear in grads, so the comparison is exact
        # (adaptive optimizers flip sign on near-zero grads under bf16
        # summation-order noise).
        cfg = RuntimeConfig(model="llama_tiny", steps=1, learning_rate=1e-2,
                            optimizer="sgd", lr_schedule="constant",
                            grad_clip_norm=None)
        optimizer = build_optimizer(cfg)
        with mesh:
            init_fn = build_init(model_def, optimizer, mesh, rules)
            step1 = build_train_step(model_def, optimizer, mesh, rules)
            step4 = build_train_step(model_def, optimizer, mesh, rules,
                                     accum_steps=4)
            tokens = jax.random.randint(jax.random.key(1), (16, 16), 0, 256)
            s_a = init_fn(jax.random.key(0))
            s_a, m_a = step1(s_a, {"tokens": tokens}, jax.random.key(2))
            s_b = init_fn(jax.random.key(0))
            s_b, m_b = step4(s_b, {"tokens": tokens}, jax.random.key(2))
        assert abs(float(m_a["loss"]) - float(m_b["loss"])) < 1e-5
        for a, b in zip(jax.tree.leaves(s_a["params"]),
                        jax.tree.leaves(s_b["params"])):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=2e-5)

        # Masked batches with uneven valid-token counts per microbatch:
        # token-weighted accumulation must still match the full batch.
        mask = np.ones((16, 16), np.int32)
        mask[10:, :] = 0
        mask[10:, 0] = 1  # last 6 rows carry a single valid token each
        batch = {"tokens": tokens, "mask": jnp.asarray(mask)}
        with mesh:
            s_a = init_fn(jax.random.key(0))
            s_a, m_a = step1(s_a, batch, jax.random.key(2))
            s_b = init_fn(jax.random.key(0))
            s_b, m_b = step4(s_b, batch, jax.random.key(2))
        assert abs(float(m_a["loss"]) - float(m_b["loss"])) < 1e-5
        for a, b in zip(jax.tree.leaves(s_a["params"]),
                        jax.tree.leaves(s_b["params"])):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=2e-5)

    def test_moe_grad_accumulation_exact(self, cpu_devices):
        """MoE mixes a mask-weighted CE with a mask-independent router
        aux. The aux is nonlinear in the batch (product of batch means),
        so accum=k is DEFINED as: token-weighted CE grads + uniform
        (1/k) aux grads. Verify the accumulated update matches that
        definition computed manually per-microbatch, with unbalanced
        masks across microbatches."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax

        from polyaxon_tpu.models import moe
        from polyaxon_tpu.parallel import build_mesh, rules_for_mesh
        from polyaxon_tpu.runtime.config import RuntimeConfig
        from polyaxon_tpu.runtime.optim import build_optimizer
        from polyaxon_tpu.runtime.step import build_init, build_train_step

        mesh = build_mesh(axes={"dp": 8})
        rules = rules_for_mesh(mesh)
        model_def = moe.model_def("moe_tiny")
        cfg = RuntimeConfig(model="moe_tiny", steps=1, learning_rate=1e-2,
                            optimizer="sgd", lr_schedule="constant",
                            grad_clip_norm=None)
        optimizer = build_optimizer(cfg)
        k = 4
        tokens = jax.random.randint(jax.random.key(1), (16, 16), 0, 256)
        # Unbalanced masks: microbatch 0 fully valid, 1 half-valid,
        # 2 nearly empty, 3 fully valid.
        mask = np.ones((16, 16), np.int32)
        mask[4:8, 8:] = 0
        mask[8:12, :] = 0
        mask[8:12, 0] = 1
        batch = {"tokens": tokens, "mask": jnp.asarray(mask)}

        with mesh:
            init_fn = build_init(model_def, optimizer, mesh, rules)
            step_k = build_train_step(model_def, optimizer, mesh, rules,
                                      accum_steps=k)
            s = init_fn(jax.random.key(0))
            s_k, _ = step_k(s, batch, jax.random.key(2))

            # Manual reference: same microbatch split, same rng split.
            s_ref = init_fn(jax.random.key(0))
            params0 = s_ref["params"]
            rngs = jax.random.split(jax.random.key(2), k)
            w = np.asarray(mask).reshape(k, 4, 16).sum(axis=(1, 2))
            W = w.sum()

            def masked_part(p, mb, r):
                loss, m, _ = model_def.apply(
                    {"params": p, "state": {}}, mb, True, r)
                return loss - m["loss_unweighted"]

            def unweighted_part(p, mb, r):
                _, m, _ = model_def.apply(
                    {"params": p, "state": {}}, mb, True, r)
                return m["loss_unweighted"]

            acc = jax.tree.map(lambda p: jnp.zeros_like(p, jnp.float32),
                               params0)
            for i in range(k):
                mb = {"tokens": tokens[i * 4:(i + 1) * 4],
                      "mask": batch["mask"][i * 4:(i + 1) * 4]}
                g_ce = jax.grad(masked_part)(params0, mb, rngs[i])
                g_aux = jax.grad(unweighted_part)(params0, mb, rngs[i])
                acc = jax.tree.map(
                    lambda a, gc, ga: a + (w[i] / W) * gc + ga / k,
                    acc, g_ce, g_aux)
            updates, _ = optimizer.update(
                jax.tree.map(lambda g, p: g.astype(p.dtype), acc, params0),
                s_ref["opt_state"], params0)
            ref_params = optax.apply_updates(params0, updates)

        for a, b in zip(jax.tree.leaves(s_k["params"]),
                        jax.tree.leaves(ref_params)):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       atol=3e-5)

    def test_checkpoint_and_resume(self, cpu_devices, tmp_path):
        art = str(tmp_path / "run")
        job = V1JAXJob.from_dict(
            {
                "kind": "jaxjob",
                "mesh": {"axes": {"dp": 2, "fsdp": 4}},
                "checkpointing": {"enabled": True, "intervalSteps": 4, "asyncSave": False},
                "runtime": {"model": "llama_tiny", "steps": 8, "batch_size": 2,
                            "seq_len": 16, "learning_rate": 1e-3},
            }
        )
        r1 = run_jaxjob(job, artifacts_dir=art)
        assert r1.steps == 8
        assert os.path.isdir(os.path.join(art, "checkpoints"))
        # Bump steps and resume: must restore from 8, not restart.
        job2 = job.clone()
        job2.runtime = {**job.runtime, "steps": 12}
        r2 = run_jaxjob(job2, artifacts_dir=art)
        assert r2.restored_from_step == 8
        assert r2.steps == 12

    def test_resume_continues_data_stream_exactly(self, cpu_devices, tmp_path):
        """An interrupted+resumed run must land on the same final metrics
        as an uninterrupted one: data batch i is a pure function of
        (seed, i), and the loop seeks the stream to the restored step."""
        def spec(steps):
            return V1JAXJob.from_dict(
                {
                    "kind": "jaxjob",
                    "mesh": {"axes": {"dp": -1}},
                    "checkpointing": {"enabled": True, "intervalSteps": 4,
                                      "asyncSave": False},
                    "runtime": {"model": "llama_tiny", "steps": steps,
                                "batch_size": 2, "seq_len": 16,
                                "learning_rate": 1e-3},
                }
            )

        straight = run_jaxjob(spec(8), artifacts_dir=str(tmp_path / "a"))
        run_jaxjob(spec(4), artifacts_dir=str(tmp_path / "b"))
        resumed = run_jaxjob(spec(8), artifacts_dir=str(tmp_path / "b"))
        assert resumed.restored_from_step == 4
        assert abs(straight.final_metrics["loss"]
                   - resumed.final_metrics["loss"]) < 1e-5

    def test_resume_of_complete_run_is_noop(self, cpu_devices, tmp_path):
        art = str(tmp_path / "run")
        job = V1JAXJob.from_dict(
            {
                "kind": "jaxjob",
                "mesh": {"axes": {"dp": -1}},
                "checkpointing": {"enabled": True, "intervalSteps": 4, "asyncSave": False},
                "runtime": {"model": "llama_tiny", "steps": 6, "batch_size": 1, "seq_len": 16},
            }
        )
        run_jaxjob(job, artifacts_dir=art)
        r2 = run_jaxjob(job, artifacts_dir=art)
        assert r2.steps == 6
        assert r2.restored_from_step == 6
        assert r2.wall_time == 0.0

    def test_global_batch_size(self, cpu_devices):
        result = run_jaxjob(tiny_job(steps=4, global_batch_size=16))
        assert result.units_per_step == 16 * 32


class TestLmText:
    def test_byte_tokenizer_stream_and_cache(self, tmp_path):
        """Real-text pipeline: tokenize-once cache, resume-exact crops,
        ids within the byte vocab."""
        from polyaxon_tpu.runtime import data as data_lib

        corpus = tmp_path / "corpus.txt"
        corpus.write_text("the quick brown fox jumps over the lazy dog\n"
                          * 40)
        it = data_lib.get_dataset("lm_text", batch_size=2, seq_len=32,
                                  path=str(corpus), seed=3)
        b0 = next(it)
        assert b0["tokens"].shape == (2, 32)
        assert b0["tokens"].dtype == np.int32
        assert 0 <= b0["tokens"].min() and b0["tokens"].max() < 256
        cache = list(tmp_path.glob("corpus.txt.*.tokens.npy"))
        assert len(cache) == 1  # tokenized once, cached beside the file

        # Resume-exact: a fresh iterator at start_batch=1 replays batch 1.
        b1 = next(it)
        it2 = data_lib.get_dataset("lm_text", batch_size=2, seq_len=32,
                                   path=str(corpus), seed=3, start_batch=1)
        np.testing.assert_array_equal(next(it2)["tokens"], b1["tokens"])

        # Stale cache (source changed) is rebuilt, not served: the new
        # corpus contains bytes ('!' = 33) the old one never had, so a
        # served-stale cache could not produce them anywhere.
        import os as _os
        import time as _time

        _time.sleep(0.01)
        corpus.write_text("!!!!" * 200)
        _os.utime(corpus)
        it3 = data_lib.get_dataset("lm_text", batch_size=1, seq_len=16,
                                   path=str(corpus), seed=0)
        fresh = next(it3)["tokens"]
        assert (fresh == ord("!")).all(), fresh

    def test_lm_text_empty_file_rejected(self, tmp_path):
        from polyaxon_tpu.runtime import data as data_lib

        corpus = tmp_path / "empty.txt"
        corpus.write_text("")
        with pytest.raises(ValueError, match="needs more than"):
            next(data_lib.get_dataset("lm_text", batch_size=1,
                                      seq_len=8, path=str(corpus)))

    def test_too_short_corpus_rejected(self, tmp_path):
        from polyaxon_tpu.runtime import data as data_lib

        corpus = tmp_path / "tiny.txt"
        corpus.write_text("short")
        with pytest.raises(ValueError, match="needs more than seq_len"):
            next(data_lib.get_dataset("lm_text", batch_size=1,
                                      seq_len=128, path=str(corpus)))

    def test_jaxjob_trains_on_text(self, tmp_path):
        """dataset: lm_text end-to-end through the runtime (the LoRA
        fine-tune input path)."""
        from polyaxon_tpu.polyflow.runs import V1JAXJob
        from polyaxon_tpu.runtime.loop import run_jaxjob

        corpus = tmp_path / "corpus.txt"
        corpus.write_text("pack my box with five dozen liquor jugs\n" * 64)
        job = V1JAXJob.from_dict({
            "kind": "jaxjob",
            "runtime": {"model": "llama_tiny", "dataset": "lm_text",
                        "path": str(corpus), "tokenizer": "bytes",
                        "steps": 2, "seq_len": 32,
                        "global_batch_size": 8, "log_every": 1},
        })
        result = run_jaxjob(job)
        assert result.steps == 2
        assert np.isfinite(result.final_metrics["loss"])




class TestLmTextPacked:
    """Packed real-text stream: per-document segment ids over a
    continuous token stream (no padding, no cross-doc attention)."""

    def test_segments_follow_document_boundaries(self, tmp_path):
        from polyaxon_tpu.runtime import data as data_lib

        docs = ["aaaa", "bbbbbb", "cc", "ddddddddd"]
        corpus = tmp_path / "docs.txt"
        corpus.write_text(("\n\n".join(docs) + "\n\n") * 8)
        it = data_lib.get_dataset("lm_text_packed", batch_size=2,
                                  seq_len=16, path=str(corpus), seed=1)
        batch = next(it)
        tok, seg = batch["tokens"], batch["segments"]
        assert tok.shape == seg.shape == (2, 16)
        # Separator bytes never leak into the stream (docs tokenize
        # independently).
        assert not np.isin(tok, [ord("\n")]).any()
        # Segment ids change EXACTLY where the letter changes: segment
        # structure mirrors document structure.
        for b in range(2):
            tok_change = tok[b][1:] != tok[b][:-1]
            seg_change = seg[b][1:] != seg[b][:-1]
            np.testing.assert_array_equal(tok_change, seg_change)
        # Per-row relabeling starts each row at segment 0.
        assert (seg[:, 0] == 0).all()
        cache = list(tmp_path.glob("docs.txt.*.ids.npy"))
        assert len(cache) == 1  # tokenized+packed once, cached (mmap-able)

    def test_resume_exact(self, tmp_path):
        from polyaxon_tpu.runtime import data as data_lib

        corpus = tmp_path / "c.txt"
        corpus.write_text("\n\n".join(f"doc {i} body text" * 3
                                       for i in range(20)))
        kw = dict(batch_size=2, seq_len=24, path=str(corpus), seed=5)
        it = data_lib.get_dataset("lm_text_packed", **kw)
        next(it)
        b1 = next(it)
        it2 = data_lib.get_dataset("lm_text_packed", start_batch=1, **kw)
        r1 = next(it2)
        np.testing.assert_array_equal(r1["tokens"], b1["tokens"])
        np.testing.assert_array_equal(r1["segments"], b1["segments"])

    def test_too_short_and_vocab_guard(self, tmp_path):
        from polyaxon_tpu.runtime import data as data_lib

        corpus = tmp_path / "tiny.txt"
        corpus.write_text("short doc")
        with pytest.raises(ValueError, match="at\n? ?least seq_len"):
            next(data_lib.get_dataset("lm_text_packed", batch_size=1,
                                      seq_len=512, path=str(corpus)))
        big = tmp_path / "big.txt"
        big.write_text("zzzz zzzz " * 40)  # byte ids ~122 >= vocab 64
        with pytest.raises(ValueError, match="vocab_size"):
            next(data_lib.get_dataset("lm_text_packed", batch_size=1,
                                      seq_len=16, path=str(big),
                                      vocab_size=64))

    def test_jaxjob_trains_packed(self, tmp_path):
        """dataset: lm_text_packed end-to-end: segments flow through
        shard_batches into the model's packed-attention path."""
        from polyaxon_tpu.polyflow.runs import V1JAXJob
        from polyaxon_tpu.runtime.loop import run_jaxjob

        corpus = tmp_path / "corpus.txt"
        corpus.write_text("\n\n".join(
            f"sentence number {i} with some body" for i in range(64)))
        job = V1JAXJob.from_dict({
            "kind": "jaxjob",
            "runtime": {"model": "llama_tiny",
                        "dataset": "lm_text_packed",
                        "path": str(corpus), "tokenizer": "bytes",
                        "steps": 2, "seq_len": 32,
                        "global_batch_size": 8, "log_every": 1},
        })
        result = run_jaxjob(job)
        assert result.steps == 2
        assert np.isfinite(result.final_metrics["loss"])


class TestEval:
    def test_eval_every_emits_held_out_metrics(self, cpu_devices):
        """eval_every runs the eval step on a FIXED held-out batch set:
        eval_loss appears at the configured cadence and in the final
        outputs, scored on the same data every time (deterministic
        across repeat evals of identical params)."""
        seen = []
        result = run_jaxjob(
            tiny_job(steps=6, eval_every=2, eval_steps=2,
                     learning_rate=0.0),  # frozen params → fixed evals
            on_metrics=lambda s, m: seen.append((s, m)))
        evals = [(s, m["eval_loss"]) for s, m in seen if "eval_loss" in m]
        assert [s for s, _ in evals[:2]] == [2, 4]
        # Frozen params + fixed eval set: every eval is identical.
        vals = [v for _, v in evals]
        assert max(vals) - min(vals) < 1e-6, vals
        assert result.final_metrics["eval_loss"] == pytest.approx(vals[-1])
        # Train metrics are unaffected (throughput accounting intact).
        assert result.throughput > 0

    def test_eval_uses_disjoint_stream(self, cpu_devices):
        """The eval batches come from a disjoint seed stream — they are
        not the training batches."""
        from polyaxon_tpu.runtime import data as data_lib

        train = next(data_lib.get_dataset("lm_synthetic", batch_size=2,
                                          seq_len=16, seed=0))
        ev = next(data_lib.get_dataset("lm_synthetic", batch_size=2,
                                       seq_len=16, seed=104_729))
        assert not np.array_equal(train["tokens"], ev["tokens"])
