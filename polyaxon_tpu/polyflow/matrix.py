"""Hyperparameter search space + matrix kinds (grid/random/hyperband/bayes/
iterative/mapping).

Capability parity with the reference's ``polyflow/matrix`` (SURVEY.md §2
"Polytune" [K], [B] names Hyperband + Bayesian opt explicitly). The spec
types here are pure data; the search *algorithms* (bracket math, GP/EI)
live in ``polyaxon_tpu.tune`` the way upstream splits polyflow from
hypertune.
"""

from __future__ import annotations

import math
import random as _random
from typing import Any, Literal, Optional, Union

from pydantic import field_validator, model_validator

from polyaxon_tpu.schemas.base import BaseSchema


# --------------------------------------------------------------------------
# Hyperparameter distributions
# --------------------------------------------------------------------------

class _Hp(BaseSchema):
    def sample(self, rng: _random.Random) -> Any:
        raise NotImplementedError

    def is_discrete(self) -> bool:
        return True

    def to_grid(self) -> list[Any]:
        raise ValueError(f"{self.__class__.__name__} cannot be enumerated for grid search")

    # Continuous-space view for Bayesian optimization: (low, high, log)
    def to_bounds(self) -> Optional[tuple[float, float, bool]]:
        return None


class V1HpChoice(_Hp):
    kind: Literal["choice"] = "choice"
    value: list[Any]

    def sample(self, rng):
        return rng.choice(self.value)

    def to_grid(self):
        return list(self.value)


class V1HpPChoice(_Hp):
    kind: Literal["pchoice"] = "pchoice"
    value: list[tuple[Any, float]]

    @field_validator("value")
    @classmethod
    def _check_probs(cls, v):
        total = sum(p for _, p in v)
        if not math.isclose(total, 1.0, rel_tol=1e-3):
            raise ValueError(f"pchoice probabilities must sum to 1, got {total}")
        return v

    def sample(self, rng):
        items = [item for item, _ in self.value]
        weights = [p for _, p in self.value]
        return rng.choices(items, weights=weights, k=1)[0]

    def to_grid(self):
        return [item for item, _ in self.value]


class V1HpRange(_Hp):
    kind: Literal["range"] = "range"
    value: list[Union[int, float]]  # [start, stop, step]

    @field_validator("value")
    @classmethod
    def _check(cls, v):
        if len(v) != 3:
            raise ValueError("range expects [start, stop, step]")
        return v

    def _items(self):
        start, stop, step = self.value
        out, x = [], start
        while (step > 0 and x < stop) or (step < 0 and x > stop):
            out.append(x)
            x = x + step
        return out

    def sample(self, rng):
        return rng.choice(self._items())

    def to_grid(self):
        return self._items()

    def to_bounds(self):
        start, stop, _ = self.value
        return (float(min(start, stop)), float(max(start, stop)), False)


def _check_triple(v, *, name):
    if len(v) != 3:
        raise ValueError(f"{name} expects [start, stop, num]")
    if int(v[2]) < 1:
        raise ValueError(f"{name} num must be >= 1")
    return v


class V1HpLinSpace(_Hp):
    kind: Literal["linspace"] = "linspace"
    value: list[Union[int, float]]  # [start, stop, num]

    @field_validator("value")
    @classmethod
    def _check(cls, v):
        return _check_triple(v, name="linspace")

    def _items(self):
        start, stop, num = self.value
        num = int(num)
        if num == 1:
            return [start]
        step = (stop - start) / (num - 1)
        return [start + i * step for i in range(num)]

    def sample(self, rng):
        return rng.choice(self._items())

    def to_grid(self):
        return self._items()

    def to_bounds(self):
        start, stop, _ = self.value
        return (float(min(start, stop)), float(max(start, stop)), False)


class V1HpLogSpace(_Hp):
    kind: Literal["logspace"] = "logspace"
    value: list[Union[int, float]]  # [start_exp, stop_exp, num] base 10

    @field_validator("value")
    @classmethod
    def _check(cls, v):
        return _check_triple(v, name="logspace")

    def _items(self):
        start, stop, num = self.value
        num = int(num)
        if num == 1:
            return [10.0 ** start]
        step = (stop - start) / (num - 1)
        return [10.0 ** (start + i * step) for i in range(num)]

    def sample(self, rng):
        return rng.choice(self._items())

    def to_grid(self):
        return self._items()


class V1HpGeomSpace(_Hp):
    kind: Literal["geomspace"] = "geomspace"
    value: list[Union[int, float]]  # [start, stop, num]

    @field_validator("value")
    @classmethod
    def _check(cls, v):
        _check_triple(v, name="geomspace")
        if v[0] == 0 or v[1] == 0:
            raise ValueError("geomspace start/stop must be nonzero")
        return v

    def _items(self):
        start, stop, num = self.value
        num = int(num)
        if num == 1:
            return [start]
        ratio = (stop / start) ** (1.0 / (num - 1))
        return [start * ratio**i for i in range(num)]

    def sample(self, rng):
        return rng.choice(self._items())

    def to_grid(self):
        return self._items()


class _ContinuousHp(_Hp):
    def is_discrete(self):
        return False


class V1HpUniform(_ContinuousHp):
    kind: Literal["uniform"] = "uniform"
    value: dict[str, float]  # {low, high}

    def sample(self, rng):
        return rng.uniform(self.value["low"], self.value["high"])

    def to_bounds(self):
        return (self.value["low"], self.value["high"], False)


class V1HpQUniform(_ContinuousHp):
    kind: Literal["quniform"] = "quniform"
    value: dict[str, float]  # {low, high, q}

    def sample(self, rng):
        q = self.value["q"]
        return round(rng.uniform(self.value["low"], self.value["high"]) / q) * q

    def to_bounds(self):
        return (self.value["low"], self.value["high"], False)


class V1HpLogUniform(_ContinuousHp):
    kind: Literal["loguniform"] = "loguniform"
    value: dict[str, float]  # {low, high} natural-log bounds

    def sample(self, rng):
        return math.exp(rng.uniform(self.value["low"], self.value["high"]))

    def to_bounds(self):
        return (self.value["low"], self.value["high"], True)


class V1HpQLogUniform(_ContinuousHp):
    kind: Literal["qloguniform"] = "qloguniform"
    value: dict[str, float]

    def sample(self, rng):
        q = self.value["q"]
        return round(math.exp(rng.uniform(self.value["low"], self.value["high"])) / q) * q

    def to_bounds(self):
        return (self.value["low"], self.value["high"], True)


class V1HpNormal(_ContinuousHp):
    kind: Literal["normal"] = "normal"
    value: dict[str, float]  # {loc, scale}

    def sample(self, rng):
        return rng.gauss(self.value["loc"], self.value["scale"])


class V1HpQNormal(_ContinuousHp):
    kind: Literal["qnormal"] = "qnormal"
    value: dict[str, float]

    def sample(self, rng):
        q = self.value["q"]
        return round(rng.gauss(self.value["loc"], self.value["scale"]) / q) * q


class V1HpLogNormal(_ContinuousHp):
    kind: Literal["lognormal"] = "lognormal"
    value: dict[str, float]

    def sample(self, rng):
        return math.exp(rng.gauss(self.value["loc"], self.value["scale"]))


class V1HpQLogNormal(_ContinuousHp):
    kind: Literal["qlognormal"] = "qlognormal"
    value: dict[str, float]

    def sample(self, rng):
        q = self.value["q"]
        return round(math.exp(rng.gauss(self.value["loc"], self.value["scale"])) / q) * q


HpParam = Union[
    V1HpChoice, V1HpPChoice, V1HpRange, V1HpLinSpace, V1HpLogSpace,
    V1HpGeomSpace, V1HpUniform, V1HpQUniform, V1HpLogUniform,
    V1HpQLogUniform, V1HpNormal, V1HpQNormal, V1HpLogNormal, V1HpQLogNormal,
]


# --------------------------------------------------------------------------
# Optimization metric + early stopping
# --------------------------------------------------------------------------

class V1Optimization:
    MAXIMIZE = "maximize"
    MINIMIZE = "minimize"


class V1OptimizationMetric(BaseSchema):
    name: str
    optimization: str = V1Optimization.MINIMIZE

    @field_validator("optimization")
    @classmethod
    def _check(cls, v):
        if v not in (V1Optimization.MAXIMIZE, V1Optimization.MINIMIZE):
            raise ValueError(f"optimization must be maximize|minimize, got {v}")
        return v

    def is_better(self, a: float, b: float) -> bool:
        """True if metric value ``a`` is strictly better than ``b``."""
        return a > b if self.optimization == V1Optimization.MAXIMIZE else a < b

    def sort_key(self, value: float) -> float:
        return -value if self.optimization == V1Optimization.MAXIMIZE else value


class V1OptimizationResource(BaseSchema):
    name: str
    type: str = "int"  # int | float

    def cast(self, value):
        return int(value) if self.type == "int" else float(value)


class V1MetricEarlyStopping(BaseSchema):
    kind: Literal["metric_early_stopping"] = "metric_early_stopping"
    metric: str
    value: float
    optimization: str = V1Optimization.MINIMIZE
    policy: Optional[dict[str, Any]] = None


class V1FailureEarlyStopping(BaseSchema):
    kind: Literal["failure_early_stopping"] = "failure_early_stopping"
    percent: float


EarlyStopping = Union[V1MetricEarlyStopping, V1FailureEarlyStopping]


# --------------------------------------------------------------------------
# Matrix kinds
# --------------------------------------------------------------------------

class V1GridSearch(BaseSchema):
    kind: Literal["grid"] = "grid"
    params: dict[str, HpParam]
    num_runs: Optional[int] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[list[EarlyStopping]] = None


class V1RandomSearch(BaseSchema):
    kind: Literal["random"] = "random"
    params: dict[str, HpParam]
    num_runs: int
    seed: Optional[int] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[list[EarlyStopping]] = None


class V1Hyperband(BaseSchema):
    """Hyperband successive-halving spec ([B] names it; math in tune/)."""

    kind: Literal["hyperband"] = "hyperband"
    params: dict[str, HpParam]
    max_iterations: int
    eta: float = 3
    resource: V1OptimizationResource
    metric: V1OptimizationMetric
    resume: Optional[bool] = None
    seed: Optional[int] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[list[EarlyStopping]] = None

    @model_validator(mode="after")
    def _check(self):
        if self.max_iterations < 1:
            raise ValueError("maxIterations must be >= 1")
        if self.eta <= 1:
            raise ValueError("eta must be > 1")
        return self

    # Bracket arithmetic (the spec-level part; rung advancement lives in
    # tune.hyperband.HyperbandManager).
    @property
    def s_max(self) -> int:
        # Round before truncating: log(243)/log(3) == 4.999999999999999
        # and a bare int() would silently drop a whole bracket.
        return int(round(math.log(self.max_iterations) / math.log(self.eta), 10))

    @property
    def B(self) -> float:  # noqa: N802 - standard Hyperband symbol
        return (self.s_max + 1) * self.max_iterations

    def bracket(self, s: int) -> tuple[int, float]:
        """(num_configs n, initial resource r) for bracket ``s``."""
        n = int(math.ceil((self.B / self.max_iterations) * (self.eta**s) / (s + 1)))
        r = self.max_iterations * (self.eta ** (-s))
        return n, r


class V1Asha(BaseSchema):
    """Asynchronous Successive Halving (Li et al., MLSys 2020).

    Unlike Hyperband's synchronized rungs (a rung must fully complete
    before promotion), ASHA promotes any trial that ranks in the top
    1/eta of COMPLETED trials at its rung the moment it finishes — no
    barrier, so stragglers and preempted trials never stall the sweep.
    The natural fit for preemptible TPU slices ([B] "trials across
    preemptible slices"): slot turnover feeds either a promotion or a
    fresh bottom-rung trial, keeping every slice busy.
    """

    kind: Literal["asha"] = "asha"
    params: dict[str, HpParam]
    num_runs: int  # bottom-rung trials to draw in total
    max_iterations: int  # R: max resource any trial reaches
    min_resource: float = 1  # r: bottom-rung resource
    eta: float = 3
    resource: V1OptimizationResource
    metric: V1OptimizationMetric
    seed: Optional[int] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[list[EarlyStopping]] = None

    @model_validator(mode="after")
    def _check(self):
        if self.num_runs < 1:
            raise ValueError("numRuns must be >= 1")
        if self.max_iterations < 1:
            raise ValueError("maxIterations must be >= 1")
        if self.eta <= 1:
            raise ValueError("eta must be > 1")
        if not 0 < self.min_resource <= self.max_iterations:
            raise ValueError(
                "minResource must be in (0, maxIterations]")
        if self.rung_resources()[0] <= 0:
            # e.g. minResource=0.5 with an int resource casts to 0.
            raise ValueError(
                f"minResource {self.min_resource} casts to a non-positive "
                f"{self.resource.type} resource")
        return self

    def rung_resources(self) -> list:
        """Resource per rung: r·eta^k capped at R (the cap rung is
        terminal). Cast duplicates are dropped — with an int resource
        and small eta, consecutive rungs can round to the same budget,
        and promoting at an identical budget would waste a trial."""
        out: list = []
        r = float(self.min_resource)
        while True:
            capped = min(r, float(self.max_iterations))
            val = self.resource.cast(capped)
            if not out or val > out[-1]:
                out.append(val)
            if capped >= self.max_iterations:
                return out
            r *= self.eta


class V1GaussianProcessConfig(BaseSchema):
    kernel: str = "matern"  # matern | rbf
    length_scale: float = 1.0
    nu: float = 1.9


class V1UtilityFunctionConfig(BaseSchema):
    acquisition_function: str = "ucb"  # ucb | ei | poi
    gaussian_process: Optional[V1GaussianProcessConfig] = None
    kappa: Optional[float] = 2.576
    eps: Optional[float] = 0.0
    num_warmup: Optional[int] = None
    num_iterations: Optional[int] = None

    @field_validator("acquisition_function")
    @classmethod
    def _check(cls, v):
        if v not in ("ucb", "ei", "poi"):
            raise ValueError(f"acquisitionFunction must be ucb|ei|poi, got {v}")
        return v


class V1Bayes(BaseSchema):
    kind: Literal["bayes"] = "bayes"
    params: dict[str, HpParam]
    num_initial_runs: int
    max_iterations: int
    metric: V1OptimizationMetric
    utility_function: Optional[V1UtilityFunctionConfig] = None
    seed: Optional[int] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[list[EarlyStopping]] = None


class V1Hyperopt(BaseSchema):
    """Hyperopt-style sequential model-based search (upstream's
    ``V1Hyperopt`` bridge, SURVEY.md §2 "Polytune" [K] — implemented
    natively in ``tune/hyperopt.py`` rather than wrapping the hyperopt
    package, which is not in this environment).

    ``algorithm``: ``tpe`` (tree-structured Parzen estimator),
    ``anneal`` (shrinking-radius search around the incumbent), or
    ``rand`` (plain random, upstream parity).
    """

    kind: Literal["hyperopt"] = "hyperopt"
    algorithm: str = "tpe"  # tpe | rand | anneal
    params: dict[str, HpParam]
    num_runs: int
    max_iterations: Optional[int] = None
    metric: V1OptimizationMetric
    num_startup_trials: Optional[int] = None  # default: max(4, num_runs // 5)
    seed: Optional[int] = None
    concurrency: Optional[int] = None
    early_stopping: Optional[list[EarlyStopping]] = None

    @field_validator("algorithm")
    @classmethod
    def _check_algorithm(cls, v):
        if v not in ("tpe", "rand", "anneal"):
            raise ValueError(f"algorithm must be tpe|rand|anneal, got {v}")
        return v

    @model_validator(mode="after")
    def _check(self):
        if self.num_runs < 1:
            raise ValueError("numRuns must be >= 1")
        if self.max_iterations is not None and self.max_iterations < 0:
            raise ValueError("maxIterations must be >= 0")
        return self

    @property
    def startup_trials(self) -> int:
        if self.num_startup_trials is not None:
            return max(1, min(self.num_startup_trials, self.num_runs))
        return max(4, min(self.num_runs // 5, 20)) if self.num_runs > 4 else 1

    @property
    def total_budget(self) -> int:
        """Total trials: numRuns, optionally tightened by maxIterations
        (a cap on *model-guided* trials after the startup batch, the
        V1Bayes analogue)."""
        if self.max_iterations is not None:
            return min(self.num_runs, self.startup_trials + self.max_iterations)
        return self.num_runs


class V1Iterative(BaseSchema):
    kind: Literal["iterative"] = "iterative"
    params: dict[str, HpParam]
    max_iterations: int
    seed: Optional[int] = None
    concurrency: Optional[int] = None
    tuner: Optional[dict[str, Any]] = None
    early_stopping: Optional[list[EarlyStopping]] = None


class V1Mapping(BaseSchema):
    kind: Literal["mapping"] = "mapping"
    values: list[dict[str, Any]]
    concurrency: Optional[int] = None
    early_stopping: Optional[list[EarlyStopping]] = None

    @property
    def num_runs(self) -> int:
        return len(self.values)


Matrix = Union[
    V1GridSearch, V1RandomSearch, V1Hyperband, V1Asha, V1Bayes, V1Hyperopt,
    V1Iterative, V1Mapping,
]
