"""Status notifiers (upstream `polyaxon/notifiers` — SURVEY.md §2:
slack/discord/pagerduty/webhook terminal-status pushes, §5.5).

Each notifier formats a terminal run status for one connection kind and
delivers it. Delivery is via stdlib urllib; the zero-egress test
environment uses ``FileNotifier`` (jsonl sink), which is also the audit
trail in production. The ``NotificationService`` resolves a run's
``notifications: [{connections: [...], trigger: ...}]`` spec against
the connection catalog and fans out on terminal transitions — wired
into the agent loop, not the store, so notification IO never blocks a
state transition.
"""

from __future__ import annotations

import json
import logging
import os
import time
import urllib.error
import urllib.request
from typing import Any, Optional

from polyaxon_tpu.connections import ConnectionCatalog, V1Connection, V1ConnectionKind
from polyaxon_tpu.lifecycle import V1Statuses

logger = logging.getLogger(__name__)


def _payload(run: dict[str, Any], status: str) -> dict[str, Any]:
    return {
        "uuid": run.get("uuid"),
        "name": run.get("name"),
        "project": run.get("project"),
        "kind": run.get("kind"),
        "status": status,
        "finished_at": run.get("finished_at"),
        "ts": time.time(),
    }


class Notifier:
    kind = "abstract"

    def __init__(self, connection: V1Connection):
        self.connection = connection

    def format(self, run: dict[str, Any], status: str) -> dict[str, Any]:
        return _payload(run, status)

    def deliver(self, body: dict[str, Any]) -> None:
        raise NotImplementedError

    def notify(self, run: dict[str, Any], status: str) -> None:
        self.deliver(self.format(run, status))

    def _post(self, url: str, body: dict[str, Any],
              headers: Optional[dict[str, str]] = None) -> None:
        req = urllib.request.Request(
            url, data=json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json", **(headers or {})},
        )
        with urllib.request.urlopen(req, timeout=10):
            pass


class WebhookNotifier(Notifier):
    kind = V1ConnectionKind.WEBHOOK

    def deliver(self, body: dict[str, Any]) -> None:
        url = (self.connection.schema_ or {}).get("url")
        if not url:
            raise ValueError(
                f"webhook connection `{self.connection.name}` has no url")
        self._post(url, body)


class SlackNotifier(WebhookNotifier):
    kind = V1ConnectionKind.SLACK

    def format(self, run: dict[str, Any], status: str) -> dict[str, Any]:
        emoji = {"succeeded": ":white_check_mark:", "failed": ":x:",
                 "stopped": ":octagonal_sign:"}.get(status, ":bell:")
        name = run.get("name") or run.get("uuid")
        return {
            "text": f"{emoji} Run *{name}* ({run.get('project')}) → *{status}*",
            "attachments": [{"fields": [
                {"title": "uuid", "value": run.get("uuid"), "short": True},
                {"title": "kind", "value": run.get("kind"), "short": True},
            ]}],
        }


class DiscordNotifier(WebhookNotifier):
    kind = V1ConnectionKind.DISCORD

    def format(self, run: dict[str, Any], status: str) -> dict[str, Any]:
        emoji = {"succeeded": "✅", "failed": "❌",
                 "stopped": "🛑"}.get(status, "🔔")
        name = run.get("name") or run.get("uuid")
        return {
            "content": f"{emoji} Run **{name}** ({run.get('project')}) → **{status}**",
            "embeds": [{"fields": [
                {"name": "uuid", "value": str(run.get("uuid")), "inline": True},
                {"name": "kind", "value": str(run.get("kind")), "inline": True},
            ]}],
        }


class PagerDutyNotifier(Notifier):
    kind = V1ConnectionKind.PAGERDUTY

    def format(self, run: dict[str, Any], status: str) -> dict[str, Any]:
        schema = self.connection.schema_ or {}
        return {
            "routing_key": schema.get("routing_key", ""),
            "event_action": "trigger",
            "payload": {
                "summary": f"run {run.get('name') or run.get('uuid')} {status}",
                "source": run.get("project") or "polyaxon-tpu",
                "severity": "error" if status == "failed" else "info",
                "custom_details": _payload(run, status),
            },
        }

    def deliver(self, body: dict[str, Any]) -> None:
        url = (self.connection.schema_ or {}).get(
            "url", "https://events.pagerduty.com/v2/enqueue")
        self._post(url, body)


class FileNotifier(Notifier):
    """Append-to-jsonl sink (custom kind with a path schema)."""

    kind = V1ConnectionKind.CUSTOM

    def deliver(self, body: dict[str, Any]) -> None:
        path = (self.connection.schema_ or {}).get("path")
        if not path:
            raise ValueError(
                f"file notifier `{self.connection.name}` has no path")
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "a") as fh:
            fh.write(json.dumps(body) + "\n")


_NOTIFIERS = {
    V1ConnectionKind.WEBHOOK: WebhookNotifier,
    V1ConnectionKind.SLACK: SlackNotifier,
    V1ConnectionKind.DISCORD: DiscordNotifier,
    V1ConnectionKind.PAGERDUTY: PagerDutyNotifier,
    V1ConnectionKind.CUSTOM: FileNotifier,
}

_TRIGGER_MATCH = {
    None: lambda s: True,
    "done": lambda s: True,
    "succeeded": lambda s: s == V1Statuses.SUCCEEDED,
    "failed": lambda s: s in (V1Statuses.FAILED, V1Statuses.UPSTREAM_FAILED),
    "stopped": lambda s: s == V1Statuses.STOPPED,
}


class NotificationService:
    def __init__(self, catalog: ConnectionCatalog):
        self.catalog = catalog

    def notifier_for(self, name: str) -> Notifier:
        connection = self.catalog.get(name)
        cls = _NOTIFIERS.get(connection.kind)
        if cls is None:
            raise ValueError(
                f"connection `{name}` (kind={connection.kind}) cannot notify")
        return cls(connection)

    def notify_terminal(self, run: dict[str, Any], status: V1Statuses,
                        notifications: list[dict[str, Any]]) -> int:
        """Fan out; returns deliveries. Failures log, never raise."""
        sent = 0
        for spec in notifications or []:
            trigger = (spec.get("trigger") or "done").lower()
            matcher = _TRIGGER_MATCH.get(trigger)
            if matcher is None or not matcher(status):
                continue
            for name in spec.get("connections") or []:
                try:
                    self.notifier_for(name).notify(run, status.value)
                    sent += 1
                except Exception as exc:
                    logger.warning("notification via `%s` failed: %s", name, exc)
        return sent
