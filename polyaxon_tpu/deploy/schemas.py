"""Deployment config schema — the upstream deploy/helm-values layer
(SURVEY.md §2 "Deploy": `polyaxon admin deploy -f` + values schema)
retargeted at TPU fleets.

Deployment types:
- ``local``    single host: embedded control plane + agent (+ gateway)
- ``compose``  multi-process on one host (api, agent, gateway rendered
               as a process list / systemd-ish units)
- ``gke-tpu``  documented production target [B]: agents own TPU slices
               via the native scheduler; rendering emits the manifests'
               inputs, not k8s objects (no cluster in this environment)
"""

from __future__ import annotations

from typing import Any, ClassVar, Optional

from polyaxon_tpu.schemas.base import BaseSchema


class V1ServiceConfig(BaseSchema):
    enabled: Optional[bool] = True
    host: Optional[str] = "127.0.0.1"
    port: Optional[int] = None
    replicas: Optional[int] = 1
    resources: Optional[dict[str, Any]] = None


class V1SliceConfig(BaseSchema):
    name: str
    accelerator: Optional[str] = "v5e"
    topology: str
    preemptible: Optional[bool] = False


class V1AgentDeployConfig(BaseSchema):
    enabled: Optional[bool] = True
    max_concurrent: Optional[int] = 4
    slices: Optional[list[V1SliceConfig]] = None
    heartbeat_timeout: Optional[float] = 60.0


class V1GatewayConfig(BaseSchema):
    enabled: Optional[bool] = True
    port: Optional[int] = 8080
    server_name: Optional[str] = "_"
    ssl: Optional[dict[str, Any]] = None


class V1DeploymentConfig(BaseSchema):
    deployment_type: str = "local"
    deployment_version: Optional[str] = None
    namespace: Optional[str] = "polyaxon-tpu"
    home: Optional[str] = None
    api: Optional[V1ServiceConfig] = None
    gateway: Optional[V1GatewayConfig] = None
    agent: Optional[V1AgentDeployConfig] = None
    artifacts_store: Optional[str] = None  # connection name
    connections: Optional[list[dict[str, Any]]] = None
    environment: Optional[dict[str, str]] = None

    TYPES: ClassVar[tuple[str, ...]] = ("local", "compose", "gke-tpu")


def check_deployment(data: dict[str, Any]) -> V1DeploymentConfig:
    config = V1DeploymentConfig.from_dict(data)
    if config.deployment_type not in V1DeploymentConfig.TYPES:
        raise ValueError(
            f"deploymentType `{config.deployment_type}` not in "
            f"{V1DeploymentConfig.TYPES}")
    names = set()
    for conn in config.connections or []:
        from polyaxon_tpu.connections import V1Connection

        parsed = V1Connection.from_dict(conn)
        parsed.validate_kind()
        if parsed.name in names:
            raise ValueError(f"duplicate connection `{parsed.name}` in deploy")
        names.add(parsed.name)
    if config.artifacts_store and config.artifacts_store not in names:
        raise ValueError(
            f"artifactsStore `{config.artifacts_store}` is not among the "
            f"declared connections {sorted(names) or '<none>'}")
    ssl = (config.gateway.ssl or {}) if config.gateway else {}
    if bool(ssl.get("cert")) != bool(ssl.get("key")):
        raise ValueError(
            "gateway.ssl needs BOTH cert and key (one alone would render "
            "a broken or silently-plaintext listener)")
    return config
