"""Page-pool allocator for paged-KV continuous batching.

Host-side bookkeeping for the device-side paged cache
(``models/llama.py`` paged surface): a fixed pool of KV pages shared by
all slots, per-slot block tables mapping position//page_size → page id.
Memory then scales with tokens actually held instead of the dense
engine's slots × max_len reservation, so `--kv-pages` can deliberately
oversubscribe (admission waits for pages; a live row that cannot
extend fails loudly rather than corrupting a neighbour).

Cross-request KV reuse is a **radix tree over token prefixes**
(``RadixPrefixIndex``): tree nodes own runs of full pages keyed by the
token chain they hold, admission longest-prefix-matches the prompt
against the tree and adopts the matched pages by refcount, a
divergence *inside* a page forks copy-on-write (the partially-shared
page is duplicated once on device, at fork time, and the new branch
writes only its divergent tokens), and unreferenced tree pages stay
resident until allocation pressure LRU-evicts them from the tails of
the coldest branches. The engine skips prefill compute for every
matched token — a thousand requests sharing a system prompt pay its
KV once (vLLM/PagedAttention + SGLang-style radix reuse, PAPERS.md).

Page 0 is scratch — never allocated; idle rows and masked holes write
there (see ``paged_coords``). The allocator is plain numpy/ints on the
host: allocation happens between decode steps at Python speed, never
inside the compiled program.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

import numpy as np


def _common(key: tuple, tokens, start: int, limit: int) -> int:
    """Length of the common prefix of ``key`` and ``tokens[start:]``,
    capped at ``limit - start`` total tokens."""
    n = min(len(key), max(limit - start, 0))
    j = 0
    while j < n and key[j] == tokens[start + j]:
        j += 1
    return j


class _RadixNode:
    """One edge of the prefix tree: a run of FULL pages and the token
    chain they hold (``len(key) == len(pages) * page_size`` always).
    Children are a list, not a first-token dict: a copy-on-write fork
    splits *inside* a page, so siblings may share up to page_size-1
    leading tokens — match picks the child with the longest agreement
    (a fully-matched first page always beats any partial sibling)."""

    __slots__ = ("key", "pages", "children", "parent", "last_used")

    def __init__(self, key: tuple, pages: list, parent: "_RadixNode"):
        self.key = key
        self.pages = pages
        self.children: list[_RadixNode] = []
        self.parent = parent
        self.last_used = 0


@dataclass
class AdmitResult:
    """What an admission reused. Truthy (admit() returns None on
    failure), so ``if pool.admit(...)`` keeps working for callers that
    only care about success."""

    matched_tokens: int = 0   # prefill positions already resident
    matched_pages: int = 0    # full pages adopted from the tree
    live_hits: int = 0        # ...of which were live in another slot
    cow: Optional[tuple] = None  # (src_page, dst_page) device copy, or None


class RadixPrefixIndex:
    """Token-prefix radix tree whose nodes own page runs. Pure host
    bookkeeping — refcounts live in the PagePool; the tree only says
    which pages hold which token chains and how recently each branch
    mattered (the LRU clock is a monotonic touch counter)."""

    def __init__(self, page_size: int):
        self.page_size = page_size
        self.root = _RadixNode((), [], None)
        self._page_owner: dict[int, _RadixNode] = {}
        self._clock = 0

    def __len__(self) -> int:
        return len(self._page_owner)

    def owns(self, page: int) -> bool:
        return page in self._page_owner

    def _touch(self) -> int:
        self._clock += 1
        return self._clock

    def _nodes(self):
        stack = [self.root]
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children)

    def n_nodes(self) -> int:
        return sum(1 for n in self._nodes() if n is not self.root)

    def match(self, tokens, limit: int, touch: bool = True):
        """Longest-prefix match of ``tokens[:limit]`` against the tree:
        (full_pages, cow) where ``full_pages`` are entirely-matched tree
        pages in chain order and ``cow`` is ``(src_page, m)`` when the
        divergence lands ``m`` tokens INTO the next page (fork point for
        copy-on-write) — None when it falls on a page boundary."""
        ps = self.page_size
        node = self.root
        i = 0
        pages: list[int] = []
        cow = None
        while True:
            best, bj = None, 0
            for child in node.children:
                j = _common(child.key, tokens, i, limit)
                if j > bj:
                    best, bj = child, j
            if best is None or bj == 0:
                break
            full = bj // ps
            pages.extend(best.pages[:full])
            if touch:
                best.last_used = self._touch()
            rem = bj - full * ps
            if rem == 0 and bj == len(best.key) and i + bj < limit:
                node = best
                i += bj
                continue
            if rem > 0:
                cow = (best.pages[full], rem)
            break
        return pages, cow

    def insert(self, tokens, pages: list) -> Optional[_RadixNode]:
        """Register a completed chain (``len(tokens) == len(pages) *
        page_size``). Existing nodes win on overlap (first-wins — the
        caller adopted matched pages at admission, so the overlap IS
        those pages); a mid-node join splits the node at the page
        boundary. Returns the ONE new leaf holding the chain's novel
        pages (None when the chain is already fully present) — the
        caller keeps it as the slot's fresh-leaf marker so a failed
        prefill can detach exactly the pages it never wrote."""
        ps = self.page_size
        node = self.root
        i, ti = 0, 0
        limit = len(tokens)
        while i < limit:
            best, bj = None, 0
            for child in node.children:
                j = _common(child.key, tokens, i, limit)
                if j > bj:
                    best, bj = child, j
            if best is None or bj == 0:
                break
            full = bj // ps
            if bj == len(best.key) and bj % ps == 0:
                node = best
                i += bj
                ti += len(best.pages)
                continue
            if full == 0:
                break  # diverges inside the child's first page: sibling
            node = self._split(best, full)
            i += full * ps
            ti += full
            break
        if ti >= len(pages):
            return None
        leaf = _RadixNode(tuple(tokens[i:]), list(pages[ti:]), node)
        leaf.last_used = self._touch()
        node.children.append(leaf)
        for page in leaf.pages:
            self._page_owner[page] = leaf
        return leaf

    def _split(self, node: _RadixNode, at_pages: int) -> _RadixNode:
        """Split ``node`` after its first ``at_pages`` pages; returns
        the (upper) prefix node. Page-aligned by construction."""
        ps = self.page_size
        suffix = _RadixNode(node.key[at_pages * ps:],
                            node.pages[at_pages:], node)
        suffix.children = node.children
        for child in suffix.children:
            child.parent = suffix
        suffix.last_used = node.last_used
        for page in suffix.pages:
            self._page_owner[page] = suffix
        node.key = node.key[:at_pages * ps]
        node.pages = node.pages[:at_pages]
        node.children = [suffix]
        return node

    def detach(self, leaf: _RadixNode) -> list[int]:
        """Unregister a fresh leaf (failed admission: its pages were
        never written by a completed prefill). Returns the pages the
        tree no longer owns."""
        if leaf.parent is not None and leaf in leaf.parent.children:
            leaf.parent.children.remove(leaf)
        for page in leaf.pages:
            self._page_owner.pop(page, None)
        pages, leaf.pages, leaf.key = leaf.pages, [], ()
        return pages

    def evict_one(self, ref: np.ndarray) -> Optional[int]:
        """Pop ONE unreferenced page from the tail of the
        least-recently-used evictable leaf (evicting a middle page
        would break the chain; a page a live slot still references is
        never a candidate). None = nothing evictable right now."""
        best = None
        for node in self._nodes():
            if node is self.root or node.children or not node.pages:
                continue
            if ref[node.pages[-1]] != 0:
                continue
            if best is None or node.last_used < best.last_used:
                best = node
        if best is None:
            return None
        page = best.pages.pop()
        best.key = best.key[:len(best.pages) * self.page_size]
        del self._page_owner[page]
        if not best.pages and best.parent is not None:
            best.parent.children.remove(best)
        return page

    def reclaimable(self, ref: np.ndarray) -> int:
        """How many tree pages repeated ``evict_one`` calls could free
        right now: pages in maximal all-unreferenced suffixes of the
        tree (a ref==0 page buried under a live descendant is resident
        but NOT reclaimable — admission planning must not count it)."""

        def visit(node: _RadixNode):
            count, kids_clean = 0, True
            for child in node.children:
                sub, clean = visit(child)
                count += sub
                kids_clean = kids_clean and clean
            if not kids_clean:
                return count, False
            i = len(node.pages)
            while i > 0 and ref[node.pages[i - 1]] == 0:
                i -= 1
                count += 1
            return count, i == 0

        return visit(self.root)[0]


class PagePool:
    def __init__(self, slots: int, max_len: int, page_size: int,
                 n_pages: int, prefix_cache: bool = True):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self.max_pages_per_row = -(-max_len // page_size)
        # Page 0 is scratch: usable pages are 1..n_pages-1.
        if n_pages < 2:
            raise ValueError(f"kv pool needs >= 2 pages, got {n_pages}")
        self.n_pages = n_pages
        self._free = list(range(n_pages - 1, 0, -1))
        self.tables = np.full((slots, self.max_pages_per_row), -1, np.int32)
        self.prefix_cache = prefix_cache
        self._ref = np.zeros(n_pages, np.int32)
        self._index = RadixPrefixIndex(page_size) if prefix_cache else None
        # The ONE leaf each slot's admission added to the tree — the
        # only pages a failed prefill must forget (matched pages hold
        # content from COMPLETED prefills and stay shareable).
        self._fresh_leaf: dict[int, _RadixNode] = {}
        # Guards every structure above: the engine loop allocates
        # between decode steps while HTTP threads read stats/invariants.
        self._lock = threading.Lock()
        self._reclaim_cache: Optional[int] = None
        self.prefix_hits = 0        # full pages adopted from the tree
        self.prefix_misses = 0      # shareable pages with no chain match
        self.prefix_hits_live = 0   # adopted pages live in another slot
        self.cow_forks = 0          # mid-page divergences forked
        self.cached_tokens_total = 0  # prefill tokens served from cache
        self.prefix_evictions = 0   # resident pages reclaimed under pressure

    @classmethod
    def dense_equivalent(cls, slots: int, max_len: int, page_size: int,
                         prefix_cache: bool = True) -> "PagePool":
        """Pool sized to the dense engine's reservation (+ scratch)."""
        maxp = -(-max_len // page_size)
        return cls(slots, max_len, page_size, slots * maxp + 1,
                   prefix_cache=prefix_cache)

    # ------------------------------------------------------------ sizing
    @property
    def free_pages(self) -> int:
        """Allocatable pages: truly free + tree pages reclaimable by
        LRU eviction right now (resident pages pinned under a live
        branch do NOT count — admission must not plan against them)."""
        with self._lock:
            return len(self._free) + self._reclaimable_locked()

    def _reclaimable_locked(self) -> int:
        if self._index is None:
            return 0
        if self._reclaim_cache is None:
            self._reclaim_cache = self._index.reclaimable(self._ref)
        return self._reclaim_cache

    def pages_for(self, length: int) -> int:
        return -(-max(length, 1) // self.page_size)

    def utilization(self) -> dict:
        """Pool occupancy in the user's units (usable pages — the
        scratch page is internal): the engine-tick gauges and /v1/stats
        both read this one snapshot. `free` counts allocatable pages,
        so reclaimable resident prefix pages land there."""
        total = self.n_pages - 1
        free = self.free_pages
        used = max(total - free, 0)
        return {"total": total, "used": used, "free": free,
                "fraction": round(used / total, 4) if total else 0.0}

    def radix_stats(self) -> dict:
        """Tree shape for the serving gauges: node count plus pages by
        state (referenced = a live slot holds them too, resident =
        retired-but-shareable)."""
        with self._lock:
            if self._index is None:
                return {"nodes": 0, "pages": 0, "referenced": 0,
                        "resident": 0}
            pages = list(self._index._page_owner)
            referenced = sum(1 for p in pages if self._ref[p] > 0)
            return {"nodes": self._index.n_nodes(), "pages": len(pages),
                    "referenced": referenced,
                    "resident": len(pages) - referenced}

    # ---------------------------------------------------------- planning
    def _match_locked(self, length: int, tokens, touch: bool):
        """(full_pages, cow) the tree offers for this prompt. Only the
        PREFILL positions 0..length-2 are matchable: the decode write
        at length-1 needs a private page regardless."""
        if self._index is None or tokens is None:
            return [], None
        return self._index.match(tokens, length - 1, touch=touch)

    def _plan_locked(self, length: int, tokens) -> int:
        """Allocatable units this admission consumes: adopted pages
        LIVE in another slot cost nothing; adopted resident pages cost
        at most their own reclaim slot (charged 1 — conservative) and
        every miss/CoW/private page costs one fresh allocation."""
        matched, _ = self._match_locked(length, tokens, touch=False)
        live = sum(1 for p in matched if self._ref[p] > 0)
        return self.pages_for(length) - live

    def can_admit(self, length: int, tokens=None) -> bool:
        with self._lock:
            return (self._plan_locked(length, tokens)
                    <= len(self._free) + self._reclaimable_locked())

    def peek_matched_tokens(self, length: int, tokens=None) -> int:
        """How many prefill tokens the radix tree would serve for this
        prompt — the cache-aware admission score. Read-only: no LRU
        touch, no allocation."""
        with self._lock:
            matched, cow = self._match_locked(length, tokens, touch=False)
            return len(matched) * self.page_size + (cow[1] if cow else 0)

    def slot_pages(self, slot: int) -> int:
        """Pages mapped into this slot's row — its live KV footprint.
        The preemption policy ranks eviction victims by it ("most
        over-budget first"), and the eviction test uses it to assert
        the exact page delta a release returns."""
        with self._lock:
            return int(np.count_nonzero(self.tables[slot] >= 0))

    # -------------------------------------------------------- allocation
    def _alloc_one_locked(self):
        """One page: free list first, then evict the LRU reclaimable
        prefix page. None = pool genuinely dry."""
        if self._free:
            return self._free.pop()
        if self._index is not None:
            page = self._index.evict_one(self._ref)
            if page is not None:
                self.prefix_evictions += 1
                self._reclaim_cache = None
                return page
        return None

    def admit(self, slot: int, length: int,
              tokens: Optional[list] = None) -> Optional[AdmitResult]:
        """Allocate pages covering positions 0..length-1 for ``slot``.
        With ``tokens`` (the full prompt) and the prefix cache on, the
        prompt longest-prefix-matches the radix tree: fully-matched
        pages are adopted by refcount (their KV is already written — the
        engine skips their prefill compute), a mid-page divergence
        reports a copy-on-write pair for the engine to duplicate on
        device, and the remaining novel full-page chain is registered
        as ONE fresh tree leaf (invalidated if the prefill never runs).
        None = nothing allocated (the request should wait).

        Page i is shareable iff fully inside the prefill range: the
        decode write at length-1 (and everything after) must land on
        private pages."""
        with self._lock:
            need = self.pages_for(length)
            row = self.tables[slot]
            assert (row < 0).all(), \
                f"slot {slot} admitted while still holding pages"
            matched, cow_src = self._match_locked(length, tokens, touch=True)
            live = sum(1 for p in matched if self._ref[p] > 0)
            if need - live > len(self._free) + self._reclaimable_locked():
                return None
            self._reclaim_cache = None
            for i, page in enumerate(matched):
                row[i] = page
                self._ref[page] += 1
            fresh_start = len(matched)
            for i in range(fresh_start, need):
                page = self._alloc_one_locked()
                if page is None:
                    # _plan said this fits, so this branch is belt-and-
                    # braces against accounting drift: roll back cleanly
                    # rather than corrupt the row.
                    self._release_locked(slot, invalidate_prefix=True)
                    return None
                row[i] = page
                self._ref[page] += 1
            result = AdmitResult(matched_pages=len(matched), live_hits=live)
            m_extra = 0
            if cow_src is not None:
                # Fork point inside page `fresh_start`: the engine
                # copies src → dst once, then the suffix prefill writes
                # only the divergent tokens into the private copy.
                src, m_extra = cow_src
                result.cow = (src, int(row[fresh_start]))
                self.cow_forks += 1
            result.matched_tokens = (len(matched) * self.page_size
                                     + m_extra)
            self.prefix_hits += len(matched)
            self.prefix_hits_live += live
            self.cached_tokens_total += result.matched_tokens
            shareable = 0
            if self._index is not None and tokens is not None:
                shareable = min((length - 1) // self.page_size, need)
            self.prefix_misses += max(shareable - len(matched), 0)
            if shareable > len(matched):
                leaf = self._index.insert(
                    tuple(tokens[:shareable * self.page_size]),
                    [int(p) for p in row[:shareable]])
                if leaf is not None:
                    self._fresh_leaf[slot] = leaf
            return result

    def ensure(self, slot: int, pos: int) -> bool:
        """Make position ``pos`` writable for ``slot`` (allocating its
        page if new). False = pool exhausted; the row keeps its pages."""
        with self._lock:
            idx = pos // self.page_size
            if idx >= self.max_pages_per_row:
                return False
            if self.tables[slot, idx] >= 0:
                return True
            page = self._alloc_one_locked()
            if page is None:
                return False
            self.tables[slot, idx] = page
            self._ref[page] += 1
            return True

    # ----------------------------------------------------------- handoff
    def handoff(self, src_slot: int, dst_slot: int) -> int:
        """Transfer ownership of ``src_slot``'s pages to ``dst_slot``
        (prefill lane → decode lane). Pure bookkeeping: the block-table
        row moves, the fresh-leaf marker follows, and refcounts are
        untouched — the pages appear in exactly one row before and
        after, so ``check_invariants`` holds across the boundary and
        nothing is recomputed or copied on device. Returns the number
        of pages transferred."""
        with self._lock:
            dst = self.tables[dst_slot]
            assert (dst < 0).all(), \
                f"handoff into slot {dst_slot} which still holds pages"
            src = self.tables[src_slot]
            dst[:] = src
            src[:] = -1
            leaf = self._fresh_leaf.pop(src_slot, None)
            if leaf is not None:
                self._fresh_leaf[dst_slot] = leaf
            return int((dst >= 0).sum())

    # ----------------------------------------------------------- release
    def commit_prefix(self, slot: int) -> None:
        """The slot's prefill completed: its fresh tree leaf now holds
        real KV content and survives the slot (drop the invalidation
        marker)."""
        with self._lock:
            self._fresh_leaf.pop(slot, None)

    def release(self, slot: int, invalidate_prefix: bool = False) -> None:
        """Drop the slot's references. A page at refcount 0 returns to
        the free list — unless the radix tree owns it, in which case it
        stays resident (LRU-evicted only under allocation pressure) so
        the next matching prompt reuses its KV.

        ``invalidate_prefix``: the slot's admission failed before its
        prefill wrote the pages — detach the ONE fresh leaf this slot
        registered (pages it merely adopted carry content from
        completed prefills and stay shareable)."""
        with self._lock:
            self._release_locked(slot, invalidate_prefix)

    def _release_locked(self, slot: int, invalidate_prefix: bool) -> None:
        leaf = self._fresh_leaf.pop(slot, None)
        if invalidate_prefix and leaf is not None and self._index is not None:
            self._index.detach(leaf)
        row = self.tables[slot]
        for idx in np.flatnonzero(row >= 0):
            page = int(row[idx])
            self._ref[page] -= 1
            if self._ref[page] <= 0:
                self._ref[page] = 0
                if self._index is None or not self._index.owns(page):
                    self._free.append(page)
        row[:] = -1
        self._reclaim_cache = None

    def invalidate_prefix_cache(self) -> None:
        """Forget the whole tree (device cache rebuilt → its content is
        gone). Unreferenced resident pages return to the free list;
        pages still referenced by live rows keep their allocation but
        lose their shareability (they free normally at release)."""
        with self._lock:
            if self._index is None:
                return
            for page in list(self._index._page_owner):
                if self._ref[page] == 0:
                    self._free.append(page)
            self._index = RadixPrefixIndex(self.page_size)
            self._fresh_leaf.clear()
            self._reclaim_cache = None

    # -------------------------------------------------------- invariants
    def check_invariants(self) -> list[str]:
        """Refcount/CoW bookkeeping cross-check (chaos tests and the CI
        radix smoke assert this stays empty): every usable page is free
        XOR referenced XOR resident-in-tree, refcounts equal block-table
        occurrences, the tree's shape is consistent, and scratch page 0
        is never allocated anywhere."""
        out = []
        with self._lock:
            counts = np.bincount(
                self.tables[self.tables >= 0].ravel(),
                minlength=self.n_pages)
            if counts[0]:
                out.append("scratch page 0 appears in a block table")
            if 0 in self._free:
                out.append("scratch page 0 on the free list")
            if len(set(self._free)) != len(self._free):
                out.append("duplicate pages on the free list")
            free = set(self._free)
            owned = set(self._index._page_owner) if self._index else set()
            if self._index is not None and 0 in owned:
                out.append("scratch page 0 owned by the radix tree")
            for page in range(1, self.n_pages):
                ref = int(self._ref[page])
                if ref != int(counts[page]):
                    out.append(f"page {page}: ref {ref} != "
                               f"{int(counts[page])} table occurrences")
                in_free = page in free
                if in_free and ref > 0:
                    out.append(f"page {page}: on free list with ref {ref}")
                if in_free and page in owned:
                    out.append(f"page {page}: on free list AND tree-owned")
                if ref == 0 and not in_free and page not in owned:
                    out.append(f"page {page}: leaked (ref 0, not free, "
                               "not tree-resident)")
            if self._index is not None:
                seen: set[int] = set()
                for node in self._index._nodes():
                    if node is self._index.root:
                        continue
                    if len(node.key) != len(node.pages) * self.page_size:
                        out.append("radix node key/page length mismatch")
                    if not node.pages:
                        out.append("empty radix node left attached")
                    for child in node.children:
                        if child.parent is not node:
                            out.append("radix child/parent link broken")
                    for page in node.pages:
                        if page in seen:
                            out.append(f"page {page}: owned by two nodes")
                        seen.add(page)
                        if self._index._page_owner.get(page) is not node:
                            out.append(f"page {page}: owner map disagrees "
                                       "with node membership")
                if seen != owned:
                    out.append("owner map and tree pages diverge")
                for slot, leaf in self._fresh_leaf.items():
                    node = leaf
                    while node.parent is not None:
                        node = node.parent
                    if node is not self._index.root:
                        out.append(f"slot {slot}: fresh leaf detached "
                                   "from the tree")
        return out

    def padded_row(self, slot: int) -> np.ndarray:
        """The slot's block-table row (fixed [max_pages_per_row])."""
        return self.tables[slot]
