"""``V1Operation`` — a concrete, parameterized execution of a component.

Parity with the reference's ``polyflow/operations`` (SURVEY.md §2/§3.1
[K]): binds params, presets, queue, matrix, schedule, DAG wiring
(dependencies/trigger/conditions/joins), and patches (``runPatch``) onto
an inline ``component`` or a referenced one (``hubRef``/``pathRef``/
``urlRef``).
"""

from __future__ import annotations

from typing import Annotated, Any, Optional, Union

from pydantic import Field, field_validator, model_validator

from polyaxon_tpu.polyflow.component import V1Component
from polyaxon_tpu.polyflow.environment import (
    V1Cache,
    V1Hook,
    V1Notification,
    V1Plugins,
    V1Termination,
)
from polyaxon_tpu.polyflow.io import V1Param
from polyaxon_tpu.polyflow.matrix import Matrix
from polyaxon_tpu.polyflow.schedules import Schedule
from polyaxon_tpu.schemas.base import BaseSchema


class V1TriggerPolicy:
    ALL_SUCCEEDED = "all_succeeded"
    ALL_FAILED = "all_failed"
    ALL_DONE = "all_done"
    ONE_SUCCEEDED = "one_succeeded"
    ONE_FAILED = "one_failed"
    ONE_DONE = "one_done"

    VALUES = {ALL_SUCCEEDED, ALL_FAILED, ALL_DONE, ONE_SUCCEEDED, ONE_FAILED, ONE_DONE}


class V1Join(BaseSchema):
    query: str
    sort: Optional[str] = None
    limit: Optional[int] = None
    params: Optional[dict[str, V1Param]] = None


class V1Build(BaseSchema):
    hub_ref: Optional[str] = None
    connection: Optional[str] = None
    params: Optional[dict[str, V1Param]] = None
    run_patch: Optional[dict[str, Any]] = None
    patch_strategy: Optional[str] = None
    queue: Optional[str] = None
    presets: Optional[list[str]] = None


class V1EventTrigger(BaseSchema):
    kinds: list[str]
    ref: str


class V1PatchStrategy:
    REPLACE = "replace"
    ISNULL = "isnull"
    POST_MERGE = "post_merge"
    PRE_MERGE = "pre_merge"

    VALUES = {REPLACE, ISNULL, POST_MERGE, PRE_MERGE}


AnnotatedMatrix = Annotated[Matrix, Field(discriminator="kind")]
AnnotatedSchedule = Annotated[Schedule, Field(discriminator="kind")]


class V1Operation(BaseSchema):
    version: Optional[float] = 1.1
    kind: Optional[str] = "operation"
    name: Optional[str] = None
    description: Optional[str] = None
    tags: Optional[list[str]] = None
    params: Optional[dict[str, V1Param]] = None
    presets: Optional[list[str]] = None
    queue: Optional[str] = None
    cache: Optional[V1Cache] = None
    termination: Optional[V1Termination] = None
    plugins: Optional[V1Plugins] = None
    build: Optional[V1Build] = None
    hooks: Optional[list[V1Hook]] = None
    notifications: Optional[list[V1Notification]] = None
    schedule: Optional[AnnotatedSchedule] = None
    events: Optional[list[V1EventTrigger]] = None
    joins: Optional[list[V1Join]] = None
    matrix: Optional[AnnotatedMatrix] = None
    dependencies: Optional[list[str]] = None
    trigger: Optional[str] = None
    conditions: Optional[str] = None
    skip_on_upstream_skip: Optional[bool] = None
    run_patch: Optional[dict[str, Any]] = None
    patch_strategy: Optional[str] = None
    is_preset: Optional[bool] = None
    is_approved: Optional[bool] = None
    component: Optional[V1Component] = None
    hub_ref: Optional[str] = None
    path_ref: Optional[str] = None
    url_ref: Optional[str] = None
    template: Optional[dict[str, Any]] = None

    @field_validator("kind")
    @classmethod
    def _check_kind(cls, v):
        if v not in (None, "operation"):
            raise ValueError(f"Expected kind `operation`, got `{v}`")
        return v

    @field_validator("trigger")
    @classmethod
    def _check_trigger(cls, v):
        if v is not None and v not in V1TriggerPolicy.VALUES:
            raise ValueError(f"Unknown trigger policy `{v}`")
        return v

    @field_validator("patch_strategy")
    @classmethod
    def _check_strategy(cls, v):
        if v is not None and v not in V1PatchStrategy.VALUES:
            raise ValueError(f"Unknown patch strategy `{v}`")
        return v

    @model_validator(mode="after")
    def _check_ref(self):
        refs = [r for r in (self.component, self.hub_ref, self.path_ref, self.url_ref) if r is not None]
        if not self.is_preset and len(refs) == 0:
            raise ValueError(
                "Operation requires one of: inline `component`, `hubRef`, `pathRef`, `urlRef`"
            )
        if len(refs) > 1:
            raise ValueError("Operation must reference exactly one component source")
        return self

    @property
    def has_component(self) -> bool:
        return self.component is not None
