"""Int8 weight-only quantization for the serving path.

Decode is HBM-bandwidth-bound: every generated token re-reads the whole
weight tree, so halving the bytes at rest (bf16 -> int8 + per-channel
f32 scales) is a direct throughput lever on TPU (SURVEY.md §6 HBM
roofline; the reference serves full-precision only — net-new surface,
held to this repo's own bar per VERDICT r2 item 10).

Scheme: symmetric per-channel quantization over the contraction axis.
JAX weights are laid out ``[..., in, out]`` (activations contract the
second-to-last axis), so the scale reduces over ``axis=-2`` only —
stacked-layer weights ``[L, in, out]`` keep per-layer per-out-channel
scales, and the dequant ``q * scale`` broadcast is always elementwise-
valid whatever the rank.

Integration contract: engines pass quantized trees through WHOLE; each
model unwraps every weight at its consumption site (``models/llama.py
_w`` / ``_embed_rows``, shared by moe/t5), duck-typed on
``.dequantize``. The placement matters: inside a ``lax.scan`` decode
loop a tree-level dequant is loop-invariant, so XLA hoists it and
materializes a bf16 copy that every step re-reads — int8 then saves
nothing. Per-consumption unwrapping keeps the convert+multiply fused
into each matmul's operand read, so int8 stays the HBM-resident format
and bf16 weights exist only in VMEM tiles (embedding rows are gathered
int8-first, never the whole table). 1-D leaves (norm gains, biases)
stay full precision — they are a rounding error of the footprint and
the quality-sensitive part.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

_QMAX = 127.0


class QuantizedTensor:
    """An int8 weight + broadcastable scale, registered as a pytree so
    quantized trees flow through jit/device_put/tree_map unchanged."""

    __slots__ = ("q", "scale", "dtype")

    def __init__(self, q, scale, dtype):
        self.q = q
        self.scale = scale
        self.dtype = np.dtype(dtype)

    @property
    def shape(self):
        return self.q.shape

    @property
    def nbytes(self) -> int:
        return int(self.q.size * self.q.dtype.itemsize
                   + self.scale.size * self.scale.dtype.itemsize)

    def dequantize(self) -> jax.Array:
        return (self.q.astype(jnp.float32) * self.scale).astype(self.dtype)

    def tree_flatten(self):
        return (self.q, self.scale), self.dtype

    @classmethod
    def tree_unflatten(cls, dtype, children):
        return cls(children[0], children[1], dtype)

    def __repr__(self):
        return f"QuantizedTensor(shape={tuple(self.q.shape)}, dtype={self.dtype})"


jax.tree_util.register_pytree_node(
    QuantizedTensor,
    lambda t: t.tree_flatten(),
    QuantizedTensor.tree_unflatten,
)


# Leaf-name fragments that mark NON-matmul per-layer vectors (norm
# gains/biases, layer-norm scale/bias pairs, additive biases). These
# are excluded BY NAME, not just rank: stacked per-layer vectors are
# 2-D ([L, D] — a rank rule can't tell them from embed/lm_head), they
# are the quality-sensitive part, and their reduced scale ([1, D],
# leading axis 1) cannot ride a lax.scan over the layer stack the way
# real stacked weights' [L, 1, out] scales can.
_SKIP_FRAGMENTS = ("norm", "bias", "scale", "ln1", "ln2", "router", "pos")
# "router": MoE router weights are a rounding error of the footprint
# ([L, D, E]) but feed an argmax/top-k — a discrete, discontinuous
# choice where quantization noise flips expert assignment outright
# rather than nudging logits. Standard practice keeps routers in full
# precision; the bytes saved would be unmeasurable.
# "pos": additive positional tables (t5 enc_pos) are 2-D but not
# matmul weights — their dequant noise adds straight into every
# activation, and they are footprint-negligible like the norms.


def _eligible(path, leaf: Any) -> bool:
    segments = [str(getattr(k, "key", k)).lower() for k in path]
    if any(frag in seg for seg in segments for frag in _SKIP_FRAGMENTS):
        return False
    if segments and segments[-1].startswith("b_"):
        return False
    return (hasattr(leaf, "ndim") and leaf.ndim >= 2
            and jnp.issubdtype(leaf.dtype, jnp.floating))


def quantize_leaf(w: jax.Array) -> QuantizedTensor:
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / _QMAX  # all-zero channels stay finite
    q = jnp.clip(jnp.round(w32 / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return QuantizedTensor(q, scale, w.dtype)


_jit_quantize_leaf = jax.jit(quantize_leaf)  # one compile per distinct shape


def quantize_tree(params: Any, *, mode: str = "int8") -> Any:
    """Quantize every matmul-shaped leaf (ndim >= 2, floating) of a
    params tree to int8 + per-channel scales. Runs jitted so sharded
    inputs produce sharded quantized weights (GSPMD propagates the
    input sharding through the elementwise quant ops)."""
    if mode != "int8":
        raise ValueError(f"unknown quantization mode {mode!r} "
                         "(supported: 'int8')")
    return jax.tree_util.tree_map_with_path(
        lambda p, w: _jit_quantize_leaf(w) if _eligible(p, w) else w,
        params)


def dequantize_tree(params: Any) -> Any:
    """Identity on plain trees; materializes bf16/f32 views of quantized
    leaves. NOT used on the serving hot path anymore (models unwrap at
    consumption — see module docstring); kept for tests and interop
    (e.g. exporting a quantized checkpoint back to full precision)."""
    return jax.tree.map(
        lambda leaf: leaf.dequantize() if isinstance(leaf, QuantizedTensor)
        else leaf,
        params, is_leaf=lambda leaf: isinstance(leaf, QuantizedTensor))


def tree_bytes(params: Any) -> int:
    """Device bytes of a (possibly quantized) params tree — the number
    the int8 path exists to halve."""
    return sum(
        leaf.nbytes for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedTensor))
        if hasattr(leaf, "nbytes"))
