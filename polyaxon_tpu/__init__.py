"""polyaxon_tpu — a TPU-native ML orchestration framework.

A ground-up rebuild of the capabilities of the reference ``okoye/polyaxon``
(a Kubernetes MLOps orchestrator; see SURVEY.md for the layer map) designed
TPU-first on JAX/XLA/pjit/Pallas:

- Polyaxonfile-compatible specs (``polyflow`` IR + ``polyaxonfile`` reader)
  compile to TPU slice launch plans instead of GPU pod specs.
- A first-class **JAXJob** distributed runtime (``runtime``) replaces
  TFJob/PyTorchJob/MPIJob delegation: XLA collectives over ICI inside
  compiled step functions, ``jax.distributed`` bootstrap over DCN.
- ``parallel`` owns meshes and sharding rules (dp/fsdp/tp/pp/sp/cp/ep).
- ``models`` + ``ops`` own the math the reference never shipped (Llama,
  ViT, ResNet, BERT, MNIST; Pallas flash/ring attention).
- ``tracking``/``streams``/``sidecar`` reimplement traceml's event
  contract with libtpu system metrics.
- ``tune`` reimplements Polytune (grid/random/Hyperband/Bayesian opt).
- ``controlplane``/``scheduler``/``agent`` collapse haupt + agent +
  operator into an embedded service over a pluggable slice provider.

Reference parity note: the reference mount was empty in every session so
far (SURVEY.md §0); parity targets come from BASELINE.json's north star
and knowledge of public upstream Polyaxon, per-claim tagged in SURVEY.md.
"""

__version__ = "0.1.0"

DIST = "polyaxon_tpu"
