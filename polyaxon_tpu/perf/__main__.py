"""``python -m polyaxon_tpu.perf`` — the communication audit CLI.

Default: audit every standard schedule point on the 8-device virtual
CPU mesh, print the per-schedule collective table, and write the full
report artifact (``collective_audit.json``). ``--check`` gates against
the committed budgets (the ci.sh audit stage); ``--update-budgets``
regenerates them after an intentional sharding change; ``--aot-probe``
runs the topology-only TPU compile probe instead.

``--audit`` (ISSUE 12) is the OVERLAP audit: compile every schedule
point against a TPU topology description with the latency-hiding
scheduler pinned (``parallel/overlap.py``), measure the per-schedule
collective ``overlap_ratio`` (``perf/hlo.py``), and — with ``--check``
— gate it against the ``min_overlap_ratio`` floors in budgets.json.
``--inject-serialize`` compiles with the scheduler forced OFF, which
demonstrably flips the gate (the ci.sh self-test). Exit codes under
``--audit --check``: 0 in budget, 1 floor violation, 3 the probe
itself failed (no workable topology — infra, not a regression).

``--json PATH`` writes the machine-readable artifact either mode
(``-`` = stdout, for ``scripts/perf_sweep.py`` and the simulator to
ingest without re-parsing the table).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _force_cpu_mesh(n: int) -> None:
    from polyaxon_tpu.utils import cpu_mesh_xla_flags

    cpu_mesh_xla_flags(n)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def _write_artifact(artifact: dict, path: str) -> None:
    if path == "-":
        json.dump(artifact, sys.stdout, indent=2)
        sys.stdout.write("\n")
        return
    with open(path, "w") as fh:
        json.dump(artifact, fh, indent=2)
        fh.write("\n")
    print(f"# wrote {path}", file=sys.stderr)


def _publish_overlap_metrics(reports: list[dict]) -> None:
    """Set the perf gauges/counters in THIS process's registry so a
    co-resident /metrics endpoint (the control-plane API) exposes the
    measurement the ``overlap-regression`` rule watches."""
    from polyaxon_tpu.obs import metrics

    metrics.ensure_perf_metrics()
    for rep in reports:
        name = rep["name"]
        metrics.perf_overlap_ratio().set(
            float(rep["overlap_ratio"]), schedule=name)
        for kind, n in rep["overlap"].get("async_by_kind", {}).items():
            metrics.perf_async_collectives_total().inc(
                int(n), schedule=name, kind=kind)


def _overlap_audit_main(args) -> int:
    from polyaxon_tpu.perf import aot, audit, budgets

    points = None
    if args.schedules:
        points = [audit.point_by_name(s.strip()).name
                  for s in args.schedules.split(",") if s.strip()]
    result = aot.run_overlap_audit(
        points=points, serialize=args.inject_serialize,
        timeout_s=args.aot_timeout or aot.PROBE_TIMEOUT_S)
    if not result.get("ok"):
        print("# overlap audit: no workable TPU topology "
              f"({json.dumps(result.get('topologies', {}))[:300]})",
              file=sys.stderr)
        # Under --check, distinguish "could not measure" (infra) from
        # "measured below floor" (regression): ci.sh treats 3 as a
        # skipped gate on hosts without the TPU compiler, 1 as red.
        return 3 if args.check else 1
    reports = result.get("reports", [])

    print(f"{'schedule':<12} {'overlap':>8} {'async':>6} {'sync':>6} "
          f"{'coll us':>9} {'hidden us':>10}   topology={result['topology']}"
          + ("  [SERIALIZED]" if args.inject_serialize else ""))
    for r in reports:
        o = r["overlap"]
        print(f"{r['name']:<12} {r['overlap_ratio']:>8.4f} "
              f"{o['n_async_collectives']:>6} {o['n_sync_collectives']:>6} "
              f"{o['coll_time_us']:>9.3f} {o['hidden_time_us']:>10.3f}")
    for pname, err in sorted(result.get("point_errors", {}).items()):
        print(f"{pname:<12} ERROR {err}", file=sys.stderr)

    _publish_overlap_metrics(reports)
    if args.json:
        _write_artifact({"overlap_audit": result}, args.json)

    if args.update_budgets:
        if args.inject_serialize:
            print("refusing to bake serialized-deopt floors into budgets",
                  file=sys.stderr)
            return 2
        path = budgets.write_overlap_floors(reports, result["topology"])
        print(f"# wrote {path}", file=sys.stderr)
        return 0

    if args.check:
        violations = budgets.check_overlap(reports, only=points)
        if violations:
            for v in violations:
                print(f"OVERLAP BUDGET VIOLATION: {v}", file=sys.stderr)
            return 1
        print("# overlap budgets OK", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m polyaxon_tpu.perf",
        description="HLO collective audit over the standard schedule "
                    "points (8-device virtual CPU mesh)")
    parser.add_argument("--schedules", default=None,
                        help="comma-separated subset of standard points "
                             "(default: all)")
    parser.add_argument("--check", action="store_true",
                        help="fail (exit 1) on any budget violation")
    parser.add_argument("--update-budgets", action="store_true",
                        help="regenerate polyaxon_tpu/perf/budgets.json "
                             "from this run")
    parser.add_argument("--json", default=None,
                        help="report artifact path ('' = don't write, "
                             "'-' = stdout; default collective_audit.json, "
                             "or overlap_audit.json under --audit)")
    parser.add_argument("--inject-reshard", action="store_true",
                        help="deliberately replicate the batch inside the "
                             "step (demonstrates the gate failing)")
    parser.add_argument("--audit", action="store_true",
                        help="AOT TPU overlap audit: compile the schedule "
                             "points against a TPU topology with the "
                             "latency-hiding scheduler pinned and gate the "
                             "measured overlap_ratio (--check)")
    parser.add_argument("--inject-serialize", action="store_true",
                        help="compile the overlap audit with the scheduler "
                             "forced OFF (demonstrates the overlap gate "
                             "failing)")
    parser.add_argument("--ops", action="store_true",
                        help="include the per-instruction op list in the "
                             "JSON artifact (large)")
    parser.add_argument("--aot-probe", action="store_true",
                        help="run the AOT topology-only TPU compile probe "
                             "and write aot_probe_results.json")
    parser.add_argument("--aot-timeout", type=float, default=None,
                        help="probe subprocess timeout seconds "
                             "(per topology candidate)")
    parser.add_argument("--aot-train-step", default=None, metavar="POINTS",
                        help="comma-separated standard points to also "
                             "compile as full train steps against the "
                             "topology (TPU collective reports), e.g. "
                             "'ulysses-cp,ring-cp'")
    parser.add_argument("--devices", type=int, default=8,
                        help="virtual CPU mesh size (default 8)")
    args = parser.parse_args(argv)
    if args.json is None:
        args.json = "overlap_audit.json" if args.audit \
            else "collective_audit.json"

    if args.audit:
        return _overlap_audit_main(args)

    if args.aot_probe:
        from polyaxon_tpu.perf import aot

        result = aot.run_probe(args.aot_timeout or aot.PROBE_TIMEOUT_S,
                               train_step_points=args.aot_train_step)
        out_path = "aot_probe_results.json"
        with open(out_path, "w") as fh:
            json.dump(result, fh, indent=2)
            fh.write("\n")
        print(json.dumps(result))
        print(f"# wrote {out_path}", file=sys.stderr)
        # A negative probe is a recorded RESULT, not a failure: only a
        # harness-level error (no JSON at all) exits nonzero.
        return 0 if ("topologies" in result or result.get("ok")) else 1

    _force_cpu_mesh(args.devices)

    from polyaxon_tpu.perf import audit, budgets

    points = list(audit.STANDARD_POINTS)
    if args.schedules:
        points = [audit.point_by_name(s.strip())
                  for s in args.schedules.split(",") if s.strip()]

    reports = []
    for point in points:
        print(f"→ {point.name} ...", flush=True, file=sys.stderr)
        reports.append(audit.audit_point(
            point, inject_reshard=args.inject_reshard, keep_ops=args.ops))

    kinds = sorted({k for r in reports for k in r["counts"]})
    header = f"{'schedule':<12} {'mesh':<18} " + " ".join(
        f"{k:>18}" for k in kinds) + f" {'est MiB/step':>13}"
    print(header)
    for r in reports:
        mesh = "x".join(f"{a}{s}" for a, s in r["axes"].items())
        row = f"{r['name']:<12} {mesh:<18} " + " ".join(
            f"{r['counts'].get(k, 0):>18}" for k in kinds)
        row += f" {r['est_wire_bytes_per_step'] / 2**20:>13.2f}"
        print(row)

    if args.json:
        artifact = {"reports": reports}
        ring = next((r for r in reports if r["name"] == "ring-cp"), None)
        uly = next((r for r in reports if r["name"] == "ulysses-cp"), None)
        if ring and uly:
            artifact["ring_vs_ulysses"] = audit.diff_reports(ring, uly)
        _write_artifact(artifact, args.json)

    if args.update_budgets:
        if args.inject_reshard:
            print("refusing to bake an injected reshard into budgets",
                  file=sys.stderr)
            return 2
        import jax

        path = budgets.write_budgets(
            reports, meta={"jax": jax.__version__,
                           "backend": "cpu-virtual",
                           "n_devices": args.devices})
        print(f"# wrote {path}", file=sys.stderr)
        return 0

    if args.check:
        violations = budgets.check_reports(reports)
        if violations:
            for v in violations:
                print(f"BUDGET VIOLATION: {v}", file=sys.stderr)
            return 1
        print("# collective budgets OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
