#!/usr/bin/env python
"""Serving decode throughput: bf16 vs --quantize int8, on the current
backend (the real chip when the tunnel is up).

Decode is HBM-bandwidth-bound — each generated token re-reads the whole
weight tree — so int8 weight-only quantization (serving/quantize.py)
should approach 2x tokens/sec on large models. This measures the real
number plus the quantization noise (greedy-token agreement vs bf16) so
`plx serve --quantize int8` ships with a recorded quality/throughput
tradeoff (VERDICT r2 item 10).

Usage: python scripts/bench_decode.py [--model llama3_1b] [--slots 8]
       [--steps 256] [--prompt-len 32]
Writes bench_decode_results.json at the repo root.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from polyaxon_tpu.utils import apply_jax_platforms_override  # noqa: E402

apply_jax_platforms_override()  # honor JAX_PLATFORMS=cpu despite sitecustomize


def measure(model: str, quantize: bool, slots: int, steps: int,
            prompt_len: int, seed: int = 0,
            lm_chunk: int | None = None) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from polyaxon_tpu.serving.quantize import quantize_tree, tree_bytes
    from polyaxon_tpu.serving.server import _family, load_params

    family = _family(model)
    cfg, params = load_params(model, seed=seed)
    if lm_chunk is not None:
        # Sweepable lever: the quantized decode-logits vocab chunk
        # (models/common.py lm_logits) — fewer/larger matmuls per step
        # at bigger chunks, with the int8-on-carry guarantee unchanged.
        import dataclasses

        cfg = dataclasses.replace(cfg, lm_logits_chunk=lm_chunk)
    full_bytes = tree_bytes(params)
    if quantize:
        params = quantize_tree(params)
    max_len = min(cfg.max_seq_len, prompt_len + steps + 8)

    # The continuous engine's exact step program, driven synchronously:
    # one ragged decode step for the whole slot pool, greedy rows.
    # Quantized trees pass through whole — weights unwrap at their
    # consumption sites inside the model (models/common.py _w), the
    # same contract the engines use.
    def step(params, cache, tokens, pos):
        logits, cache = family.decode_step_ragged(
            cfg, params, cache, tokens, pos)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

    step = jax.jit(step, donate_argnums=(1,))

    cache = family.cb_init_cache(cfg, slots, max_len)
    prompt = jax.random.randint(jax.random.key(1), (1, prompt_len), 0,
                                cfg.vocab_size, jnp.int32)
    row = jax.jit(
        lambda p, t: family.cb_prefill(cfg, p, t, max_len)
    )(params, prompt)
    for b in range(slots):
        cache = family.insert_cache_row(cache, row, jnp.int32(b))
    pos = jnp.full((slots,), prompt_len - 1, jnp.int32)
    cur = jnp.full((slots,), int(prompt[0, -1]), jnp.int32)

    # Warm (compile) + timed run.
    cur, cache = step(params, cache, cur, pos)
    pos = pos + 1
    jax.block_until_ready(cur)
    emitted = []
    t0 = time.perf_counter()
    for _ in range(steps):
        cur, cache = step(params, cache, cur, pos)
        pos = pos + 1
        emitted.append(cur)
    jax.block_until_ready(cur)
    dt = time.perf_counter() - t0
    tokens = np.asarray(jnp.stack(emitted))  # [steps, slots]
    # The EFFECTIVE vocab chunk, not just the request: _lm_chunk_len
    # floors to a power of two capped at V//2, so distinct --lm-chunk
    # values can compile the SAME program — the sweep record must show
    # that, or a no-op delta reads as a lever effect.
    from polyaxon_tpu.models.common import _lm_chunk_len

    effective_chunk = (_lm_chunk_len(cfg.vocab_size, cfg.lm_logits_chunk)
                       if quantize else None)
    return {
        "model": model,
        "quantize": "int8" if quantize else None,
        **({"lm_chunk_effective": effective_chunk}
           if effective_chunk is not None else {}),
        "slots": slots,
        "decode_steps": steps,
        "weight_bytes": tree_bytes(params),
        "weight_bytes_bf16": full_bytes,
        "tokens_per_sec": round(steps * slots / dt, 2),
        "step_ms": round(dt / steps * 1e3, 3),
        "tokens": tokens,
    }


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="llama3_1b")
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--steps", type=int, default=256)
    parser.add_argument("--prompt-len", type=int, default=32)
    def _positive(v):
        v = int(v)
        if v < 1:
            raise argparse.ArgumentTypeError(
                "lm-chunk must be >= 1 (chunk<=0 would silently fall "
                "back to the monolithic dequant this bench exists to "
                "avoid)")
        return v

    parser.add_argument("--lm-chunk", type=_positive, default=None,
                        help="quantized decode-logits vocab chunk "
                             "(default: the model config's 4096)")
    args = parser.parse_args()

    import jax

    rows = []
    for quantize in (False, True):
        r = measure(args.model, quantize, args.slots, args.steps,
                    args.prompt_len, lm_chunk=args.lm_chunk)
        print(f"{args.model} quantize={r['quantize']}: "
              f"{r['tokens_per_sec']} tok/s ({r['step_ms']} ms/step, "
              f"weights {r['weight_bytes'] / 2**20:.0f} MiB)", flush=True)
        rows.append(r)

    bf16, int8 = rows
    agree = float((bf16.pop("tokens") == int8.pop("tokens")).mean())
    # Bandwidth roofline context: each decode step re-reads the whole
    # weight tree, so implied bandwidth = weight_bytes / step_time. On
    # a v5e (~819 GB/s HBM) a bandwidth-bound step cannot beat
    # weight_bytes/819e9 — if the bf16 step is near that bound, int8
    # SHOULD approach 2x; if far below it, decode is latency/compute
    # bound there and int8's ceiling shrinks accordingly.
    V5E_HBM_GBPS = 819.0
    for r in rows:
        gb = r["weight_bytes"] / 1e9
        # 3 SIGNIFICANT digits, not 3 decimals: tiny-model bounds are
        # sub-microsecond and fixed rounding would record 0.0.
        r["implied_gbps"] = float(f"{gb / (r['step_ms'] / 1e3):.3g}")
        r["hbm_bound_step_ms_v5e"] = float(f"{gb / V5E_HBM_GBPS * 1e3:.3g}")
    out = {
        "backend": jax.devices()[0].platform,
        **({"lm_chunk": args.lm_chunk}
           if args.lm_chunk is not None else {}),
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        "results": rows,
        "int8_speedup": round(int8["tokens_per_sec"]
                              / bf16["tokens_per_sec"], 3),
        # Greedy-token agreement over the whole run: the end-to-end
        # quality signal (argmax flips compound once sequences diverge,
        # so this is a conservative lower bound on per-step agreement).
        "greedy_token_agreement": round(agree, 4),
    }
    path = os.path.join(REPO, "bench_decode_results.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
    print(f"int8 speedup {out['int8_speedup']}x, greedy agreement "
          f"{out['greedy_token_agreement']}; wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
