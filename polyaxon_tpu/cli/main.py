from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

import click

from polyaxon_tpu.tracking.events import V1EventKind as _V1EventKind

DEFAULT_HOME = os.path.join(os.path.expanduser("~"), ".polyaxon_tpu")


def get_home() -> str:
    return os.environ.get("POLYAXON_TPU_HOME", DEFAULT_HOME)


def get_plane():
    from polyaxon_tpu.controlplane import ControlPlane

    return ControlPlane(get_home())


def get_run_or_fail(plane, uid):
    try:
        return plane.get_run(uid)
    except KeyError as exc:
        raise click.ClickException(str(exc.args[0])) from exc


def _parse_params(params: tuple[str, ...]) -> dict:
    out = {}
    for item in params:
        if "=" not in item:
            raise click.BadParameter(f"-P expects name=value, got `{item}`")
        name, raw = item.split("=", 1)
        try:
            out[name] = json.loads(raw)
        except json.JSONDecodeError:
            out[name] = raw
    return out


def _echo_run(record, verbose: bool = False) -> None:
    status = record.status.value if hasattr(record.status, "value") else record.status
    click.echo(f"{record.uuid}  {status:12s}  {record.kind or '-':10s}  "
               f"{record.project}/{record.name or '-'}")
    if verbose and record.meta:
        click.echo(f"  meta: {json.dumps(record.meta)[:200]}")


@click.group()
def cli():
    """polyaxon_tpu: TPU-native ML orchestration."""
    from polyaxon_tpu.utils import apply_jax_platforms_override

    apply_jax_platforms_override()


# ------------------------------------------------------------------- config
@cli.group("config")
def config_group():
    """Client configuration (~/.polyaxon_tpu/config.json)."""


def _read_json_or_empty(path: str) -> dict:
    if os.path.exists(path):
        try:
            with open(path) as fh:
                return json.load(fh)
        except (OSError, json.JSONDecodeError):
            return {}
    return {}


@config_group.command("set")
@click.option("--host", default=None, help="API host, e.g. http://plx:8000")
@click.option("--token", default=None,
              help="bearer token for an auth-enabled server "
                   "(plx server --auth-token/--owner-token)")
@click.argument("pairs", nargs=-1)
def config_set(host, token, pairs):
    """Set client host/token and/or home config key=value PAIRS."""
    from polyaxon_tpu.client.client import CONFIG_DIR, CONFIG_FILE

    out = {}
    if host or token:
        os.makedirs(CONFIG_DIR, exist_ok=True)
        data = _read_json_or_empty(CONFIG_FILE)
        if host:
            data["host"] = host
        if token:
            data["token"] = token
        with open(CONFIG_FILE, "w") as fh:
            json.dump(data, fh, indent=2)
        out["client"] = data
    if pairs:
        path = os.path.join(get_home(), "config.json")
        cfg = _read_json_or_empty(path)
        for item in pairs:
            key, _, value = item.partition("=")
            cfg[key] = value
        os.makedirs(get_home(), exist_ok=True)
        with open(path, "w") as fh:
            json.dump(cfg, fh, indent=2)
        out["home"] = cfg
    click.echo(json.dumps(out, indent=2))


@config_group.command("show")
def config_show():
    from polyaxon_tpu.client.client import CONFIG_FILE, resolve_host

    click.echo(json.dumps({
        "client_file": CONFIG_FILE,
        "client": _read_json_or_empty(CONFIG_FILE),
        "home": _read_json_or_empty(os.path.join(get_home(), "config.json")),
        "resolved_host": resolve_host(),
    }, indent=2))


# ---------------------------------------------------------------------- run
@cli.command()
@click.option("-f", "--polyaxonfile", "files", multiple=True, type=click.Path(),
              help="Polyaxonfile path(s); later files patch earlier ones.")
@click.option("-P", "--param", "params", multiple=True, help="name=value override")
@click.option("--preset", "presets", multiple=True, help="preset file/name to apply")
@click.option("-p", "--project", default="default")
@click.option("--name", default=None)
@click.option("--hub", default=None, help="hub component ref")
@click.option("-w", "--watch", is_flag=True, help="execute locally and stream status")
@click.option("--eager", is_flag=True, help="alias for --watch")
@click.option("-u", "--upload", is_flag=True, hidden=True)
def run(files, params, presets, project, name, hub, watch, eager, upload):
    """Submit an operation (optionally executing it to completion)."""
    from polyaxon_tpu.polyaxonfile import PolyaxonfileError

    plane = get_plane()
    try:
        record = plane.submit(
            list(files) if files else None,
            project=project,
            params=_parse_params(params),
            presets=list(presets) or None,
            name=name,
        )
    except (PolyaxonfileError, ValueError) as exc:
        raise click.ClickException(str(exc)) from exc
    click.echo(f"Run created: {record.uuid} (project={project})")
    if watch or eager:
        from polyaxon_tpu.agent import Agent

        agent = Agent(plane, in_process=True)
        click.echo("Executing locally...")
        last = None
        deadline = time.monotonic() + 24 * 3600
        while time.monotonic() < deadline:
            agent.reconcile_once()
            current = plane.get_run(record.uuid)
            if current.status != last:
                click.echo(f"  status: {current.status.value}")
                last = current.status
            if current.is_done:
                children = plane.list_runs(pipeline_uuid=record.uuid)
                if all(c.is_done for c in children):
                    break
            time.sleep(0.3)
        outputs = plane.streams.get_outputs(record.uuid)
        if outputs:
            click.echo("outputs: " + json.dumps(outputs, indent=2, default=str))
        sys.exit(0 if plane.get_run(record.uuid).status.value == "succeeded" else 1)


# ---------------------------------------------------------------------- ops
@cli.group()
def ops():
    """Inspect and manage runs."""


@ops.command("ls")
@click.option("-p", "--project", default=None)
@click.option("--status", default=None)
@click.option("--limit", default=50)
@click.option("--pipeline", default=None,
              help="only children of this sweep/DAG uuid")
def ops_ls(project, status, limit, pipeline):
    from polyaxon_tpu.lifecycle import V1Statuses

    plane = get_plane()
    statuses = [V1Statuses(status)] if status else None
    for record in plane.list_runs(project=project, statuses=statuses,
                                  limit=limit, pipeline_uuid=pipeline):
        _echo_run(record)


@ops.command("trials")
@click.option("-uid", "--uid", required=True, help="sweep (matrix) run uuid")
def ops_trials(uid):
    """Sweep trials grouped by bracket/rung, best metric first — the
    CLI twin of the dashboard's bracket view."""
    plane = get_plane()
    record = get_run_or_fail(plane, uid)
    # Explicit limit: the store defaults to 1000 and a big sweep's table
    # must never silently drop (possibly the best) trials.
    children = plane.list_runs(pipeline_uuid=record.uuid, limit=1_000_000)
    if not children:
        click.echo("no trials yet")
        return
    matrix = (record.spec or {}).get("matrix") or {}
    metric = (matrix.get("metric") or {}).get("name")
    maximize = (matrix.get("metric") or {}).get("optimization") == "maximize"
    groups: dict[tuple, list] = {}
    for child in children:
        meta = child.meta or {}
        key = (meta.get("bracket"), meta.get("rung"))
        value = plane.get_metric(child.uuid, metric) if metric else None
        groups.setdefault(key, []).append((child, value))
    for key in sorted(groups, key=lambda k: (k[0] is None, k)):
        bracket, rung = key
        label = (f"bracket {bracket} rung {rung}"
                 if bracket is not None else "trials")
        click.echo(f"{label}:")
        trials = sorted(  # best first; metric-less rows last
            groups[key],
            key=lambda t: (t[1] is None,
                           0 if t[1] is None
                           else (-t[1] if maximize else t[1])))
        for child, value in trials:
            params = (child.meta or {}).get("trial_params") or {}
            pstr = " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in params.items())
            vstr = f"{value:.6g}" if value is not None else "-"
            click.echo(f"  {child.uuid[:12]}  {child.status.value:10s} "
                       f"{vstr:>12s}  {pstr}")


@ops.command("get")
@click.option("-uid", "--uid", required=True)
def ops_get(uid):
    plane = get_plane()
    record = get_run_or_fail(plane, uid)
    data = {
        "uuid": record.uuid, "project": record.project, "name": record.name,
        "kind": record.kind, "status": record.status.value,
        "created_at": record.created_at, "finished_at": record.finished_at,
        "meta": record.meta, "params": record.params,
    }
    click.echo(json.dumps(data, indent=2, default=str))


@ops.command("statuses")
@click.option("-uid", "--uid", required=True)
def ops_statuses(uid):
    plane = get_plane()
    for cond in plane.get_statuses(uid):
        click.echo(f"{cond['created_at']}  {cond['type']:16s} "
                   f"{cond.get('reason') or ''} {cond.get('message') or ''}")


def _render_timeline(timeline) -> None:
    """Span-tree waterfall shared by the run timeline and the serving
    request timeline (both are obs.trace.build_timeline output)."""
    t0 = timeline["t0"]
    click.echo(f"trace {timeline['trace_id']}  "
               f"spans={timeline['span_count']}  "
               f"wall={timeline['duration_ms']/1e3:.2f}s")

    def fmt_attrs(attrs):
        keep = {k: v for k, v in (attrs or {}).items() if v is not None}
        return (" " + " ".join(f"{k}={v}" for k, v in keep.items())
                if keep else "")

    def walk(node, depth):
        offset_ms = (node["start"] - t0) * 1e3
        marker = "!" if node.get("status") == "error" else " "
        click.echo(
            f"{marker} {'  ' * depth}{node['name']:<14} "
            f"+{offset_ms:9.1f}ms {node['duration_ms']:10.1f}ms"
            f"{fmt_attrs(node.get('attributes'))}"
            + (f"  [{node['error']}]" if node.get("error") else ""))
        for event in node.get("events") or []:
            ev_off = ((event.get("time") or node["start"]) - t0) * 1e3
            click.echo(f"  {'  ' * depth}* {event['name']} "
                       f"+{ev_off:.1f}ms{fmt_attrs(event.get('attributes'))}")
        for child in node.get("children") or []:
            walk(child, depth + 1)

    for root in timeline["spans"]:
        walk(root, 0)
    for event in timeline.get("events") or []:
        ev_off = ((event.get("time") or t0) - t0) * 1e3
        click.echo(f"* {event['name']} +{ev_off:.1f}ms"
                   f"{fmt_attrs(event.get('attributes'))}")


@ops.command("timeline")
@click.option("-uid", "--uid", required=True)
@click.option("--json", "as_json", is_flag=True,
              help="raw span tree instead of the waterfall rendering")
def ops_timeline(uid, as_json):
    """Run-lifecycle waterfall (ISSUE 5): the ordered span tree —
    compile → admission → placement → execute → runtime steps →
    checkpoint → sidecar sync — with chaos faults and retries as
    annotated events, so a slow or chaos-drilled run explains itself."""
    plane = get_plane()
    get_run_or_fail(plane, uid)
    timeline = plane.timeline(uid)
    if as_json:
        click.echo(json.dumps(timeline, indent=2, default=str))
        return
    if not timeline["spans"]:
        click.echo("(no lifecycle spans recorded for this run yet)")
        return
    _render_timeline(timeline)


@ops.command("request-timeline")
@click.option("--url", default="http://127.0.0.1:8080",
              help="serving server base URL")
@click.option("-id", "--id", "request_id", default=None,
              help="request id (a generate response's request_ids, or "
                   "pick one from the listing this prints when omitted)")
@click.option("--json", "as_json", is_flag=True,
              help="raw payload instead of the rendered waterfall")
def ops_request_timeline(url, request_id, as_json):
    """Per-request serving waterfall (ISSUE 10): one request's span
    tree — queue_wait → prefill (chunk events) → decode (first_token /
    spec_round / eviction events) — fetched from a live serving
    server's bounded trace ring, with the phase/TTFT summary on top.
    Without --id, lists the ring's recent requests instead."""
    import urllib.error
    import urllib.request

    base = url.rstrip("/")
    target = (f"{base}/requests/{request_id}/timeline"
              if request_id else f"{base}/requests")
    try:
        with urllib.request.urlopen(target, timeout=10) as resp:
            payload = json.loads(resp.read().decode())
    except urllib.error.HTTPError as exc:
        detail = exc.read().decode(errors="replace")
        try:
            detail = json.loads(detail).get("error", detail)
        except (json.JSONDecodeError, AttributeError):
            pass
        raise click.ClickException(f"HTTP {exc.code} from {target}: {detail}")
    except (urllib.error.URLError, OSError) as exc:
        raise click.ClickException(f"cannot reach {target}: {exc}")
    if as_json:
        click.echo(json.dumps(payload, indent=2, default=str))
        return
    if request_id is None:
        requests = payload.get("requests") or []
        if not requests:
            click.echo("(no traced requests in the ring yet)")
            return
        for row in requests:
            state = row.get("phase") or (
                "done" if row.get("done") else "pending")
            click.echo(f"{row['request_id']}  {row.get('class') or '-':<10} "
                       f"{state:<10} {row.get('status') or ''}"
                       + (f"  [{row['error']}]" if row.get("error") else ""))
        return
    summary = payload.get("summary") or {}
    if summary:
        phases = " ".join(f"{name}={ms}ms" for name, ms
                          in (summary.get("phases_ms") or {}).items())
        cached = summary.get("prefix_cached_tokens")
        click.echo(f"request {summary.get('request_id')}  "
                   f"class={summary.get('class')}  "
                   f"status={summary.get('status')}  "
                   f"ttft={summary.get('ttft_ms')}ms  "
                   f"tokens={summary.get('tokens_out')}"
                   + (f"  prefix_cached={cached}" if cached else "")
                   + f"  {phases}")
    _render_timeline(payload)


@ops.command("fleet")
@click.option("--url", default="http://127.0.0.1:8080",
              help="serving server base URL")
@click.option("--json", "as_json", is_flag=True,
              help="raw payload instead of the rendered breakdown")
def ops_fleet(url, as_json):
    """Fleet telemetry breakdown (ISSUE 20): per-replica TTFT
    p50/p99, preemption totals, and the cross-replica skew ratio read
    from the component-scoped metric series of a live fleet server's
    ``/v1/fleet``, plus replica states and routing decisions."""
    import urllib.error
    import urllib.request

    target = url.rstrip("/") + "/v1/fleet"
    try:
        with urllib.request.urlopen(target, timeout=10) as resp:
            payload = json.loads(resp.read().decode())
    except urllib.error.HTTPError as exc:
        detail = exc.read().decode(errors="replace")
        try:
            detail = json.loads(detail).get("error", detail)
        except (json.JSONDecodeError, AttributeError):
            pass
        raise click.ClickException(f"HTTP {exc.code} from {target}: {detail}")
    except (urllib.error.URLError, OSError) as exc:
        raise click.ClickException(f"cannot reach {target}: {exc}")
    if as_json:
        click.echo(json.dumps(payload, indent=2, default=str))
        return
    stats = payload.get("stats") or {}
    states = stats.get("states") or {}
    skew = payload.get("ttft_skew")
    click.echo("fleet: "
               + " ".join(f"{s}={n}" for s, n in states.items() if n)
               + (f"  ttft_skew={skew:.2f}" if skew is not None else "")
               + f"  hit_rate={stats.get('prefix_hit_rate')}")
    router = stats.get("router") or {}
    if router.get("routed"):
        click.echo("routed: " + " ".join(
            f"{k}={v}" for k, v in sorted(router["routed"].items())))
    per_replica = payload.get("per_replica") or {}
    replicas = stats.get("replicas") or {}
    for rid in sorted(set(per_replica) | set(replicas)):
        t = per_replica.get(rid) or {}
        r = replicas.get(rid) or {}
        click.echo(f"{rid:<6} {r.get('state') or '-':<9} "
                   f"served={r.get('served', 0):<5} "
                   f"ttft_p50={t.get('ttft_p50_ms')}ms "
                   f"p99={t.get('ttft_p99_ms')}ms "
                   f"preemptions={t.get('preemptions', 0)}")


@ops.command("report")
@click.option("-uid", "--uid", required=True)
@click.option("--json", "as_json", is_flag=True,
              help="raw report instead of the rendered tables")
def ops_report(uid, as_json):
    """Performance attribution report (ISSUE 6): where the run's wall
    clock went (compile / input-wait / step / checkpoint / restore /
    sync ...), whether step time drifted (rolling-median/MAD anomaly
    flags), and which phases absorbed retries, chaos faults, and
    requeues — a regression arrives pre-attributed."""
    plane = get_plane()
    get_run_or_fail(plane, uid)
    report = plane.report(uid)
    if as_json:
        click.echo(json.dumps(report, indent=2, default=str))
        return
    click.echo(f"run {report['run_uuid']}  status={report['status']}  "
               f"attempts={report['attempts']}  "
               f"wall={report['wall_clock_ms'] / 1e3:.2f}s  "
               f"(phases sum {report['phase_sum_ms'] / 1e3:.2f}s)")
    for name, entry in report["phases"].items():
        extra = ""
        if name == "restore":
            # Tier/culling audit (ISSUE 16): which tier answered each
            # restore and which corrupt steps the fallback skipped.
            if entry.get("tiers"):
                extra += "  tiers " + " ".join(
                    f"{t}:{n}" for t, n in entry["tiers"].items())
            if entry.get("skipped_steps"):
                extra += (f"  skipped={entry['skipped_steps']}")
        frac = (f"{entry['fraction'] * 100:5.1f}%"
                if entry["fraction"] is not None else "    -")
        click.echo(f"  {name:<13} {entry['ms']:>10.1f}ms  {frac}"
                   f"  x{entry['count']}{extra}")
    steps = report["steps"]
    if steps["windows"]:
        click.echo(f"step windows: {len(steps['windows'])}  "
                   f"rolling median {steps['rolling_median_ms']}ms  "
                   f"anomalies {len(steps['anomalies'])}")
        for anom in steps["anomalies"]:
            click.echo(f"  ! step<={anom['to_step']} "
                       f"{anom['step_time_ms']}ms vs median "
                       f"{anom['median_ms']}ms "
                       f"({anom['deviation_sigmas']:+.1f} sigma)")
    notes = report["annotations"]
    for kind in ("retries", "chaos", "requeues"):
        if notes.get(kind):
            pairs = " ".join(f"{k}={v}" for k, v in notes[kind].items())
            click.echo(f"{kind}: {pairs}")
    for alert in report.get("alerts") or []:
        click.echo(f"alert: {alert['rule']} ({alert['severity']}) "
                   f"fired on this run")


@ops.command("verify")
@click.option("-uid", "--uid", default=None,
              help="scope the run-surface invariants to one run "
                   "(fleet-wide when omitted)")
@click.option("--json", "as_json", is_flag=True)
def ops_verify(uid, as_json):
    """Telemetry-oracle verdicts (ISSUE 13): the committed invariant
    set (obs/oracle.json) judged against the plane's end state — run
    terminal statuses, phase accounting, metric/SLO predicates, loss
    continuity, and unresolved alerts — with the offending
    run/series/alert attached as evidence. Exits nonzero on any
    failed invariant."""
    plane = get_plane()
    if uid is not None:
        get_run_or_fail(plane, uid)
    result = plane.verify(uid)
    if as_json:
        click.echo(json.dumps(result, indent=2, default=str))
    else:
        for verdict in result["verdicts"]:
            marker = {"pass": "ok  ", "skip": "skip",
                      "fail": "FAIL"}[verdict["verdict"]]
            line = f"  [{marker}] {verdict['invariant']}"
            if verdict["verdict"] != "pass":
                line += ("  "
                         + json.dumps(verdict["evidence"],
                                      default=str)[:160])
            click.echo(line)
        counts = result["counts"]
        click.echo(f"verdicts: {counts['pass']} pass / "
                   f"{counts['fail']} fail / {counts['skip']} skip")
    if not result["passed"]:
        raise SystemExit(1)


@ops.command("alerts")
@click.option("--json", "as_json", is_flag=True)
@click.option("--all", "show_all", is_flag=True,
              help="every rule's state, not just firing alerts")
@click.option("--since", default=None, metavar="WINDOW",
              help="bound history to the last WINDOW (e.g. 15m, 2h)")
@click.option("--limit", default=None, type=int, metavar="N",
              help="at most N most-recent history events")
def ops_alerts(as_json, show_all, since, limit):
    """Alert-rule state over the live registry (ISSUE 6): the committed
    ruleset (obs/rules.json) evaluated now — firing alerts first, then
    (with --all) every rule's current value vs its threshold. History
    (fired/resolved transitions) is bounded by --since/--limit."""
    import time as _time

    from polyaxon_tpu.obs import rules as obs_rules

    plane = get_plane()
    engine = obs_rules.default_engine()
    engine.evaluate(plane=plane)
    payload = engine.to_json()
    if since is not None:
        try:
            horizon = _time.time() - obs_rules.parse_window(
                since, field_name="--since")
        except obs_rules.RuleError as exc:
            raise click.UsageError(str(exc))
        payload["history"] = [e for e in payload["history"]
                              if float(e.get("at") or 0) >= horizon]
    if limit is not None:
        if limit < 0:
            raise click.UsageError("--limit must be >= 0")
        payload["history"] = payload["history"][-limit:] if limit else []
    if as_json:
        click.echo(json.dumps(payload, indent=2, default=str))
        return
    if not payload["alerts"]:
        click.echo("no firing alerts")
    for alert in payload["alerts"]:
        click.echo(f"FIRING [{alert['severity']}] {alert['rule']}: "
                   f"value={alert['value']} threshold={alert['threshold']}"
                   f"  {alert['description']}")
    if show_all:
        for rule in payload["rules"]:
            click.echo(f"  {rule['state']:<9} {rule['rule']:<24} "
                       f"{rule['metric']} value={rule['value']} "
                       f"threshold={rule['threshold']}")
    if since is not None or limit is not None:
        click.echo(f"history ({len(payload['history'])} event(s)):")
        for event in payload["history"]:
            click.echo(f"  {event.get('event'):<9} {event.get('rule')}"
                       f"  at={event.get('at')}")


_SPARK_GLYPHS = "▁▂▃▄▅▆▇█"


def _sparkline(values):
    lo, hi = min(values), max(values)
    if hi - lo < 1e-12:
        return _SPARK_GLYPHS[0] * len(values)
    scale = (len(_SPARK_GLYPHS) - 1) / (hi - lo)
    return "".join(_SPARK_GLYPHS[int((v - lo) * scale)] for v in values)


def _point_scalar(sample):
    # Histogram points carry the cumulative sample dict; plot the count.
    if isinstance(sample, dict):
        return float(sample.get("count") or 0.0)
    return float(sample)


@ops.command("history")
@click.argument("metric", required=False)
@click.option("--window", default=None, metavar="WINDOW",
              help="scope to a marked window name (e.g. storm) or a "
                   "trailing span (e.g. 15m)")
@click.option("--labels", "labels_raw", default=None, metavar="K=V[,K=V]",
              help="pick one labeled series of the family")
@click.option("--json", "as_json", is_flag=True)
def ops_history(metric, window, labels_raw, as_json):
    """Sampled metrics history (obs.history): the bounded ring the
    alert engine and the telemetry oracle share. Without METRIC, lists
    the sampled families; with one, renders each series as a sparkline
    over the selected scope (a marked window or a trailing span)."""
    from polyaxon_tpu.obs import history as obs_history
    from polyaxon_tpu.obs import rules as obs_rules

    plane = get_plane()
    # Evaluating the default engine force-samples the shared ring, so a
    # fresh process still answers with at least the current instant.
    obs_rules.default_engine().evaluate(plane=plane)
    labels = None
    if labels_raw:
        labels = {}
        for part in labels_raw.split(","):
            key, sep, value = part.partition("=")
            if not sep or not key.strip():
                raise click.UsageError(
                    f"bad --labels selector {labels_raw!r} "
                    "(want k=v[,k2=v2])")
            labels[key.strip()] = value.strip()
    try:
        payload = obs_history.query_history(
            obs_history.default_history().to_json(),
            name=metric, window=window, labels=labels)
    except ValueError as exc:
        raise click.UsageError(str(exc))
    if as_json:
        click.echo(json.dumps(payload, indent=2, default=str))
        return
    cov = payload.get("coverage") or {}
    span = ((float(cov["end"]) - float(cov["start"]))
            if cov.get("start") is not None else 0.0)
    click.echo(f"coverage: {cov.get('samples', 0)} sample(s) over "
               f"{span:.1f}s; cadence {payload.get('cadence')}s")
    scope = payload.get("scope")
    if scope:
        click.echo(f"scope: {scope['window']} "
                   f"[{scope['start']:.3f} .. {scope['end']:.3f}]")
    if metric is None:
        for name in payload.get("metrics") or []:
            click.echo(f"  {name}")
        return
    family = payload["metric"]
    for key, points in sorted(family["series"].items()):
        values = [_point_scalar(p[1]) for p in points]
        label = key if key else "(no labels)"
        if not values:
            click.echo(f"  {label}: no points in scope")
            continue
        click.echo(f"  {label}: {_sparkline(values)}  "
                   f"last={values[-1]:g} n={len(values)}")


@ops.command("logs")
@click.option("-uid", "--uid", required=True)
@click.option("--follow", is_flag=True)
def ops_logs(uid, follow):
    plane = get_plane()
    names = plane.streams.log_files(uid)
    if not names:
        click.echo("(no logs)")
        return
    offsets = {}
    for name in names:
        chunk, offsets[name] = plane.streams.read_logs(uid, name)
        if chunk:
            click.echo(chunk, nl=False)
    if follow:
        record = get_run_or_fail(plane, uid)

        def done():
            return plane.get_run(uid).is_done

        if not record.is_done:
            for chunk in plane.streams.follow_logs(
                uid, names[0], should_stop=done, offset=offsets[names[0]]
            ):
                click.echo(chunk, nl=False)


@ops.command("outputs")
@click.option("-uid", "--uid", required=True)
def ops_outputs(uid):
    plane = get_plane()
    click.echo(json.dumps(plane.streams.get_outputs(uid), indent=2, default=str))


@ops.command("artifacts")
@click.option("-uid", "--uid", required=True)
@click.option("--download", "download_rel", default=None,
              help="run-relative artifact path to copy out")
@click.option("-o", "--output", default=".",
              help="(with --download) destination file or directory")
def ops_artifacts(uid, download_rel, output):
    import shutil

    plane = get_plane()
    if download_rel:
        try:
            src = plane.streams.artifact_path(uid, download_rel)
        except ValueError as exc:  # traversal guard → clean CLI error
            raise click.ClickException(str(exc)) from exc
        if not os.path.isfile(src):
            raise click.ClickException(f"artifact not found: {download_rel}")
        dest = output
        # A trailing slash or an existing dir both mean "into this dir".
        if os.path.isdir(dest) or dest.endswith(os.sep):
            dest = os.path.join(dest, os.path.basename(download_rel))
        os.makedirs(os.path.dirname(os.path.abspath(dest)), exist_ok=True)
        shutil.copy2(src, dest)
        click.echo(dest)
        return
    for rel in plane.streams.list_artifacts(uid):
        click.echo(rel)


@ops.command("metrics")
@click.option("-uid", "--uid", required=True)
@click.option("--name", "names", multiple=True)
def ops_metrics(uid, names):
    plane = get_plane()
    metrics = plane.streams.get_metrics(uid, list(names) or None)
    click.echo(json.dumps(metrics, indent=2, default=str))


@ops.command("compare")
@click.argument("uids", nargs=-1, required=True)
@click.option("--metric", "metric_names", multiple=True,
              help="metric(s) to tabulate (default: the union across "
                   "the runs; absent values print '-')")
def ops_compare(uids, metric_names):
    """Side-by-side comparison of N runs — the CLI twin of the
    dashboard's compare view: final value of each metric per run, plus
    the params whose values DIFFER across the selection."""
    if len(uids) < 2:
        raise click.BadParameter("give at least two run uuids")
    plane = get_plane()
    records = [get_run_or_fail(plane, uid) for uid in uids]
    labels = [r.name or r.uuid[:12] for r in records]

    def vals_of(record):
        out = {}
        for key, value in (record.params or {}).items():
            if isinstance(value, dict) and "value" in value:
                value = value["value"]
            out[key] = value
        out.update((record.meta or {}).get("trial_params") or {})
        return out

    per_run = [vals_of(r) for r in records]
    keys = sorted({k for vals in per_run for k in vals})
    differing = [k for k in keys
                 if len({json.dumps(v.get(k), sort_keys=True, default=str)
                         for v in per_run}) > 1]

    def fmt(v):
        if v is None:
            return "-"
        return f"{v:.6g}" if isinstance(v, float) else str(v)

    width = max([len(x) for x in labels] + [12])
    header = "  ".join(f"{name:>{width}}" for name in labels)
    click.echo(f"  {'':>20s}  {header}")
    if differing:
        click.echo("differing params:")
        for k in differing:
            cells = "  ".join(f"{fmt(v.get(k)):>{width}}" for v in per_run)
            click.echo(f"  {k:>20s}  {cells}")
    all_metrics = metric_names or sorted(
        set().union(*[plane.streams.metric_names(r.uuid) for r in records]))
    if all_metrics:
        click.echo("final metrics:")
        for name in all_metrics:
            row = [fmt(plane.streams.last_metric(r.uuid, name))
                   for r in records]
            cells = "  ".join(f"{v:>{width}}" for v in row)
            click.echo(f"  {name:>20s}  {cells}")


@ops.command("events")
@click.option("-uid", "--uid", required=True)
@click.option("--kind", default="metric",
              type=click.Choice(sorted(_V1EventKind.VALUES)))
@click.option("--name", "names", multiple=True)
def ops_events(uid, kind, names):
    plane = get_plane()
    events = plane.streams.get_events(uid, kind, list(names) or None)
    click.echo(json.dumps(events, indent=2, default=str))


@ops.command("lineage")
@click.option("-uid", "--uid", required=True)
@click.option("--graph", is_flag=True,
              help="cross-run inputs → run → outputs graph (param "
                   "refs, DAG deps, joins, cache adoption) instead of "
                   "this run's artifact records")
def ops_lineage(uid, graph):
    plane = get_plane()
    if graph:
        get_run_or_fail(plane, uid)  # clean CLI error on unknown uid
        data = plane.lineage_graph(uid)
        by_uuid = {n["uuid"]: n for n in data["nodes"]}

        def label(u):
            n = by_uuid.get(u) or {}
            return f"{n.get('name') or u[:8]} [{n.get('status', '?')}]"

        for e in data["edges"]:
            tag = e["kind"] + (f":{e['label']}" if e.get("label") else "")
            click.echo(f"{label(e['from'])} --{tag}--> {label(e['to'])}")
        for a in data["artifacts"]:
            click.echo(f"{label(uid)} --artifact--> "
                       f"{a.get('kind', 'artifact')}:{a.get('name')}")
        for k in data["outputs"]:
            click.echo(f"{label(uid)} --output--> {k}")
        if not (data["edges"] or data["artifacts"] or data["outputs"]):
            click.echo("(no lineage edges recorded)")
        return
    click.echo(json.dumps(plane.streams.get_lineage(uid), indent=2,
                          default=str))


@ops.command("stop")
@click.option("-uid", "--uid", required=True)
def ops_stop(uid):
    plane = get_plane()
    plane.stop(uid)
    click.echo(f"Stop requested for {uid}")


@ops.command("restart")
@click.option("-uid", "--uid", required=True)
@click.option("--copy", is_flag=True)
def ops_restart(uid, copy):
    plane = get_plane()
    record = plane.restart(uid, copy=copy)
    click.echo(f"Restarted as {record.uuid}")


@ops.command("resume")
@click.option("-uid", "--uid", required=True)
def ops_resume(uid):
    plane = get_plane()
    try:
        record = plane.resume(uid)
    except ValueError as exc:
        raise click.ClickException(str(exc)) from exc
    click.echo(f"Resumed {record.uuid}")


# ------------------------------------------------------------------ project
@cli.group()
def projects():
    """Manage projects."""


@projects.command("create")
@click.option("--name", required=True)
@click.option("--description", default="")
def projects_create(name, description):
    plane = get_plane()
    plane.store.create_project(name, description)
    click.echo(f"Project `{name}` created")


@projects.command("ls")
def projects_ls():
    plane = get_plane()
    for proj in plane.store.list_projects():
        click.echo(f"{proj['name']}  {proj.get('description') or ''}")


# -------------------------------------------------------------- scheduling
@cli.group("queue")
def queue_group():
    """Manage scheduling queues (docs/scheduling.md)."""


@queue_group.command("ls")
def queue_ls():
    """List queues with priority, caps, and live depth/usage."""
    plane = get_plane()
    stats = plane.scheduling_stats()
    click.echo(f"{'NAME':16s} {'PRIO':>4s} {'CAP':>4s} {'SPOT':>4s} "
               f"{'DEPTH':>5s} {'RUNNING':>7s}")
    for queue in stats["queues"]:
        cap = queue["concurrency"]
        click.echo(f"{queue['name']:16s} {queue['priority']:>4d} "
                   f"{('-' if cap is None else str(cap)):>4s} "
                   f"{('yes' if queue['preemptible'] else 'no'):>4s} "
                   f"{queue['depth']:>5d} {queue['running']:>7d}")


@queue_group.command("add")
@click.argument("name")
@click.option("--priority", default=0, help="higher admits (and evicts) first")
@click.option("--concurrency", default=None, type=int,
              help="max concurrent runs admitted from this queue")
@click.option("--preemptible", is_flag=True,
              help="runs admitted here may be evicted for higher-priority work")
@click.option("--description", default="")
def queue_add(name, priority, concurrency, preemptible, description):
    """Create or update a queue."""
    plane = get_plane()
    queue = plane.upsert_queue(name, priority=priority,
                               concurrency=concurrency,
                               preemptible=preemptible,
                               description=description)
    click.echo(json.dumps(queue, indent=2, default=str))


@queue_group.command("rm")
@click.argument("name")
def queue_rm(name):
    plane = get_plane()
    try:
        removed = plane.delete_queue(name)
    except ValueError as exc:
        raise click.ClickException(str(exc)) from exc
    if not removed:
        raise click.ClickException(f"queue `{name}` not found")
    click.echo(f"Queue `{name}` removed")


@queue_group.command("inspect")
@click.argument("name")
def queue_inspect(name):
    """One queue's config + depth + the runs currently queued/live on it."""
    from polyaxon_tpu.lifecycle import V1Statuses
    from polyaxon_tpu.scheduling import LIVE_STATUSES, sched_info

    plane = get_plane()
    stats = plane.scheduling_stats()
    queue = next((q for q in stats["queues"] if q["name"] == name), None)
    if queue is None:
        raise click.ClickException(f"queue `{name}` not found")
    click.echo(json.dumps(queue, indent=2, default=str))
    rows = plane.list_runs(statuses=[V1Statuses.QUEUED] + LIVE_STATUSES)
    members = [r for r in rows if sched_info(r).queue == name]
    if members:
        click.echo("runs:")
        for record in members:
            _echo_run(record)


@cli.group("quota")
def quota_group():
    """Manage per-project quotas (docs/scheduling.md)."""


@quota_group.command("ls")
def quota_ls():
    """List project quotas with live usage."""
    plane = get_plane()
    stats = plane.scheduling_stats()
    click.echo(f"{'PROJECT':16s} {'MAXRUNS':>7s} {'MAXCHIPS':>8s} "
               f"{'WEIGHT':>6s} {'RUNS':>4s} {'CHIPS':>5s} {'QUEUED':>6s}")
    for quota in stats["quotas"]:
        click.echo(
            f"{quota['project']:16s} "
            f"{('-' if quota['max_runs'] is None else str(quota['max_runs'])):>7s} "
            f"{('-' if quota['max_chips'] is None else str(quota['max_chips'])):>8s} "
            f"{quota['weight']:>6.2f} {quota['used_runs']:>4d} "
            f"{quota['used_chips']:>5d} {quota['queued']:>6d}")


@quota_group.command("set")
@click.argument("project")
@click.option("--max-runs", default=None, type=int,
              help="max concurrent runs for the project")
@click.option("--max-chips", default=None, type=int,
              help="max concurrent TPU chips for the project")
@click.option("--weight", default=1.0, help="fair-share weight")
def quota_set(project, max_runs, max_chips, weight):
    plane = get_plane()
    quota = plane.set_quota(project, max_runs=max_runs, max_chips=max_chips,
                            weight=weight)
    click.echo(json.dumps(quota, indent=2, default=str))


@quota_group.command("rm")
@click.argument("project")
def quota_rm(project):
    plane = get_plane()
    if not plane.delete_quota(project):
        raise click.ClickException(f"no quota for project `{project}`")
    click.echo(f"Quota for `{project}` removed")


# -------------------------------------------------------------------- check
@cli.command()
@click.option("-f", "--polyaxonfile", "files", multiple=True, required=True,
              type=click.Path())
@click.option("-P", "--param", "params", multiple=True)
def check(files, params):
    """Validate a Polyaxonfile and print the resolved operation."""
    from polyaxon_tpu.polyaxonfile import PolyaxonfileError, check_polyaxonfile

    try:
        op = check_polyaxonfile(list(files), params=_parse_params(params))
    except (PolyaxonfileError, ValueError) as exc:
        raise click.ClickException(str(exc)) from exc
    click.echo(json.dumps(op.to_dict(), indent=2, default=str))


def _parse_slices(entries) -> list[tuple[str, str, bool]]:
    """NAME:TOPOLOGY[:spot] → (name, topology, preemptible) triples."""
    parsed = []
    for entry in entries:
        parts = entry.split(":")
        if len(parts) not in (2, 3):
            raise click.ClickException(
                f"--slice must be NAME:TOPOLOGY[:spot], got {entry!r}")
        if len(parts) == 3 and parts[2] != "spot":
            raise click.ClickException(
                f"--slice third token must be `spot`, got {parts[2]!r}")
        parsed.append((parts[0], parts[1], len(parts) == 3))
    return parsed


# -------------------------------------------------------------------- admin
@cli.group("admin")
def admin_group():
    """Deploy/manage the control-plane stack (upstream `admin deploy`)."""


@admin_group.command("deploy")
@click.option("-f", "--file", "config_file", required=True, type=click.Path(exists=True))
@click.option("--dry-run", is_flag=True, help="validate and show the plan only")
def admin_deploy(config_file, dry_run):
    import yaml

    from polyaxon_tpu.deploy import check_deployment, render_deployment

    with open(config_file) as fh:
        data = yaml.safe_load(fh)
    try:
        config = check_deployment(data or {})
    except ValueError as exc:
        raise click.ClickException(str(exc)) from exc
    home = config.home or get_home()
    if dry_run:
        click.echo(json.dumps({"valid": True,
                               "deploymentType": config.deployment_type,
                               "home": home}, indent=2))
        return
    written = render_deployment(config, home)
    click.echo(json.dumps(written, indent=2))


@admin_group.command("teardown")
@click.option("-f", "--file", "config_file", default=None,
              type=click.Path(exists=True),
              help="deploy values file (to locate a custom home:)")
def admin_teardown(config_file):
    import shutil

    home = get_home()
    if config_file:
        import yaml

        with open(config_file) as fh:
            data = yaml.safe_load(fh) or {}
        home = data.get("home") or home
    deploy_dir = os.path.join(home, "deploy")
    if not os.path.isdir(deploy_dir):
        click.echo("nothing deployed")
        return
    # Remove every artifact deploy recorded — including ones rendered
    # outside deploy/ (connections.yaml feeds the live catalog).
    summary_path = os.path.join(deploy_dir, "deploy.json")
    removed = []
    if os.path.exists(summary_path):
        try:
            with open(summary_path) as fh:
                artifacts = json.load(fh).get("artifacts") or {}
            for path in artifacts.values():
                if os.path.isfile(path) and not path.startswith(deploy_dir):
                    os.remove(path)
                    removed.append(path)
        except (OSError, json.JSONDecodeError):
            pass
    shutil.rmtree(deploy_dir)
    removed.append(deploy_dir)
    click.echo(json.dumps({"removed": removed}))


# ------------------------------------------------------------------- server
@cli.command("server")
@click.option("--host", default="127.0.0.1")
@click.option("--port", default=8000)
@click.option("--with-agent", is_flag=True,
              help="also run the agent reconcile loop in this process")
@click.option("--max-concurrent", default=4,
              help="(with --with-agent) max concurrent gangs")
@click.option("--heartbeat-timeout", default=60.0,
              help="(with --with-agent) slice-pool heartbeat timeout seconds")
@click.option("--slice", "slices", multiple=True,
              help="(with --with-agent) register a TPU slice NAME:TOPOLOGY[:spot]")
@click.option("--auth-token", default=None, envvar="POLYAXON_TPU_AUTH_TOKEN",
              help="admin bearer token; enables auth (default: open server)")
@click.option("--owner-token", "owner_tokens", multiple=True,
              help="OWNER=TOKEN per-owner scoped credential (repeatable); "
                   "implies auth")
@click.option("--chaos-plan", default=None,
              help="(with --with-agent) JSON fault plan injected at the "
                   "store/gang/checkpoint/tick seams (docs/robustness.md)")
def server_cmd(host, port, with_agent, max_concurrent, heartbeat_timeout,
               slices, auth_token, owner_tokens, chaos_plan):
    """Serve the REST API (control plane + streams) in the foreground."""
    import threading

    from polyaxon_tpu.api import ApiServer

    if chaos_plan:
        from polyaxon_tpu import chaos

        chaos.install(chaos.ChaosPlan.load(chaos_plan))
        click.echo(f"chaos plan armed from {chaos_plan}")
    scoped = {}
    for item in owner_tokens:
        owner, sep, token = item.partition("=")
        if not sep or not owner or not token:
            raise click.BadParameter(
                f"--owner-token needs OWNER=TOKEN, got {item!r}")
        scoped[owner] = token
    plane = get_plane()
    manager = None
    if with_agent and slices:
        from polyaxon_tpu.agent import SliceManager

        manager = SliceManager(_parse_slices(slices),
                               heartbeat_timeout=heartbeat_timeout)
    server = ApiServer(plane, host, port, slice_manager=manager,
                       auth_token=auth_token, owner_tokens=scoped)
    if with_agent:
        from polyaxon_tpu.agent import Agent

        agent = Agent(plane, slice_manager=manager,
                      max_concurrent=max_concurrent)
        # polycheck: ignore[invariant-daemon-drain] -- foreground CLI: the agent lives exactly as long as the blocking serve_forever below; process exit is the teardown
        threading.Thread(target=agent.serve_forever, daemon=True).start()
    click.echo(f"API serving on {server.url} (home={get_home()})"
               + (" with agent" if with_agent else ""))
    try:
        server.httpd.serve_forever()
    finally:
        server.stop()


# -------------------------------------------------------------------- serve
@cli.command("serve")
@click.option("--model", required=True, help="model zoo name, e.g. llama3_8b")
@click.option("--checkpoint", default=None,
              help="orbax checkpoint dir (a saved JAXJob train state)")
@click.option("--host", default="127.0.0.1")
@click.option("--port", default=8080)
@click.option("--seed", default=0)
@click.option("--batching", default="static",
              type=click.Choice(["static", "continuous"]),
              help="continuous = slot-pool batcher: concurrent requests "
                   "interleave token-by-token (decoder models)")
@click.option("--slots", default=4,
              help="KV-cache slots for --batching continuous")
@click.option("--mesh", "mesh_str", default=None,
              help="shard weights over a device mesh, e.g. 'tp=4' or "
                   "'fsdp=-1' (-1 = all devices); decode collectives are "
                   "GSPMD-inserted")
@click.option("--quantize", default=None, type=click.Choice(["int8"]),
              help="weight-only quantization at load: int8 + per-channel "
                   "scales (halves HBM-resident weight bytes; decode is "
                   "bandwidth-bound)")
@click.option("--kv", default="dense", type=click.Choice(["dense", "paged"]),
              help="KV-cache layout for --batching continuous: paged = "
                   "vLLM-style shared page pool with per-slot block "
                   "tables (memory scales with held tokens, not "
                   "slots x max_len)")
@click.option("--kv-page-size", default=16,
              help="tokens per KV page (--kv paged)")
@click.option("--kv-pages", default=None, type=int,
              help="usable KV pages in the pool (--kv paged; matches "
                   "kv_pages_total in /v1/stats); default = the dense-"
                   "equivalent reservation, lower = deliberate "
                   "oversubscription with admission backpressure")
@click.option("--draft-model", default=None,
              help="speculative decoding draft (static engine, greedy "
                   "requests): lossless — output is the target's own "
                   "greedy sequence, the draft buys back decode steps")
@click.option("--draft-checkpoint", default=None,
              help="orbax checkpoint for the draft model")
@click.option("--spec-k", default=4,
              help="draft tokens proposed per verify round")
@click.option("--lora-alpha", default=16.0,
              help="alpha used when --checkpoint is a LoRA fine-tune "
                   "(adapters fold into dense weights at load; must "
                   "match training)")
@click.option("--max-pending", default=None, type=int,
              help="(--batching continuous) cap on queued requests; a "
                   "saturated POST /v1/generate answers 503 with "
                   "Retry-After instead of queueing unbounded work")
def serve_cmd(model, checkpoint, host, port, seed, batching, slots, mesh_str,
              quantize, kv, kv_page_size, kv_pages, draft_model,
              draft_checkpoint, spec_k, lora_alpha, max_pending):
    """Serve a model for generation (KV-cache decode over HTTP)."""
    from polyaxon_tpu.serving import ServingServer

    mesh_axes = None
    if mesh_str:
        from polyaxon_tpu.parallel import parse_mesh_axes

        try:
            mesh_axes = parse_mesh_axes(mesh_str)
        except ValueError as exc:
            raise click.BadParameter(str(exc)) from None
    server = ServingServer(model, checkpoint, host=host, port=port, seed=seed,
                           batching=batching, slots=slots,
                           mesh_axes=mesh_axes, quantize=quantize,
                           kv=kv, page_size=kv_page_size, kv_pages=kv_pages,
                           draft_model=draft_model,
                           draft_checkpoint=draft_checkpoint, spec_k=spec_k,
                           lora_alpha=lora_alpha, max_pending=max_pending)
    click.echo(f"serving {model} at {server.url}")
    try:
        server.httpd.serve_forever()  # foreground; no background thread
    except KeyboardInterrupt:
        pass
    finally:
        # One teardown path: ServingServer.stop() owns the shutdown
        # sequence (httpd + engine); shutdown() returns immediately
        # since serve_forever has already exited.
        server.stop()


# ------------------------------------------------------------------ convert
@cli.command("convert")
@click.option("--model", required=True,
              help="target model zoo name, e.g. llama3_8b")
@click.option("--from-hf", "hf_path", default=None,
              help="import: HF checkpoint (.safetensors/.bin file or a "
                   "model dir) → Orbax at --out")
@click.option("--from-orbax", "orbax_path", default=None,
              help="export: Orbax checkpoint dir (a train state, incl. "
                   "LoRA fine-tunes — adapters merge) → HF safetensors "
                   "+ config.json at --out")
@click.option("--out", "out_dir", required=True,
              help="output dir (Orbax when importing, HF when exporting)")
def convert_cmd(model, hf_path, orbax_path, out_dir):
    """Convert between HuggingFace and Orbax llama checkpoints, either
    direction (models/convert.py::from_hf_llama / to_hf_llama)."""
    from polyaxon_tpu.models import llama
    from polyaxon_tpu.models.convert import from_hf_llama
    from polyaxon_tpu.polyflow.runs import V1JaxCheckpointing
    from polyaxon_tpu.runtime.checkpoint import CheckpointManager

    if (hf_path is None) == (orbax_path is None):
        raise click.UsageError(
            "pass exactly one of --from-hf (import) or --from-orbax "
            "(export)")
    if model not in llama.CONFIGS:
        raise click.BadParameter(
            f"`{model}` is not a llama-family model "
            f"(choices: {sorted(llama.CONFIGS)})")
    cfg = llama.CONFIGS[model]

    if orbax_path is not None:
        return _export_to_hf(model, cfg, orbax_path, out_dir)

    def load_state_dict(path):
        if os.path.isdir(path):
            names = sorted(os.listdir(path))
            # Prefer safetensors; otherwise HF weight shards only —
            # Trainer dirs also hold non-weight pickles like
            # training_args.bin that torch.load(weights_only) rejects.
            files = [os.path.join(path, f) for f in names
                     if f.endswith(".safetensors")]
            if not files:
                files = [os.path.join(path, f) for f in names
                         if f.startswith("pytorch_model")
                         and f.endswith(".bin")]
            if not files:
                raise click.ClickException(
                    f"no *.safetensors or pytorch_model*.bin under {path}")
        else:
            files = [path]
        state = {}
        for f in files:
            if f.endswith(".safetensors"):
                from safetensors.numpy import load_file

                state.update(load_file(f))
            else:
                import torch

                state.update(torch.load(f, map_location="cpu",
                                        weights_only=True))
        return state

    ckpt = CheckpointManager(
        out_dir, V1JaxCheckpointing(enabled=True, interval_steps=1,
                                    async_save=False))
    try:
        if ckpt.latest_step() is not None:
            raise click.ClickException(
                f"{out_dir} already contains a checkpoint "
                f"(step {ckpt.latest_step()}); choose a new --out or "
                "delete it first")
        state_dict = load_state_dict(hf_path)
        try:
            variables = from_hf_llama(state_dict, cfg)
        except (KeyError, ValueError) as exc:
            raise click.ClickException(
                f"checkpoint does not match model `{model}`: {exc}"
            ) from exc
        ckpt.save(0, {"params": variables["params"]}, force=True)
    finally:
        ckpt.close()
    import jax

    n_params = sum(int(p.size) for p in jax.tree.leaves(variables["params"]))
    click.echo(f"converted {model}: {n_params:,} params → {out_dir}")


def _export_to_hf(model: str, cfg, orbax_path: str, out_dir: str) -> None:
    """Orbax train state (plain or LoRA) → HF-loadable dir:
    model.safetensors + config.json."""
    import json as _json

    import orbax.checkpoint as ocp
    from safetensors.numpy import save_file

    from polyaxon_tpu.models.convert import to_hf_llama

    if cfg.sliding_window is not None:
        raise click.ClickException(
            f"`{model}` uses sliding-window attention, which HF's llama "
            "architecture does not express — an export would silently "
            "attend past the window; not supported")
    if os.path.exists(os.path.join(out_dir, "model.safetensors")):
        raise click.ClickException(
            f"{out_dir} already contains model.safetensors; choose a new "
            "--out or delete it first")
    with ocp.CheckpointManager(orbax_path) as mgr:
        step = mgr.latest_step()
        if step is None:
            raise click.ClickException(f"no checkpoint under {orbax_path}")
        restored = mgr.restore(step, args=ocp.args.StandardRestore())
    params = restored.get("params", restored)
    if isinstance(params, dict) and set(params) == {"base", "lora"}:
        from polyaxon_tpu.models.lora import merge_saved

        params = merge_saved(params["base"], params["lora"], host=True)
        click.echo("merged LoRA adapters into dense weights")
    state_dict = to_hf_llama(params, cfg)
    os.makedirs(out_dir, exist_ok=True)
    # metadata format=pt: transformers' loader checks it before
    # trusting the file.
    save_file(state_dict, os.path.join(out_dir, "model.safetensors"),
              metadata={"format": "pt"})
    config = {
        "architectures": ["LlamaForCausalLM"],
        "model_type": "llama",
        "hidden_size": cfg.dim,
        "intermediate_size": cfg.ffn_dim,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.max_seq_len,
        "rms_norm_eps": cfg.norm_eps,
        "rope_theta": cfg.rope_theta,
        "tie_word_embeddings": cfg.tie_embeddings,
        "torch_dtype": "float32",
    }
    if cfg.rope_scaling is not None:
        # Ours carries the public llama3 rule's fields; HF wants the
        # same dict plus its rope_type tag. Dropping this would export
        # llama31_* with silently unscaled RoPE.
        config["rope_scaling"] = {"rope_type": "llama3",
                                  **dict(cfg.rope_scaling)}
    with open(os.path.join(out_dir, "config.json"), "w") as fh:
        _json.dump(config, fh, indent=2)
    n_params = sum(int(v.size) for v in state_dict.values())
    click.echo(f"exported {model} step {step}: {n_params:,} params → "
               f"{out_dir} (model.safetensors + config.json)")


# -------------------------------------------------------------------- agent
@cli.command("agent")
@click.option("--poll", default=1.0)
@click.option("--max-concurrent", default=4)
@click.option("--slice", "slices", multiple=True,
              help="Register a TPU slice: NAME:TOPOLOGY[:spot], e.g. "
                   "pool0:8x8 or spot0:4x4:spot. Enables the native "
                   "topology-aware gang scheduler.")
@click.option("--chaos-plan", default=None,
              help="JSON fault plan (file or inline) injected at the "
                   "store/gang/checkpoint/tick seams — resilience "
                   "drills against a live agent (docs/robustness.md)")
def agent_cmd(poll, max_concurrent, slices, chaos_plan):
    """Run the agent reconcile loop in the foreground."""
    from polyaxon_tpu.agent import Agent

    if chaos_plan:
        from polyaxon_tpu import chaos

        chaos.install(chaos.ChaosPlan.load(chaos_plan))
        click.echo(f"chaos plan armed from {chaos_plan}")
    manager = None
    if slices:
        from polyaxon_tpu.agent import SliceManager

        manager = SliceManager(_parse_slices(slices))
    plane = get_plane()
    agent = Agent(plane, max_concurrent=max_concurrent, slice_manager=manager)
    click.echo(f"Agent serving (home={get_home()}"
               + (f", slices={[s for s in slices]}" if slices else "") + ")")
    agent.serve_forever(poll_seconds=poll)


# ------------------------------------------------------------------- models
@cli.command("version")
def version_cmd():
    """Print client/library version."""
    from polyaxon_tpu import __version__

    click.echo(json.dumps({"version": __version__}))


@cli.command("models")
def models_cmd():
    """List builtin model zoo entries."""
    from polyaxon_tpu.models import available_models

    for name in available_models():
        click.echo(name)


if __name__ == "__main__":
    cli()
