"""Multi-tenant scheduling tests (ISSUE 2): queue/quota catalog CRUD,
compile-time validation + stamping, fair-share admission ordering,
starvation-bounded priority preemption, and the end-to-end
preemption-for-priority drill against the native slice pool.

Everything here is CPU-only and deterministic (`scheduling` marker;
its own stage in scripts/ci.sh).
"""

import time

import pytest

from polyaxon_tpu import chaos
from polyaxon_tpu.agent import Agent
from polyaxon_tpu.controlplane import ControlPlane
from polyaxon_tpu.lifecycle import V1Statuses
from polyaxon_tpu.scheduling import (
    AdmissionController,
    PRIORITY_CLASSES,
    SchedulingError,
    gang_priority,
    resolve_priority_class,
    sched_info,
)


@pytest.fixture()
def plane(tmp_path):
    return ControlPlane(str(tmp_path / "home"))


def job_spec(*, sleep=0.1, queue=None, priority_class=None, project_env=None,
             topology=None, preemptible=False):
    env = {}
    if priority_class:
        env["priorityClassName"] = priority_class
    if topology:
        env["tpu"] = {"accelerator": "v5e", "topology": topology,
                      "preemptible": preemptible}
    spec = {
        "kind": "operation",
        "component": {
            "run": {
                "kind": "job",
                **({"environment": env} if env else {}),
                "container": {"command": [
                    "python", "-c", f"import time; time.sleep({sleep})"]},
            },
        },
    }
    if queue:
        spec["queue"] = queue
    return spec


def submit_queued(plane, project="default", **kwargs):
    """Submit + compile so the run lands in QUEUED."""
    record = plane.submit(job_spec(**kwargs), project=project)
    plane.compile_run(record.uuid)
    return plane.get_run(record.uuid)


def mark_running(plane, record):
    for status in (V1Statuses.SCHEDULED, V1Statuses.STARTING,
                   V1Statuses.RUNNING):
        plane.store.transition(record.uuid, status)
    return plane.get_run(record.uuid)


class TestCatalog:
    def test_priority_classes(self):
        assert resolve_priority_class(None) == PRIORITY_CLASSES["default"]
        assert resolve_priority_class("CRITICAL") == 3
        with pytest.raises(SchedulingError, match="unknown priority class"):
            resolve_priority_class("platinum")

    def test_gang_priority_queue_dominates_class(self):
        # Any class on a higher-priority queue outranks every class on
        # a lower one; within a queue the class ladder breaks ties.
        assert gang_priority(1, 0) > gang_priority(0, 3)
        assert gang_priority(0, 2) > gang_priority(0, 1)

    def test_queue_crud_roundtrip(self, plane):
        plane.upsert_queue("prod", priority=10, concurrency=2,
                           preemptible=False)
        row = plane.store.get_queue("prod")
        assert row["priority"] == 10 and row["concurrency"] == 2
        plane.upsert_queue("prod", priority=20)  # upsert updates
        assert plane.store.get_queue("prod")["priority"] == 20
        assert plane.delete_queue("prod")
        assert plane.store.get_queue("prod") is None
        with pytest.raises(ValueError, match="default queue"):
            plane.delete_queue("default")

    def test_quota_crud_roundtrip(self, plane):
        plane.set_quota("team-a", max_runs=3, max_chips=16, weight=2.0)
        row = plane.store.get_quota("team-a")
        assert row["max_runs"] == 3 and row["weight"] == 2.0
        assert plane.delete_quota("team-a")
        assert plane.store.get_quota("team-a") is None


class TestCompileValidation:
    def test_unknown_queue_fails_at_compile(self, plane):
        record = plane.submit(job_spec(queue="nope"))
        with pytest.raises(SchedulingError, match="unknown queue"):
            plane.compile_run(record.uuid)

    def test_unknown_priority_class_fails_at_compile(self, plane):
        record = plane.submit(job_spec(priority_class="platinum"))
        with pytest.raises(SchedulingError, match="unknown priority class"):
            plane.compile_run(record.uuid)

    def test_scheduler_tick_fails_bad_queue_run_not_loop(self, plane):
        from polyaxon_tpu.controlplane.scheduler import Scheduler

        record = plane.submit(job_spec(queue="nope"))
        Scheduler(plane).tick()
        final = plane.get_run(record.uuid)
        assert final.status == V1Statuses.FAILED
        last = plane.get_statuses(record.uuid)[-1]
        assert "unknown queue" in (last.get("message") or "")

    def test_compile_stamps_scheduling_meta(self, plane):
        plane.upsert_queue("prod", priority=7)
        record = submit_queued(plane, queue="prod", priority_class="high",
                               topology="2x2")
        stamp = record.meta["scheduling"]
        assert stamp == {"queue": "prod", "priority_class": "high",
                         "priority": 2, "chips": 4, "preemptible": False}

    def test_sched_info_fallback_without_stamp(self, plane):
        plane.upsert_queue("prod", priority=7)
        record = submit_queued(plane, queue="prod", priority_class="high",
                               topology="2x2")
        meta = dict(record.meta)
        meta.pop("scheduling")
        plane.store.update_run(record.uuid, meta=meta)
        info = sched_info(plane.get_run(record.uuid))
        assert info.queue == "prod" and info.priority == 2
        assert info.chips == 4


class TestStoreOrdering:
    def test_created_at_tie_breaks_by_insertion_order(self, plane):
        uuids = [plane.submit(job_spec()).uuid for _ in range(5)]
        # Force identical timestamps: same-second submissions must
        # still admit in insertion (rowid) order.
        with plane.store._lock, plane.store._conn() as conn:
            conn.execute("UPDATE runs SET created_at='2026-01-01T00:00:00'")
        listed = [r.uuid for r in plane.list_runs()]
        assert listed == uuids
        newest = [r.uuid for r in plane.list_runs(newest_first=True)]
        assert newest == list(reversed(uuids))


class TestAdmissionOrdering:
    def test_queue_priority_orders_admission(self, plane):
        plane.upsert_queue("prod", priority=10)
        plane.upsert_queue("batch", priority=0)
        low = submit_queued(plane, queue="batch")
        high = submit_queued(plane, queue="prod")
        controller = AdmissionController(plane)
        decision = controller.plan(
            plane.list_runs(statuses=[V1Statuses.QUEUED]), capacity=2,
            active=set())
        order = [r.uuid for r, _ in decision.admitted]
        assert order == [high.uuid, low.uuid]

    def test_fair_share_converges_to_weights(self, plane):
        """Two projects flooding one queue split admissions by their
        quota weights (2:1), regardless of submission order."""
        plane.set_quota("heavy", weight=2.0)
        plane.set_quota("light", weight=1.0)
        for _ in range(9):
            submit_queued(plane, project="heavy")
        for _ in range(9):
            submit_queued(plane, project="light")
        controller = AdmissionController(plane)
        admitted_by_project = {"heavy": 0, "light": 0}
        # Simulate 3 ticks of capacity 3: admitted runs become live.
        for _ in range(3):
            queued = [r for r in plane.list_runs(statuses=[V1Statuses.QUEUED])]
            decision = controller.plan(queued, capacity=3, active=set())
            for record, _ in decision.admitted[:3]:
                mark_running(plane, record)
                admitted_by_project[record.project] += 1
        assert admitted_by_project["heavy"] == 6
        assert admitted_by_project["light"] == 3

    def test_quota_max_runs_blocks_with_visible_condition(self, plane):
        plane.set_quota("team-a", max_runs=1)
        first = submit_queued(plane, project="team-a")
        mark_running(plane, first)
        blocked = submit_queued(plane, project="team-a")
        controller = AdmissionController(plane)
        decision = controller.plan([plane.get_run(blocked.uuid)], capacity=4,
                                   active=set())
        assert decision.admitted == []
        assert decision.blocked[blocked.uuid] == "QuotaExceeded"
        conditions = plane.get_statuses(blocked.uuid)
        last = conditions[-1]
        assert last["type"] == "queued"
        assert last["reason"] == "QuotaExceeded"
        # Re-planning must not spam a condition per tick.
        controller.plan([plane.get_run(blocked.uuid)], capacity=4,
                        active=set())
        assert len(plane.get_statuses(blocked.uuid)) == len(conditions)

    def test_quota_max_chips_blocks_topology_runs(self, plane):
        plane.set_quota("team-a", max_chips=4)
        first = submit_queued(plane, project="team-a", topology="2x2")
        mark_running(plane, first)  # 4 chips in use
        blocked = submit_queued(plane, project="team-a", topology="2x2")
        small = submit_queued(plane, project="team-a")  # 0 chips: admissible
        controller = AdmissionController(plane)
        decision = controller.plan(
            [plane.get_run(blocked.uuid), plane.get_run(small.uuid)],
            capacity=4, active=set())
        assert [r.uuid for r, _ in decision.admitted] == [small.uuid]
        assert decision.blocked[blocked.uuid] == "QuotaExceeded"

    def test_queue_concurrency_cap(self, plane):
        plane.upsert_queue("narrow", priority=0, concurrency=1)
        first = submit_queued(plane, queue="narrow")
        mark_running(plane, first)
        blocked = submit_queued(plane, queue="narrow")
        controller = AdmissionController(plane)
        decision = controller.plan([plane.get_run(blocked.uuid)], capacity=4,
                                   active=set())
        assert decision.admitted == []
        assert decision.blocked[blocked.uuid] == "QueueSaturated"


class TestStarvationPreemption:
    def test_starved_high_priority_picks_one_lowest_victim(self, plane):
        plane.upsert_queue("batch", priority=0, preemptible=True)
        plane.upsert_queue("prod", priority=10)
        victims = [submit_queued(plane, queue="batch") for _ in range(2)]
        for v in victims:
            mark_running(plane, v)
        high = submit_queued(plane, queue="prod", priority_class="critical")
        controller = AdmissionController(plane, starvation_ticks=2)
        active = {v.uuid for v in victims}
        # Tick 1: starved but under the K-tick threshold — no eviction.
        decision = controller.plan([plane.get_run(high.uuid)], capacity=0,
                                   active=active)
        assert decision.victims == []
        # Tick 2: exactly ONE victim, stamped with the preemptor.
        decision = controller.plan([plane.get_run(high.uuid)], capacity=0,
                                   active=active)
        assert len(decision.victims) == 1
        victim = plane.get_run(decision.victims[0])
        assert victim.uuid in active
        assert victim.meta["scheduling"]["evicted_for"] == high.uuid

    def test_non_preemptible_queue_is_never_victimized(self, plane):
        plane.upsert_queue("prod", priority=10)
        low = submit_queued(plane)  # default queue: not preemptible
        mark_running(plane, low)
        high = submit_queued(plane, queue="prod")
        controller = AdmissionController(plane, starvation_ticks=1)
        decision = controller.plan([plane.get_run(high.uuid)], capacity=0,
                                   active={low.uuid})
        assert decision.victims == []

    def test_quota_wall_never_triggers_preemption(self, plane):
        plane.upsert_queue("batch", priority=0, preemptible=True)
        plane.upsert_queue("prod", priority=10)
        plane.set_quota("greedy", max_runs=1)
        low = submit_queued(plane, queue="batch")
        mark_running(plane, low)
        running = submit_queued(plane, project="greedy")
        mark_running(plane, running)
        blocked = submit_queued(plane, project="greedy", queue="prod")
        controller = AdmissionController(plane, starvation_ticks=1)
        for _ in range(3):
            decision = controller.plan([plane.get_run(blocked.uuid)],
                                       capacity=0,
                                       active={low.uuid, running.uuid})
        assert decision.victims == []
        assert decision.blocked[blocked.uuid] == "QuotaExceeded"


class TestChaosAdmissionSeam:
    def test_admission_fault_starves_named_queue(self, plane):
        plane.upsert_queue("batch", priority=0)
        record = submit_queued(plane, queue="batch")
        other = submit_queued(plane)  # default queue: unaffected
        plan = chaos.install(chaos.ChaosPlan.from_dict(
            {"faults": [{"seam": "admission", "op": "batch", "times": 2}]}))
        try:
            controller = AdmissionController(plane)
            for _ in range(2):
                decision = controller.plan(
                    [plane.get_run(record.uuid), plane.get_run(other.uuid)],
                    capacity=4, active=set())
                assert [r.uuid for r, _ in decision.admitted] == [other.uuid]
                assert decision.blocked[record.uuid] == "ChaosStarved"
            # Fault budget spent: the queue drains again.
            decision = controller.plan([plane.get_run(record.uuid)],
                                       capacity=4, active=set())
            assert [r.uuid for r, _ in decision.admitted] == [record.uuid]
            assert plan.done
        finally:
            chaos.uninstall()


class TestAgentIntegration:
    def _drive(self, agent, predicate, timeout=30, label=""):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            agent.reconcile_once()
            if predicate():
                return
            time.sleep(0.05)
        raise AssertionError(f"timed out waiting for {label or predicate}")

    def test_head_of_line_blocking_fixed(self, plane):
        """Regression (ISSUE 2 satellite 1): one placement-pending run
        at the head of the queue must not waste the only free slot a
        clearable run behind it could use."""
        from polyaxon_tpu.agent import SliceManager

        manager = SliceManager([("pool", "2x2", False)])
        agent = Agent(plane, max_concurrent=2, slice_manager=manager)
        try:
            hog = plane.submit(job_spec(sleep=10, topology="2x2"))
            self._drive(agent,
                        lambda: hog.uuid in agent.executor.active_runs,
                        label="hog running")
            # Head of queue: same topology, pool full → pending forever.
            stuck = plane.submit(job_spec(sleep=0.1, topology="2x2"))
            behind = plane.submit(job_spec(sleep=0.1))  # no topology
            self._drive(
                agent,
                lambda: plane.get_run(behind.uuid).status
                == V1Statuses.SUCCEEDED,
                label="behind run succeeded past the stuck head")
            assert plane.get_run(stuck.uuid).status == V1Statuses.QUEUED
            plane.stop(hog.uuid)
        finally:
            manager.close()

    def test_quota_exceeded_surfaces_while_agent_runs(self, plane):
        plane.set_quota("team-a", max_runs=1)
        agent = Agent(plane, max_concurrent=4)
        first = plane.submit(job_spec(sleep=5), project="team-a")
        self._drive(agent,
                    lambda: first.uuid in agent.executor.active_runs,
                    label="first running")
        blocked = plane.submit(job_spec(sleep=0.1), project="team-a")
        self._drive(
            agent,
            lambda: any(c.get("reason") == "QuotaExceeded"
                        for c in plane.get_statuses(blocked.uuid)),
            label="QuotaExceeded condition pinned")
        assert plane.get_run(blocked.uuid).status == V1Statuses.QUEUED
        stats = plane.scheduling_stats()
        assert stats["quotas"][0]["used_runs"] == 1
        assert stats["quotas"][0]["queued"] == 1
        plane.stop(first.uuid)
        agent.reconcile_once()

    def test_low_priority_flood_never_starves_high_beyond_bound(
            self, plane, monkeypatch):
        """Starvation invariant: a saturating preemptible low-priority
        flood yields to a high-priority submission within a bounded
        number of ticks (K starvation ticks + kill/reap/admit)."""
        monkeypatch.setenv("POLYAXON_TPU_BACKOFF_BASE", "0.05")
        monkeypatch.setenv("POLYAXON_TPU_BACKOFF_MAX", "0.1")
        plane.upsert_queue("batch", priority=0, preemptible=True)
        plane.upsert_queue("prod", priority=10)
        agent = Agent(
            plane, max_concurrent=2,
            admission=AdmissionController(plane, starvation_ticks=2))
        flood = [plane.submit(job_spec(sleep=30, queue="batch",
                                       priority_class="low"))
                 for _ in range(4)]
        self._drive(agent, lambda: len(agent.executor.active_runs) == 2,
                    label="flood saturates capacity")
        high = plane.submit(job_spec(sleep=0.1, queue="prod",
                                     priority_class="high"))
        ticks = 0
        while plane.get_run(high.uuid).status != V1Statuses.SUCCEEDED:
            agent.reconcile_once()
            ticks += 1
            assert ticks < 200, "high-priority run starved past the bound"
            time.sleep(0.02)
        preempted = [r for r in flood
                     if any(c["type"] == "preempted"
                            for c in plane.get_statuses(r.uuid))]
        assert len(preempted) == 1  # exactly one victim evicted
        for record in flood:
            plane.stop(record.uuid)
        for _ in range(10):
            agent.reconcile_once()


@pytest.mark.gang
class TestPreemptionDrillE2E:
    """Acceptance drill: an agent at capacity running a preemptible
    low-priority gang on a spot slice; a high-priority run on a
    higher-priority queue evicts exactly one victim (PREEMPTED →
    backoff requeue), reaches RUNNING within a bounded tick budget, and
    the victim later reaches SUCCEEDED — with queue depth and quota
    usage queryable throughout."""

    def test_priority_preemption_end_to_end(self, tmp_path, monkeypatch):
        from polyaxon_tpu.agent import SliceManager

        monkeypatch.setenv("POLYAXON_TPU_BACKOFF_BASE", "0.05")
        monkeypatch.setenv("POLYAXON_TPU_BACKOFF_MAX", "0.1")
        plane = ControlPlane(str(tmp_path / "home"))
        plane.upsert_queue("batch", priority=0, preemptible=True)
        plane.upsert_queue("prod", priority=10)
        plane.set_quota("tenant", max_runs=2)
        manager = SliceManager([("spot", "2x2", True)])
        agent = Agent(
            plane, max_concurrent=1, slice_manager=manager,
            admission=AdmissionController(plane, starvation_ticks=2))
        try:
            victim = plane.submit(
                job_spec(sleep=1.5, queue="batch", priority_class="low",
                         topology="2x2", preemptible=True),
                project="tenant")
            deadline = time.monotonic() + 30
            while victim.uuid not in agent.executor.active_runs:
                assert time.monotonic() < deadline
                agent.reconcile_once()
                time.sleep(0.05)

            high = plane.submit(
                job_spec(sleep=0.2, queue="prod", priority_class="critical",
                         topology="2x2"),
                project="tenant")
            # Queue depth + quota usage are queryable mid-drill.
            agent.reconcile_once()
            stats = plane.scheduling_stats()
            by_name = {q["name"]: q for q in stats["queues"]}
            assert by_name["prod"]["depth"] == 1
            assert by_name["batch"]["running"] == 1
            quota = next(q for q in stats["quotas"]
                         if q["project"] == "tenant")
            assert quota["used_runs"] == 1 and quota["queued"] == 1

            ticks = 0
            seen_running = False
            while True:
                agent.reconcile_once()
                ticks += 1
                assert ticks < 400, "drill did not converge"
                status = plane.get_run(high.uuid).status
                if status in (V1Statuses.RUNNING, V1Statuses.SUCCEEDED):
                    seen_running = True
                if (seen_running
                        and plane.get_run(high.uuid).status
                        == V1Statuses.SUCCEEDED
                        and plane.get_run(victim.uuid).status
                        == V1Statuses.SUCCEEDED):
                    break
                time.sleep(0.02)

            victim_conditions = plane.get_statuses(victim.uuid)
            kinds = [c["type"] for c in victim_conditions]
            assert "preempted" in kinds and "retrying" in kinds
            assert any(c.get("reason") == "PreemptedForPriority"
                       for c in victim_conditions)
            assert plane.get_run(victim.uuid).meta["scheduling"][
                "evicted_for"] == high.uuid
            # Exactly one eviction: the victim was preempted once.
            assert kinds.count("preempted") == 1
        finally:
            manager.close()
