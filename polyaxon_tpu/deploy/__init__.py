from polyaxon_tpu.deploy.schemas import V1DeploymentConfig, check_deployment
from polyaxon_tpu.deploy.render import render_deployment

__all__ = ["V1DeploymentConfig", "check_deployment", "render_deployment"]
