"""V1Join resolution: collect values from matching runs into params.

Upstream joins (SURVEY.md §2 "Polyflow IR": joins) let an operation
gather its inputs from a QUERY over other runs — e.g. every trial of a
sweep contributes its best checkpoint path to a selection job. The
query grammar here is the upstream search subset that matters for the
embedded plane:

    "pipeline: <uuid>, status: succeeded, tags: best"

comma-separated ``field: value`` filters over pipeline, parent, project,
status, kind, name, uuid, and tags (tags matches ANY listed tag).
``sort`` orders by created_at (``-created_at`` for newest first);
``limit`` caps the result.

Each join param's value is a *context reference* evaluated per matched
run and collected into a list:

    uuid | name | status | artifacts_dir | outputs | outputs.<key> |
    inputs.<name>
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from polyaxon_tpu.controlplane.store import RunRecord, Store
from polyaxon_tpu.lifecycle import V1Statuses

logger = logging.getLogger(__name__)


class JoinError(ValueError):
    pass


_FIELDS = {"pipeline", "parent", "project", "status", "kind", "name", "uuid",
           "tags"}


def parse_query(query: str) -> dict[str, str]:
    filters: dict[str, str] = {}
    for clause in query.split(","):
        clause = clause.strip()
        if not clause:
            continue
        field, sep, value = clause.partition(":")
        field, value = field.strip(), value.strip()
        if not sep or not value:
            raise JoinError(f"join query clause {clause!r} is not `field: value`")
        if field not in _FIELDS:
            raise JoinError(
                f"unknown join query field `{field}` (known: {sorted(_FIELDS)})")
        filters[field] = value
    if not filters:
        raise JoinError(f"empty join query {query!r}")
    return filters


def find_runs(store: Store, query: str, *, project: str,
              sort: Optional[str] = None,
              limit: Optional[int] = None) -> list[RunRecord]:
    filters = parse_query(query)
    kwargs: dict[str, Any] = {}
    if "pipeline" in filters:
        kwargs["pipeline_uuid"] = filters["pipeline"]
    if "parent" in filters:
        kwargs["parent_uuid"] = filters["parent"]
    if "kind" in filters:
        kwargs["kind"] = filters["kind"]
    if "status" in filters:
        kwargs["statuses"] = [V1Statuses(filters["status"])]
    kwargs["project"] = filters.get("project", project)
    records = store.list_runs(**kwargs)
    if "uuid" in filters:
        records = [r for r in records if r.uuid == filters["uuid"]]
    if "name" in filters:
        records = [r for r in records if r.name == filters["name"]]
    if "tags" in filters:
        wanted = {t.strip() for t in filters["tags"].split("|")}
        records = [r for r in records if wanted & set(r.tags or [])]
    reverse = False
    if sort:
        reverse = sort.startswith("-")
        key = sort.lstrip("-")
        if key != "created_at":
            raise JoinError(f"unsupported join sort `{sort}`")
    records.sort(key=lambda r: r.created_at, reverse=reverse)
    if limit:
        records = records[:limit]
    return records


def _context_value(record: RunRecord, streams, ref: str) -> Any:
    if ref == "uuid":
        return record.uuid
    if ref == "name":
        return record.name
    if ref == "status":
        return record.status.value
    if ref == "artifacts_dir":
        return streams.run_dir(record.uuid)
    if ref == "outputs":
        return streams.get_outputs(record.uuid)
    if ref.startswith("outputs."):
        return streams.get_outputs(record.uuid).get(ref[len("outputs."):])
    if ref.startswith("inputs."):
        name = ref[len("inputs."):]
        param = (record.params or {}).get(name) or {}
        return param.get("value") if isinstance(param, dict) else param
    raise JoinError(f"unknown join context ref `{ref}`")


def resolve_joins(store: Store, streams, joins: list[dict], *,
                  project: str,
                  matched: Optional[list] = None) -> dict[str, list]:
    """Evaluate every join; returns {param_name: [value per matched run]}.
    ``matched`` (optional out-param): collects the matched runs' uuids —
    the compile step stamps them as the run's upstream lineage edges."""
    out: dict[str, list] = {}
    for join in joins:
        records = find_runs(
            store, join["query"], project=project,
            sort=join.get("sort"), limit=join.get("limit"))
        if matched is not None:
            matched.extend(r.uuid for r in records)
        for name, param in (join.get("params") or {}).items():
            ref = param.get("value") if isinstance(param, dict) else param
            if not isinstance(ref, str):
                raise JoinError(
                    f"join param `{name}` must reference a context value")
            out[name] = [_context_value(r, streams, ref) for r in records]
    return out
