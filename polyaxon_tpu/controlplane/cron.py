"""Minimal 5-field cron parser for V1CronSchedule (no external deps —
croniter is not in the TPU-VM image).

Supported per field: ``*``, ``*/n``, ``a``, ``a-b``, ``a-b/n``, and
comma lists thereof. Fields: minute hour day-of-month month day-of-week
(0=Sunday, 7 accepted as Sunday). Matching semantics follow vixie-cron:
when BOTH day-of-month and day-of-week are restricted, a time matches
if EITHER does.
"""

from __future__ import annotations

import datetime as dt
from typing import Optional

_RANGES = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))


class CronError(ValueError):
    pass


def _parse_field(text: str, lo: int, hi: int, *, dow: bool = False) -> set[int]:
    # Day-of-week accepts 7 as Sunday (vixie-cron): parse with hi=7 and
    # fold 7→0 AFTER range expansion so "5-7" (Fri-Sun) and "0-7" work.
    parse_hi = 7 if dow else hi
    values: set[int] = set()
    for part in text.split(","):
        part = part.strip()
        step = 1
        if "/" in part:
            part, step_text = part.split("/", 1)
            try:
                step = int(step_text)
            except ValueError as exc:
                raise CronError(f"bad step {step_text!r}") from exc
            if step <= 0:
                raise CronError(f"step must be positive, got {step}")
        if part in ("*", ""):
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            try:
                start, end = int(a), int(b)
            except ValueError as exc:
                raise CronError(f"bad range {part!r}") from exc
        else:
            try:
                start = end = int(part)
            except ValueError as exc:
                raise CronError(f"bad value {part!r}") from exc
        if not (lo <= start <= parse_hi and lo <= end <= parse_hi and start <= end):
            raise CronError(f"value {part!r} outside [{lo}, {parse_hi}]")
        values.update(range(start, end + 1, step))
    if dow:
        values = {v % 7 for v in values}
    return values


class Cron:
    def __init__(self, expr: str):
        fields = expr.split()
        if len(fields) != 5:
            raise CronError(
                f"cron {expr!r} must have 5 fields (minute hour dom month dow)")
        self.minutes = _parse_field(fields[0], *_RANGES[0])
        self.hours = _parse_field(fields[1], *_RANGES[1])
        self.dom = _parse_field(fields[2], *_RANGES[2])
        self.months = _parse_field(fields[3], *_RANGES[3])
        self.dow = _parse_field(fields[4], *_RANGES[4], dow=True)
        self.dom_star = fields[2] == "*"
        self.dow_star = fields[4] == "*"

    def _day_matches(self, t: dt.datetime) -> bool:
        dom_ok = t.day in self.dom
        dow_ok = (t.weekday() + 1) % 7 in self.dow  # python Mon=0 → cron Sun=0
        if self.dom_star and self.dow_star:
            return True
        if self.dom_star:
            return dow_ok
        if self.dow_star:
            return dom_ok
        return dom_ok or dow_ok  # vixie-cron OR semantics

    def matches(self, t: dt.datetime) -> bool:
        return (t.minute in self.minutes and t.hour in self.hours
                and t.month in self.months and self._day_matches(t))

    def next_after(self, after: dt.datetime) -> dt.datetime:
        """First matching minute strictly after ``after`` (≤ 4 years out)."""
        t = after.replace(second=0, microsecond=0) + dt.timedelta(minutes=1)
        limit = after + dt.timedelta(days=365 * 4 + 1)
        while t <= limit:
            if t.month not in self.months:
                # jump to the 1st of the next month
                year, month = t.year + (t.month == 12), t.month % 12 + 1
                t = t.replace(year=year, month=month, day=1, hour=0, minute=0)
                continue
            if not self._day_matches(t):
                t = (t + dt.timedelta(days=1)).replace(hour=0, minute=0)
                continue
            if t.hour not in self.hours:
                t = (t + dt.timedelta(hours=1)).replace(minute=0)
                continue
            if t.minute not in self.minutes:
                t += dt.timedelta(minutes=1)
                continue
            return t
        raise CronError(f"no matching time within 4 years after {after}")


def next_fire(expr: str, after: dt.datetime) -> dt.datetime:
    return Cron(expr).next_after(after)
