#!/usr/bin/env python
"""Serving engine load benchmark: tokens/sec and latency under
concurrent requests, across engine configs (dense / paged / +int8).

Drives the real HTTP surface (ServingServer) with N concurrent client
threads issuing mixed-length prompts, and reads /v1/stats occupancy so
the result shows WHY a config wins (slots busy vs admission-bound).
Writes bench_serve_results.json at the repo root.

Usage: python scripts/bench_serve.py [--model llama3_1b] [--clients 8]
       [--requests 32] [--max-new 64] [--slots 8] [--quick]
       [--workload mixed|shared-prefix|conversation-tree]
       [--configs paged,paged-nocache] [--check-prefix] [--fleet N]
CPU smoke: JAX_PLATFORMS=cpu ... --model llama_tiny --quick
Fleet A/B (ISSUE 17): --fleet N routes the workload through a
ServingFleet of N paged replicas twice — prefix-affinity router vs
blind round-robin — recording per-mode hit rate, p50 latency, and the
routed-reason breakdown.
Radix A/B (ISSUE 11): the paged vs paged-nocache rows + the top-level
`prefix_ab` block record prefill tokens skipped, hit rate, and the
interactive p50-TTFT dividend per workload.
Lane A/B (ISSUE 18): --workload long-prompt-storm drives short
interactive traffic against concurrent long prefills through the same
engine twice — interleaved vs disaggregated prefill/decode — recording
decode-step gap p99 and computed-prefill tokens/s per arm; the
`lane_ab` block carries the ratios --check-lanes gates on, and
--inject lane-starve is the must-fail self-test.
Class A/B (ISSUE 19): --streams N drives N concurrent mixed-class
streams (best-effort camps every slot first, then batch+interactive
land on a saturated engine) through three engine-level arms — an
interactive-only unloaded baseline, class-aware admission with
preemptive eviction, and the FIFO baseline (--no-class-admission) —
recording per-class TTFT/TPOT p50/p99, preemption/re-admission
counts, and aggregate tok/s; --check-classes gates on interactive
TTFT p99 ≤ 1.5x unloaded with preemptions > 0, invariants clean, and
the FIFO pair (p99 improves, tok/s ≥ 0.9x); --inject no-preempt is
the must-fail self-test.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from polyaxon_tpu.utils import apply_jax_platforms_override  # noqa: E402

apply_jax_platforms_override()


def drive(url: str, prompts: list[list[int]], max_new: int,
          clients: int, klass: str = "interactive",
          timeout: float = 600) -> dict:
    """Fan the prompts over `clients` threads; returns latency stats."""
    lat: list[float] = []
    errors: list[str] = []
    lock = threading.Lock()
    queue = list(enumerate(prompts))

    def worker():
        while True:
            with lock:
                if not queue:
                    return
                i, prompt = queue.pop()
            body = json.dumps({"tokens": [prompt], "max_new_tokens": max_new,
                               "seed": i, "class": klass}).encode()
            req = urllib.request.Request(
                url + "/v1/generate", method="POST", data=body,
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    out = json.load(resp)
                assert len(out["tokens"][0]) == max_new
                with lock:
                    lat.append(time.perf_counter() - t0)
            except Exception as exc:  # noqa: BLE001 — recorded, not fatal
                with lock:
                    errors.append(f"{type(exc).__name__}: {exc}"[:200])

    threads = [threading.Thread(target=worker) for _ in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lat.sort()
    n = len(lat)
    return {
        "wall_s": round(wall, 2),
        "completed": n,
        "errors": errors[:5],
        "tokens_per_sec": round(n * max_new / wall, 2) if wall else None,
        "latency_p50_s": round(lat[n // 2], 3) if n else None,
        "latency_p95_s": round(lat[int(n * 0.95)], 3) if n else None,
    }


def _stats(url: str) -> dict:
    return json.load(urllib.request.urlopen(url + "/v1/stats", timeout=10))


def _timeline_ttft_p50_ms(url: str, n: int):
    """Exact p50 TTFT (ms) over the last `n` requests, read from their
    span timelines — the SLO histograms answer the same question but
    at bucket resolution, too coarse for a CPU-scale A/B delta."""
    try:
        recent = json.load(urllib.request.urlopen(url + "/requests",
                                                  timeout=10))
    except Exception:  # noqa: BLE001 — static engine / tracing off
        return None
    ttfts = []
    for row in (recent.get("requests") or recent or [])[:n]:
        rid = row.get("request_id") if isinstance(row, dict) else None
        if not rid:
            continue
        try:
            tl = json.load(urllib.request.urlopen(
                f"{url}/requests/{rid}/timeline", timeout=10))
        except Exception:  # noqa: BLE001 — evicted from the ring
            continue
        ttft = (tl.get("summary") or {}).get("ttft_ms")
        if ttft is not None:
            ttfts.append(float(ttft))
    if not ttfts:
        return None
    ttfts.sort()
    return round(ttfts[len(ttfts) // 2], 3)


def _slo_percentiles() -> dict:
    """Per-class TTFT/TPOT p50/p99 straight from the in-process
    registry (ServingServer shares this process): the trajectory
    record item 1's per-class policies will be judged against."""
    from polyaxon_tpu.obs import metrics as obs_metrics

    out: dict[str, dict] = {}
    for stem, hist in (("ttft", obs_metrics.serving_ttft_hist()),
                       ("tpot", obs_metrics.serving_tpot_hist())):
        # Under a fleet each series key may carry a trailing replica
        # component ("interactive,r0"); the per-class numbers here are
        # the FEDERATED view, so strip to the base class and merge
        # every component's buckets.
        classes = {key.split(",")[0] for key in hist.snapshot()["series"]}
        for klass in classes:
            entry = out.setdefault(klass or "batch", {})
            for q, tag in ((0.5, "p50"), (0.99, "p99")):
                value = hist.quantile_merged(q, **{"class": klass})
                entry[f"{stem}_{tag}_s"] = (round(value, 4)
                                            if value is not None else None)
    return out


def run_config(name: str, model: str, prompts, max_new, clients,
               **server_kw) -> dict:
    import jax

    from polyaxon_tpu.obs import metrics as obs_metrics
    from polyaxon_tpu.serving import ServingServer

    print(f"→ {name} ...", flush=True)
    with ServingServer(model, batching="continuous", **server_kw) as s:
        # Warm EVERY distinct prompt-length's prefill compile (the
        # engine jits per exact length) outside the timed window —
        # otherwise the timed run measures XLA compile, not serving.
        # This also warms the prefix cache: the timed numbers describe
        # steady-state serving of a repeated-prefix workload.
        seen: dict[int, list[int]] = {}
        for p in prompts:
            seen.setdefault(len(p), p)
        # Twice: the first pass populates the radix tree and compiles
        # the monolithic prefills; the SECOND pass re-admits against a
        # warm tree and compiles the suffix-prefill programs the timed
        # window will actually run (per distinct suffix length).
        drive(s.url, list(seen.values()), max_new, clients=2)
        drive(s.url, list(seen.values()), max_new, clients=2)
        # The warm-up polluted the SLO histograms (compile-dominated
        # TTFTs): reset so the per-class percentiles describe the
        # timed window only. Accessor-style recorders re-create their
        # families on next touch, so the engine keeps recording.
        obs_metrics.REGISTRY.reset()
        before = _stats(s.url)
        result = drive(s.url, prompts, max_new, clients)
        after = _stats(s.url)
        slo_by_class = _slo_percentiles()
        ttft_exact = _timeline_ttft_p50_ms(s.url, len(prompts))
    # Timed-window deltas (the raw gauges are lifetime counters).
    occupancy = None
    dsteps = (after.get("decode_steps") or 0) - (before.get("decode_steps") or 0)
    if dsteps > 0 and after.get("avg_occupancy") is not None:
        live = (after["avg_occupancy"] * after["decode_steps"]
                - (before["avg_occupancy"] or 0) * before["decode_steps"])
        occupancy = round(live / dsteps, 4)
    row = {"name": name, **result, "avg_occupancy": occupancy,
           # Comparable across pod sizes the day the TPU tunnel
           # returns: per-chip normalization + per-class SLO numbers.
           "tokens_per_sec_per_chip": (
               round(result["tokens_per_sec"] / jax.device_count(), 2)
               if result["tokens_per_sec"] is not None else None),
           "slo_by_class": slo_by_class,
           "ttft_p50_ms": ttft_exact,
           "rejected": after.get("rejected") or {}}
    if after.get("spec_rounds") is not None:
        row["spec_tokens_per_round"] = after.get("spec_tokens_per_round")
    if after.get("kv_prefix_hits") is not None:
        row["kv_prefix_hits"] = (after["kv_prefix_hits"]
                                 - before["kv_prefix_hits"])
        row["kv_prefix_misses"] = (after["kv_prefix_misses"]
                                   - before["kv_prefix_misses"])
    if after.get("prefill_tokens_total") is not None:
        # Radix prefix-reuse dividend over the TIMED window only.
        total = (after["prefill_tokens_total"]
                 - (before.get("prefill_tokens_total") or 0))
        skipped = (after["prefill_tokens_skipped"]
                   - (before.get("prefill_tokens_skipped") or 0))
        row["prefill_tokens_total"] = total
        row["prefill_tokens_skipped"] = skipped
        row["prefix_hit_rate"] = (round(skipped / total, 4)
                                  if total else None)
        row["kv_cow_forks"] = (after.get("kv_cow_forks") or 0) - (
            before.get("kv_cow_forks") or 0)
        row["kv_prefix_evictions"] = (
            (after.get("kv_prefix_evictions") or 0)
            - (before.get("kv_prefix_evictions") or 0))
        # Headroom: free pages INCLUDE resident-but-unreferenced radix
        # pages (reclaimable on demand) — the cache costs no capacity.
        radix = after.get("kv_radix") or {}
        row["kv_pages_total"] = after.get("kv_pages_total")
        row["kv_pages_free"] = after.get("kv_pages_free")
        row["kv_pages_headroom_reclaimable"] = max(
            (radix.get("resident") or 0) - (radix.get("referenced") or 0), 0)
        row["kv_invariant_violations"] = after.get("kv_invariant_violations")
    print(f"  {name}: {result['tokens_per_sec']} tok/s, "
          f"p50 {result['latency_p50_s']}s, "
          f"occupancy {row['avg_occupancy']}", flush=True)
    return row


def make_prompts(workload: str, requests: int, prompt_len: int,
                 rng) -> list[list[int]]:
    """The three serving mixes the radix cache is judged against.

    - ``mixed``: half the requests share one system prompt, half are
      cold — the honest production blend.
    - ``shared-prefix``: EVERY request is system-prompt + short user
      turn — the workload prefix caching exists for (the acceptance
      trace: >= 40% of prefill tokens skipped).
    - ``conversation-tree``: multi-turn chats forking from shared
      histories at non-page-aligned points — exercises radix splits
      and copy-on-write forks, not just whole-page adoption.
    """
    if workload == "mixed":
        sys_prefix = [rng.randrange(100) for _ in range(prompt_len // 2)]
        prompts = []
        for i in range(requests):
            tail_len = rng.randrange(4, max(prompt_len // 2, 5))
            tail = [rng.randrange(100) for _ in range(tail_len)]
            prompts.append((sys_prefix + tail) if i % 2 == 0 else
                           ([rng.randrange(100) for _ in range(8)] + tail))
        return prompts
    if workload == "shared-prefix":
        sys_prefix = [rng.randrange(100)
                      for _ in range(max(prompt_len * 3 // 4, 8))]
        return [sys_prefix + [rng.randrange(100) for _ in range(
                    rng.randrange(4, max(prompt_len // 4, 5)))]
                for _ in range(requests)]
    if workload == "conversation-tree":
        # A branching tree of token blocks; each request's prompt is a
        # root→node path (a chat history). Block length is NOT a page
        # multiple, so sibling branches diverge mid-page.
        block = max(prompt_len // 8, 3)
        paths = [[rng.randrange(100) for _ in range(block * 2)]]  # root
        prompts: list[list[int]] = []
        while len(prompts) < requests:
            parent = paths[rng.randrange(len(paths))]
            child = parent + [rng.randrange(100) for _ in range(block)]
            if len(child) <= prompt_len * 2:
                paths.append(child)
            prompts.append(list(child))
        return prompts
    raise ValueError(f"unknown workload {workload!r}")


def make_storm_prompts(requests: int, prompt_len: int, rng,
                       trials: int = 3):
    """The ``long-prompt-storm`` mix (ISSUE 18): a stream of short
    interactive prompts plus concurrent LONG prompts whose prefills
    ARE the storm. Returns ``(warm_rows, trial_sets)``: one
    ``(short, long)`` prompt pair per timed trial, all disjoint.

    One fixed length per class and a distinct first token per prompt
    (across warm AND every trial — each admission is a radix miss)
    keep both arms replaying the same warm skip=0 programs, so the
    A/B measures *scheduling*, not XLA compiles or cache luck. The
    trials exist because a single sub-second window on a busy CPU is
    one tick of noise away from any throughput ratio — the gate reads
    the per-trial median."""
    short_len = max(prompt_len // 4, 6)
    long_len = prompt_len * 2
    n_short = max(requests, 12)
    n_long = max(requests // 2, 8)
    counter = iter(range(1_000_000))

    def mk(length: int) -> list[int]:
        return ([next(counter) % 250]
                + [rng.randrange(100) for _ in range(length - 1)])

    warm_rows = [mk(short_len), mk(long_len)]
    trial_sets = [([mk(short_len) for _ in range(n_short)],
                   [mk(long_len) for _ in range(n_long)])
                  for _ in range(trials)]
    return warm_rows, trial_sets


def _run_storm(eng, short, long_rows, max_new, clients,
               timeout) -> tuple:
    """One timed storm trial against one engine: short interactive
    traffic and long batch prefills drive it concurrently. Returns
    ``(wall_seconds, completed, errors)``."""
    completed = 0
    errors: list[str] = []
    lock = threading.Lock()

    def _drive(rows, klass):
        nonlocal completed
        for prompt in rows:
            try:
                req = eng.submit(prompt, max_new, klass=klass)
                out = req.wait(timeout=timeout)
                assert len(out) == max_new
                with lock:
                    completed += 1
            except Exception as exc:  # noqa: BLE001 — recorded
                with lock:
                    errors.append(f"{type(exc).__name__}: {exc}"[:200])

    # >= 2 clients per class: a one-client "storm" serializes its own
    # prefills and measures chunk-pacing latency, not lane throughput
    # — the disaggregation trade only exists under concurrency.
    nc = max(clients // 2, 2)
    threads = ([threading.Thread(target=_drive, daemon=True,
                                 args=(long_rows[i::nc], "batch"))
                for i in range(nc)]
               + [threading.Thread(target=_drive, daemon=True,
                                   args=(short[i::nc], "interactive"))
                  for i in range(nc)])
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return time.perf_counter() - t0, completed, errors


def _median(values):
    if not values:
        return None
    ordered = sorted(values)
    return ordered[len(ordered) // 2]


def run_lane_ab(arms, model: str, warm_rows, trial_sets, max_new,
                clients, *, warm: bool = True,
                timeout: float = 600) -> list:
    """Run the lane A/B *paired*: every arm's engine is built and
    warmed up front, then each trial's prompt set runs back-to-back on
    every arm before the next trial starts. Each engine has its own
    radix tree, so the same prompts are a fresh skip=0 storm on every
    arm — identical inputs, near-identical machine conditions. The
    gate downstream reads the median of PER-TRIAL ratios, which
    cancels the slow cross-minute CPU drift that made sequential
    whole-arm runs flap.

    Per (arm, trial) the metrics registry is reset so the decode-gap
    histogram (``polyaxon_serving_decode_tpot_seconds``) holds exactly
    that trial's observations; idle engines record nothing (the gap
    clock parks on idle), so arms can't pollute each other.

    Engine-level, no HTTP (the run_fleet posture): the A/B compares
    SCHEDULERS, and on a CPU box the HTTP stack's queueing jitter is
    the same order of magnitude as the per-tick effect under test."""
    from polyaxon_tpu.obs import metrics as obs_metrics
    from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
    from polyaxon_tpu.serving.server import load_params

    cfg, params = load_params(model, seed=0)
    engines = []
    try:
        for name, kw in arms:
            kw = dict(kw)
            if "kv_pages" not in kw:
                # Equal-memory A/B: lane rows should add BLOCK-TABLE
                # rows, not pool capacity — both arms get the decode
                # pool's dense-equivalent page budget. Without this
                # the disaggregated arm's default pool is
                # (slots+prefill_slots)/slots times larger, and on CPU
                # every decode step pays for the bigger buffers — the
                # ratio would measure memory, not scheduling.
                kw["kv_pages"] = (kw.get("slots", 4)
                                  * (cfg.max_seq_len
                                     // kw.get("page_size", 16)))
            engines.append(
                (name, ContinuousBatchingEngine(model, cfg, params,
                                                **kw)))
        acc = {name: {"tps": [], "gaps": [], "completed": 0,
                      "expected": 0, "errors": [], "computed": 0,
                      "wall": 0.0, "slo": None}
               for name, _ in engines}
        if warm:
            # One pass per length class compiles every program the
            # timed trials will run (storm prompts carry distinct
            # first tokens, so re-admissions replay these skip=0
            # shapes instead of discovering suffix shapes mid-storm).
            for name, eng in engines:
                print(f"→ warming {name} ...", flush=True)
                for prompt in warm_rows:
                    eng.generate([prompt], max_new_tokens=max_new)
        for trial, (short, long_rows) in enumerate(trial_sets):
            for name, eng in engines:
                a = acc[name]
                obs_metrics.REGISTRY.reset()
                before = eng.stats()
                wall, completed, errors = _run_storm(
                    eng, short, long_rows, max_new, clients, timeout)
                after = eng.stats()
                computed = (
                    (after.get("prefill_tokens_total") or 0)
                    - (before.get("prefill_tokens_total") or 0)
                    - ((after.get("prefill_tokens_skipped") or 0)
                       - (before.get("prefill_tokens_skipped") or 0)))
                a["computed"] += computed
                a["wall"] += wall
                a["completed"] += completed
                a["expected"] += len(short) + len(long_rows)
                a["errors"].extend(errors)
                if wall:
                    a["tps"].append(computed / wall)
                gap = obs_metrics.serving_decode_tpot_hist() \
                    .quantile(0.99)
                if gap is not None:
                    a["gaps"].append(gap)
                # Snapshot is per-trial (registry was just reset), so
                # this ends up holding the LAST trial's class SLOs —
                # a representative sample, not a pooled aggregate.
                a["slo"] = _slo_percentiles()
    finally:
        for _, eng in engines:
            eng.stop()
    rows = []
    for name, eng in engines:
        a = acc[name]
        final = eng.stats()
        gap_med = _median(a["gaps"])
        tps_med = _median(a["tps"])
        row = {
            "name": name,
            "trials": len(trial_sets),
            "wall_s": round(a["wall"], 2),
            "completed": a["completed"],
            "expected": a["expected"],
            "errors": a["errors"][:5],
            # THE decode-lane number: p99 wall gap between consecutive
            # decode steps, including whatever prefill work the
            # scheduler let land in between (median over trials).
            "decode_gap_p99_s": (round(gap_med, 4)
                                 if gap_med is not None else None),
            "decode_gap_p99_s_trials": [round(g, 4)
                                        for g in a["gaps"]],
            "prefill_tokens_computed": a["computed"],
            "prefill_tokens_per_sec": (round(tps_med, 1)
                                       if tps_med is not None
                                       else None),
            "prefill_tokens_per_sec_trials": [round(t, 1)
                                              for t in a["tps"]],
            "slo_by_class": a["slo"],
            "kv_invariant_violations":
                final.get("kv_invariant_violations"),
        }
        if final.get("handoffs") is not None:
            row["handoffs"] = final["handoffs"]
            row["handoff_pages"] = final["handoff_pages"]
        print(f"  {name}: decode gap p99 {row['decode_gap_p99_s']}s, "
              f"prefill {row['prefill_tokens_per_sec']} tok/s (median "
              f"of {len(a['tps'])} trials), completed "
              f"{row['completed']}/{row['expected']}", flush=True)
        rows.append(row)
    return rows


def _paired_ratio(num_trials, den_trials):
    """Median of per-trial ratios — the paired statistic the lane gate
    reads. Falls back to None when a trial pair is missing/zero."""
    ratios = [n / d for n, d in zip(num_trials, den_trials) if d]
    med = _median(ratios)
    return round(med, 3) if med is not None else None


def run_lanes(args) -> int:
    """The ``--workload long-prompt-storm`` path: interleaved vs
    disaggregated over the same storm, plus the ``lane-starve``
    red-team arm (decode budget zeroed → nothing completes → exit 1,
    which ci.sh inverts)."""
    import random

    import jax

    rng = random.Random(0)
    warm_rows, trial_sets = make_storm_prompts(args.requests,
                                               args.prompt_len, rng,
                                               trials=5)
    base = dict(slots=args.slots, kv="paged", page_size=args.kv_page_size)
    # Chunk sizing is the fairness/throughput dial: 4 pages per chunk
    # keeps each lane program well under a monolithic long prefill
    # (the decode-gap ceiling) without paying per-tick overhead per
    # page, and 2 chunks/tick keeps lane throughput at parity while
    # decode rows are live.
    chunk = max(6 * args.kv_page_size, 48)
    disagg_kw = dict(prefill_slots=4, prefill_chunk=chunk,
                     prefill_lane_budget=3, decode_lane_budget=2,
                     **base)
    if args.inject == "lane-starve":
        # No warm pass: nothing ever completes under a zeroed decode
        # budget, so warming would just burn a full timeout. One trial
        # is enough — the arm exists to prove it CANNOT complete.
        rows = run_lane_ab(
            [("disaggregated-starved",
              dict(prefill_slots=2, prefill_chunk=chunk,
                   decode_lane_budget=0, **base))],
            args.model, warm_rows, trial_sets[:1], args.max_new,
            args.clients, warm=False, timeout=5)
    else:
        rows = run_lane_ab(
            [("interleaved", dict(base)),
             ("disaggregated", disagg_kw)],
            args.model, warm_rows, trial_sets, args.max_new,
            args.clients)
    by_name = {r["name"]: r for r in rows}
    out = {
        "backend": jax.devices()[0].platform,
        "model": args.model, "workload": "long-prompt-storm",
        "load": {"clients": args.clients, "requests": args.requests,
                 "max_new": args.max_new, "slots": args.slots,
                 "prompt_len": args.prompt_len,
                 "kv_page_size": args.kv_page_size,
                 "prefill_slots": disagg_kw["prefill_slots"],
                 "prefill_chunk": chunk,
                 "prefill_lane_budget":
                     disagg_kw["prefill_lane_budget"],
                 "decode_lane_budget": disagg_kw["decode_lane_budget"],
                 "inject": args.inject},
        "results": rows,
    }
    inter = by_name.get("interleaved")
    disagg = by_name.get("disaggregated")
    if inter is not None and disagg is not None:
        gi, gd = inter["decode_gap_p99_s"], disagg["decode_gap_p99_s"]
        pi = inter["prefill_tokens_per_sec"]
        pd = disagg["prefill_tokens_per_sec"]
        out["lane_ab"] = {
            "decode_gap_p99_s_interleaved": gi,
            "decode_gap_p99_s_disaggregated": gd,
            # Paired statistics: per-trial ratio (same prompts, same
            # machine minute, both engines), median over trials. The
            # pooled medians above are reported for eyeballs; the GATE
            # reads these.
            "decode_gap_p99_ratio": _paired_ratio(
                disagg["decode_gap_p99_s_trials"],
                inter["decode_gap_p99_s_trials"]),
            "prefill_tokens_per_sec_interleaved": pi,
            "prefill_tokens_per_sec_disaggregated": pd,
            "prefill_throughput_ratio": _paired_ratio(
                disagg["prefill_tokens_per_sec_trials"],
                inter["prefill_tokens_per_sec_trials"]),
            "handoffs": disagg.get("handoffs"),
            "handoff_pages": disagg.get("handoff_pages"),
        }
        print(f"lane A/B: decode gap p99 {gd}s disaggregated vs {gi}s "
              f"interleaved (ratio "
              f"{out['lane_ab']['decode_gap_p99_ratio']}), prefill "
              f"{pd} vs {pi} tok/s (ratio "
              f"{out['lane_ab']['prefill_throughput_ratio']})",
              flush=True)
    path = args.out or os.path.join(REPO, "bench_serve_results.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
    print(f"wrote {path}")
    incomplete = [r["name"] for r in rows
                  if r["completed"] < r["expected"]]
    if incomplete:
        print(f"ERROR: configs with failed requests: {incomplete} "
              "(see errors in the JSON)", file=sys.stderr)
        return 1
    if args.check_lanes:
        if inter is None or disagg is None:
            print("ERROR: --check-lanes needs both A/B arms",
                  file=sys.stderr)
            return 1
        ab = out["lane_ab"]
        failures = []
        if not (disagg.get("handoffs") or 0) > 0:
            failures.append("no prefill→decode page handoffs happened")
        for r in (inter, disagg):
            if r["kv_invariant_violations"] != 0:
                failures.append(
                    f"{r['name']}: {r['kv_invariant_violations']} page "
                    "refcount invariant violations")
        ratio = ab["decode_gap_p99_ratio"]
        if ratio is None or ratio > 1.15:
            failures.append(
                f"decode gap p99 ratio {ratio} > 1.15 — the prompt "
                "storm is occupying ticks the decode batch needed")
        # 0.90, not parity: pacing prefill behind a per-tick budget is
        # the POINT of the lane split — it deliberately trades a few
        # percent of prefill throughput (lane bookkeeping + handoff +
        # chunk pacing, ~5% observed on the CPU sim) for a >10x
        # decode-gap improvement under the storm. The gate catches
        # starvation (budget bugs collapse this ratio toward 0), not
        # the designed trade.
        tput = ab["prefill_throughput_ratio"]
        if tput is None or tput < 0.90:
            failures.append(
                f"prefill throughput ratio {tput} < 0.90 — the lane "
                "split is starving prefill instead of pacing it")
        if failures:
            for f in failures:
                print(f"ERROR: {f}", file=sys.stderr)
            return 1
        print(f"lane check ok: decode gap ratio {ratio}, prefill "
              f"throughput ratio {tput}, "
              f"{disagg['handoffs']} handoffs, invariants clean")
    return 0


def run_fleet(model: str, prompts: list[list[int]], max_new: int,
              clients: int, *, replicas: int, slots: int,
              page_size: int, blind: bool) -> dict:
    """Drive the workload through a ServingFleet (router + replicas,
    no HTTP — the fleet front door is engine-level). The affinity vs
    blind pair is the fleet A/B: same replicas, same pool, only the
    routing discipline differs."""
    from polyaxon_tpu.obs import metrics as obs_metrics
    from polyaxon_tpu.serving.fleet import ServingFleet, engine_factory
    from polyaxon_tpu.serving.router import FleetRouter

    fleet = ServingFleet(
        engine_factory(model, slots=slots, kv="paged",
                       page_size=page_size),
        replicas=replicas, standby=0, min_replicas=1,
        max_replicas=replicas,
        router=FleetRouter(blind=blind), warmup_rows=[prompts[0]])
    fleet.start()
    # start() drove the warm-up row through every replica (compile
    # churn): reset so the SLO percentiles describe the timed window
    # only. run_config has done this since the radix A/B; the fleet
    # path shipped without it, so its per-class numbers silently
    # included warm-up compiles.
    obs_metrics.REGISTRY.reset()
    lat: list[float] = []
    lock = threading.Lock()
    queue = list(prompts)
    t0 = time.monotonic()
    try:
        def worker():
            while True:
                with lock:
                    if not queue:
                        return
                    row = queue.pop()
                start = time.monotonic()
                req, _ = fleet.submit(row, max_new, klass="interactive")
                req.wait(timeout=300)
                with lock:
                    lat.append(time.monotonic() - start)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        stats = fleet.stats()
        # Per-replica breakdown from the component-scoped series
        # (ISSUE 20): which replica served how much, at what TTFT,
        # evicting how often — the routing A/B's per-node evidence.
        per_replica = fleet.per_replica_telemetry()
        for rid, row in per_replica.items():
            row["served"] = (stats["replicas"].get(rid)
                             or {}).get("served", 0)
    finally:
        fleet.stop()
    lat.sort()
    return {
        "name": "fleet-blind" if blind else "fleet-affinity",
        "replicas": replicas, "completed": len(lat),
        "wall_seconds": round(wall, 3),
        "latency_p50_ms": (round(lat[len(lat) // 2] * 1e3, 1)
                           if lat else None),
        # Post-reset per-class percentiles: timed window only.
        "slo_by_class": _slo_percentiles(),
        "prefix_hit_rate": stats["prefix_hit_rate"],
        "prefill_tokens_skipped": stats["prefill_tokens_skipped"],
        "kv_invariant_violations": stats["kv_invariant_violations"],
        "routed": stats["router"]["routed"],
        "per_replica": per_replica,
    }


def make_stream_specs(streams: int, rng) -> list:
    """(klass, tokens, max_new) per stream for the --streams harness.

    70% best-effort / 20% batch / 10% interactive — the shape the
    admission catalog was designed for: a deep well of preemptible
    bulk work, a mid-tier, and a thin latency-critical stream. Each
    class draws a 4-token family prefix from a small pool (radix
    hotness is a live rank input, so the workload must have some) and
    a unique suffix (so prompts are distinct streams, not replays).
    max_new is the pressure dial: best-effort decodes long enough to
    wall every slot, interactive is a handful of tokens whose latency
    is entirely admission-bound."""
    n_int = max(streams // 10, 8)
    n_batch = max(streams // 5, 8)
    n_be = max(streams - n_int - n_batch, 8)
    shapes = {"best-effort": (n_be, 12, 48), "batch": (n_batch, 16, 8),
              "interactive": (n_int, 8, 4)}
    fams = {k: [[rng.randrange(2, 250) for _ in range(4)]
                for _ in range(8)] for k in shapes}
    specs = []
    for klass, (count, plen, max_new) in shapes.items():
        for i in range(count):
            prefix = fams[klass][i % len(fams[klass])]
            suffix = [rng.randrange(2, 250) for _ in range(plen - 4)]
            specs.append((klass, prefix + suffix, max_new))
    return specs


def _exact_pct(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(int(len(sorted_vals) * q), len(sorted_vals) - 1)
    return sorted_vals[idx]


def _run_stream_arm(name: str, model: str, cfg, params, specs, *,
                    slots: int, page_size: int, class_admission: bool,
                    preemption: bool = True, rng=None,
                    timeout: float = 1800) -> dict:
    """One arm of the thousand-stream A/B: every best-effort stream is
    submitted first and the engine runs until all slots are decoding
    (the camped-full posture the admission policy exists for), THEN
    the batch+interactive mix lands on the saturated engine all at
    once. TTFT is exact per request (submit → first emission, which
    spans any preemptions — an evicted-then-readmitted victim's clock
    restarts, see batching._evict_slot); TPOT rides along bucketed in
    slo_by_class. Engine-level, no HTTP, same rationale as
    run_lane_ab: the A/B compares ADMISSION POLICIES."""
    from polyaxon_tpu.obs import metrics as obs_metrics
    from polyaxon_tpu.serving.batching import ContinuousBatchingEngine

    print(f"→ {name}: {len(specs)} streams ...", flush=True)
    engine = ContinuousBatchingEngine(
        model, cfg, params, slots=slots, kv="paged",
        page_size=page_size, class_admission=class_admission,
        preemption=preemption)
    campers = [s for s in specs if s[0] == "best-effort"]
    rest = [s for s in specs if s[0] != "best-effort"]
    if rng is not None:
        rng.shuffle(rest)
    try:
        # Compile every prompt-length's prefill outside the timed
        # window (token 1 prefix: disjoint from the spec prompts, so
        # the radix tree stays cold for the measured streams).
        for length in sorted({len(t) for _, t, _ in specs}):
            engine.generate([[1] * length], max_new_tokens=2)
        obs_metrics.REGISTRY.reset()
        reqs = []
        for klass, toks, max_new in campers:
            reqs.append((klass, engine.submit(toks, max_new,
                                              klass=klass)))
        deadline = time.monotonic() + 120
        while (engine.health()["decode_active"] < slots
               and time.monotonic() < deadline):
            time.sleep(0.01)
        t0 = time.monotonic()
        peak = len([1 for _, r in reqs if not r.done.is_set()])
        for klass, toks, max_new in rest:
            in_flight = sum(1 for _, r in reqs
                            if not r.done.is_set()) + 1
            peak = max(peak, in_flight)
            reqs.append((klass, engine.submit(toks, max_new,
                                              klass=klass)))
        for _, r in reqs:
            r.wait(timeout=timeout)
        wall = time.monotonic() - t0
        stats = engine.stats()
    finally:
        engine.stop()
    ttft: dict[str, list[float]] = {}
    for klass, r in reqs:
        if r.first_token_at is not None:
            ttft.setdefault(klass, []).append(
                r.first_token_at - r.submitted_at)
    per_class = {}
    for klass, vals in ttft.items():
        vals.sort()
        per_class[klass] = {
            "requests": len(vals),
            "ttft_p50_s": round(_exact_pct(vals, 0.5), 4),
            "ttft_p99_s": round(_exact_pct(vals, 0.99), 4),
        }
    completed = sum(1 for _, r in reqs
                    if r.done.is_set() and not r.error)
    return {
        "name": name, "streams": len(specs),
        "streams_in_flight_peak": peak,
        "completed": completed, "wall_s": round(wall, 2),
        "tokens_per_sec": round(stats["tokens_generated"] / wall, 1)
        if wall else None,
        "per_class_ttft": per_class,
        "slo_by_class": _slo_percentiles(),
        "preemptions": stats.get("preemptions", {}),
        "readmit_suffix_tokens": stats.get("readmit_suffix_tokens", 0),
        "kv_invariant_violations": stats.get("kv_invariant_violations"),
    }


def run_streams(args) -> int:
    """The ``--streams N`` path (ISSUE 19): class-aware admission +
    preemptive eviction judged under N concurrent mixed-class streams,
    paired against the FIFO baseline, with an interactive-only
    unloaded pass as the TTFT yardstick. ``--inject no-preempt`` runs
    the class arm with eviction disabled — interactive TTFT climbs to
    the natural-retirement wall and preemptions stay 0, so the gate
    MUST exit 1 (ci.sh inverts this as the red-team self-test)."""
    import random

    import jax

    from polyaxon_tpu.serving.server import load_params

    streams = args.streams
    if args.quick:
        streams = min(streams, 64)
    rng = random.Random(0)
    specs = make_stream_specs(streams, rng)
    unloaded_specs = [s for s in specs if s[0] == "interactive"]
    cfg, params = load_params(args.model, seed=0)
    kw = dict(slots=args.slots, page_size=args.kv_page_size)
    results = [_run_stream_arm(
        "unloaded-interactive", args.model, cfg, params,
        unloaded_specs, class_admission=True, **kw)]
    if args.inject == "no-preempt":
        results.append(_run_stream_arm(
            "class-admission-no-preempt", args.model, cfg, params,
            specs, class_admission=True, preemption=False,
            rng=random.Random(1), **kw))
    else:
        if not args.no_class_admission:
            results.append(_run_stream_arm(
                "class-admission", args.model, cfg, params, specs,
                class_admission=True, rng=random.Random(1), **kw))
        results.append(_run_stream_arm(
            "fifo", args.model, cfg, params, specs,
            class_admission=False, rng=random.Random(1), **kw))
    by_name = {r["name"]: r for r in results}
    unloaded = by_name["unloaded-interactive"]
    klass_arm = (by_name.get("class-admission")
                 or by_name.get("class-admission-no-preempt"))
    fifo = by_name.get("fifo")
    out = {
        "backend": jax.devices()[0].platform,
        "model": args.model, "workload": "class-streams",
        "load": {"streams": streams, "slots": args.slots,
                 "kv_page_size": args.kv_page_size,
                 "mix": {k: sum(1 for s in specs if s[0] == k)
                         for k in ("best-effort", "batch",
                                   "interactive")},
                 "inject": args.inject},
        "results": results,
    }

    def _int_p99(row):
        if row is None:
            return None
        return (row.get("per_class_ttft", {})
                .get("interactive", {}).get("ttft_p99_s"))

    if klass_arm is not None:
        preempted = sum((klass_arm.get("preemptions") or {}).values())
        out["class_ab"] = {
            "interactive_ttft_p99_s_unloaded": _int_p99(unloaded),
            "interactive_ttft_p99_s_class": _int_p99(klass_arm),
            "interactive_ttft_p99_s_fifo": _int_p99(fifo),
            "preemptions": klass_arm.get("preemptions"),
            "readmit_suffix_tokens":
                klass_arm.get("readmit_suffix_tokens"),
            "tokens_per_sec_class": klass_arm.get("tokens_per_sec"),
            "tokens_per_sec_fifo":
                fifo.get("tokens_per_sec") if fifo else None,
            "throughput_ratio": (
                round(klass_arm["tokens_per_sec"]
                      / fifo["tokens_per_sec"], 4)
                if fifo and fifo.get("tokens_per_sec")
                and klass_arm.get("tokens_per_sec") else None),
        }
        print(f"class A/B: interactive ttft p99 "
              f"{_int_p99(klass_arm)}s class vs "
              f"{_int_p99(fifo)}s fifo "
              f"(unloaded {_int_p99(unloaded)}s), "
              f"{preempted} preemptions, throughput ratio "
              f"{out['class_ab']['throughput_ratio']}", flush=True)
    path = args.out or os.path.join(REPO, "bench_serve_results.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
    print(f"wrote {path}")
    incomplete = [r["name"] for r in results
                  if r["completed"] < r["streams"]]
    if incomplete:
        print(f"ERROR: arms with failed requests: {incomplete}",
              file=sys.stderr)
        return 1
    if args.check_classes:
        if klass_arm is None:
            print("ERROR: --check-classes needs the class-admission "
                  "arm (drop --no-class-admission)", file=sys.stderr)
            return 1
        failures = []
        unl, cls = _int_p99(unloaded), _int_p99(klass_arm)
        # 1.5x, not parity: landing on a camped-full engine costs an
        # eviction tick plus a slot-drain ramp that the idle baseline
        # never pays. The gate catches admission failure (no
        # preemption → the natural-retirement wall blows well past
        # 1.5x), not the designed overhead.
        if unl is None or cls is None or cls > 1.5 * unl:
            failures.append(
                f"interactive ttft p99 {cls}s > 1.5x unloaded {unl}s "
                "— class admission is not protecting the interactive "
                "stream")
        preempted = (klass_arm.get("preemptions") or {})
        if not preempted.get("best-effort", 0) > 0:
            failures.append(
                f"preemptions {preempted} — no best-effort slot was "
                "evicted under full-slot pressure")
        for row in results:
            if row.get("kv_invariant_violations") not in (0, None):
                failures.append(
                    f"{row['name']}: {row['kv_invariant_violations']} "
                    "page refcount invariant violations")
        if fifo is not None:
            fifo_p99 = _int_p99(fifo)
            if cls is None or fifo_p99 is None or not cls < fifo_p99:
                failures.append(
                    f"interactive ttft p99 {cls}s class vs {fifo_p99}s "
                    "fifo — the policy did not beat the baseline")
            ratio = out["class_ab"]["throughput_ratio"]
            # 0.90, not parity: evictions discard the victim's private
            # tail-page decode work by design; the radix prefix makes
            # re-admission suffix-only, which is what keeps the waste
            # bounded. The gate catches eviction storms, not the
            # designed trade.
            if ratio is None or ratio < 0.90:
                failures.append(
                    f"throughput ratio {ratio} < 0.90 — preemption is "
                    "discarding more decode work than the class win "
                    "justifies")
        if args.streams >= 1000 and not args.quick:
            peak = max(r["streams_in_flight_peak"] for r in results)
            if peak < 1000:
                failures.append(
                    f"streams_in_flight_peak {peak} < 1000 — the load "
                    "harness never reached thousand-stream concurrency")
        if failures:
            for f in failures:
                print(f"ERROR: {f}", file=sys.stderr)
            return 1
        print(f"class check ok: interactive ttft p99 {cls}s "
              f"(unloaded {unl}s), preemptions {preempted}, "
              "invariants clean")
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="llama3_1b")
    parser.add_argument("--clients", type=int, default=8)
    parser.add_argument("--requests", type=int, default=32)
    parser.add_argument("--max-new", type=int, default=64)
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--prompt-len", type=int, default=48)
    parser.add_argument("--workload", default="mixed",
                        choices=["mixed", "shared-prefix",
                                 "conversation-tree",
                                 "long-prompt-storm"],
                        help="prompt mix (see make_prompts); "
                             "long-prompt-storm switches to the lane "
                             "A/B: interleaved vs disaggregated "
                             "prefill/decode under concurrent long "
                             "prefills (see run_lanes)")
    parser.add_argument("--kv-page-size", type=int, default=16)
    parser.add_argument("--configs", default=None,
                        help="comma list to restrict the configs run, "
                             "e.g. 'paged,paged-nocache'")
    parser.add_argument("--draft", default=None,
                        help="also bench continuous speculative with "
                             "this draft model (vocab must match)")
    parser.add_argument("--spec-k", type=int, default=4)
    parser.add_argument("--fleet", type=int, default=0, metavar="N",
                        help="bench a ServingFleet of N replicas "
                             "instead of the single-engine configs: "
                             "prefix-affinity routing vs blind "
                             "round-robin over the same workload "
                             "(docs/serving.md 'Serving fleet')")
    parser.add_argument("--quick", action="store_true",
                        help="tiny load (CPU smoke of the harness)")
    parser.add_argument("--check-prefix", action="store_true",
                        help="CI gate: exit 1 unless the paged config "
                             "saw prefix_hit_rate > 0 with zero "
                             "refcount-invariant violations")
    parser.add_argument("--check-lanes", action="store_true",
                        help="(long-prompt-storm) CI gate: exit 1 "
                             "unless disaggregated decode gap p99 "
                             "stays within 1.15x of interleaved while "
                             "prefill throughput holds >= 0.95x, with "
                             "handoffs > 0 and invariants clean")
    parser.add_argument("--streams", type=int, default=0, metavar="N",
                        help="drive N concurrent mixed-class streams "
                             "through the class-admission A/B instead "
                             "of the config sweep (see run_streams; "
                             "the acceptance run uses N >= 1000)")
    parser.add_argument("--no-class-admission", action="store_true",
                        help="(--streams) run only the FIFO baseline "
                             "arm; the paired A/B runs it "
                             "automatically, this is the standalone "
                             "escape hatch")
    parser.add_argument("--check-classes", action="store_true",
                        help="(--streams) CI gate: exit 1 unless "
                             "interactive TTFT p99 stays within 1.5x "
                             "its unloaded value with best-effort "
                             "preemptions > 0, invariants clean, and "
                             "the FIFO pair beaten (p99 lower, tok/s "
                             ">= 0.9x)")
    parser.add_argument("--inject",
                        choices=["lane-starve", "no-preempt"],
                        default=None,
                        help="red-team arms: lane-starve "
                             "(long-prompt-storm) zeroes the decode "
                             "lane budget; no-preempt (--streams) "
                             "disables eviction so interactive TTFT "
                             "hits the natural-retirement wall — "
                             "either way the run MUST exit 1 (ci.sh "
                             "inverts this)")
    parser.add_argument("--out", default=None,
                        help="result path (default: repo-root "
                             "bench_serve_results.json)")
    args = parser.parse_args()
    if args.quick:
        args.clients, args.requests, args.max_new = 3, 6, 8

    if args.streams:
        return run_streams(args)

    if args.workload == "long-prompt-storm":
        return run_lanes(args)

    import random

    import jax

    rng = random.Random(0)
    prompts = make_prompts(args.workload, args.requests, args.prompt_len,
                           rng)

    if args.fleet:
        results = [run_fleet(args.model, prompts, args.max_new,
                             args.clients, replicas=args.fleet,
                             slots=args.slots,
                             page_size=args.kv_page_size, blind=blind)
                   for blind in (False, True)]
        out = {
            "backend": jax.devices()[0].platform,
            "model": args.model, "workload": args.workload,
            "load": {"clients": args.clients, "requests": args.requests,
                     "max_new": args.max_new, "slots": args.slots,
                     "replicas": args.fleet,
                     "prompt_len": args.prompt_len,
                     "kv_page_size": args.kv_page_size},
            "results": results,
        }
        for r in results:
            print(f"{r['name']}: hit_rate {r['prefix_hit_rate']}, "
                  f"p50 {r['latency_p50_ms']}ms, routed {r['routed']}",
                  flush=True)
        path = args.out or os.path.join(REPO, "bench_serve_results.json")
        with open(path, "w") as fh:
            json.dump(out, fh, indent=2)
        print(f"wrote {path}")
        incomplete = [r["name"] for r in results
                      if r["completed"] < args.requests]
        if incomplete:
            print(f"ERROR: configs with failed requests: {incomplete}",
                  file=sys.stderr)
            return 1
        return 0

    configs = [
        ("dense", dict(slots=args.slots)),
        ("paged", dict(slots=args.slots, kv="paged",
                       page_size=args.kv_page_size)),
        # The A/B baseline: same pool, radix sharing off — every
        # admission recomputes its full prefill.
        ("paged-nocache", dict(slots=args.slots, kv="paged",
                               page_size=args.kv_page_size,
                               prefix_cache=False)),
        ("paged-int8", dict(slots=args.slots, kv="paged",
                            page_size=args.kv_page_size,
                            quantize="int8")),
    ]
    if args.draft:
        # Continuous speculative (r4): ragged per-row acceptance over
        # the slot pool. Greedy-only engine; the drive() load is
        # already greedy (no temperature), so the same workload runs.
        configs.append(("dense-spec", dict(
            slots=args.slots, draft_model=args.draft, spec_k=args.spec_k)))
    if args.configs:
        wanted = {name.strip() for name in args.configs.split(",")}
        unknown = wanted - {name for name, _ in configs}
        if unknown:
            parser.error(f"unknown configs: {sorted(unknown)}")
        configs = [(n, kw) for n, kw in configs if n in wanted]
    results = [run_config(name, args.model, prompts, args.max_new,
                          args.clients, **kw)
               for name, kw in configs]
    by_name = {r["name"]: r for r in results}
    out = {
        "backend": jax.devices()[0].platform,
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        "model": args.model,
        "workload": args.workload,
        "load": {"clients": args.clients, "requests": args.requests,
                 "max_new": args.max_new, "slots": args.slots,
                 "prompt_len": args.prompt_len,
                 "kv_page_size": args.kv_page_size},
        "results": results,
    }
    # The acceptance A/B: radix sharing on vs off, same pool, same
    # workload — skip fraction and the interactive-TTFT dividend.
    cached, nocache = by_name.get("paged"), by_name.get("paged-nocache")
    if cached is not None and nocache is not None:
        # Exact per-request TTFT from the span timelines; the bucketed
        # histogram percentiles ride along in each row's slo_by_class.
        t_on, t_off = cached.get("ttft_p50_ms"), nocache.get("ttft_p50_ms")
        out["prefix_ab"] = {
            "workload": args.workload,
            "prefix_hit_rate": cached.get("prefix_hit_rate"),
            "prefill_tokens_skipped": cached.get("prefill_tokens_skipped"),
            "ttft_p50_ms_cached": t_on,
            "ttft_p50_ms_nocache": t_off,
            "ttft_p50_improvement": (
                round(1.0 - t_on / t_off, 4)
                if t_on is not None and t_off else None),
        }
        print(f"prefix A/B ({args.workload}): hit_rate "
              f"{out['prefix_ab']['prefix_hit_rate']}, ttft p50 "
              f"{t_on}ms cached vs {t_off}ms nocache", flush=True)
    path = args.out or os.path.join(REPO, "bench_serve_results.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
    print(f"wrote {path}")
    incomplete = [r["name"] for r in results
                  if r["completed"] < args.requests]
    if incomplete:
        print(f"ERROR: configs with failed requests: {incomplete} "
              "(see errors in the JSON)", file=sys.stderr)
        return 1
    if args.check_prefix:
        paged = by_name.get("paged")
        if paged is None:
            print("ERROR: --check-prefix needs the 'paged' config",
                  file=sys.stderr)
            return 1
        rate = paged.get("prefix_hit_rate") or 0.0
        violations = paged.get("kv_invariant_violations")
        if not rate > 0:
            print(f"ERROR: prefix_hit_rate {rate} — the radix cache "
                  "served nothing", file=sys.stderr)
            return 1
        if violations != 0:
            print(f"ERROR: {violations} page refcount invariant "
                  "violations after the run", file=sys.stderr)
            return 1
        print(f"prefix check ok: hit_rate {rate}, invariants clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
