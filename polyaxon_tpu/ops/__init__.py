from polyaxon_tpu.ops.attention import dot_product_attention, xla_attention

__all__ = ["dot_product_attention", "xla_attention"]
