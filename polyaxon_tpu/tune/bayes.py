"""Bayesian optimization: Gaussian-process surrogate + UCB/EI/POI
acquisition (SURVEY.md §2 "Polytune" [K]; [B] names Bayesian opt).

Numpy/scipy implementation (both in-env [E]):
- Matern-5/2 (default) or RBF kernel with jittered Cholesky;
- continuous params optimize over their (log-)bounds; discrete params
  (choice/range/...) are sampled and the acquisition picks among them;
- acquisition maximized by dense random search (cheap and robust for
  the <=20-dim spaces Polyaxonfiles declare);
- internally the objective is always *maximized* (minimize flips sign).
"""

from __future__ import annotations

import math
import random
from typing import Any, Optional

import numpy as np
from scipy.stats import norm

from polyaxon_tpu.polyflow.matrix import V1Bayes, V1Optimization
from polyaxon_tpu.tune.base import Observation, Params


def _matern52(dist: np.ndarray, length_scale: float) -> np.ndarray:
    scaled = np.sqrt(5.0) * dist / length_scale
    return (1.0 + scaled + scaled**2 / 3.0) * np.exp(-scaled)


def _rbf(dist: np.ndarray, length_scale: float) -> np.ndarray:
    return np.exp(-0.5 * (dist / length_scale) ** 2)


class GaussianProcess:
    def __init__(self, kernel: str = "matern", length_scale: float = 1.0,
                 noise: float = 1e-6):
        self.kernel = kernel
        self.length_scale = length_scale
        self.noise = noise
        self._x: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._chol: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0

    def _k(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        dist = np.linalg.norm(a[:, None, :] - b[None, :, :], axis=-1)
        fn = _matern52 if self.kernel == "matern" else _rbf
        return fn(dist, self.length_scale)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        self._x = np.asarray(x, dtype=float)
        y = np.asarray(y, dtype=float)
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        yn = (y - self._y_mean) / self._y_std
        k = self._k(self._x, self._x) + self.noise * np.eye(len(yn))
        for jitter in (0.0, 1e-8, 1e-6, 1e-4):
            try:
                self._chol = np.linalg.cholesky(k + jitter * np.eye(len(yn)))
                break
            except np.linalg.LinAlgError:
                continue
        else:
            raise np.linalg.LinAlgError("GP covariance not PD even with jitter")
        self._alpha = np.linalg.solve(
            self._chol.T, np.linalg.solve(self._chol, yn)
        )

    def predict(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        x = np.asarray(x, dtype=float)
        k_star = self._k(x, self._x)
        mean = k_star @ self._alpha
        v = np.linalg.solve(self._chol, k_star.T)
        var = np.clip(1.0 - np.sum(v**2, axis=0), 1e-12, None)
        return mean * self._y_std + self._y_mean, np.sqrt(var) * self._y_std


def acquisition(
    kind: str, mean: np.ndarray, std: np.ndarray, best: float,
    kappa: float = 2.576, eps: float = 0.0,
) -> np.ndarray:
    if kind == "ucb":
        return mean + kappa * std
    if kind == "ei":
        improve = mean - best - eps
        z = improve / std
        return improve * norm.cdf(z) + std * norm.pdf(z)
    if kind == "poi":
        return norm.cdf((mean - best - eps) / std)
    raise ValueError(f"Unknown acquisition `{kind}`")


class BayesManager:
    def __init__(self, config: V1Bayes):
        self.config = config
        self.rng = random.Random(config.seed)
        util = config.utility_function
        gp_cfg = (util.gaussian_process if util and util.gaussian_process else None)
        self.gp = GaussianProcess(
            kernel=(gp_cfg.kernel if gp_cfg else "matern"),
            length_scale=(gp_cfg.length_scale if gp_cfg else 1.0),
        )
        self.acq_kind = util.acquisition_function if util else "ucb"
        self.kappa = (util.kappa if util and util.kappa is not None else 2.576)
        self.eps = (util.eps if util and util.eps is not None else 0.0)
        self._names = list(config.params.keys())
        self._sign = 1.0 if config.metric.optimization == V1Optimization.MAXIMIZE else -1.0

    # -- encoding ----------------------------------------------------------
    def _encode(self, params: Params) -> list[float]:
        vec = []
        for name in self._names:
            hp = self.config.params[name]
            bounds = hp.to_bounds()
            value = params[name]
            if bounds is not None:
                low, high, is_log = bounds
                v = math.log(value) if is_log else float(value)
                span = (high - low) or 1.0
                vec.append((v - low) / span)
            else:
                grid = hp.to_grid()
                vec.append(grid.index(value) / max(len(grid) - 1, 1)
                           if value in grid else 0.5)
        return vec

    def _sample_candidates(self, n: int) -> list[Params]:
        return [
            {name: hp.sample(self.rng) for name, hp in self.config.params.items()}
            for _ in range(n)
        ]

    # -- public API --------------------------------------------------------
    def initial_suggestions(self) -> list[Params]:
        return self._sample_candidates(self.config.num_initial_runs)

    def get_suggestions(
        self, observations: list[Observation], count: int = 1,
        n_candidates: int = 2000,
    ) -> list[Params]:
        usable = [o for o in observations if o.usable]
        if len(usable) < max(2, min(self.config.num_initial_runs, 2)):
            return self._sample_candidates(count)
        x = np.array([self._encode(o.params) for o in usable])
        y = np.array([self._sign * o.metric for o in usable])
        try:
            self.gp.fit(x, y)
        except np.linalg.LinAlgError:
            return self._sample_candidates(count)
        best = float(y.max())
        picked: list[Params] = []
        for _ in range(count):
            candidates = self._sample_candidates(n_candidates)
            cx = np.array([self._encode(c) for c in candidates])
            mean, std = self.gp.predict(cx)
            scores = acquisition(self.acq_kind, mean, std, best,
                                 kappa=self.kappa, eps=self.eps)
            order = np.argsort(-scores)
            for idx in order:
                cand = candidates[int(idx)]
                if cand not in picked and all(cand != o.params for o in usable):
                    picked.append(cand)
                    break
            else:
                picked.append(candidates[int(order[0])])
        return picked

    def is_done(self, observations: list[Observation]) -> bool:
        finished = len([o for o in observations if o.status != "preempted"])
        return finished >= self.config.num_initial_runs + self.config.max_iterations
