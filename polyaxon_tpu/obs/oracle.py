"""Telemetry oracle (ISSUE 13 tentpole): declarative end-state
invariants over everything the repo can already measure.

The observability stack collects four surfaces — lifecycle span
timelines (``obs.trace.build_timeline``), attribution reports
(``obs.analyze``), the metrics-registry snapshot (``obs.metrics``),
and alert state + firing history (``obs.rules``) — but until now
nothing *consumed* them as a verification plane. This module closes
that loop (ROADMAP item 6: "the observability layer becomes the test
oracle"): a committed invariant set (``obs/oracle.json``, schema-gated
exactly like ``rules.json`` — load time IS the gate) is evaluated
against a :class:`TelemetryBundle` of those surfaces and produces
structured verdicts ``{invariant, verdict, evidence}`` with the
offending run/span/series/alert attached.

Invariant kinds:

- ``run_terminal``     — end-state predicates over runs: every run must
  sit in an allowed terminal status; ``forbid`` pins statuses that must
  never survive to the end (a stuck QUEUED run, a parked PREEMPTED one).
- ``phase_budget``     — a run report's phase decomposition must sum to
  its wall clock within ``tolerance`` (the "phases explain the time"
  contract the attribution plane promises).
- ``metric``           — threshold predicates over the registry
  snapshot with label selectors: instantaneous values, baseline deltas
  (``mode: "delta"`` against the bundle's baseline snapshot), or
  interpolated histogram quantiles (``quantile``).
- ``loss_continuity``  — step-window continuity across restore/resize
  boundaries, read from the ``step`` spans: step indices never skip
  forward past ``max_gap_steps``, never regress between windows, and
  (when windows carry a ``loss``) the loss never jumps more than
  ``max_loss_jump`` across a boundary.
- ``alerts_resolved``  — zero unresolved alerts at end: no rule may
  still be firing (``allow`` whitelists rule ids that may).
- ``slo``              — per-class SLO adherence from histogram
  buckets: ``objective`` of observations ≤ the ``le`` bound, per label
  selector (Prometheus SLI semantics, but as an acceptance check).
- ``metric_during``    — a threshold predicate scoped in *time*: the
  value (gauge worst-instant via ``agg``, counter in-window movement,
  or histogram in-window ``quantile``) judged over a named history
  window (``window: "storm"``) or a trailing ``span``, read from the
  bundle's metrics history (``obs.history``).
- ``slo_during``       — the ``slo`` bucket-ratio check over only the
  observations that landed inside the named window / trailing span
  (bucket-wise difference of carry-forward history samples).
- ``quota_violation``  — no sampled instant shows a project over its
  quota: every ``polyaxon_project_usage`` point is compared against
  the carry-forward ``polyaxon_project_quota_limit`` for the same
  (project, resource) series; a limit of 0 means unlimited.

Missing telemetry is handled per invariant via ``missing``: ``skip``
(default — verdict ``skip`` with the reason as evidence), ``fail``
(absence is itself a failure), or ``zero`` (an absent series reads as
0 — right for "this error counter never moved" invariants).

Surfaces: ``python -m polyaxon_tpu.obs.oracle --check`` (the ci.sh
schema gate), ``plx ops verify [--json]``, ``GET .../runs/{uuid}/
verify`` (ControlPlane.verify), and the fleet-sim mini-gauntlet +
incident replay (``sim/gauntlet.py``, ``sim/replay.py``) whose pass
criteria are *only* these verdicts.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from polyaxon_tpu.obs import history as obs_history
from polyaxon_tpu.obs import metrics as obs_metrics

DEFAULT_ORACLE_PATH = os.path.join(os.path.dirname(__file__), "oracle.json")

KINDS = ("run_terminal", "phase_budget", "metric", "loss_continuity",
         "alerts_resolved", "slo", "metric_during", "slo_during",
         "quota_violation")
WINDOW_AGGS = ("max", "min", "last")
MISSING_POLICIES = ("skip", "fail", "zero")
EVIDENCE_CAP = 16  # offending items attached per verdict, not a census

_OPS: dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
}


class OracleError(ValueError):
    """An invariant spec that must not ship: CI's schema gate raises
    this (the ``rules.RuleError`` posture)."""


@dataclass
class Invariant:
    id: str
    kind: str
    description: str = ""
    missing: str = "skip"
    # run_terminal
    allow: list[str] = field(default_factory=list)
    forbid: list[str] = field(default_factory=list)
    # phase_budget
    tolerance: float = 0.35
    # metric / slo
    metric: Optional[str] = None
    op: str = "<="
    value: Optional[float] = None
    quantile: Optional[float] = None
    labels: dict[str, str] = field(default_factory=dict)
    mode: str = "value"  # value | delta
    le: Optional[float] = None
    objective: Optional[float] = None
    # loss_continuity
    max_gap_steps: int = 0
    max_loss_jump: Optional[float] = None
    # metric_during / slo_during (window-scoped judgments)
    window: Optional[str] = None   # named history window, e.g. "storm"
    span: Optional[float] = None   # trailing seconds before coverage end
    agg: str = "max"               # gauge aggregation inside the window

    @classmethod
    def from_dict(cls, data: dict) -> "Invariant":
        if not isinstance(data, dict):
            raise OracleError(
                f"invariant must be an object, got {type(data).__name__}")
        inv_id = data.get("id")
        if not inv_id or not isinstance(inv_id, str):
            raise OracleError(f"invariant needs a string `id`, got {inv_id!r}")
        kind = data.get("kind")
        if kind not in KINDS:
            raise OracleError(f"invariant {inv_id}: unknown kind {kind!r} "
                              f"(one of {KINDS})")
        missing = data.get("missing", "skip")
        if missing not in MISSING_POLICIES:
            raise OracleError(
                f"invariant {inv_id}: missing policy must be one of "
                f"{MISSING_POLICIES}, got {missing!r}")
        op = data.get("op", "<=")
        if op not in _OPS:
            raise OracleError(f"invariant {inv_id}: unknown op {op!r} "
                              f"(one of {sorted(_OPS)})")
        metric = data.get("metric")
        quantile = data.get("quantile")
        if quantile is not None and not 0.0 <= float(quantile) <= 1.0:
            raise OracleError(f"invariant {inv_id}: quantile {quantile!r} "
                              "outside [0, 1]")
        mode = data.get("mode", "value")
        window = data.get("window")
        span = data.get("span")
        if window is not None and (not isinstance(window, str) or not window):
            raise OracleError(f"invariant {inv_id}: `window` must be a "
                              f"non-empty window name, got {window!r}")
        if span is not None:
            from polyaxon_tpu.obs import rules as obs_rules
            try:
                span = obs_rules.parse_window(span, field_name="span")
            except obs_rules.RuleError as exc:
                raise OracleError(f"invariant {inv_id}: {exc}") from exc
        agg = data.get("agg", "max")
        if agg not in WINDOW_AGGS:
            raise OracleError(f"invariant {inv_id}: agg must be one of "
                              f"{WINDOW_AGGS}, got {agg!r}")
        if kind in ("metric_during", "slo_during"):
            if (window is None) == (span is None):
                raise OracleError(
                    f"invariant {inv_id}: {kind} needs exactly one of "
                    "`window` (a named marker) or `span` (a trailing "
                    "duration)")
        elif window is not None or span is not None:
            raise OracleError(
                f"invariant {inv_id}: `window`/`span` only apply to "
                "metric_during|slo_during")
        if kind == "metric":
            if not metric or not isinstance(metric, str):
                raise OracleError(f"invariant {inv_id}: metric kind needs "
                                  "a `metric` name")
            if data.get("value") is None:
                raise OracleError(f"invariant {inv_id}: metric kind needs "
                                  "a `value` to compare against")
            if mode not in ("value", "delta"):
                raise OracleError(f"invariant {inv_id}: mode must be "
                                  f"value|delta, got {mode!r}")
            if mode == "delta" and quantile is not None:
                raise OracleError(f"invariant {inv_id}: quantile predicates "
                                  "only run on absolute snapshots "
                                  "(mode: value)")
        elif kind == "metric_during":
            if not metric or not isinstance(metric, str):
                raise OracleError(f"invariant {inv_id}: metric_during "
                                  "kind needs a `metric` name")
            if data.get("value") is None:
                raise OracleError(f"invariant {inv_id}: metric_during "
                                  "kind needs a `value` to compare against")
        elif kind in ("slo", "slo_during"):
            if not metric or not isinstance(metric, str):
                raise OracleError(f"invariant {inv_id}: {kind} kind needs "
                                  "a `metric` name")
            le = data.get("le")
            objective = data.get("objective")
            if le is None or objective is None:
                raise OracleError(f"invariant {inv_id}: {kind} needs `le` "
                                  "and `objective`")
            if not 0.0 < float(objective) <= 1.0:
                raise OracleError(f"invariant {inv_id}: objective "
                                  f"{objective!r} must be in (0, 1]")
        elif kind == "phase_budget":
            tolerance = float(data.get("tolerance", 0.35))
            if tolerance <= 0:
                raise OracleError(f"invariant {inv_id}: tolerance must be "
                                  f"> 0, got {tolerance!r}")
        elif kind == "loss_continuity":
            if int(data.get("max_gap_steps", 0)) < 0:
                raise OracleError(f"invariant {inv_id}: max_gap_steps "
                                  "must be >= 0")
        elif kind == "run_terminal":
            from polyaxon_tpu.lifecycle import V1Statuses

            known = {s.value for s in V1Statuses}
            for key in ("allow", "forbid"):
                vals = data.get(key) or []
                if not isinstance(vals, list):
                    raise OracleError(f"invariant {inv_id}: `{key}` must "
                                      "be a list of statuses")
                unknown = [v for v in vals if v not in known]
                if unknown:
                    raise OracleError(f"invariant {inv_id}: unknown "
                                      f"statuses in `{key}`: {unknown}")
        return cls(
            id=inv_id, kind=kind,
            description=str(data.get("description") or ""),
            missing=missing,
            allow=[str(v) for v in (data.get("allow") or [])],
            forbid=[str(v) for v in (data.get("forbid") or [])],
            tolerance=float(data.get("tolerance", 0.35)),
            metric=metric, op=op,
            value=(float(data["value"]) if data.get("value") is not None
                   else None),
            quantile=float(quantile) if quantile is not None else None,
            labels={str(k): str(v)
                    for k, v in (data.get("labels") or {}).items()},
            mode=mode,
            le=float(data["le"]) if data.get("le") is not None else None,
            objective=(float(data["objective"])
                       if data.get("objective") is not None else None),
            max_gap_steps=int(data.get("max_gap_steps", 0)),
            max_loss_jump=(float(data["max_loss_jump"])
                           if data.get("max_loss_jump") is not None
                           else None),
            window=window, span=span, agg=agg,
        )


def load_invariants(source: Any = None) -> list[Invariant]:
    """Invariants from a dict, a JSON file path, or the committed
    default (``obs/oracle.json``). Duplicate ids and unknown metric
    names raise :class:`OracleError` here — load time IS the schema
    gate, same posture as ``rules.load_ruleset``."""
    if source is None:
        source = DEFAULT_ORACLE_PATH
    if isinstance(source, str):
        with open(source) as fh:
            source = json.load(fh)
    if not isinstance(source, dict) or not isinstance(
            source.get("invariants"), list):
        raise OracleError("oracle set must be {\"invariants\": [...]}")
    invariants = [Invariant.from_dict(i) for i in source["invariants"]]
    seen: set[str] = set()
    for inv in invariants:
        if inv.id in seen:
            raise OracleError(f"duplicate invariant id {inv.id!r}")
        seen.add(inv.id)
    known = obs_metrics.catalog_metric_names()
    for inv in invariants:
        if inv.metric is not None and inv.metric not in known:
            raise OracleError(
                f"invariant {inv.id}: unknown metric {inv.metric!r} "
                f"(known: {sorted(known)})")
    return invariants


# ------------------------------------------------------------ the bundle
@dataclass
class TelemetryBundle:
    """Everything one oracle evaluation sees, as plain data — so the
    same engine judges a live control plane, a sim gauntlet, and a
    replayed incident without caring where the telemetry came from.

    ``runs`` rows carry at least ``uuid``/``status``; ``reports`` maps
    run uuid → ``obs.analyze.analyze_timeline`` output; ``snapshot``/
    ``baseline`` are ``MetricsRegistry.snapshot()`` dicts; ``alerts``
    is ``AlertEngine.to_json()`` (alerts / rules / history); ``history``
    is ``MetricsHistory.to_json()`` — the time-series surface the
    ``*_during`` and ``quota_violation`` kinds judge."""

    runs: list[dict] = field(default_factory=list)
    timelines: dict[str, dict] = field(default_factory=dict)
    reports: dict[str, dict] = field(default_factory=dict)
    snapshot: Optional[dict] = None
    baseline: Optional[dict] = None
    alerts: Optional[dict] = None
    history: Optional[dict] = None

    def deltas(self) -> Optional[dict]:
        """Changed-series registry movement vs the baseline (None when
        either snapshot is absent — delta invariants then follow their
        ``missing`` policy)."""
        if self.snapshot is None or self.baseline is None:
            return None
        return obs_metrics.snapshot_delta(self.snapshot, self.baseline)

    @classmethod
    def from_plane(cls, plane, *, run_uuid: Optional[str] = None,
                   engine=None, baseline: Optional[dict] = None,
                   registry: Optional[obs_metrics.MetricsRegistry] = None,
                   max_timelines: int = 64) -> "TelemetryBundle":
        """Gather the four surfaces from a live ``ControlPlane``.
        ``run_uuid`` scopes the run surface to one run (the per-run
        ``GET .../verify`` shape); timelines/reports attach for up to
        ``max_timelines`` runs that actually persisted spans."""
        from polyaxon_tpu.obs import rules as obs_rules
        from polyaxon_tpu.obs.analyze import analyze_timeline
        from polyaxon_tpu.obs.trace import build_timeline, read_trace

        registry = registry if registry is not None else obs_metrics.REGISTRY
        if run_uuid is not None:
            records = [plane.get_run(run_uuid)]
        else:
            records = plane.list_runs(limit=100000)
        runs = [{
            "uuid": r.uuid,
            "status": r.status.value,
            "kind": r.kind,
            "project": r.project,
            "name": r.name,
        } for r in records]
        timelines: dict[str, dict] = {}
        reports: dict[str, dict] = {}
        for record in records:
            if len(timelines) >= max_timelines:
                break
            if record.kind in ("matrix", "dag", "schedule"):
                continue  # pipeline shells have no execution spans
            run_dir = plane.run_artifacts_dir(record.uuid)
            span_records = read_trace(run_dir)
            if not span_records:
                continue
            timeline = build_timeline(span_records, trace_id=record.uuid)
            timelines[record.uuid] = timeline
            reports[record.uuid] = analyze_timeline(timeline)
        if engine is None:
            engine = obs_rules.default_engine()
        hist = obs_history.history_for(registry)
        hist.sample(force=True)  # coverage end = bundle time
        return cls(runs=runs, timelines=timelines, reports=reports,
                   snapshot=registry.snapshot(), baseline=baseline,
                   alerts=engine.to_json(), history=hist.to_json())


# --------------------------------------------------------- snapshot math
def _select_series(family: dict, labels: dict[str, str]) -> Optional[Any]:
    """One series sample from a snapshot family by label selector
    (None = no such series). The selector subset-matches: dimensions
    it does not name — the fleet's hidden ``component`` dimension
    above all — are wildcards, and multiple matches merge into the
    federated sample (counters sum, gauges max, histogram buckets
    merge). Empty selector on a labeled family sums
    scalars / returns None for histograms (ambiguous)."""
    series = family.get("series") or {}
    labelnames = family.get("labels") or []
    if labels:
        matched = [v for k, v in series.items()
                   if obs_metrics.match_series(labelnames, k, labels)]
        if not matched:
            return None
        if len(matched) == 1:
            return matched[0]
        return obs_metrics.merge_snap_samples(
            family.get("type") or "", matched)
    if not labelnames:
        return series.get("")
    scalars = [v for v in series.values() if not isinstance(v, dict)]
    if scalars:
        return max(float(v) for v in scalars)
    return None


def _snapshot_quantile(sample: dict, q: float) -> Optional[float]:
    """``Histogram.quantile`` semantics over a *snapshot* bucket dict
    (bound-string → per-bucket count): linear interpolation within the
    landing bucket, +Inf clamped to the largest finite bound."""
    count = int(sample.get("count") or 0)
    if count == 0:
        return None
    bounds: list[float] = []
    counts: list[int] = []
    for bound, n in sample["buckets"].items():
        bounds.append(math.inf if bound == "+Inf" else float(bound))
        counts.append(int(n))
    rank = q * count
    cumulative = 0
    finite = [b for b in bounds if b != math.inf]
    for i, n in enumerate(counts):
        prev = cumulative
        cumulative += n
        if n and cumulative >= rank:
            if bounds[i] == math.inf:
                return finite[-1] if finite else None
            hi = bounds[i]
            lo = bounds[i - 1] if i > 0 else 0.0
            return lo + (hi - lo) * max(rank - prev, 0.0) / n
    return finite[-1] if finite else None


def _slo_counts(family: dict, le: float,
                labels: dict[str, str]) -> Optional[tuple[float, float]]:
    """(good, total) across the selected histogram series; None when
    the family has no matching samples or ``le`` is not a bucket
    bound."""
    series = family.get("series") or {}
    labelnames = family.get("labels") or []
    if labels:
        samples = [v for k, v in series.items()
                   if obs_metrics.match_series(labelnames, k, labels)]
    else:
        samples = list(series.values())
    good = total = 0.0
    seen = False
    for sample in samples:
        if not isinstance(sample, dict):
            continue
        matched = False
        cumulative = 0
        for bound, n in sample["buckets"].items():
            cumulative += int(n)
            if bound != "+Inf" and abs(float(bound) - le) < 1e-12:
                good += cumulative
                matched = True
                break
        if not matched:
            return None  # le is not a bound of this layout: spec bug
        seen = True
        total += int(sample.get("count") or 0)
    return (good, total) if seen else None


# ------------------------------------------------------------ evaluation
def _verdict(inv: Invariant, verdict: str, evidence: dict) -> dict:
    return {
        "invariant": inv.id,
        "kind": inv.kind,
        "verdict": verdict,
        "description": inv.description,
        "evidence": evidence,
    }


def _missing(inv: Invariant, reason: str) -> dict:
    if inv.missing == "fail":
        return _verdict(inv, "fail", {"missing": reason})
    return _verdict(inv, "skip", {"missing": reason})


def _eval_run_terminal(inv: Invariant, bundle: TelemetryBundle) -> dict:
    from polyaxon_tpu.lifecycle import DONE_STATUSES

    if not bundle.runs:
        return _missing(inv, "no runs in bundle")
    allowed = set(inv.allow) or {s.value for s in DONE_STATUSES}
    forbidden = set(inv.forbid)
    offenders = []
    counts: dict[str, int] = {}
    for run in bundle.runs:
        status = run.get("status")
        counts[status] = counts.get(status, 0) + 1
        if status in forbidden or status not in allowed:
            offenders.append({k: run.get(k)
                              for k in ("uuid", "status", "kind", "project")})
    evidence = {"runs": len(bundle.runs), "status_counts": counts}
    if offenders:
        evidence["offending_runs"] = offenders[:EVIDENCE_CAP]
        evidence["offending_total"] = len(offenders)
        return _verdict(inv, "fail", evidence)
    return _verdict(inv, "pass", evidence)


def _eval_phase_budget(inv: Invariant, bundle: TelemetryBundle) -> dict:
    judged = 0
    offenders = []
    for uuid, report in bundle.reports.items():
        wall = float(report.get("wall_clock_ms") or 0.0)
        phase_sum = float(report.get("phase_sum_ms") or 0.0)
        if wall <= 0 or not report.get("phases"):
            continue
        judged += 1
        ratio = phase_sum / wall
        if abs(ratio - 1.0) > inv.tolerance:
            offenders.append({
                "run_uuid": uuid,
                "wall_clock_ms": wall,
                "phase_sum_ms": phase_sum,
                "ratio": round(ratio, 4),
            })
    if not judged:
        return _missing(inv, "no attributable reports in bundle")
    evidence = {"reports_judged": judged, "tolerance": inv.tolerance}
    if offenders:
        evidence["offending_reports"] = offenders[:EVIDENCE_CAP]
        return _verdict(inv, "fail", evidence)
    return _verdict(inv, "pass", evidence)


def _eval_metric(inv: Invariant, bundle: TelemetryBundle) -> dict:
    if inv.mode == "delta":
        deltas = bundle.deltas()
        if deltas is None:
            return _missing(inv, "no baseline snapshot for delta mode")
        family = (deltas.get("deltas") or {}).get(inv.metric)
        if family is None:
            # No movement at all: a delta of zero, by construction.
            observed: Optional[float] = 0.0
        else:
            sample = _select_series(family, inv.labels)
            if isinstance(sample, dict):
                observed = float(sample.get("count") or 0)
            elif sample is None:
                observed = 0.0
            else:
                observed = float(sample)
    else:
        if bundle.snapshot is None:
            return _missing(inv, "no registry snapshot in bundle")
        family = bundle.snapshot.get(inv.metric)
        if family is None:
            if inv.missing == "zero":
                observed = 0.0
            else:
                return _missing(inv, f"metric {inv.metric} not in snapshot")
        else:
            sample = _select_series(family, inv.labels)
            if sample is None:
                if inv.missing == "zero":
                    observed = 0.0
                else:
                    return _missing(
                        inv, f"no series matches labels {inv.labels}")
            elif isinstance(sample, dict):
                if inv.quantile is not None:
                    observed = _snapshot_quantile(sample, inv.quantile)
                    if observed is None:
                        return _missing(inv, "histogram has no samples")
                else:
                    observed = float(sample.get("count") or 0)
            else:
                observed = float(sample)
    holds = _OPS[inv.op](observed, inv.value)
    evidence = {
        "metric": inv.metric,
        "labels": inv.labels or None,
        "mode": inv.mode,
        **({"quantile": inv.quantile} if inv.quantile is not None else {}),
        "observed": round(observed, 6),
        "op": inv.op,
        "value": inv.value,
    }
    return _verdict(inv, "pass" if holds else "fail", evidence)


def _eval_loss_continuity(inv: Invariant, bundle: TelemetryBundle) -> dict:
    judged = 0
    offenders = []
    for uuid, report in bundle.reports.items():
        windows = (report.get("steps") or {}).get("windows") or []
        windows = [w for w in windows
                   if w.get("from_step") is not None
                   and w.get("to_step") is not None]
        if len(windows) < 2:
            continue
        judged += 1
        restores = ((report.get("phases") or {}).get("restore")
                    or {}).get("count", 0)
        for prev, nxt in zip(windows, windows[1:]):
            gap = int(nxt["from_step"]) - int(prev["to_step"]) - 1
            problem = None
            if gap > inv.max_gap_steps:
                problem = f"skipped {gap} step(s)"
            elif int(nxt["from_step"]) < int(prev["from_step"]):
                problem = "step window regressed"
            elif (inv.max_loss_jump is not None
                  and prev.get("loss") is not None
                  and nxt.get("loss") is not None
                  and abs(float(nxt["loss"]) - float(prev["loss"]))
                  > inv.max_loss_jump):
                problem = (f"loss jumped "
                           f"{abs(float(nxt['loss']) - float(prev['loss'])):.4f}")
            if problem:
                offenders.append({
                    "run_uuid": uuid,
                    "problem": problem,
                    "window": {k: prev.get(k)
                               for k in ("from_step", "to_step", "loss")},
                    "next_window": {k: nxt.get(k)
                                    for k in ("from_step", "to_step", "loss")},
                    "restores": restores,
                })
    if not judged:
        return _missing(inv, "no run has >= 2 step windows")
    evidence = {"runs_judged": judged, "max_gap_steps": inv.max_gap_steps}
    if offenders:
        evidence["discontinuities"] = offenders[:EVIDENCE_CAP]
        return _verdict(inv, "fail", evidence)
    return _verdict(inv, "pass", evidence)


def _eval_alerts_resolved(inv: Invariant, bundle: TelemetryBundle) -> dict:
    if bundle.alerts is None:
        return _missing(inv, "no alert state in bundle")
    allowed = set(inv.allow)
    firing = [a for a in (bundle.alerts.get("alerts") or [])
              if a.get("rule") not in allowed]
    history = bundle.alerts.get("history") or []
    evidence = {
        "history_events": len(history),
        "fired_total": sum(1 for e in history if e.get("event") == "fired"),
        "resolved_total": sum(1 for e in history
                              if e.get("event") == "resolved"),
    }
    if firing:
        evidence["unresolved_alerts"] = firing[:EVIDENCE_CAP]
        return _verdict(inv, "fail", evidence)
    return _verdict(inv, "pass", evidence)


def _eval_slo(inv: Invariant, bundle: TelemetryBundle) -> dict:
    if bundle.snapshot is None:
        return _missing(inv, "no registry snapshot in bundle")
    family = bundle.snapshot.get(inv.metric)
    if family is None or family.get("type") != "histogram":
        return _missing(inv, f"no histogram {inv.metric} in snapshot")
    counts = _slo_counts(family, inv.le, inv.labels)
    if counts is None:
        return _missing(
            inv, f"le={inv.le} is not a bucket bound of {inv.metric}")
    good, total = counts
    if total <= 0:
        return _missing(inv, "histogram has no observations")
    ratio = good / total
    evidence = {
        "metric": inv.metric,
        "labels": inv.labels or None,
        "le": inv.le,
        "objective": inv.objective,
        "good": int(good),
        "total": int(total),
        "ratio": round(ratio, 6),
    }
    return _verdict(inv, "pass" if ratio >= inv.objective else "fail",
                    evidence)


def _window_scope(inv: Invariant,
                  hist: dict) -> tuple[Optional[tuple[float, float]], str]:
    """The (start, end) seconds an invariant judges, or (None, reason)."""
    if inv.window is not None:
        bounds = obs_history.window_bounds(hist, inv.window)
        if bounds is None:
            return None, f"no window {inv.window!r} marked in history"
        return bounds, ""
    bounds = obs_history.trailing_bounds(hist, inv.span)
    if bounds is None:
        return None, "history has no sample coverage"
    return bounds, ""


def _scope_evidence(inv: Invariant, start: float, end: float) -> dict:
    scope = ({"window": inv.window} if inv.window is not None
             else {"span": inv.span})
    scope["start"] = round(start, 3)
    scope["end"] = round(end, 3)
    return scope


def _eval_metric_during(inv: Invariant, bundle: TelemetryBundle) -> dict:
    if bundle.history is None:
        return _missing(inv, "no metrics history in bundle")
    bounds, reason = _window_scope(inv, bundle.history)
    if bounds is None:
        return _missing(inv, reason)
    start, end = bounds
    selected = obs_history.select_series_points(
        bundle.history, inv.metric, inv.labels)
    family = (bundle.history.get("series") or {}).get(inv.metric) or {}
    kind = family.get("type")
    observed: Optional[float] = None
    if selected:
        if kind == "histogram":
            merged: Optional[dict] = None
            for pts in selected.values():
                sample = obs_history.windowed_hist_sample(pts, start, end)
                if sample is None:
                    continue
                if merged is None:
                    merged = {"count": 0, "sum": 0.0,
                              "buckets": {b: 0 for b in sample["buckets"]}}
                merged["count"] += sample["count"]
                merged["sum"] += sample["sum"]
                for b, n in sample["buckets"].items():
                    merged["buckets"][b] = merged["buckets"].get(b, 0) + n
            if merged is not None:
                if inv.quantile is not None:
                    observed = _snapshot_quantile(merged, inv.quantile)
                else:
                    observed = float(merged["count"])
        elif kind == "counter":
            deltas = [obs_history.windowed_counter_delta(pts, start, end)
                      for pts in selected.values()]
            deltas = [d for d in deltas if d is not None]
            if deltas:
                observed = sum(deltas)
        else:  # gauge: worst/best/final instant, per `agg`
            extents = [obs_history.windowed_gauge_extent(
                pts, start, end, agg=inv.agg)
                for pts in selected.values()]
            extents = [e for e in extents if e is not None]
            if extents:
                observed = {"min": min, "max": max}.get(
                    inv.agg, max)(extents)
    if observed is None:
        if inv.missing == "zero":
            observed = 0.0
        else:
            return _missing(
                inv, f"no sampled points for {inv.metric} "
                     f"(labels {inv.labels or {}}) inside the window")
    holds = _OPS[inv.op](observed, inv.value)
    evidence = {
        "metric": inv.metric,
        "labels": inv.labels or None,
        "scope": _scope_evidence(inv, start, end),
        **({"quantile": inv.quantile} if inv.quantile is not None else {}),
        **({"agg": inv.agg} if kind == "gauge" else {}),
        "observed": round(observed, 6),
        "op": inv.op,
        "value": inv.value,
    }
    return _verdict(inv, "pass" if holds else "fail", evidence)


def _eval_slo_during(inv: Invariant, bundle: TelemetryBundle) -> dict:
    if bundle.history is None:
        return _missing(inv, "no metrics history in bundle")
    bounds, reason = _window_scope(inv, bundle.history)
    if bounds is None:
        return _missing(inv, reason)
    start, end = bounds
    family = (bundle.history.get("series") or {}).get(inv.metric)
    if not family or family.get("type") != "histogram":
        return _missing(inv, f"no histogram {inv.metric} in history")
    selected = obs_history.select_series_points(
        bundle.history, inv.metric, inv.labels)
    if not selected:
        return _missing(inv, f"no series matches labels {inv.labels}")
    good = total = 0.0
    for pts in selected.values():
        sample = obs_history.windowed_hist_sample(pts, start, end)
        if sample is None:
            continue
        counts = obs_history.sample_slo_counts(sample, inv.le)
        if counts is None:
            return _missing(
                inv, f"le={inv.le} is not a bucket bound of {inv.metric}")
        good += counts[0]
        total += counts[1]
    if total <= 0:
        return _missing(inv, "no observations inside the window")
    ratio = good / total
    evidence = {
        "metric": inv.metric,
        "labels": inv.labels or None,
        "scope": _scope_evidence(inv, start, end),
        "le": inv.le,
        "objective": inv.objective,
        "good": int(good),
        "total": int(total),
        "ratio": round(ratio, 6),
    }
    return _verdict(inv, "pass" if ratio >= inv.objective else "fail",
                    evidence)


def _eval_quota_violation(inv: Invariant, bundle: TelemetryBundle) -> dict:
    """No sampled instant may show a project over its quota: every
    usage point is compared against the carry-forward limit for the
    same (project, resource) series. Limit <= 0 (or never sampled)
    means unlimited — admission semantics."""
    if bundle.history is None:
        return _missing(inv, "no metrics history in bundle")
    series = bundle.history.get("series") or {}
    usage = (series.get("polyaxon_project_usage") or {}).get("series") or {}
    limits = ((series.get("polyaxon_project_quota_limit") or {})
              .get("series") or {})
    if not usage:
        return _missing(inv, "no project-usage samples in history")
    breaches = []
    instants = 0
    for key, points in usage.items():
        limit_points = limits.get(key) or []
        for t, used in points:
            if isinstance(used, dict):
                continue
            instants += 1
            limit = obs_history.value_at(limit_points, t)
            if limit is None or float(limit) <= 0:
                continue
            if float(used) > float(limit) + 1e-9:
                breaches.append({
                    "series": key,
                    "at": round(float(t), 3),
                    "used": float(used),
                    "limit": float(limit),
                })
    evidence = {"series_checked": len(usage),
                "instants_checked": instants}
    if breaches:
        evidence["breaches"] = breaches[:EVIDENCE_CAP]
        evidence["breach_total"] = len(breaches)
        return _verdict(inv, "fail", evidence)
    return _verdict(inv, "pass", evidence)


_EVALUATORS = {
    "run_terminal": _eval_run_terminal,
    "phase_budget": _eval_phase_budget,
    "metric": _eval_metric,
    "loss_continuity": _eval_loss_continuity,
    "alerts_resolved": _eval_alerts_resolved,
    "slo": _eval_slo,
    "metric_during": _eval_metric_during,
    "slo_during": _eval_slo_during,
    "quota_violation": _eval_quota_violation,
}


def evaluate(invariants: list[Invariant],
             bundle: TelemetryBundle) -> list[dict]:
    """One pass: every invariant judged against the bundle. Pure —
    the verdict-count metric is the only side effect."""
    verdicts = [_EVALUATORS[inv.kind](inv, bundle) for inv in invariants]
    for verdict in verdicts:
        obs_metrics.oracle_verdicts_total().inc(verdict=verdict["verdict"])
    return verdicts


def summarize(verdicts: list[dict]) -> dict:
    counts = {"pass": 0, "fail": 0, "skip": 0}
    for v in verdicts:
        counts[v["verdict"]] = counts.get(v["verdict"], 0) + 1
    return {
        "passed": counts["fail"] == 0,
        "counts": counts,
        "verdicts": verdicts,
    }


def verify_plane(plane, *, run_uuid: Optional[str] = None,
                 source: Any = None, engine=None,
                 baseline: Optional[dict] = None) -> dict:
    """Evaluate the committed invariant set (or ``source``) against a
    live control plane — the engine behind ``plx ops verify`` and
    ``GET .../runs/{uuid}/verify``. Alert rules are evaluated first so
    the alert surface reflects *now*, not the last reconcile pass."""
    from polyaxon_tpu.obs import rules as obs_rules

    invariants = load_invariants(source)
    if engine is None:
        engine = obs_rules.default_engine()
    engine.evaluate(plane=plane)
    bundle = TelemetryBundle.from_plane(plane, run_uuid=run_uuid,
                                        engine=engine, baseline=baseline)
    result = summarize(evaluate(invariants, bundle))
    if run_uuid is not None:
        result["run_uuid"] = run_uuid
    return result


# ----------------------------------------------------------- schema gate
def check_invariants(path: Optional[str] = None) -> list[Invariant]:
    """CI entry: load (and thereby fully validate) an invariant file."""
    return load_invariants(path or DEFAULT_ORACLE_PATH)


def _main(argv: Optional[list[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="Validate a telemetry-oracle invariant set "
                    "(scripts/ci.sh oracle stage)")
    parser.add_argument("--check", action="store_true", required=True)
    parser.add_argument("path", nargs="?", default=DEFAULT_ORACLE_PATH)
    args = parser.parse_args(argv)
    try:
        invariants = check_invariants(args.path)
    except (OracleError, OSError, json.JSONDecodeError) as exc:
        print(f"ORACLE INVALID: {exc}")
        return 1
    kinds = sorted({inv.kind for inv in invariants})
    print(f"oracle ok: {len(invariants)} invariant(s) in {args.path} "
          f"(kinds: {', '.join(kinds)})")
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via ci.sh
    raise SystemExit(_main())
