"""Native slice daemon (C++ pool via ctypes): ICI-aware gang placement,
liveness, preemption, restart policy — the operator-equivalent layer
(SURVEY.md §2a; upstream tests its Go operator with envtest, here the
pool is driven directly in-process)."""

import subprocess

import pytest

from polyaxon_tpu.native import SlicePool, SlicedError, ensure_built


@pytest.fixture(scope="module")
def built():
    return ensure_built()


@pytest.fixture()
def pool(built):
    with SlicePool() as p:
        yield p


class TestPlacement:
    def test_simple_placement(self, pool):
        pool.add_slice("a", "4x4")
        gid = pool.request_gang("run-1", "2x2")
        gang = pool.gang(gid)
        assert gang.state == "running"
        assert gang.slice == "a"
        assert len(gang.chips) == 4
        assert pool.free_chips("a") == 12
        events = pool.tick(0.0)
        assert any(e.kind == "PLACED" and e.gang_id == gid for e in events)

    def test_contiguous_sub_torus(self, pool):
        """A 2x2 gang on a 4x4 torus must be a real sub-torus: chip rows
        adjacent (wraparound allowed), not scattered."""
        pool.add_slice("a", "4x4")
        gid = pool.request_gang("r", "2x2")
        chips = pool.gang(gid).chips
        rows = sorted({c // 4 for c in chips})
        cols = sorted({c % 4 for c in chips})
        def contiguous(vals, dim):
            span = {(v - vals[0]) % dim for v in vals}
            return span == set(range(len(vals)))
        assert contiguous(rows, 4) and contiguous(cols, 4)

    def test_fills_to_capacity_then_queues(self, pool):
        pool.add_slice("a", "4x4")
        ids = [pool.request_gang(f"r{i}", "2x2") for i in range(4)]
        assert all(pool.gang(g).state == "running" for g in ids)
        assert pool.free_chips("a") == 0
        extra = pool.request_gang("r-extra", "2x2")
        assert pool.gang(extra).state == "pending"
        # Releasing one frees a placement on the next tick.
        pool.release_gang(ids[0])
        assert pool.gang(extra).state == "running"

    def test_dimension_permutation(self, pool):
        """An 8x1 request fits a 4x... no — a 1x8 fits an 8x2 slice by
        permuting request dims onto slice dims."""
        pool.add_slice("a", "8x2")
        gid = pool.request_gang("r", "8")
        gang = pool.gang(gid)
        assert gang.state == "running"
        assert len(gang.chips) == 8

    def test_never_fits_raises(self, pool):
        pool.add_slice("a", "2x2")
        with pytest.raises(SlicedError, match="never fit"):
            pool.request_gang("r", "4x4")

    def test_malformed_topology_raises(self, pool):
        pool.add_slice("a", "2x2")
        with pytest.raises(SlicedError, match="malformed"):
            pool.request_gang("r", "2xx")

    def test_tightest_fit_first(self, pool):
        """Small gangs land on the smallest slice that fits, keeping the
        big slice whole for big gangs."""
        pool.add_slice("big", "8x8")
        pool.add_slice("small", "2x2")
        gid = pool.request_gang("r", "2x2")
        assert pool.gang(gid).slice == "small"
        big = pool.request_gang("r2", "8x8")
        assert pool.gang(big).state == "running"


class TestLiveness:
    def test_heartbeat_timeout_restarts_then_fails(self, pool):
        pool.add_slice("a", "2x2")
        gid = pool.request_gang("r", "2x2", max_restarts=1)
        pool.tick(0.0)  # drain PLACED
        assert pool.heartbeat(gid, 0, 0.0)
        events = pool.tick(100.0, heartbeat_timeout=30.0)
        kinds = [e.kind for e in events if e.gang_id == gid]
        assert kinds == ["LOST", "RESTART"]
        assert pool.gang(gid).state == "restarting"
        assert pool.free_chips("a") == 0  # chips stay reserved for restart

        # Heartbeat after restart → running again.
        assert pool.heartbeat(gid, 0, 110.0)
        assert pool.gang(gid).state == "running"

        events = pool.tick(200.0, heartbeat_timeout=30.0)
        kinds = [e.kind for e in events if e.gang_id == gid]
        assert kinds == ["LOST", "FAILED"]
        assert pool.gang(gid).state == "failed"
        assert pool.free_chips("a") == 4  # chips released on failure

    def test_no_heartbeats_means_no_timeout(self, pool):
        pool.add_slice("a", "2x2")
        gid = pool.request_gang("r", "2x2")
        pool.tick(0.0)
        assert pool.tick(1e6) == []  # never heartbeated → not lost
        assert pool.gang(gid).state == "running"


class TestPreemption:
    def test_slice_eviction(self, pool):
        pool.add_slice("spot", "2x2", preemptible=True)
        gid = pool.request_gang("r", "2x2")
        pool.tick(0.0)
        assert pool.preempt_slice("spot") == 1
        assert pool.gang(gid).state == "preempted"
        events = pool.tick(0.0)
        assert any(e.kind == "PREEMPTED" and e.gang_id == gid for e in events)
        assert pool.free_chips("spot") == 4

    def test_priority_evicts_lower_on_preemptible(self, pool):
        pool.add_slice("spot", "2x2", preemptible=True)
        low = pool.request_gang("low", "2x2", priority=0)
        pool.tick(0.0)
        high = pool.request_gang("high", "2x2", priority=10)
        assert pool.gang(low).state == "preempted"
        assert pool.gang(high).state == "running"

    def test_priority_never_evicts_on_reserved(self, pool):
        pool.add_slice("reserved", "2x2", preemptible=False)
        low = pool.request_gang("low", "2x2", priority=0)
        high = pool.request_gang("high", "2x2", priority=10)
        assert pool.gang(low).state == "running"
        assert pool.gang(high).state == "pending"


class TestDaemonBinary:
    def test_line_protocol_end_to_end(self, built):
        import os

        binary = os.path.join(os.path.dirname(built), "sliced")
        if not os.path.exists(binary):
            subprocess.run(["make", "-C", os.path.dirname(os.path.dirname(built)),
                            "build/sliced"], check=True, capture_output=True)
        script = (
            "ADD a 4x4 0\n"
            "REQ run-1 2x2 0 0\n"
            "INFO 1\n"
            "TICK 0 30\n"
            "REL 1\n"
            "QUIT\n"
        )
        out = subprocess.run([binary], input=script, capture_output=True,
                             text=True, timeout=30).stdout.splitlines()
        assert out[0] == "ok"
        assert out[1] == "1"
        assert out[2].startswith("running a")
        assert any("PLACED" in line for line in out)
        assert "ok" in out[-1]


class TestAgentIntegration:
    """Agent + SliceManager: topology requests gate gang starts through
    the native pool (the §3.2 spine with the operator-equivalent in the
    loop)."""

    @pytest.fixture()
    def plane(self, tmp_path):
        from polyaxon_tpu.controlplane import ControlPlane

        return ControlPlane(str(tmp_path / "home"))

    def _tpu_job(self, sleep=0.2, topology="2x2", preemptible=False):
        return {
            "kind": "component",
            "run": {
                "kind": "job",
                "environment": {
                    "tpu": {"accelerator": "v5e", "topology": topology,
                            "preemptible": preemptible},
                },
                "container": {"command": [
                    "python", "-c", f"import time; time.sleep({sleep})"]},
            },
        }

    def test_topology_gates_capacity(self, plane):
        from polyaxon_tpu.agent import Agent, SliceManager
        from polyaxon_tpu.lifecycle import V1Statuses

        manager = SliceManager([("a", "2x2", False)])
        agent = Agent(plane, max_concurrent=8, slice_manager=manager)
        first = plane.submit(self._tpu_job(sleep=1.0))
        second = plane.submit(self._tpu_job(sleep=0.1))
        agent.reconcile_once()
        agent.reconcile_once()
        # Only one 2x2 gang fits the single 2x2 slice.
        assert plane.get_run(first.uuid).status in (
            V1Statuses.RUNNING, V1Statuses.STARTING)
        assert plane.get_run(second.uuid).status == V1Statuses.QUEUED
        assert agent.run_until_done(second.uuid, timeout=60) == V1Statuses.SUCCEEDED
        assert plane.get_run(first.uuid).status == V1Statuses.SUCCEEDED
        manager.close()

    def test_unschedulable_topology_fails(self, plane):
        from polyaxon_tpu.agent import Agent, SliceManager
        from polyaxon_tpu.lifecycle import V1Statuses

        manager = SliceManager([("a", "2x2", False)])
        agent = Agent(plane, slice_manager=manager)
        record = plane.submit(self._tpu_job(topology="8x8"))
        agent.reconcile_once()
        agent.reconcile_once()
        assert plane.get_run(record.uuid).status == V1Statuses.FAILED
        last = plane.get_statuses(record.uuid)[-1]
        assert "Unschedulable" in (last.get("reason") or "")
        manager.close()

    def test_slice_preemption_requeues_run(self, plane):
        import time as _time

        from polyaxon_tpu.agent import Agent, SliceManager
        from polyaxon_tpu.lifecycle import V1Statuses

        manager = SliceManager([("spot", "2x2", True)])
        agent = Agent(plane, slice_manager=manager)
        record = plane.submit(self._tpu_job(sleep=30, preemptible=True))
        deadline = _time.monotonic() + 20
        while record.uuid not in agent.executor.active_runs:
            assert _time.monotonic() < deadline
            agent.reconcile_once()
            _time.sleep(0.05)
        manager.preempt_slice("spot")
        deadline = _time.monotonic() + 20
        while True:
            agent.reconcile_once()
            conditions = [c["type"] for c in plane.get_statuses(record.uuid)]
            if "preempted" in conditions and "retrying" in conditions:
                break
            assert _time.monotonic() < deadline
            _time.sleep(0.05)
        plane.stop(record.uuid)
        agent.reconcile_once()
        manager.close()


class TestReviewFixes:
    """Regressions for the native-pool review findings."""

    def test_higher_dim_request_rejected_not_underallocated(self, pool):
        pool.add_slice("a", "8x8")
        with pytest.raises(SlicedError, match="never fit"):
            pool.request_gang("r", "2x2x2")  # 3D on 2D torus

    def test_release_erases_gang(self, pool):
        pool.add_slice("a", "2x2")
        gid = pool.request_gang("r", "2x2")
        pool.release_gang(gid)
        with pytest.raises(SlicedError, match="unknown gang"):
            pool.gang(gid)
        assert pool.free_chips("a") == 4

    def test_eviction_is_minimal(self, pool):
        pool.add_slice("spot", "8x8", preemptible=True)
        lows = [pool.request_gang(f"low{i}", "2x2", priority=0) for i in range(4)]
        pool.tick(0.0)
        # Free capacity exists: a high-priority request must not evict.
        high = pool.request_gang("high", "2x2", priority=10)
        assert pool.gang(high).state == "running"
        assert all(pool.gang(g).state == "running" for g in lows)
        # Fill the slice; the next high-priority gang evicts EXACTLY one.
        more = [pool.request_gang(f"fill{i}", "2x2", priority=0)
                for i in range(11)]
        pool.tick(0.0)
        high2 = pool.request_gang("high2", "2x2", priority=10)
        assert pool.gang(high2).state == "running"
        evicted = [g for g in lows + more
                   if pool.gang(g).state == "preempted"]
        assert len(evicted) == 1


class TestRaceDetection:
    def test_tsan_stress_is_clean(self, built):
        """SURVEY.md §5.2: the daemon's `go test -race` equivalent."""
        import os

        native_dir = os.path.dirname(os.path.dirname(built))
        subprocess.run(["make", "-C", native_dir, "tsan"], check=True,
                       capture_output=True)
        result = subprocess.run(
            [os.path.join(native_dir, "build", "sliced_tsan")],
            env={**os.environ, "TSAN_OPTIONS": "halt_on_error=1"},
            capture_output=True, text=True, timeout=120)
        assert result.returncode == 0, result.stderr[-2000:]
        assert "stress ok" in result.stdout


class TestPreemptResumeE2E:
    """VERDICT r3 #5: the COMPOSED preempt→resume path. A checkpointing
    JAXJob gang is preempted mid-run at the slice layer; the scheduler
    requeues it in place (same uuid, same artifacts dir); the second
    attempt must restore from the checkpoint — `restored_from_step > 0`
    in the run outputs — not silently restart at step 0."""

    @pytest.fixture()
    def plane(self, tmp_path):
        from polyaxon_tpu.controlplane import ControlPlane

        return ControlPlane(str(tmp_path / "home"))

    def test_preempted_jaxjob_resumes_from_checkpoint(
            self, plane, monkeypatch):
        import os
        import time as _time

        from polyaxon_tpu.agent import Agent, SliceManager
        from polyaxon_tpu.lifecycle import V1Statuses

        # Gang subprocesses contribute their own devices (gang tests'
        # convention): drop the test process's 8-device host flag.
        monkeypatch.setenv("XLA_FLAGS", "")
        manager = SliceManager([("spot", "2x2", True)])
        agent = Agent(plane, slice_manager=manager)
        record = plane.submit({
            "kind": "component",
            "name": "ckpt-preempt",
            "run": {
                "kind": "jaxjob",
                "environment": {
                    "tpu": {"accelerator": "v5e", "topology": "2x2",
                            "preemptible": True},
                },
                "checkpointing": {"enabled": True, "intervalSteps": 50,
                                  "asyncSave": False},
                "runtime": {"model": "llama_tiny",
                            "dataset": "lm_synthetic",
                            "steps": 4000, "seq_len": 64,
                            "global_batch_size": 4,
                            "log_every": 10**9},
            },
        })
        try:
            # Preempt only after a checkpoint is COMMITTED on disk
            # (async_save off → a clean numeric step dir is committed;
            # orbax keeps uncommitted work under *-tmp-* names).
            ckpt_dir = os.path.join(
                plane.run_artifacts_dir(record.uuid), "checkpoints")

            def committed_steps():
                if not os.path.isdir(ckpt_dir):
                    return []
                return [d for d in os.listdir(ckpt_dir)
                        if d.isdigit()
                        and os.path.isdir(os.path.join(ckpt_dir, d))]

            deadline = _time.monotonic() + 300
            while not committed_steps():
                assert _time.monotonic() < deadline, \
                    "no checkpoint appeared before deadline"
                run = plane.get_run(record.uuid)
                assert run.status not in (
                    V1Statuses.FAILED, V1Statuses.SUCCEEDED), (
                    f"run reached {run.status} before preemption; "
                    "raise steps to widen the window")
                agent.reconcile_once()
                _time.sleep(0.1)

            manager.preempt_slice("spot")
            # The agent observes the eviction, the scheduler requeues
            # in place, a second gang attempt runs to completion.
            deadline = _time.monotonic() + 60
            while True:
                agent.reconcile_once()
                conditions = [c["type"]
                              for c in plane.get_statuses(record.uuid)]
                if "preempted" in conditions and "retrying" in conditions:
                    break
                assert _time.monotonic() < deadline, conditions
                _time.sleep(0.05)
            status = agent.run_until_done(record.uuid, timeout=600)
            assert status == V1Statuses.SUCCEEDED

            outputs = plane.streams.get_outputs(record.uuid)
            # The composed assertion: attempt 2 resumed from the
            # checkpoint, completed the FULL budget, under the SAME run.
            assert outputs.get("restored_from_step") is not None, outputs
            assert outputs["restored_from_step"] >= 50
            assert outputs["steps"] == 4000
            # TPU-native accounting: preemption is not a failure —
            # the retry budget is untouched (preemptionCountsAsRetry
            # defaults off), so a tuner charges the trial once.
            assert plane.get_run(record.uuid).retries == 0
        finally:
            manager.close()
