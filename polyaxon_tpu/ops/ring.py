"""Ring attention: context-parallel attention over the ``cp`` mesh axis.

Net-new surface vs the reference (SURVEY.md §5.7: long-context is
absent upstream — it ships no model math at all). Design:

- Every device holds one contiguous sequence block of Q, K, V
  (``seq → cp`` in the CP rule table). Queries stay resident; K/V
  blocks rotate around the ICI ring via ``lax.ppermute`` — each step
  overlaps the matmul for the current block with the DMA of the next.
- Online-softmax accumulation (flash-style running max/denominator in
  f32) combines the per-block partial attentions exactly, so the full
  S×S score matrix never exists on any chip: memory is
  O(S_local² · heads) per step and activations scale to sequence
  lengths ∝ number of chips.
- Causality is a pure position test (global query index ≥ global key
  index), which uniformly covers the three block cases (fully visible /
  diagonal / fully masked). Blocks ahead of the diagonal are masked
  rather than skipped — balanced "zigzag" block placement is a later
  optimization.
- The loop is a ``lax.scan`` (not ``fori_loop``) so the whole ring is
  reverse-differentiable: ppermute transposes to the inverse
  permutation and the backward pass runs the ring the other way.

``ring_attention`` can be called either inside an existing
``shard_map`` (axis already bound) or under plain jit, where it wraps
itself in ``jax.shard_map`` over the ambient mesh's ``cp`` axis with
all other axes left to GSPMD (partial-manual sharding).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _axis_bound(axis_name: str) -> bool:
    """True when ``axis_name`` is a bound manual-collective axis here."""
    try:
        jax.lax.axis_index(axis_name)
        return True
    except (NameError, KeyError, ValueError):
        return False


def ambient_mesh():
    """The mesh entered via ``with mesh:`` (as the runtime loop does)."""
    try:
        from jax.interpreters import pxla

        mesh = pxla.thread_resources.env.physical_mesh
        if mesh is not None and not mesh.empty:
            return mesh
    except Exception:
        pass
    try:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is not None and not mesh.empty:
            return mesh
    except Exception:
        pass
    return None


def _ring_attention_sharded(
    q: jax.Array,  # [B, S_loc, H, D] local shard
    k: jax.Array,  # [B, S_loc, KV, D]
    v: jax.Array,
    *,
    causal: bool,
    scale: float,
    axis_name: str,
) -> jax.Array:
    from polyaxon_tpu.ops.attention import repeat_kv

    cp = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    n_rep = h // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)

    q_f = q.astype(jnp.float32)
    q_pos = idx * s_loc + jnp.arange(s_loc)  # global query positions
    local_pos = jnp.arange(s_loc)

    # Send kv to the next device each step: after step s, device `idx`
    # holds the block that started at device `(idx - s - 1) mod cp`.
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def step(carry, s):
        (k_cur, v_cur), (o, m, l) = carry
        src = (idx - s) % cp  # which block this kv shard is
        k_pos = src * s_loc + local_pos

        logits = (
            jnp.einsum(
                "bqhd,bkhd->bhqk", q_f, k_cur.astype(jnp.float32),
            )
            * scale
        )  # [B, H, Sq, Sk] f32
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]  # [Sq, Sk]
            logits = jnp.where(mask[None, None], logits, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))  # [B,H,Sq]
        p = jnp.exp(logits - m_new[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_cur.astype(jnp.float32))
        o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv

        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return ((k_nxt, v_nxt), (o_new, m_new, l_new)), None

    o0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    ((_, (o, _, l)), _) = jax.lax.scan(
        step, ((k, v), (o0, m0, l0)), jnp.arange(cp)
    )
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = o / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,  # [B, S, H, D] (global, seq sharded over cp)
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    axis_name: str = "cp",
    mesh=None,
) -> jax.Array:
    scale = softmax_scale if softmax_scale is not None else q.shape[-1] ** -0.5
    if _axis_bound(axis_name):
        return _ring_attention_sharded(
            q, k, v, causal=causal, scale=scale, axis_name=axis_name
        )

    mesh = mesh if mesh is not None else ambient_mesh()
    if mesh is None or axis_name not in mesh.axis_names:
        raise ValueError(
            f"ring_attention needs mesh axis `{axis_name}`: call inside "
            "shard_map, pass mesh=, or enter `with mesh:` (the runtime "
            "loop does) with a cp axis in the mesh"
        )
    spec = P(None, axis_name, None, None)  # seq dim sharded over cp
    fn = jax.shard_map(
        functools.partial(
            _ring_attention_sharded, causal=causal, scale=scale, axis_name=axis_name
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={axis_name},
        check_vma=False,
    )
    return fn(q, k, v)
