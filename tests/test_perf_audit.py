"""Communication-audit subsystem (polyaxon_tpu/perf).

Fast tiers: HLO parsing against hand-written instruction lines,
wire-byte formulas vs hand-computed shapes (including a compiled
single-collective program on the 8-device mesh), budget-gate logic on
synthetic reports, and AOT-probe timeout containment.

``slow``-marked: the full train-step audits per schedule (golden
collective counts == the committed budgets, the reshard-injection
drill) — each compiles the real train step on the 8-device mesh, so
they run in the ci.sh audit stage rather than tier-1.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from polyaxon_tpu.perf import audit, budgets
from polyaxon_tpu.perf.hlo import (
    parse_collectives,
    summarize_collectives,
)


class TestHloParse:
    def test_counts_shapes_and_groups(self):
        hlo = """
  %all-reduce.1 = f32[256,64]{1,0} all-reduce(f32[256,64]{1,0} %add.5), channel_id=1, replica_groups={{0,1,2,3},{4,5,6,7}}, to_apply=%sum
  %ag = bf16[8,128]{1,0} all-gather(bf16[1,128]{1,0} %p0), channel_id=2, replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %a2a = f32[2,512,1,16]{3,2,1,0} all-to-all(f32[2,512,1,16]{3,2,1,0} %x), channel_id=3, replica_groups=[2,4]<=[8], dimensions={1}
  %cp = f32[2,64]{1,0} collective-permute(f32[2,64]{1,0} %y), channel_id=4, source_target_pairs={{0,1},{1,2},{2,3},{3,0}}
"""
        ops = parse_collectives(hlo, n_devices=8)
        assert [o.kind for o in ops] == [
            "all-reduce", "all-gather", "all-to-all", "collective-permute"]
        ar, ag, a2a, cp = ops
        # explicit replica_groups: first group has 4 members
        assert ar.group_size == 4
        assert ar.result_bytes == 256 * 64 * 4
        # iota-format groups [2,4]<=[8]: 2 groups of 4
        assert a2a.group_size == 4
        # bf16 = 2 bytes
        assert ag.result_bytes == 8 * 128 * 2

    def test_async_start_done_counted_once(self):
        hlo = """
  %ar0 = f32[64]{0} all-reduce-start(f32[64]{0} %x), replica_groups={{0,1}}, to_apply=%sum
  %ar1 = f32[64]{0} all-reduce-done(f32[64]{0} %ar0)
"""
        ops = parse_collectives(hlo, n_devices=2)
        assert len(ops) == 1
        assert ops[0].kind == "all-reduce"

    def test_tuple_result_shapes_sum(self):
        hlo = ("  %ar = (f32[16]{0}, bf16[8]{0}) all-reduce"
               "(f32[16]{0} %a, bf16[8]{0} %b), replica_groups={{0,1}}, "
               "to_apply=%sum\n")
        (op,) = parse_collectives(hlo, n_devices=2)
        assert op.result_bytes == 16 * 4 + 8 * 2

    def test_wire_byte_formulas_hand_computed(self):
        b = 1024  # one f32[256] tensor
        hlo = (
            "  %ar = f32[256]{0} all-reduce(f32[256]{0} %x), "
            "replica_groups={{0,1,2,3}}, to_apply=%s\n"
            "  %ag = f32[256]{0} all-gather(f32[64]{0} %x), "
            "replica_groups={{0,1,2,3}}, dimensions={0}\n"
            "  %rs = f32[256]{0} reduce-scatter(f32[1024]{0} %x), "
            "replica_groups={{0,1,2,3}}, to_apply=%s, dimensions={0}\n"
            "  %aa = f32[256]{0} all-to-all(f32[256]{0} %x), "
            "replica_groups={{0,1,2,3}}, dimensions={0}\n"
            "  %cp = f32[256]{0} collective-permute(f32[256]{0} %x), "
            "source_target_pairs={{0,1},{1,0}}\n")
        ops = {o.kind: o for o in parse_collectives(hlo, n_devices=4)}
        assert ops["all-reduce"].wire_bytes == pytest.approx(2 * b * 3 / 4)
        assert ops["all-gather"].wire_bytes == pytest.approx(b * 3 / 4)
        # reduce-scatter: result is the 1/g shard; receives (g-1) shards
        assert ops["reduce-scatter"].wire_bytes == pytest.approx(b * 3)
        assert ops["all-to-all"].wire_bytes == pytest.approx(b * 3 / 4)
        assert ops["collective-permute"].wire_bytes == pytest.approx(b)

    def test_summary_aggregates(self):
        hlo = (
            "  %a = f32[64]{0} all-reduce(f32[64]{0} %x), "
            "replica_groups={{0,1}}, to_apply=%s\n"
            "  %b = f32[64]{0} all-reduce(f32[64]{0} %y), "
            "replica_groups={{0,1}}, to_apply=%s\n")
        summary = summarize_collectives(parse_collectives(hlo, n_devices=2))
        assert summary["counts"] == {"all-reduce": 2}
        assert summary["n_collectives"] == 2
        assert summary["est_wire_bytes_per_step"] == 2 * int(2 * 256 * 0.5)


class TestCompiledBytesSanity:
    """The estimator against a REAL compiled program whose traffic is
    hand-computable: psum of a known tensor over the 8-device mesh."""

    def test_psum_all_reduce_bytes(self, cpu_devices):
        mesh = Mesh(np.array(cpu_devices).reshape(8), ("dp",))
        n = 1024
        x = jax.device_put(
            jnp.arange(8 * n, dtype=jnp.float32).reshape(8, n),
            NamedSharding(mesh, P("dp")))

        @jax.jit
        def f(x):
            return jax.lax.with_sharding_constraint(
                x.sum(axis=0, keepdims=True) + 0.0,
                NamedSharding(mesh, P()))

        compiled = f.lower(x).compile()
        ops = parse_collectives(compiled.as_text(), n_devices=8)
        reduces = [o for o in ops
                   if o.kind in ("all-reduce", "reduce-scatter")]
        assert reduces, "expected a cross-device reduction in the HLO"
        # The reduced payload is the f32[1, n] row = 4n bytes; the ring
        # estimate for an 8-way all-reduce of it is 2 * 4n * 7/8.
        payload = 4 * n
        assert any(o.result_bytes == payload for o in reduces)
        ar = next(o for o in reduces if o.result_bytes == payload)
        assert ar.group_size == 8
        assert ar.wire_bytes == pytest.approx(2 * payload * 7 / 8)


class TestBudgetGate:
    def _report(self, **over):
        rep = {
            "name": "dp", "model": "llama_tiny", "axes": {"dp": 8},
            "attention": "xla", "seq_len": 256, "global_batch": 8,
            "counts": {"all-reduce": 15},
            "est_wire_bytes_per_step": 500_000,
        }
        rep.update(over)
        return rep

    def _budgets(self):
        return {
            "_meta": {"bytes_tolerance": 0.25},
            "dp": {
                "counts": {"all-reduce": 15},
                "est_wire_bytes_per_step": 500_000,
                "axes": {"dp": 8}, "model": "llama_tiny",
                "attention": "xla", "seq_len": 256, "global_batch": 8,
            },
        }

    def test_within_budget_passes(self):
        assert budgets.check_report(self._report(), self._budgets()) == []

    def test_extra_op_kind_fails(self):
        rep = self._report(counts={"all-reduce": 15, "all-gather": 1})
        violations = budgets.check_report(rep, self._budgets())
        assert violations and "all-gather" in violations[0]

    def test_count_regression_fails(self):
        rep = self._report(counts={"all-reduce": 16})
        assert budgets.check_report(rep, self._budgets())

    def test_bytes_regression_fails_past_tolerance(self):
        ok = self._report(est_wire_bytes_per_step=600_000)  # +20% < 25%
        assert budgets.check_report(ok, self._budgets()) == []
        bad = self._report(est_wire_bytes_per_step=700_000)  # +40%
        assert budgets.check_report(bad, self._budgets())

    def test_missing_entry_is_a_violation(self):
        rep = self._report(name="brand-new-schedule")
        violations = budgets.check_report(rep, self._budgets())
        assert violations and "no budget entry" in violations[0]

    def test_config_drift_demands_regeneration(self):
        rep = self._report(seq_len=512)
        violations = budgets.check_report(rep, self._budgets())
        assert violations and "regenerate" in violations[0]

    def test_committed_budget_file_loads_and_covers_standard_points(self):
        table = budgets.load_budgets()
        for point in audit.STANDARD_POINTS:
            assert point.name in table, (
                f"budgets.json is missing {point.name}; run "
                f"python -m polyaxon_tpu.perf --update-budgets")
            assert table[point.name]["counts"], point.name


class TestAotProbeContainment:
    def test_timeout_is_contained_and_structured(self):
        from polyaxon_tpu.perf import aot

        import time as _time

        t0 = _time.time()
        result = aot.run_probe(timeout_s=2.0,
                               extra_child_args=["--sleep", "60"])
        wall = _time.time() - t0
        assert result["timed_out"] is True
        assert result["ok"] is False
        assert "timeout" in result["error"]
        # SIGTERM grace is 60s on top of the timeout; a contained probe
        # must come back well before a CI-stage budget would notice.
        assert wall < 70

    def test_probe_returns_dict_never_raises(self):
        from polyaxon_tpu.perf import aot

        result = aot.run_probe(timeout_s=1.0,
                               extra_child_args=["--sleep", "30"])
        assert isinstance(result, dict) and result.get("ok") is False


@pytest.mark.slow
class TestAuditGolden:
    """Golden collective counts per schedule: a fresh compile of the
    real train step must reproduce the committed budgets exactly.
    Each case compiles on the 8-device mesh (seconds-to-minutes on this
    host), so the module's slow tier runs in the ci.sh audit stage."""

    @pytest.fixture(scope="class")
    def budget_table(self):
        return budgets.load_budgets()

    @pytest.mark.parametrize("name", [p.name for p in audit.STANDARD_POINTS])
    def test_golden_counts_match_budgets(self, name, budget_table,
                                         cpu_devices):
        report = audit.audit_point(audit.point_by_name(name),
                                   devices=cpu_devices)
        assert report["counts"] == budget_table[name]["counts"]
        assert budgets.check_report(report, budget_table) == []

    def test_cp_schedules_keep_batch_sharded(self, cpu_devices):
        """The r6 reshard fix, locked in: neither manual attention
        schedule may all-gather Q/K/V over the batch axes (the
        pre-fix full-manual specs cost 4 all-gathers + dp-redundant
        attention compute per step)."""
        for name in ("ring-cp", "ulysses-cp"):
            report = audit.audit_point(audit.point_by_name(name),
                                       devices=cpu_devices)
            assert report["counts"].get("all-gather", 0) == 0, report

    def test_injected_reshard_fails_the_gate(self, budget_table,
                                             cpu_devices):
        report = audit.audit_point(audit.point_by_name("dp"),
                                   inject_reshard=True,
                                   devices=cpu_devices)
        violations = budgets.check_report(report, budget_table)
        assert violations, "an injected reshard must trip the budget gate"

    def test_report_artifact_is_json_serializable(self, cpu_devices):
        report = audit.audit_point(audit.point_by_name("dp"),
                                   devices=cpu_devices, keep_ops=True)
        parsed = json.loads(json.dumps(report))
        assert parsed["ops"], "keep_ops should include the instruction list"
