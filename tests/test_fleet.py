"""Serving-fleet tier (ISSUE 17): consistent-hash placement bounds,
router decision order (affinity → hotness/pressure spill → hash), and
the autoscaler state machine (prewarm-before-commit up, drain-before-
release down) — all over fake engines at pure-Python speed."""

import threading
import time

import pytest

from polyaxon_tpu.obs import metrics as obs_metrics
from polyaxon_tpu.serving.fleet import SCALE_UP_RULES, ServingFleet
from polyaxon_tpu.serving.router import (ConsistentHashRing, FleetRouter,
                                         prefix_key)


def _conv(c, n=8):
    return [c * 131 + j for j in range(n)]


# --------------------------------------------------------- fake engine
class _FakeReq:
    def __init__(self):
        self.done = threading.Event()
        self.done.set()

    def wait(self, timeout=None):
        return [1, 2]


class _FakeEngine:
    def __init__(self):
        self.queued = 0
        self.active = 0
        self.stopped = False
        self.warm_calls = 0
        self.submits = []

    def generate(self, rows, max_new_tokens, **kw):
        self.warm_calls += 1
        return [[0]] * len(rows)

    def submit(self, tokens, max_new_tokens, **kw):
        self.submits.append(list(tokens))
        return _FakeReq()

    def health(self):
        return {"status": "stopped" if self.stopped else "ok",
                "queued": self.queued, "active": self.active,
                "radix_hit_rate": None, "kv_headroom": None}

    def stats(self):
        return {"prefill_tokens_total": 16,
                "prefill_tokens_skipped": 12,
                "kv_invariant_violations": 0,
                "requests_served": len(self.submits)}

    def stop(self):
        self.stopped = True


def _fleet(*, replicas=2, standby=1, max_replicas=4, prewarm=True,
           factory=None, clock=None, **kw):
    reg = obs_metrics.MetricsRegistry()
    engines = []

    def default_factory():
        engines.append(_FakeEngine())
        return engines[-1]

    fleet = ServingFleet(
        factory or default_factory, replicas=replicas, standby=standby,
        max_replicas=max_replicas, prewarm=prewarm,
        warmup_rows=[[1, 2, 3]], router=FleetRouter(registry=reg),
        registry=reg, cooldown=1.0, idle_hold=1.0,
        clock=clock or time.monotonic, **kw)
    fleet.start()
    return fleet, engines


# ==================================================== consistent hash
class TestConsistentHashRing:
    def test_keyspace_movement_bounded_on_add(self):
        """Adding the Nth replica remaps ~1/N of keys, never a
        wholesale reshuffle (the property that makes scale-up cheap
        for every OTHER replica's radix cache)."""
        ring = ConsistentHashRing(["r0", "r1", "r2"], seed=3)
        keys = [prefix_key(_conv(i)) for i in range(2000)]
        before = {k: ring.owner(k) for k in keys}
        ring.add("r3")
        moved = sum(1 for k in keys if ring.owner(k) != before[k])
        # ideal is 1/4; allow generous vnode-variance headroom but pin
        # well under any "most keys moved" regression.
        assert moved / len(keys) < 0.4
        # every moved key landed on the newcomer — an add never
        # shuffles keys between surviving replicas.
        for k in keys:
            if ring.owner(k) != before[k]:
                assert ring.owner(k) == "r3"

    def test_keyspace_movement_bounded_on_remove(self):
        ring = ConsistentHashRing(["r0", "r1", "r2", "r3"], seed=3)
        keys = [prefix_key(_conv(i)) for i in range(2000)]
        before = {k: ring.owner(k) for k in keys}
        ring.remove("r1")
        for k in keys:
            if before[k] != "r1":
                # survivors keep every key they already owned
                assert ring.owner(k) == before[k]
            else:
                assert ring.owner(k) != "r1"

    def test_add_then_remove_restores_ownership(self):
        ring = ConsistentHashRing(["a", "b", "c"], seed=7)
        keys = [prefix_key(_conv(i)) for i in range(500)]
        before = {k: ring.owner(k) for k in keys}
        ring.add("d")
        ring.remove("d")
        assert {k: ring.owner(k) for k in keys} == before

    def test_deterministic_across_instances_and_seeds(self):
        keys = [prefix_key(_conv(i)) for i in range(200)]
        a = ConsistentHashRing(["x", "y", "z"], seed=5)
        b = ConsistentHashRing(["z", "x", "y"], seed=5)  # order-free
        assert [a.owner(k) for k in keys] == [b.owner(k) for k in keys]
        c = ConsistentHashRing(["x", "y", "z"], seed=6)
        assert [a.owner(k) for k in keys] != [c.owner(k) for k in keys]


# ============================================================= router
class TestFleetRouter:
    def test_affinity_sticks_after_first_route(self):
        r = FleetRouter(["a", "b", "c"], seed=1)
        first = r.route(_conv(4))
        assert first.reason == "hash"
        for _ in range(5):
            d = r.route(_conv(4))
            assert (d.reason, d.replica) == ("affinity", first.replica)

    def test_routing_deterministic_for_fixed_set_and_seed(self):
        def drive():
            r = FleetRouter(["a", "b", "c"], seed=9)
            return [(r.route(_conv(i % 6)).replica,
                     r.route(_conv(i % 6)).reason) for i in range(60)]
        assert drive() == drive()

    def test_spill_lands_on_hash_owner(self):
        """The hotness cap deflects a drifted-affinity prefix to its
        ring owner — never to an arbitrary replica."""
        r = FleetRouter(["a", "b", "c"], seed=1, recent=32, hot_min=16,
                        hot_fraction=0.5, spill_depth=None)
        convs = [_conv(i) for i in range(6)]
        for _ in range(4):
            for c in convs:
                r.route(c)
        r.add_replica("d")  # ownership moves for ~1/4 of prefixes
        moved = [c for c in convs
                 if r.ring.owner(prefix_key(c)) == "d"]
        assert moved, "seed must move at least one conversation"
        decisions = [r.route(moved[0]) for _ in range(40)]
        spills = [d for d in decisions if d.reason == "spill"]
        assert spills, "hot drifted prefix must spill"
        assert all(d.replica == r.ring.owner(d.prefix) == "d"
                   for d in spills)

    def test_pressure_spill_uses_queue_telemetry(self):
        r = FleetRouter(["a", "b"], seed=2, spill_depth=4, hot_min=999)
        d0 = r.route(_conv(1))
        target = d0.replica
        r.ring.remove(target)  # force the prefix's owner to differ
        r.ring.add(target)
        owner = r.ring.owner(d0.prefix)
        telemetry = {target: {"status": "ok", "queued": 10}}
        d = r.route(_conv(1), telemetry=telemetry)
        if owner == target:
            assert d.reason == "affinity"  # at home: cap is a no-op
        else:
            assert (d.reason, d.replica) == ("spill", owner)

    def test_unhealthy_replica_skipped(self):
        r = FleetRouter(["a", "b"], seed=1)
        d0 = r.route(_conv(2))
        sick = d0.replica
        well = ({"a", "b"} - {sick}).pop()
        d = r.route(_conv(2),
                    telemetry={sick: {"status": "stopped", "queued": 0}})
        assert d.replica == well

    def test_blind_mode_round_robins_and_learns_nothing(self):
        r = FleetRouter(["a", "b"], seed=1, blind=True)
        seq = [r.route(_conv(3)).replica for _ in range(4)]
        assert seq == ["a", "b", "a", "b"]
        assert r.stats()["affinity_entries"] == 0

    def test_remove_replica_drops_its_affinity(self):
        r = FleetRouter(["a", "b"], seed=1)
        d = r.route(_conv(5))
        r.remove_replica(d.replica)
        d2 = r.route(_conv(5))
        assert d2.replica != d.replica
        assert d2.reason in ("hash", "spill")


# ========================================================= autoscaler
class TestServingFleetAutoscaler:
    def test_start_builds_warm_ready_and_standby(self):
        fleet, engines = _fleet(replicas=2, standby=1)
        try:
            assert fleet.stats()["states"]["ready"] == 2
            assert fleet.stats()["states"]["standby"] == 1
            # prewarm discipline: every engine (standby included) ran
            # its warmup passes before any admission could reach it.
            assert all(e.warm_calls == 2 for e in engines)
        finally:
            fleet.stop()

    def test_cold_fleet_skips_warmup(self):
        fleet, engines = _fleet(replicas=1, standby=1, prewarm=False)
        try:
            assert all(e.warm_calls == 0 for e in engines)
        finally:
            fleet.stop()

    def test_scale_up_promotes_standby_on_rule_state(self):
        clock = [100.0]
        fleet, engines = _fleet(clock=lambda: clock[0])
        try:
            ev = fleet.maybe_scale({"fleet-replica-hot"})
            assert ev["mode"] == "promote" and ev["outcome"] == "ok"
            assert len(fleet.ready) == 3
            assert fleet.router.replicas == {"r0", "r1", "r2"}
        finally:
            fleet.stop()

    def test_cooldown_blocks_immediate_flap(self):
        clock = [100.0]
        fleet, _ = _fleet(clock=lambda: clock[0])
        try:
            assert fleet.maybe_scale(SCALE_UP_RULES) is not None
            assert fleet.maybe_scale(SCALE_UP_RULES) is None
            clock[0] += 2.0
            assert fleet.maybe_scale(SCALE_UP_RULES) is not None
        finally:
            fleet.stop()

    def test_background_build_commits_only_when_warm(self):
        clock = [100.0]
        fleet, engines = _fleet(standby=0, clock=lambda: clock[0])
        try:
            ev = fleet.maybe_scale({"serving-queue-saturation"})
            assert ev["mode"] == "build"
            assert fleet.wait_settled(timeout=10.0)
            assert len(fleet.ready) == 3
            assert engines[-1].warm_calls == 2  # warmed before commit
            assert fleet.scale_events[-1]["outcome"] == "ok"
        finally:
            fleet.stop()

    def test_failed_build_records_failed_event(self):
        built = []

        def flaky():
            if built:
                raise RuntimeError("no capacity")
            built.append(1)
            return _FakeEngine()

        clock = [100.0]
        fleet = ServingFleet(flaky, replicas=1, standby=0,
                             max_replicas=2,
                             router=FleetRouter(
                                 registry=obs_metrics.MetricsRegistry()),
                             registry=obs_metrics.MetricsRegistry(),
                             cooldown=0.0, clock=lambda: clock[0])
        fleet.start()
        try:
            fleet.maybe_scale({"fleet-replica-hot"})
            assert fleet.wait_settled(timeout=10.0)
            assert fleet.scale_events[-1] == {
                "direction": "up", "outcome": "failed",
                "replica": "r1", "mode": "build"}
            assert len(fleet.ready) == 1  # failure never strands routing
        finally:
            fleet.stop()

    def test_scale_down_drains_in_flight_before_release(self):
        clock = [100.0]
        fleet, engines = _fleet(replicas=3, standby=0,
                                clock=lambda: clock[0])
        try:
            victim_engine = engines[2]
            victim_engine.queued = 3  # in-flight work
            fleet.poll()
            clock[0] += 5.0
            fleet.maybe_scale(set())  # idle clock starts (not idle yet)
            clock[0] += 5.0
            ev = fleet.maybe_scale(set())
            # the fleet is NOT idle (queued=3) so no down-scale yet
            assert ev is None
            victim_engine.queued = 0
            fleet.poll()
            clock[0] += 5.0
            fleet.maybe_scale(set())
            clock[0] += 5.0
            ev = fleet.maybe_scale(set())
            assert ev and ev["direction"] == "down"
            # the victim left the router the moment draining started
            assert fleet.router.replicas == {"r0", "r1"}
            assert fleet.wait_settled(timeout=10.0)
            assert victim_engine.stopped
            assert fleet.stats()["states"]["released"] == 1
            assert fleet.scale_events[-1]["outcome"] == "ok"
        finally:
            fleet.stop()

    def test_scale_down_waits_for_drain(self):
        """stop() must not land while the victim still holds work: the
        drain thread spins until queued+active hits zero."""
        clock = [100.0]
        fleet, engines = _fleet(replicas=2, standby=0,
                                clock=lambda: clock[0])
        try:
            victim = engines[1]
            victim.queued = 2  # in-flight work BEFORE drain starts
            ev = fleet.scale_down(timeout=10.0)
            assert ev["mode"] == "drain"
            time.sleep(0.1)
            assert not victim.stopped  # still draining
            victim.queued = 0
            assert fleet.wait_settled(timeout=10.0)
            assert victim.stopped
        finally:
            fleet.stop()

    def test_scale_down_refused_at_min(self):
        fleet, _ = _fleet(replicas=1, standby=0)
        try:
            ev = fleet.scale_down()
            assert ev["outcome"] == "refused"
            assert len(fleet.ready) == 1
        finally:
            fleet.stop()

    def test_no_scale_up_past_max(self):
        clock = [100.0]
        fleet, _ = _fleet(replicas=2, standby=1, max_replicas=3,
                          clock=lambda: clock[0])
        try:
            fleet.maybe_scale({"fleet-replica-hot"})  # 3 ready (max)
            clock[0] += 5.0
            assert fleet.maybe_scale({"fleet-replica-hot"}) is None
        finally:
            fleet.stop()

    def test_stats_aggregates_fleet_wide(self):
        fleet, _ = _fleet(replicas=2, standby=0)
        try:
            fleet.generate([[1, 2, 3], [4, 5, 6]], 2)
            s = fleet.stats()
            assert s["prefix_hit_rate"] == pytest.approx(0.75)
            assert s["kv_invariant_violations"] == 0
            assert set(s["router"]["routed"]) <= {"affinity", "hash",
                                                  "spill"}
        finally:
            fleet.stop()

    def test_poll_feeds_router_view_for_ready_only(self):
        fleet, engines = _fleet(replicas=2, standby=1)
        try:
            view = fleet.poll()
            assert set(view) == {"r0", "r1"}  # standby not routable
            assert all(v["status"] == "ok" for v in view.values())
        finally:
            fleet.stop()
