from polyaxon_tpu.agent.agent import Agent
from polyaxon_tpu.agent.executor import LocalExecutor
from polyaxon_tpu.agent.slices import SliceManager

__all__ = ["Agent", "LocalExecutor", "SliceManager"]
