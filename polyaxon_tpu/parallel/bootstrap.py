"""Multi-host process-group bootstrap over DCN.

TPU-native replacement for the reference's rendezvous env contract
(SURVEY.md §2c [K]: Kubeflow operators inject ``TF_CONFIG`` /
``MASTER_ADDR`` / ``RANK`` and MPIJob runs ``mpirun`` with hostfiles):
here the launch plan injects ``POLYAXON_TPU_COORDINATOR`` /
``POLYAXON_TPU_NUM_PROCESSES`` / ``POLYAXON_TPU_PROCESS_ID`` (discovered
by the tpu_metadata init phase on real TPU-VMs [B]) and every process
calls ``jax.distributed.initialize`` — after which XLA collectives ride
ICI within a slice and DCN across slices with no NCCL anywhere.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional

logger = logging.getLogger(__name__)

ENV_COORDINATOR = "POLYAXON_TPU_COORDINATOR"
ENV_NUM_PROCESSES = "POLYAXON_TPU_NUM_PROCESSES"
ENV_PROCESS_ID = "POLYAXON_TPU_PROCESS_ID"
ENV_LOCAL_DEVICE_IDS = "POLYAXON_TPU_LOCAL_DEVICE_IDS"


@dataclass
class ProcessGroup:
    coordinator: Optional[str]
    num_processes: int
    process_id: int
    initialized: bool

    @property
    def is_multiprocess(self) -> bool:
        return self.num_processes > 1


def read_env_contract(env: Optional[dict[str, str]] = None) -> ProcessGroup:
    env = dict(os.environ if env is None else env)
    return ProcessGroup(
        coordinator=env.get(ENV_COORDINATOR),
        num_processes=int(env.get(ENV_NUM_PROCESSES, "1")),
        process_id=int(env.get(ENV_PROCESS_ID, "0")),
        initialized=False,
    )


def initialize(group: Optional[ProcessGroup] = None) -> ProcessGroup:
    """Idempotently bootstrap the JAX process group from the env contract.

    Single-process (the common local/emulator case) is a no-op; multi-
    process calls ``jax.distributed.initialize`` against the coordinator
    over DCN.
    """
    group = group or read_env_contract()
    if not group.is_multiprocess:
        group.initialized = True
        return group
    if not group.coordinator:
        raise RuntimeError(
            f"{ENV_NUM_PROCESSES}={group.num_processes} but {ENV_COORDINATOR} is unset; "
            "the launch plan must inject the coordinator address"
        )
    import jax

    local_ids = os.environ.get(ENV_LOCAL_DEVICE_IDS)
    kwargs = {}
    if local_ids:
        kwargs["local_device_ids"] = [int(i) for i in local_ids.split(",")]
    jax.distributed.initialize(
        coordinator_address=group.coordinator,
        num_processes=group.num_processes,
        process_id=group.process_id,
        **kwargs,
    )
    logger.info(
        "jax.distributed initialized: process %d/%d via %s",
        group.process_id, group.num_processes, group.coordinator,
    )
    group.initialized = True
    return group
