"""Unified retry/backoff layer (ISSUE 1 tentpole).

One policy shared by every layer that survives cluster weather: the
scheduler's restart-policy requeues, the fs layer's object-store ops,
and the executor's init-phase artifact downloads. Two primitives:

- :func:`backoff_delay` — exponential backoff with DETERMINISTIC jitter:
  the jitter fraction is a hash of ``(key, attempt)``, so a scheduler
  tick that recomputes a run's delay gets the same number every time
  (idempotent ticks), while different runs decorrelate. Delays are
  strictly monotone in ``attempt`` (growth factor dominates the jitter
  band), which run ``meta["backoff"]["delays"]`` audits rely on.
- :func:`with_retries` — bounded attempts around a callable with typed
  transient-vs-permanent classification: permanent errors raise on the
  first attempt, transient ones retry through the backoff schedule.
"""

from __future__ import annotations

import hashlib
import logging
import time
from typing import Any, Callable, Iterable, Optional, Type, Union

Classifier = Union[Callable[[BaseException], bool],
                   Iterable[Type[BaseException]]]


def _jitter_fraction(key: Optional[str], attempt: int) -> float:
    """Deterministic fraction in [0, 1) from (key, attempt); random when
    no key is given (callers without an identity to pin)."""
    if key is None:
        import random

        return random.random()
    digest = hashlib.sha256(f"{key}:{attempt}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / 2**64


def backoff_delay(
    attempt: int,
    *,
    base: float = 0.5,
    factor: float = 2.0,
    max_delay: float = 60.0,
    jitter: float = 0.25,
    key: Optional[str] = None,
) -> float:
    """Delay in seconds before retry number ``attempt`` (0-based).

    ``base * factor**attempt``, capped at ``max_delay``, stretched by up
    to ``jitter`` fraction. Jitter only ADDS (never subtracts) so the
    sequence stays strictly increasing until the cap.
    """
    raw = min(base * (factor ** max(attempt, 0)), max_delay)
    return raw * (1.0 + max(jitter, 0.0) * _jitter_fraction(key, attempt))


def is_transient_default(exc: BaseException) -> bool:
    """Default classification: network/timeout shapes are transient,
    missing-resource and usage errors are permanent."""
    if isinstance(exc, (FileNotFoundError, IsADirectoryError,
                        NotADirectoryError, PermissionError)):
        return False
    if isinstance(exc, (TimeoutError, ConnectionError, InterruptedError,
                        OSError)):
        return True
    return False


def with_retries(
    fn: Callable[[], Any],
    *,
    attempts: int = 3,
    base: float = 0.1,
    factor: float = 2.0,
    max_delay: float = 5.0,
    jitter: float = 0.25,
    transient: Optional[Classifier] = None,
    key: Optional[str] = None,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
) -> Any:
    """Call ``fn()`` with up to ``attempts`` tries.

    ``transient`` is either an exception-type tuple/list or a predicate;
    anything it rejects (or any exception when classification says
    permanent) re-raises immediately. The final transient failure
    re-raises as-is — callers see the real error, not a wrapper.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    if transient is None:
        classify = is_transient_default
    elif callable(transient):
        classify = transient
    else:
        types = tuple(transient)
        classify = lambda exc: isinstance(exc, types)  # noqa: E731

    for attempt in range(attempts):
        try:
            return fn()
        except Exception as exc:  # noqa: BLE001 — classified below
            if attempt + 1 >= attempts or not classify(exc):
                raise
            if on_retry is not None:
                on_retry(attempt, exc)
            _note_retry(attempt, exc, key)
            sleep(backoff_delay(attempt, base=base, factor=factor,
                                max_delay=max_delay, jitter=jitter, key=key))


def _note_retry(attempt: int, exc: BaseException,
                key: Optional[str]) -> None:
    """Observability tap on every transient retry: bump the unified
    registry's counter and annotate the active lifecycle span (so a
    chaos-injected store fault shows up as fault + retry ON the phase
    it hit). Passive by contract — never raises into the retry loop."""
    try:
        from polyaxon_tpu.obs import metrics as obs_metrics
        from polyaxon_tpu.obs import trace as obs_trace

        obs_metrics.retry_attempts().inc()
        obs_trace.add_event(
            "retry", attempt=attempt + 1,
            error=f"{type(exc).__name__}: {exc}"[:200],
            **({"key": key} if key else {}))
    except Exception as obs_exc:  # observability stays passive
        logging.getLogger(__name__).debug(
            "retry observability tap failed: %s", obs_exc)
