"""The mini-gauntlet (ISSUE 13): one compressed fleet episode whose
pass criteria are ONLY telemetry-oracle verdicts.

A fixed-seed :class:`FleetSim` replays a composed scenario — a
low-priority training job, preemptible tune churn (sweep + restart
jobs), mixed-class serving traffic, a mid-episode preemption storm,
and a chaos plan stalling scheduler ticks — while a fresh
``AlertEngine`` (the committed ruleset) watches every few ticks. At
the end nothing asserts on internals: the episode's telemetry is
bundled (:class:`obs.oracle.TelemetryBundle`) and judged against the
committed invariant set (``obs/oracle.json``). The stage passes iff
no invariant fails AND the load-bearing pair — ``all-runs-terminal``
and ``zero-unresolved-alerts`` — actually evaluated (a gauntlet whose
anchor invariants skip proved nothing).

The alert engine's injectable clock is fast-forwarded once the fleet
drains so rate/burn windows that the storm legitimately tripped can
empty and resolve — the fire-then-resolve arc lands in ``history``
(oracle evidence) instead of leaving a stale FIRING state that only
reflects the compressed timescale.

``--inject stuck-requeue`` is the self-test that the oracle CAN fail:
it suppresses the scheduler's preempted-run requeue path, so the
storm's victims sit PREEMPTED forever, the drain times out, and the
``all-runs-terminal`` invariant must flip the exit code — proving the
gauntlet's green is load-bearing, not decorative.

An optional real-serving segment (``--serving``) runs mixed-class
traffic through an actual ``ContinuousBatchingEngine`` (llama_tiny)
and dumps its request-timeline ring on stop, feeding the serving SLO
invariant real TTFT samples; CI keeps it off to stay CPU-cheap.
"""

from __future__ import annotations

import json
import logging
import tempfile
import time
from typing import Any, Optional

from polyaxon_tpu import chaos
from polyaxon_tpu.sim import traces
from polyaxon_tpu.sim.traces import TraceEvent, job_op, serving_op, sweep_op

logger = logging.getLogger(__name__)

GAUNTLET_SEED = 7
HORIZON = 6.0
INJECTS = ("stuck-requeue", "stuck-resize")
# The invariants a green gauntlet must have actually judged (verdict
# `pass`, not `skip`): terminal end state and a clean alert board are
# the whole point of the episode.
REQUIRED_INVARIANTS = ("all-runs-terminal", "zero-unresolved-alerts")

_CHAOS_PLAN = json.dumps({
    "seed": GAUNTLET_SEED,
    "faults": [
        {"seam": "tick", "op": "skip", "at": 5, "times": 2},
        {"seam": "tick", "op": "skip", "at": 40, "times": 1},
    ],
})


def build_gauntlet_trace(seed: int = GAUNTLET_SEED) -> list[TraceEvent]:
    """The composed episode, deterministic in ``seed``: serving deploys
    anchor capacity early (the storm's guaranteed victims alongside the
    train job), a low-priority train job and a tune sweep land on the
    preemptible batch queue, restart churn hammers best-effort, a
    half-fleet preemption storm hits mid-episode."""
    import random

    rng = random.Random(seed)
    events: list[TraceEvent] = [
        TraceEvent(0.0, "serving", serving_op(), "serving"),
        TraceEvent(0.1, "serving", serving_op(), "serving"),
        TraceEvent(0.2, "job",
                   job_op(queue="batch", name="train-lowpri"),
                   "research"),
        # The elastic lane (ISSUE 14): a long train job loses a slice
        # mid-run (shrink in place), capacity returns (grow back) — in
        # sim time, via SyntheticExecutor.request_resize.
        TraceEvent(0.2, "elastic",
                   job_op(queue="batch", name="train-elastic"),
                   "research"),
        TraceEvent(1.5, "slice-loss", None, payload={"op": "kill"}),
        TraceEvent(2.5, "slice-loss", None, payload={"op": "restore"}),
        TraceEvent(0.5, "sweep", sweep_op(8, queue="batch"), "research"),
    ]
    for _ in range(12):
        events.append(TraceEvent(
            round(rng.uniform(0.2, HORIZON), 6), "churn",
            job_op(queue="best-effort", restart=True),
            rng.choice(traces.PROJECTS)))
    for _ in range(30):
        queue = rng.choice(("batch", "best-effort", None))
        events.append(TraceEvent(
            round(rng.uniform(0.0, HORIZON), 6), "job", job_op(queue=queue),
            rng.choice(traces.PROJECTS)))
    events.append(TraceEvent(3.0, "storm", None,
                             payload={"fraction": 0.5}))
    events.sort(key=lambda e: (e.at, e.kind, e.project))
    return events


def _serving_segment(dump_dir: str) -> Optional[str]:
    """Mixed-class traffic through a REAL continuous-batching engine,
    ring dumped on stop. Returns the dump path (None when the serving
    stack is unavailable — the gauntlet core does not depend on jax)."""
    import os

    try:
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
        from polyaxon_tpu.serving.server import load_params
    except Exception:
        logger.warning("serving stack unavailable; gauntlet runs "
                       "without the serving segment", exc_info=True)
        return None
    dump_path = os.path.join(dump_dir, "request-timelines.json")
    cfg, params = load_params("llama_tiny", seed=0)
    engine = ContinuousBatchingEngine(
        "llama_tiny", cfg, params, slots=2,
        trace_dump_path=dump_path)
    try:
        rows = [[(i * 7 + j) % cfg.vocab_size for j in range(6)]
                for i in range(6)]
        for i, klass in enumerate(("interactive", "batch", "best-effort",
                                   "interactive", "batch", "interactive")):
            engine.generate([rows[i]], max_new_tokens=4, klass=klass)
    finally:
        engine.stop()
    return dump_path if os.path.exists(dump_path) else None


def run_gauntlet(*, seed: int = GAUNTLET_SEED,
                 inject: Optional[str] = None, serving: bool = False,
                 max_wall: float = 60.0,
                 oracle_source: Any = None) -> dict:
    """One gauntlet episode → ``{passed, oracle, sim, ...}``.

    ``inject`` applies a named deopt before the episode (see
    :data:`INJECTS`); the caller asserts the oracle catches it."""
    from polyaxon_tpu.obs import metrics as obs_metrics
    from polyaxon_tpu.obs import oracle as obs_oracle
    from polyaxon_tpu.obs import rules as obs_rules
    from polyaxon_tpu.sim.fleet import FleetSim

    if inject is not None and inject not in INJECTS:
        raise ValueError(f"unknown inject {inject!r} (one of {INJECTS})")
    invariants = obs_oracle.load_invariants(oracle_source)
    events = build_gauntlet_trace(seed)

    sim = FleetSim(seed=seed, capacity=24)
    # A storm that preempts nothing proves nothing: deploys submitted
    # at t=0 go live within the first ticks and are still running at
    # t=3.0, so the storm always has victims.
    clock_skew = [0.0]
    engine = obs_rules.AlertEngine(
        obs_rules.load_ruleset(),
        clock=lambda: time.time() + clock_skew[0])
    if inject == "stuck-requeue":
        # The oracle-can-fail self-test: preempted runs never requeue,
        # the storm's victims sit PREEMPTED past the drain timeout, and
        # all-runs-terminal MUST flip the episode to failure.
        sim.agent.scheduler._tick_preempted = lambda record: 0
        max_wall = min(max_wall, 20.0)
    elif inject == "stuck-resize":
        # The elastic self-test: the slice-loss lane's shrink never
        # completes, so the gang is never reapable (or, if the storm
        # kills it first, its stale `resizing` meta holds the PREEMPTED
        # requeue) — either way the drain times out and
        # all-runs-terminal MUST flip the episode to failure.
        sim.executor.suppress_resize_completion = True
        max_wall = min(max_wall, 20.0)
    chaos.install(chaos.ChaosPlan.load(_CHAOS_PLAN))
    baseline = obs_metrics.REGISTRY.snapshot()
    serving_dump: Optional[str] = None
    try:
        orig_tick = sim.tick

        def tick_with_alerts() -> None:
            orig_tick()
            if len(sim.tick_seconds) % 5 == 0:
                engine.evaluate(plane=sim.plane)

        sim.tick = tick_with_alerts
        sim_result = sim.run_trace(events, max_wall=max_wall)
        if serving:
            with tempfile.TemporaryDirectory(
                    prefix="plx-gauntlet-") as tmp:
                serving_dump = _serving_segment(tmp)
                if serving_dump is not None:
                    from polyaxon_tpu.obs import reqtrace

                    dump = reqtrace.read_ring_dump(serving_dump)
                    serving_dump = (f"{len((dump or {}).get('requests', []))}"
                                    " request timelines dumped")
        # The storm's rate windows (requeue-storm et al) see the burst
        # for their full window length; the fleet is drained, so jump
        # the engine clock past every window and let firings resolve —
        # the fire→resolve episode is the history the oracle inspects.
        clock_skew[0] = 600.0
        engine.evaluate(plane=sim.plane)
        bundle = obs_oracle.TelemetryBundle.from_plane(
            sim.plane, engine=engine, baseline=baseline)
        verdicts = obs_oracle.evaluate(invariants, bundle)
    finally:
        chaos.uninstall()
        sim.close()
    oracle_result = obs_oracle.summarize(verdicts)
    by_id = {v["invariant"]: v["verdict"] for v in verdicts}
    anchors_held = all(by_id.get(i) == "pass" for i in REQUIRED_INVARIANTS)
    return {
        "passed": oracle_result["passed"] and anchors_held,
        "anchors": {i: by_id.get(i, "missing")
                    for i in REQUIRED_INVARIANTS},
        "inject": inject,
        "trace_events": len(events),
        "serving_segment": serving_dump,
        "sim": sim_result,
        "oracle": oracle_result,
    }


# ------------------------------------------------------ the cluster day
# ROADMAP item 6's full profile: a compressed fleet day through the REAL
# scheduler/admission/store/serving stack, judged exclusively by oracle
# verdicts — including the window-scoped ones the metrics history
# enables (serving p99 DURING the marked storm, zero sampled quota
# breaches across the whole day).

# The paper's Hyperband throughput anchor (trials/hour sustained by the
# tuning lane over a cluster day). A real day at this rate is ~7094
# trials; the compressed day keeps the mapping-sweep lane (~90k trials)
# and sizes the Hyperband lane to a CI-feasible fraction of the anchor.
TRIALS_PER_HOUR = 295.6
CLUSTER_DAY_INJECTS = ("quota-breach", "stuck-requeue", "tier0-loss",
                       "stuck-tier0-commit")
# Invariants a green cluster day must have actually judged (pass, not
# skip). The serving-p99-during-storm anchor joins when the real
# serving engine ran (it skips only when the serving stack is absent),
# and serving-ttft-during-scaleup joins when the serving-fleet lane
# ran (ISSUE 17: interactive TTFT p99 through a rule-fired scale-up,
# judged over the lane's own marked window).
CLUSTER_DAY_REQUIRED = ("all-runs-terminal", "zero-unresolved-alerts",
                        "quota-violations-zero")

_CLUSTER_DAY_CHAOS = json.dumps({
    "seed": GAUNTLET_SEED,
    "faults": [
        # Store-fault lane: transient artifact-store errors mid-day.
        {"seam": "store", "op": "*", "at": 3, "times": 2,
         "config": {"error": "transient"}},
        # Stalled control plane: swallowed scheduler ticks.
        {"seam": "tick", "op": "skip", "at": 25, "times": 2},
    ],
})

_PROFILES = {
    # capacity, storm offset/span (compressed s), quotas (max_runs per
    # project), history cadence, hyperband sweeps (count, maxIterations,
    # eta), default wall budget.
    "quick": {"capacity": 24, "storm_at": 3.0, "storm_span": 2.0,
              "max_runs": 10, "cadence": 0.25,
              "hyperband": (1, 4, 2.0), "max_wall": 180.0},
    "full": {"capacity": 1000, "storm_at": 60.0, "storm_span": 10.0,
             "max_runs": 400, "cadence": 1.0,
             "hyperband": (8, 27, 3.0), "max_wall": 2400.0},
}


def build_cluster_day_trace(profile: str = "quick",
                            seed: int = GAUNTLET_SEED) -> list[TraceEvent]:
    """The day's arrival trace: the composed ``traces.make_trace``
    profile (jobs, mapping sweeps, DAGs, cron schedules, deploys,
    churn) minus its storm events — the driver fires the storm itself
    so it can mark the window and run serving traffic inside it — plus
    the Hyperband tuning lane."""
    import random

    from polyaxon_tpu.sim.traces import hyperband_op

    spec = _PROFILES[profile]
    base_profile = "day" if profile == "full" else "quick"
    events = [e for e in traces.make_trace(base_profile, seed=seed)
              if e.kind != "storm"]
    rng = random.Random(seed + 1)
    count, max_iter, eta = spec["hyperband"]
    horizon = max((e.at for e in events), default=0.0)
    for i in range(count):
        events.append(TraceEvent(
            round(rng.uniform(0.0, horizon * 0.5), 6), "sweep",
            hyperband_op(queue="batch", max_iterations=max_iter,
                         eta=eta, seed=seed + i),
            "research"))
    events.sort(key=lambda e: (e.at, e.kind, e.project))
    return events


def _start_serving():
    """(engine, prompt rows) for the continuous-traffic lane, or None
    when the serving stack is unavailable (the day still runs; the
    serving anchors then skip)."""
    try:
        from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
        from polyaxon_tpu.serving.server import load_params
    except Exception:
        logger.warning("serving stack unavailable; cluster day runs "
                       "without the serving lane", exc_info=True)
        return None
    cfg, params = load_params("llama_tiny", seed=0)
    engine = ContinuousBatchingEngine("llama_tiny", cfg, params, slots=2)
    rows = [[(i * 7 + j) % cfg.vocab_size for j in range(6)]
            for i in range(6)]
    return engine, rows


def _storm_lane(history) -> dict:
    """The ISSUE 18 lane: a DISAGGREGATED prefill/decode engine under a
    long-prompt storm, judged by the ``decode-tpot-during-prompt-storm``
    invariant over its own marked window.

    Shape discipline matters more than load here: every prompt class
    (short interactive, long batch) is driven through the engine ONCE
    before the window opens, with first tokens distinct from the storm
    prompts', so skip=0 re-admissions inside the window replay warm
    programs and the decode-gap histogram measures *scheduling* — not
    XLA compiles, which on the CI CPU would dwarf any real
    interference signal."""
    import threading

    from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
    from polyaxon_tpu.serving.server import load_params

    cfg, params = load_params("llama_tiny", seed=0)
    eng = ContinuousBatchingEngine(
        "llama_tiny", cfg, params, slots=2, kv="paged", page_size=8,
        prefill_slots=2, prefill_chunk=16)
    vocab = cfg.vocab_size
    # Distinct first tokens per prompt (warm AND storm) keep every
    # admission a radix miss: same skip=0 compile shapes throughout.
    short = [[(101 + 13 * i + j) % vocab for j in range(6)]
             for i in range(6)]
    long_rows = [[(211 + 17 * i + 3 * j) % vocab for j in range(40)]
                 for i in range(4)]
    try:
        eng.generate([short.pop()], max_new_tokens=6, klass="interactive")
        eng.generate([long_rows.pop()], max_new_tokens=4, klass="batch")
        history.sample(force=True)  # pre-window baseline for the delta
        history.mark_window("long-prompt-storm", start=True)
        errs: list = []

        def _drive(rows, klass, max_new):
            try:
                for r in rows:
                    eng.generate([r], max_new_tokens=max_new, klass=klass)
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        storm = threading.Thread(target=_drive,
                                 args=(long_rows, "batch", 4), daemon=True)
        storm.start()
        _drive(short, "interactive", 6)  # decode lane under the storm
        storm.join()
        history.sample(force=True)  # catch in-window TPOT before close
        history.mark_window("long-prompt-storm", end=True)
        if errs:
            raise errs[0]
        stats = eng.stats()
        return {
            "requests": stats["requests_served"],
            "handoffs": stats["handoffs"],
            "handoff_pages": stats["handoff_pages"],
            "kv_invariant_violations": stats["kv_invariant_violations"],
        }
    finally:
        eng.stop()


def _skew_drill(engine, plane=None) -> bool:
    """Drill the ``fleet-replica-skew`` rule's FIRE half on the real
    evaluate path (ISSUE 20): three component-scoped views record TTFT
    — two healthy, one far over — ``publish_fleet_rollups`` derives
    the max/median ratio, and the threshold rule must fire. Teardown
    then releases the drill components (``drop_component``, the same
    GC a real replica release runs) and recomputes the rollup; the
    RESOLVED half is asserted by the caller after the day's stepped
    alert-clock passes, so the evidence in alert history is the full
    fire→resolve arc. Returns whether the rule fired."""
    from polyaxon_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.REGISTRY
    # Two fast components pin the median near the healthy TTFT; the
    # slow one is far past threshold x median, so the ratio fires the
    # rule regardless of what the day's real replicas observed.
    for comp, ttft in (("drill-a", 0.04), ("drill-b", 0.05),
                       ("drill-slow", 30.0)):
        view = reg.scoped(comp)
        for _ in range(4):
            obs_metrics.serving_ttft_hist(view).observe(
                ttft, **{"class": "drill"})
    obs_metrics.publish_fleet_rollups(reg)
    engine.evaluate(plane=plane)
    fired = any(a["rule"] == "fleet-replica-skew"
                for a in engine.active())
    for comp in ("drill-slow", "drill-a", "drill-b"):
        reg.drop_component(comp)
    obs_metrics.publish_fleet_rollups(reg)
    return fired


def _class_storm_lane(history) -> dict:
    """The ISSUE 19 lane: best-effort traffic camps every decode slot,
    then interactive arrivals must admit via preemptive slot/KV
    eviction — judged by the ``interactive-ttft-during-storm``
    invariant over the lane's own marked window (interactive p99 ONLY:
    the all-class invariants average the best-effort wall in and so
    cannot see priority inversion).

    Same shape discipline as the long-prompt-storm lane: every prompt
    shape runs once pre-window, so in-window admissions replay warm
    programs and the TTFT histogram measures *scheduling* — not XLA
    compiles, which on the CI CPU would dwarf the preemption signal."""
    from polyaxon_tpu.serving.batching import ContinuousBatchingEngine
    from polyaxon_tpu.serving.server import load_params

    cfg, params = load_params("llama_tiny", seed=0)
    eng = ContinuousBatchingEngine(
        "llama_tiny", cfg, params, slots=2, kv="paged", page_size=4)
    vocab = cfg.vocab_size
    # Distinct first tokens per prompt keep every admission a radix
    # miss: same skip=0 compile shapes throughout.
    best_effort = [[(31 + 19 * i + 5 * j) % vocab for j in range(6)]
                   for i in range(4)]
    interactive = [[(173 + 23 * i + 7 * j) % vocab for j in range(6)]
                   for i in range(4)]
    try:
        eng.generate([interactive.pop()], max_new_tokens=4,
                     klass="interactive")
        eng.generate([best_effort.pop()], max_new_tokens=4,
                     klass="best-effort")
        # Saturate: long best-effort generations camp every decode slot
        # (plus one queued spare) BEFORE the window opens, so every
        # in-window interactive arrival finds the engine full.
        campers = [eng.submit(r, 48, klass="best-effort")
                   for r in best_effort]
        deadline = time.monotonic() + 30.0
        while (eng.health()["decode_active"] < 2
               and time.monotonic() < deadline):
            time.sleep(0.01)
        history.sample(force=True)  # pre-window baseline for the delta
        history.mark_window("class-preemption-storm", start=True)
        for row in interactive:
            eng.generate([row], max_new_tokens=4, klass="interactive")
        history.sample(force=True)  # catch in-window TTFT before close
        history.mark_window("class-preemption-storm", end=True)
        for r in campers:  # evicted campers re-admit and finish
            r.wait(timeout=120)
        # Close the books in REAL time: the victims' re-emission TTFTs
        # (long by design — they span the eviction round trip) land in
        # this sample, so the post-skew final evaluate's trailing
        # window diffs two identical carry-forward edges instead of
        # bracketing only the lane's tail and reading it as a 100%
        # TTFT-SLO error rate (day-end firings cannot resolve: there
        # is no evaluate after the last one).
        history.sample(force=True)
        stats = eng.stats()
        return {
            "requests": stats["requests_served"],
            "preemptions": sum(stats["preemptions"].values()),
            "readmit_suffix_tokens": stats["readmit_suffix_tokens"],
            "kv_invariant_violations": stats["kv_invariant_violations"],
        }
    finally:
        eng.stop()


_TRAFFIC_CLASSES = ("interactive", "batch", "interactive", "best-effort")


def run_cluster_day(*, profile: str = "quick", seed: int = GAUNTLET_SEED,
                    inject: Optional[str] = None, serving: bool = True,
                    max_wall: Optional[float] = None,
                    oracle_source: Any = None) -> dict:
    """One compressed cluster day → ``{passed, oracle, sim, ...}``.

    Phases: (1) the morning — arrival trace up to the storm offset,
    with continuous mixed-class serving traffic riding the tick loop;
    (2) the marked mid-day preemption storm — ``mark_window("storm")``
    brackets it while interactive/batch traffic keeps flowing, so the
    during-storm invariants have in-window samples; (3) the rest of
    the day plus drain; (4) the serving-fleet lane (ISSUE 17) — a
    traffic spike in its own marked window driving a rule-fired
    scale-up, then drain + scale-down, with interactive TTFT p99
    judged through the scale event; (5) the long-prompt-storm lane
    (ISSUE 18) — a disaggregated prefill/decode engine absorbing
    concurrent long-batch prefills inside its own marked window, with
    decode TPOT p99 judged during the storm; (6) the
    class-preemption-storm lane (ISSUE 19) — best-effort traffic
    saturates the engine and interactive arrivals admit via preemptive
    eviction, with interactive-only TTFT p99 judged inside the lane's
    window; (7) alert-clock fast-forward and the oracle's single
    judgment pass. Pass criteria are ONLY oracle verdicts plus the
    fleet/storm/class lanes' hit-rate/handoff/preemption/invariant
    checks.

    ``inject="quota-breach"`` is the red-team self-test: admission's
    quota check is bypassed (and quotas tightened), so sampled usage
    must exceed the limit gauges and ``quota-violations-zero`` MUST
    flip the exit code.

    The checkpoint-lane injects (ISSUE 16) drill both directions:
    ``tier0-loss`` adds an inexhaustible chaos fault that drops the
    cheap tiers before every restore — the day must STILL pass via the
    store fallback (the restore-budget anchor is waived; no tier-0
    samples exist to judge) — while ``stuck-tier0-commit`` wedges the
    tier-1 atomic commit (``tiers.WEDGE_TIER0_COMMITS``), gangs with an
    outstanding commit are never reaped, and ``all-runs-terminal`` MUST
    flip the exit code."""
    import dataclasses

    from polyaxon_tpu.obs import history as obs_history
    from polyaxon_tpu.obs import metrics as obs_metrics
    from polyaxon_tpu.obs import oracle as obs_oracle
    from polyaxon_tpu.obs import rules as obs_rules
    from polyaxon_tpu.runtime import tiers
    from polyaxon_tpu.sim.fleet import FleetSim

    if inject is not None and inject not in CLUSTER_DAY_INJECTS:
        raise ValueError(
            f"unknown inject {inject!r} (one of {CLUSTER_DAY_INJECTS})")
    spec = _PROFILES[profile]
    if max_wall is None:
        max_wall = spec["max_wall"]
    invariants = obs_oracle.load_invariants(oracle_source)
    events = build_cluster_day_trace(profile, seed)
    storm_at = spec["storm_at"]
    morning = [e for e in events if e.at <= storm_at]
    evening = [dataclasses.replace(e, at=round(e.at - storm_at, 6))
               for e in events if e.at > storm_at]

    sim = FleetSim(seed=seed, capacity=spec["capacity"],
                   checkpoint_lane=True)
    quota_runs = 2 if inject == "quota-breach" else spec["max_runs"]
    for project, weight in (("platform", 2.0), ("research", 1.0),
                            ("serving", 4.0), ("growth", 1.0)):
        sim.plane.set_quota(project, max_runs=quota_runs, weight=weight)
    if inject == "quota-breach":
        # Enforcement off, limits still published: sampled usage must
        # cross the limit gauges and the oracle must catch it.
        orig_admissible = sim.admission._admissible

        def _no_quota(record, info, queue, quotas, usage, plan, blocked):
            return orig_admissible(record, info, queue, {}, usage,
                                   plan, blocked)

        sim.admission._admissible = _no_quota
    elif inject == "stuck-requeue":
        sim.agent.scheduler._tick_preempted = lambda record: 0
        max_wall = min(max_wall, 30.0)
    elif inject == "stuck-tier0-commit":
        tiers.WEDGE_TIER0_COMMITS = True  # reset in the finally below
        max_wall = min(max_wall, 30.0)

    clock_skew = [0.0]
    engine = obs_rules.AlertEngine(
        obs_rules.load_ruleset(),
        clock=lambda: time.time() + clock_skew[0])
    # The day gets its own default history ring (tight cadence at quick
    # scale) — the agent hook, the oracle bundle, and the window
    # markers all share it via default_history().
    prior_history = obs_history.default_history()
    history = obs_history.MetricsHistory(
        obs_metrics.REGISTRY, cadence=spec["cadence"])
    obs_history.set_default_history(history)
    chaos_spec = json.loads(_CLUSTER_DAY_CHAOS)
    if inject == "tier0-loss":
        # Inexhaustible: EVERY restore finds its cheap tiers dropped
        # and must walk down to the store stand-in.
        chaos_spec["faults"].append(
            {"seam": "tier0-loss", "op": "drop", "times": 1000000})
    chaos.install(chaos.ChaosPlan.load(json.dumps(chaos_spec)))
    baseline = obs_metrics.REGISTRY.snapshot()
    serving_lane = _start_serving() if serving else None
    traffic = [0]  # requests served (continuous lane + storm lane)

    def _one_request() -> None:
        if serving_lane is None:
            return
        eng, rows = serving_lane
        i = traffic[0]
        eng.generate([rows[i % len(rows)]], max_new_tokens=2,
                     klass=_TRAFFIC_CLASSES[i % len(_TRAFFIC_CLASSES)])
        traffic[0] += 1

    t_start = time.monotonic()
    try:
        orig_tick = sim.tick

        def tick_with_lanes() -> None:
            orig_tick()
            ticks = len(sim.tick_seconds)
            if ticks % 8 == 0:
                _one_request()  # continuous mixed-class traffic
            if ticks % 8 == 4:
                sim.executor.drill_restore()  # day-wide restore samples
            if ticks % 5 == 0:
                engine.evaluate(plane=sim.plane)

        sim.tick = tick_with_lanes
        sim.run_trace(morning, max_wall=max_wall * 0.4, drain=False)
        # -- the marked mid-day storm ---------------------------------
        sim._submit_event(TraceEvent(
            0.0, "storm", None,
            payload={"fraction": 0.5, "window": "storm",
                     "window_seconds": spec["storm_span"]}))
        storm_deadline = time.monotonic() + spec["storm_span"]
        while time.monotonic() < storm_deadline:
            _one_request()  # in-window serving samples
            sim.executor.drill_restore()  # in-window restore samples
            sim.tick()
        history.sample(force=True)  # catch in-window TTFT before close
        sim.tick()  # past the deadline: closes the storm window
        # -- the rest of the day + drain ------------------------------
        remaining = max(max_wall - (time.monotonic() - t_start), 30.0)
        sim.run_trace(evening, max_wall=remaining)
        if serving_lane is not None:
            serving_lane[0].stop()
        # -- the serving-fleet lane (ISSUE 17) ------------------------
        # Spike → rule-fired scale-up inside its OWN marked window →
        # drain → scale-down, over real engine replicas behind the
        # prefix-affinity router. It shares the day's history ring and
        # alert engine, so the oracle judges it on the same evidence
        # plane as the storm (serving-ttft-during-scaleup is the
        # anchor). Runs after the day drains: the single-host CI box
        # can't afford replica compile churn during the storm window.
        fleet_summary = None
        if serving_lane is not None and inject is None:
            try:
                from polyaxon_tpu.sim import fleet_serve
                fleet, vocab, fspec = fleet_serve.build_fleet(
                    profile=profile, seed=seed)
                try:
                    fleet_serve.warm_phase(fleet, vocab, fspec, seed)
                    spike = fleet_serve.spike_phase(
                        fleet, vocab, fspec, seed, history, engine,
                        plane=sim.plane)
                    # Federated-view coverage while every replica's
                    # scoped series are still live (release drops them).
                    gaps = fleet_serve.telemetry_gaps(fleet)
                    drained = fleet_serve.drain_phase(
                        fleet, engine, clock_skew, plane=sim.plane)
                    fstats = fleet.stats()
                    traffic[0] += spike["requests"]
                    # Skew drill: fire the fleet-replica-skew rule on
                    # scoped drill series; the stepped clock passes at
                    # the end of the day must then observe it resolve.
                    skew_fired = _skew_drill(engine, plane=sim.plane)
                    fleet_summary = {
                        "requests": spike["requests"],
                        "scale_up_committed": spike["scale_up_committed"],
                        "scale_down_drained": drained,
                        "telemetry_gaps": gaps,
                        "skew_fired": skew_fired,
                        "prefix_hit_rate": fstats["prefix_hit_rate"],
                        "kv_invariant_violations":
                            fstats["kv_invariant_violations"],
                        "routed": fstats["router"]["routed"],
                        "scale_events": fstats["scale_events"],
                    }
                finally:
                    fleet.stop()
            # polycheck: ignore[invariant-swallow] -- lane degradation, same posture as _start_serving: the day still runs and the scale-up anchor is simply not required
            except Exception:  # noqa: BLE001
                logger.warning("fleet lane unavailable; cluster day "
                               "runs without it", exc_info=True)
        # -- the long-prompt-storm lane (ISSUE 18) --------------------
        # A disaggregated prefill/decode engine absorbs concurrent
        # long-batch prefills while short interactive decodes keep
        # stepping; the marked window scopes the decode-TPOT invariant
        # to exactly that pressure. Same posture as the fleet lane:
        # runs after the day drains, degrades to "anchor not required"
        # if the serving stack can't build it.
        lane_summary = None
        if serving_lane is not None and inject is None:
            try:
                lane_summary = _storm_lane(history)
                traffic[0] += lane_summary["requests"]
            # polycheck: ignore[invariant-swallow] -- lane degradation, same posture as the fleet lane: the day still runs and the storm anchor is simply not required
            except Exception:  # noqa: BLE001
                logger.warning("long-prompt-storm lane unavailable; "
                               "cluster day runs without it",
                               exc_info=True)
        # -- the class-preemption-storm lane (ISSUE 19) ---------------
        # Best-effort traffic saturates every slot, then interactive
        # arrivals must admit via preemptive eviction inside the
        # lane's own marked window; the interactive-only TTFT p99
        # invariant is the judge. Same degradation posture as above.
        class_lane_summary = None
        if serving_lane is not None and inject is None:
            try:
                class_lane_summary = _class_storm_lane(history)
                traffic[0] += class_lane_summary["requests"]
            # polycheck: ignore[invariant-swallow] -- lane degradation, same posture as the fleet lane: the day still runs and the preemption anchor is simply not required
            except Exception:  # noqa: BLE001
                logger.warning("class-preemption-storm lane unavailable; "
                               "cluster day runs without it",
                               exc_info=True)
        # Drained: fast-forward the alert clock past every rate/burn
        # window so storm-tripped firings resolve (the mini-gauntlet
        # posture — the fire→resolve arc is the evidence). STEPPED,
        # not a single jump: tick-loop evaluates stop at trace end but
        # serving activity continues through the lanes, so a burn rule
        # still breaching at its last real-clock evaluate (the class
        # lane's preemption round trips land exactly there) only
        # STARTS its resolve_after clock at the first skewed pass —
        # resolution needs a later clear evaluate, and each step's
        # windows are empty (no samples move past the last real one).
        for skew in (600.0, 700.0, 800.0):
            clock_skew[0] = skew
            engine.evaluate(plane=sim.plane)
        if fleet_summary is not None:
            # The drill's resolve half: after the stepped passes the
            # skew rule must be clear (the drilled components were
            # dropped and the fleet's own teardown unset the gauge).
            fleet_summary["skew_resolved"] = not any(
                a["rule"] == "fleet-replica-skew"
                for a in engine.active())
        bundle = obs_oracle.TelemetryBundle.from_plane(
            sim.plane, engine=engine, baseline=baseline)
        verdicts = obs_oracle.evaluate(invariants, bundle)
        sim_result = {
            "events": len(events),
            "submitted": sim.submitted_total,
            "started": sim.executor.started_total,
            "reaped": sim.executor.reaped_total,
            "wall_seconds": round(time.monotonic() - t_start, 3),
            "divergence_total": sim.admission.divergence_total,
            "restores_by_tier": dict(sim.executor.restores_by_tier),
            **sim.tick_report(),
        }
        window = obs_history.window_bounds(bundle.history or {}, "storm")
    finally:
        if serving_lane is not None:
            try:
                serving_lane[0].stop()
            # polycheck: ignore[invariant-swallow] -- cleanup in a finally: a lane already stopped by the episode raising must not shadow the original exception
            except Exception:  # noqa: BLE001
                pass
        tiers.WEDGE_TIER0_COMMITS = False
        chaos.uninstall()
        sim.close()
        obs_history.set_default_history(prior_history)
    oracle_result = obs_oracle.summarize(verdicts)
    by_id = {v["invariant"]: v["verdict"] for v in verdicts}
    required = list(CLUSTER_DAY_REQUIRED)
    if serving_lane is not None:
        required.append("serving-p99-during-storm")
    if fleet_summary is not None:
        required.append("serving-ttft-during-scaleup")
        # The fleet-federated TTFT invariant judges the merged
        # per-component series over the same window (ISSUE 20).
        required.append("serving-ttft-federated-during-scaleup")
    if lane_summary is not None:
        required.append("decode-tpot-during-prompt-storm")
    if class_lane_summary is not None:
        required.append("interactive-ttft-during-storm")
    if inject != "tier0-loss":
        # Under tier0-loss every restore lands on the store tier, so no
        # tier-0 samples exist in the window and the invariant rightly
        # skips — requiring it there would punish the fallback working.
        required.append("restore-budget-during-storm")
    anchors_held = all(by_id.get(i) == "pass" for i in required)
    # The fleet lane's own acceptance (ISSUE 17): cross-replica prefix
    # reuse actually happened, every replica's pool invariants held,
    # and the spike really drove a committed scale-up.
    fleet_held = (fleet_summary is None
                  or ((fleet_summary["prefix_hit_rate"] or 0.0) > 0
                      and fleet_summary["kv_invariant_violations"] == 0
                      and fleet_summary["scale_up_committed"]
                      # ISSUE 20: every serving replica present in the
                      # federated view, and the skew rule drilled
                      # through its full fire→resolve arc.
                      and not fleet_summary["telemetry_gaps"]
                      and fleet_summary["skew_fired"]
                      and fleet_summary["skew_resolved"]))
    # The storm lane's own acceptance (ISSUE 18): pages really crossed
    # the prefill→decode boundary and the pool's refcount/CoW
    # invariants held through every handoff.
    lane_held = (lane_summary is None
                 or (lane_summary["handoffs"] > 0
                     and lane_summary["kv_invariant_violations"] == 0))
    # The class lane's own acceptance (ISSUE 19): interactive arrivals
    # really forced evictions, and every release went through the
    # fresh-leaf path cleanly.
    class_lane_held = (class_lane_summary is None
                       or (class_lane_summary["preemptions"] > 0
                           and class_lane_summary[
                               "kv_invariant_violations"] == 0))
    scaleup_window = obs_history.window_bounds(bundle.history or {},
                                               "scale-up")
    storm_lane_window = obs_history.window_bounds(bundle.history or {},
                                                  "long-prompt-storm")
    class_lane_window = obs_history.window_bounds(
        bundle.history or {}, "class-preemption-storm")
    return {
        "passed": (oracle_result["passed"] and anchors_held
                   and fleet_held and lane_held and class_lane_held),
        "profile": profile,
        "anchors": {i: by_id.get(i, "missing") for i in required},
        "inject": inject,
        "trace_events": len(events),
        "serving_requests": traffic[0],
        "storm_window": ([round(t, 3) for t in window] if window
                         else None),
        "scale_up_window": ([round(t, 3) for t in scaleup_window]
                            if scaleup_window else None),
        "fleet": fleet_summary,
        "long_prompt_storm": lane_summary,
        "long_prompt_storm_window": (
            [round(t, 3) for t in storm_lane_window]
            if storm_lane_window else None),
        "class_preemption_storm": class_lane_summary,
        "class_preemption_storm_window": (
            [round(t, 3) for t in class_lane_window]
            if class_lane_window else None),
        "history_samples": ((bundle.history or {}).get("coverage")
                            or {}).get("samples"),
        "sim": sim_result,
        "oracle": oracle_result,
    }


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="Mini-gauntlet: composed fleet episode judged "
                    "exclusively by the telemetry oracle")
    parser.add_argument("--seed", type=int, default=GAUNTLET_SEED)
    parser.add_argument("--inject", choices=INJECTS, default=None,
                        help="apply a named deopt; the run is EXPECTED "
                             "to fail (exit flips accordingly only in "
                             "the caller — this exits nonzero on fail)")
    parser.add_argument("--serving", action="store_true",
                        help="include the real-engine serving segment "
                             "(needs jax; slower)")
    parser.add_argument("--max-wall", type=float, default=60.0)
    parser.add_argument("--json", action="store_true", dest="as_json")
    args = parser.parse_args(argv)
    result = run_gauntlet(seed=args.seed, inject=args.inject,
                          serving=args.serving, max_wall=args.max_wall)
    if args.as_json:
        print(json.dumps(result, indent=2, default=str))
    else:
        print_result(result, label="mini-gauntlet")
    return 0 if result["passed"] else 1


def print_result(result: dict, label: str = "mini-gauntlet") -> None:
    """Human summary of a gauntlet result (mini or cluster-day)."""
    counts = result["oracle"]["counts"]
    print(f"{label}: {result['trace_events']} events, "
          f"{result['sim']['reaped']} runs reaped in "
          f"{result['sim']['wall_seconds']}s")
    for v in result["oracle"]["verdicts"]:
        marker = {"pass": "ok  ", "skip": "skip", "fail": "FAIL"}
        detail = ("" if v["verdict"] == "pass"
                  else f"  {json.dumps(v['evidence'], default=str)[:160]}")
        print(f"  [{marker[v['verdict']]}] {v['invariant']}{detail}")
    print(f"verdicts: {counts['pass']} pass / {counts['fail']} fail "
          f"/ {counts['skip']} skip; anchors: {result['anchors']}")
    print("GAUNTLET " + ("PASSED" if result["passed"] else "FAILED"))


if __name__ == "__main__":  # pragma: no cover - exercised via ci.sh
    raise SystemExit(main())
