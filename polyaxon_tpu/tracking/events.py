"""Event-file contract: typed jsonl streams per run.

Parity with traceml's event model (SURVEY.md §2 "Tracking", §5.5 [K]):
each ``log_*`` call appends a typed jsonl line under the run's events
dir; the sidecar ships the tree to the artifacts store; streams serve it
back. Layout (under ``<artifacts>/<run_uuid>/``):

    events/metric/<name>.jsonl     {"timestamp", "step", "value"}
    events/<kind>/<name>.jsonl     other typed kinds
    logs/<name>.log                plain text
    statuses.jsonl                 condition stream
    outputs.json                   declared outputs (merged)
    lineage.jsonl                  artifact lineage records
"""

from __future__ import annotations

import datetime as _dt
import json
import os
from typing import Any, Iterator, Optional


class V1EventKind:
    METRIC = "metric"
    IMAGE = "image"
    HISTOGRAM = "histogram"
    TEXT = "text"
    HTML = "html"
    AUDIO = "audio"
    VIDEO = "video"
    MODEL = "model"
    DATAFRAME = "dataframe"
    ARTIFACT = "artifact"
    CURVE = "curve"
    CONFUSION = "confusion"
    SYSTEM = "system"
    SPAN = "span"  # lifecycle trace spans (obs.trace)

    VALUES = {METRIC, IMAGE, HISTOGRAM, TEXT, HTML, AUDIO, VIDEO, MODEL,
              DATAFRAME, ARTIFACT, CURVE, CONFUSION, SYSTEM, SPAN}


def _now_iso() -> str:
    return _dt.datetime.now(_dt.timezone.utc).isoformat()


class EventWriter:
    """Append-only jsonl writer for one run directory. Buffered per file;
    ``flush()`` is cheap and called by the tracking Run on every batch."""

    def __init__(self, run_dir: str):
        self.run_dir = run_dir
        self._handles: dict[str, Any] = {}

    def _handle(self, kind: str, name: str):
        key = f"{kind}/{name}"
        if key not in self._handles:
            path = os.path.join(self.run_dir, "events", kind, f"{name}.jsonl")
            os.makedirs(os.path.dirname(path), exist_ok=True)
            self._handles[key] = open(path, "a", buffering=1)
        return self._handles[key]

    def write(self, kind: str, name: str, record: dict[str, Any]) -> None:
        record.setdefault("timestamp", _now_iso())
        self._handle(kind, name).write(json.dumps(record) + "\n")

    def metric(self, name: str, value: float, step: Optional[int] = None) -> None:
        self.write(V1EventKind.METRIC, name, {"step": step, "value": float(value)})

    def flush(self) -> None:
        for handle in self._handles.values():
            handle.flush()

    def close(self) -> None:
        """Release every lazily-opened handle. Idempotent; invoked from
        the tracking Run teardown, the runtime loop's ExitStack (via its
        RunTracer), and the executor's gang reap — a finished run must
        not pin open fds for its whole process lifetime."""
        for handle in self._handles.values():
            try:
                handle.close()
            except OSError:
                pass
        self._handles.clear()

    def __enter__(self) -> "EventWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def safe_subpath(root: str, rel: str) -> str:
    """Join a (possibly namespaced) user-supplied name under ``root``,
    rejecting absolute paths and ``..`` escapes. The single guard every
    read path (events, metrics, logs) funnels through."""
    path = os.path.abspath(os.path.join(root, rel))
    root = os.path.abspath(root)
    if not path.startswith(root + os.sep):
        raise ValueError(f"name escapes its directory: {rel!r}")
    return path


def read_jsonl(path: str) -> list[dict[str, Any]]:
    """Tolerant jsonl reader: skips blank and torn lines (a sidecar may
    sync a file mid-write). Shared by event and lineage readers."""
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail write mid-sync
    return out


def read_events(run_dir: str, kind: str, name: str,
                since_step: Optional[int] = None) -> list[dict[str, Any]]:
    path = safe_subpath(os.path.join(run_dir, "events", kind), f"{name}.jsonl")
    records = read_jsonl(path)
    if since_step is not None:
        records = [r for r in records if (r.get("step") or 0) > since_step]
    return records


def list_event_names(run_dir: str, kind: str) -> list[str]:
    """All event names of a kind, recursively — slash-namespaced names
    ('eval/sample') live in nested dirs and are returned with their
    relative path as the name."""
    root = os.path.join(run_dir, "events", kind)
    if not os.path.isdir(root):
        return []
    names = []
    for dirpath, _, files in os.walk(root):
        rel = os.path.relpath(dirpath, root)
        for f in files:
            if f.endswith(".jsonl"):
                name = f[:-6] if rel == "." else f"{rel}/{f[:-6]}"
                names.append(name.replace(os.sep, "/"))
    return sorted(names)


def tail_file(path: str, offset: int = 0) -> tuple[str, int]:
    """Read text from ``offset``; returns (chunk, new_offset)."""
    if not os.path.exists(path):
        return "", offset
    with open(path) as fh:
        fh.seek(offset)
        chunk = fh.read()
        return chunk, fh.tell()
