#!/usr/bin/env python
"""Profiler-trace analysis: name the top time sinks of a captured step.

VERDICT r4 item 2's evidence step, scripted so a tunnel window spends
its minutes measuring, not spelunking: given a trace directory (a
``--profile`` sweep point's ``profiles/<tag>/`` or any run's
``<artifacts>/profile``), this finds the newest ``*.xplane.pb``,
converts it with the in-env xprof tooling, and prints

- a category rollup (matmul/convolution self-time share = the ceiling
  on MFU this program can reach no matter how fast the MXU runs), and
- the top-N ops by self time with their measured GFLOP/s and memory
  bandwidth — the non-matmul sink VERDICT asks to be named is the
  first non-matmul row.

Ends with ONE JSON line (machine-readable, perf_sweep-attachable).

Caveat: XLA:CPU traces carry no per-op device stats (hlo_stats comes
back empty and framework_op_stats holds a lone host IDLE row — checked
2026-08-01), so off-chip runs only validate the plumbing; the analysis
itself is for real-TPU captures.

Usage: python scripts/analyze_trace.py <trace-dir> [--top 15]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def find_xplane(root: str) -> str:
    hits = sorted(glob.glob(os.path.join(root, "**", "*.xplane.pb"),
                            recursive=True), key=os.path.getmtime)
    if not hits:
        raise SystemExit(f"no *.xplane.pb under {root!r} — pass a "
                         "profiles/<tag>/ dir or a run's artifacts/profile")
    return hits[-1]


def _gviz_rows(table: dict) -> list[dict]:
    cols = [c["id"] for c in table.get("cols", [])]
    rows = []
    for row in table.get("rows", []):
        vals = [cell.get("v") if isinstance(cell, dict) else cell
                for cell in row.get("c", [])]
        rows.append(dict(zip(cols, vals)))
    return rows


def load_op_stats(xplane: str) -> tuple[list[dict], str]:
    """(rows, tool) — hlo_stats (per-HLO, the TPU view) with a
    framework_op_stats fallback: CPU traces leave hlo_stats empty, and
    the framework table keeps the analyzer testable off-chip (same
    self-time/occurrence columns, coarser op identity)."""
    from xprof.convert import raw_to_tool_data

    data, _ = raw_to_tool_data.xspace_to_tool_data([xplane], "hlo_stats", {})
    rows = _gviz_rows(json.loads(
        data if isinstance(data, str) else data.decode()))
    if rows:
        return rows, "hlo_stats"
    data, _ = raw_to_tool_data.xspace_to_tool_data(
        [xplane], "framework_op_stats", {})
    parsed = json.loads(data if isinstance(data, str) else data.decode())
    tables = parsed if isinstance(parsed, list) else [parsed]
    rows = [r for t in tables for r in _gviz_rows(t)]
    # A device table that is pure IDLE carries no information (the CPU
    # backend's device plane) — the host table holds the real ops then.
    informative = [r for r in rows
                   if str(r.get("operation", "")).upper() != "IDLE"]
    rows = informative or rows
    for r in rows:  # map the framework columns onto the hlo names
        r.setdefault("category", r.get("type"))
        r.setdefault("hlo_op_name", r.get("operation"))
        r.setdefault("total_self_time", r.get("total_self_time")
                     or r.get("total_time"))
    return rows, "framework_op_stats"


MATMUL_CATEGORIES = {"convolution", "convolution fusion", "matmul",
                     "dot", "output fusion"}
# TPU hlo_stats buckets MXU work mostly under "convolution"/"dot"/
# fused variants; everything else (loop fusion, copy, reduce,
# all-reduce, ...) is the non-matmul time MFU analysis hunts.


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("trace_dir")
    parser.add_argument("--top", type=int, default=15)
    args = parser.parse_args()

    xplane = find_xplane(args.trace_dir)
    rows, tool = load_op_stats(xplane)
    if not rows:
        print(json.dumps({"trace": xplane, "error": "no op stats"}))
        return 1

    def f(row, key):
        try:
            return float(row.get(key) or 0.0)
        except (TypeError, ValueError):
            return 0.0

    total_self = sum(f(r, "total_self_time") for r in rows) or 1.0
    by_cat: dict[str, float] = {}
    for r in rows:
        cat = (r.get("category") or "?").lower()
        by_cat[cat] = by_cat.get(cat, 0.0) + f(r, "total_self_time")
    cat_table = sorted(by_cat.items(), key=lambda kv: -kv[1])
    matmul_pct = 100.0 * sum(
        t for c, t in by_cat.items() if c in MATMUL_CATEGORIES) / total_self

    print(f"# trace: {xplane}")
    print(f"# total self time: {total_self / 1e3:.2f} ms across "
          f"{len(rows)} ops ({tool})")
    print(f"\n== category rollup (matmul-ish share = {matmul_pct:.1f}% — "
          "the MFU ceiling of this program)")
    for cat, t in cat_table:
        print(f"  {100.0 * t / total_self:5.1f}%  {t / 1e3:8.2f} ms  {cat}")

    ranked = sorted(rows, key=lambda r: -f(r, "total_self_time"))
    print(f"\n== top {args.top} ops by self time")
    print(f"  {'self%':>6} {'ms':>8} {'GFLOP/s':>9} {'GiB/s':>7} "
          f"{'category':<18} op")
    for r in ranked[: args.top]:
        cat = (r.get("category") or "?").lower()
        pct = 100.0 * f(r, "total_self_time") / total_self
        name = str(r.get("hlo_op_name") or "?")[:60]
        print(f"  {pct:6.1f} {f(r, 'total_self_time') / 1e3:8.2f} "
              f"{f(r, 'model_flop_rate'):9.1f} "
              f"{f(r, 'measured_memory_bw'):7.1f} {cat:<18} {name}")
    # The headline answer walks the FULL ranking, not the display
    # slice — a matmul-dominated top-N must not report null while a
    # real non-matmul sink sits just below the cutoff.
    top_non_matmul = None
    for r in ranked:
        cat = (r.get("category") or "?").lower()
        if cat not in MATMUL_CATEGORIES:
            top_non_matmul = {
                "op": str(r.get("hlo_op_name") or "?")[:60],
                "category": cat,
                "self_pct": round(
                    100.0 * f(r, "total_self_time") / total_self, 2),
            }
            break

    print()
    print(json.dumps({
        "trace": xplane,
        "tool": tool,
        "total_self_ms": round(total_self / 1e3, 2),
        "matmul_self_pct": round(matmul_pct, 2),
        "top_non_matmul": top_non_matmul,
        "categories": {c: round(100.0 * t / total_self, 2)
                       for c, t in cat_table},
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
