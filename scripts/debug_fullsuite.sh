#!/bin/sh
# Single-process full-suite DIAGNOSTIC harness (faulthandler + RSS
# sampling) for the intermittent abort history. Since the 2026-08-04
# promotion, plain `ci.sh --full` already runs one process; the
# per-module quarantine lives on as `ci.sh --full-modules`. Run THIS
# when a crash needs attribution, not just a green/red.
#
# Root cause (identified 2026-08-01, see tests/conftest.py NOTE 2):
# XLA:CPU's collective-rendezvous watchdog CHECK-aborts the whole
# process when a starved device thread misses a rendezvous for 40 s —
# easy on this 1-core host with 8 device threads. The SIGABRT dump
# shows the main thread (often mid-compile), which is why it first
# read as a compiler segfault. conftest now raises the watchdog via
# utils/env.py cpu_mesh_xla_flags; THIS script validates that fix by
# running the suite as ONE process with:
#   - faulthandler enabled (python stacks on any fatal signal),
#   - core dumps enabled (native stack recoverable via gdb),
#   - an RSS/thread sampler (rules memory pressure in or out).
#
# Usage: scripts/debug_fullsuite.sh [extra pytest args]
# Output: /tmp/fullsuite-debug/{pytest.log,rss.log,core*} — cores drop
# in the repo cwd first and are swept into the output dir at the end.
set -u
REPO=$(CDPATH= cd "$(dirname "$0")/.." && pwd)
OUT=/tmp/fullsuite-debug
mkdir -p "$OUT"
ulimit -c unlimited 2>/dev/null || echo "# core dumps unavailable"
# Run from the REPO (fixture paths are repo-relative); cores then drop
# in the repo cwd on plain `core` core_patterns — the tail of this
# script sweeps both locations.
cd "$REPO" || exit 1

JAX_PLATFORMS=cpu PYTHONFAULTHANDLER=1 PYTHONPATH="$REPO" \
python -X faulthandler -m pytest "$REPO/tests/" -q "$@" \
    > "$OUT/pytest.log" 2>&1 &
PID=$!
echo "# pytest pid $PID; sampling RSS/threads every 30s to rss.log"
: > "$OUT/rss.log"
while kill -0 "$PID" 2>/dev/null; do
    if [ -r "/proc/$PID/status" ]; then
        RSS=$(awk '/VmRSS/{print $2}' "/proc/$PID/status")
        THR=$(awk '/Threads/{print $2}' "/proc/$PID/status")
        echo "$(date +%s) rss_kb=$RSS threads=$THR" >> "$OUT/rss.log"
    fi
    sleep 30
done
wait "$PID"
RC=$?
echo "# pytest exited rc=$RC"
tail -5 "$OUT/pytest.log"
# Sweep any core out of the working tree (multi-GB at this suite's
# RSS; must not dirty git or risk accidental staging).
mv "$REPO"/core* "$OUT"/ 2>/dev/null
CORES=$(find "$OUT" -maxdepth 1 -name 'core*' 2>/dev/null)
if [ -n "$CORES" ]; then
    ls -la $CORES
else
    echo "# no core dumped"
fi
exit "$RC"
