"""Small environment helpers shared by the CLI and runtime entrypoints."""

from __future__ import annotations

import os


def apply_jax_platforms_override() -> None:
    """Honor ``JAX_PLATFORMS`` even where a sitecustomize hook (e.g. the
    axon TPU-emulator plugin) pinned ``jax_platforms`` before our code
    ran — required to target the virtual CPU mesh from the CLI:
    ``JAX_PLATFORMS=cpu plx run ...``. No-op when unset or when jax is
    unavailable/already initialized with the same value.
    """
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    try:
        import jax

        jax.config.update("jax_platforms", platforms)
    except ImportError:
        pass
