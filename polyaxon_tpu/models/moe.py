"""Sparse Mixture-of-Experts decoder (Mixtral-style) with expert
parallelism — the §2b "EP/MoE" obligation (absent upstream; net-new).

Two dispatch formulations, selected by ``MoEConfig.dispatch``:

**ragged** (the default — measured faster, see below): tokens shard
over the ``ep`` mesh axis alongside the batch (EP_RULES), and a
partial-manual ``shard_map`` moves each token to its experts' owner
device by explicit ``jax.lax.all_to_all``, with buffer slots assigned
from per-destination / per-expert COUNTS (cumsum of one-hot masks —
integer ops, not matmuls). Expert compute is one batched FFN einsum
[E_loc,C,D]×[E_loc,D,F]; dispatch/combine are pure gather/scatter data
movement. Two all_to_alls per block ride the ICI.

**dense**: the classic GShard/Switch one-hot pattern — top-k routing
builds a dispatch tensor [T, E, C] and a combine tensor, so selection
becomes three einsums ([T,E,C]×[T,D]→[E,C,D] gather, batched FFN,
[T,E,C]×[E,C,D]→[T,D] combine) and GSPMD inserts the all-to-alls.
MXU-friendly but the dispatch einsums cost O(T·E·C·D) — ~10× the
token-FLOPs of the FFN itself at E=8/top-2/cf=1.25, growing with E.

Measured (moe_dispatch_results.json, dp2×ep4 8-device CPU mesh,
train-step median, E∈{8,16,32}): ragged 2.0–2.4× faster end-to-end;
the gap holds across E. The advantage is a FLOP-count argument (the
dense dispatch einsums do ~10× the FFN's token-FLOPs at E=8/top-2),
not a CPU artifact, but on-chip confirmation is pending — run
``scripts/perf_sweep.py --moe --moe-platform tpu`` when a chip is
reachable. Decode always uses dense: its dispatch group is a handful
of slots where the einsum overhead is nil, and serving has no ep
mesh.

Tokens over a full expert's capacity are dropped (residual path keeps
them intact), the standard capacity-factor contract; decode floors
capacity at the group size so serving never drops.

Attention/RoPE/norms reuse the Llama block (models/llama.py).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp

from polyaxon_tpu.models.common import (
    Batch,
    ModelDef,
    Variables,
    chunked_lm_loss,
    rms_norm,
    sample_logits,
    scaled_init,
    shift_right,
    truncated_normal_init,
)
from polyaxon_tpu.models.common import _embed_rows, _w, lm_logits
from polyaxon_tpu.models.llama import _rope
from polyaxon_tpu.ops.attention import dot_product_attention
from polyaxon_tpu.parallel import compat


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32_000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14_336  # per expert
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01
    # Chunked lm-head loss slab length (see LlamaConfig.loss_chunk).
    loss_chunk: int = 256
    # Vocab-chunk for quantized decode logits (see LlamaConfig.lm_logits_chunk).
    lm_logits_chunk: int = 4096
    # "top_k": tokens choose experts (GShard; needs the aux loss for
    # balance). "expert_choice": experts choose their top-capacity
    # tokens (Zhou et al. 2022) — perfectly load-balanced by
    # construction, no aux loss. Caveat: expert-choice selection
    # competes across ALL positions in the batch, so token t's routing
    # depends on later tokens — training losses are not strict
    # autoregressive likelihoods and decode cannot reproduce
    # training-time routing; prefer it for encoder/non-AR settings.
    router: str = "top_k"
    # "ragged" (default): explicit shard_map all-to-all dispatch/
    # combine with per-expert counts — gather/scatter data movement
    # instead of one-hot einsums (see _moe_ragged; measured 2.0-2.4x
    # faster per train step on the 8-device CPU mesh,
    # moe_dispatch_results.json — on-chip confirmation pending).
    # "dense": GShard one-hot dispatch tensors (three einsums; cost
    # scales with E×C — module docstring). Decode always uses dense
    # (the group is a handful of slots; no ep mesh exists at serve).
    # Ragged applies to top_k routing; expert_choice always uses its
    # dense gather.
    dispatch: str = "ragged"
    # Ragged-only: per-(source, destination) send-buffer headroom as a
    # multiple of the balanced share. The ragged path has a SECOND cap
    # the dense path doesn't — each source can ship at most
    # send_capacity_margin × (its balanced share × capacity_factor)
    # pairs to one owner device, so per-SOURCE routing skew toward one
    # owner can drop pairs dense would have kept (per-expert capacity
    # is a global budget there). 2.0 absorbs 2× skew for 2× dispatch
    # all_to_all bytes; raise it (up to ep for never-drops-first) if
    # router collapse is expected, at proportional bandwidth cost.
    send_capacity_margin: float = 2.0
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: str = "none"
    attention_impl: str = "xla"
    # Paged decode attention (same semantics as LlamaConfig's field):
    # "auto" = Pallas page-streaming kernel on real TPU, gather off it.
    paged_attention_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


CONFIGS: dict[str, MoEConfig] = {
    "mixtral_8x7b": MoEConfig(),
    "moe_8x200m": MoEConfig(
        vocab_size=32_000, dim=1024, n_layers=12, n_heads=16, n_kv_heads=8,
        ffn_dim=2816, n_experts=8, max_seq_len=2048, rope_theta=10_000.0,
    ),
    "moe_tiny": MoEConfig(
        vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, n_experts=4, max_seq_len=128, rope_theta=10_000.0,
    ),
}


def init(cfg: MoEConfig, rng: jax.Array) -> Variables:
    keys = jax.random.split(rng, 12)
    L, D, F, E = cfg.n_layers, cfg.dim, cfg.ffn_dim, cfg.n_experts
    H, KV, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    params = {
        "embed": truncated_normal_init(keys[0], (cfg.vocab_size, D)),
        "layers": {
            "attn_norm": jnp.ones((L, D)),
            "wq": scaled_init(keys[1], (L, D, H * Hd), fan_in=D),
            "wk": scaled_init(keys[2], (L, D, KV * Hd), fan_in=D),
            "wv": scaled_init(keys[3], (L, D, KV * Hd), fan_in=D),
            "wo": scaled_init(keys[4], (L, H * Hd, D), fan_in=H * Hd),
            "moe_norm": jnp.ones((L, D)),
            "router": scaled_init(keys[5], (L, D, E), fan_in=D),
            "w_gate": scaled_init(keys[6], (L, E, D, F), fan_in=D),
            "w_up": scaled_init(keys[7], (L, E, D, F), fan_in=D),
            "w_down": scaled_init(keys[8], (L, E, F, D), fan_in=F),
        },
        "final_norm": jnp.ones((D,)),
        "lm_head": truncated_normal_init(keys[9], (D, cfg.vocab_size)),
    }
    return {"params": params, "state": {}}


def logical_axes(cfg: MoEConfig) -> Variables:
    del cfg
    return {
        "params": {
            "embed": ("vocab", "embed"),
            "layers": {
                "attn_norm": ("layers", "embed"),
                "wq": ("layers", "embed", "heads"),
                "wk": ("layers", "embed", "kv_heads"),
                "wv": ("layers", "embed", "kv_heads"),
                "wo": ("layers", "heads", "embed"),
                "moe_norm": ("layers", "embed"),
                "router": ("layers", "embed", "expert"),
                "w_gate": ("layers", "expert", "embed", "mlp"),
                "w_up": ("layers", "expert", "embed", "mlp"),
                "w_down": ("layers", "expert", "mlp", "embed"),
            },
            "final_norm": ("embed",),
            "lm_head": ("embed", "vocab"),
        },
        "state": {},
    }


def _router_aux_loss(cfg: MoEConfig, frac_tokens: jax.Array,
                     frac_probs: jax.Array) -> jax.Array:
    """Load-balancing aux loss (Switch eq. 4) from the two GLOBAL mean
    vectors: E * sum_e(frac_tokens_e * frac_probs_e); 1.0 when
    perfectly uniform. Takes the vectors (not raw probs) so the
    sharded ragged path can pmean them first — the formula is a
    product of global means, and a mean of per-shard products would be
    a different statistic."""
    return cfg.n_experts * jnp.sum(frac_tokens * frac_probs)


def _moe_ragged_sharded(cfg: MoEConfig, x, router_w, w_gate, w_up, w_down,
                        *, ep: int, axis_name: Optional[str]):
    """Ragged expert dispatch for one ep shard (or the whole problem
    when ``ep == 1``): tokens travel to their experts' owner devices by
    ``jax.lax.all_to_all`` and positions come from per-destination /
    per-expert COUNTS (cumsum), so expert selection is gather/scatter
    data movement plus one batched FFN einsum — none of the dense
    path's [T,E,C] one-hot dispatch einsums, whose compute scales with
    E×C (VERDICT r2 missing #5 / weak #4).

    x: [T_loc, D] this device's token shard (token-major pair order).
    Weights: [E_loc, D/F, ...] this device's expert shard.
    Returns (out [T_loc, D], aux scalar f32 — pmean'd over ep).

    Drop semantics vs dense: the owner-side per-expert capacity uses
    the SAME formula as the dense path, but pair order is
    source-major (not choice-major) AND there is an additional
    per-(source, destination) send cap ``s_cap`` — per-source skew
    toward one owner device can drop pairs dense would keep (see
    ``MoEConfig.send_capacity_margin``). Parity with dense holds at
    no-drop capacity, the setting the parity tests pin.
    """
    T_loc, D = x.shape
    E, K = cfg.n_experts, cfg.experts_per_token
    E_loc = E // ep
    T = T_loc * ep
    dt = cfg.dtype
    # Owner-side per-expert capacity: same formula as dense. Send-side
    # cap: the balanced per-destination share × a skew margin, never
    # more than "send everything" (T_loc*K).
    capacity = max(int(math.ceil(T * cfg.capacity_factor * K / E)), K)
    s_cap = max(int(math.ceil(T_loc * K * cfg.capacity_factor
                              * cfg.send_capacity_margin / ep)), K)
    s_cap = min(s_cap, T_loc * K)

    logits = (x @ _w(router_w, dt)).astype(jnp.float32)  # [T_loc, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_probs, top_idx = jax.lax.top_k(probs, K)  # [T_loc, K]
    top_probs = top_probs / jnp.sum(top_probs, axis=-1, keepdims=True)

    # ---- flatten (token, choice) pairs, token-major -----------------
    P_ = T_loc * K
    dest = (top_idx // E_loc).reshape(P_)  # owner device per pair
    eloc = (top_idx % E_loc).reshape(P_)  # local expert id at owner
    w_pair = top_probs.reshape(P_)
    tok = jnp.arange(P_, dtype=jnp.int32) // K

    # ---- dispatch: count-based slots, scatter into send buffers -----
    dest_oh = jax.nn.one_hot(dest, ep, dtype=jnp.int32)  # [P, ep]
    pos_in_dest = jnp.sum(
        (jnp.cumsum(dest_oh, axis=0) - dest_oh) * dest_oh, axis=-1)
    keep = pos_in_dest < s_cap
    slot = jnp.where(keep, pos_in_dest, s_cap)  # OOB → dropped scatter
    send_x = jnp.zeros((ep, s_cap, D), dt).at[dest, slot].set(
        x[tok], mode="drop")
    send_eloc = jnp.full((ep, s_cap), -1, jnp.int32).at[dest, slot].set(
        eloc, mode="drop")

    if axis_name is not None:
        recv_x = jax.lax.all_to_all(send_x, axis_name, split_axis=0,
                                    concat_axis=0, tiled=True)
        recv_eloc = jax.lax.all_to_all(send_eloc, axis_name, split_axis=0,
                                       concat_axis=0, tiled=True)
    else:
        recv_x, recv_eloc = send_x, send_eloc

    # ---- owner side: per-expert counts → gather → batched FFN -------
    R = ep * s_cap
    rx = recv_x.reshape(R, D)
    re = recv_eloc.reshape(R)  # -1 = empty slot
    e_oh = jax.nn.one_hot(re, E_loc, dtype=jnp.int32)  # [R, E_loc]; -1→0s
    pos_in_e = jnp.sum((jnp.cumsum(e_oh, axis=0) - e_oh) * e_oh, axis=-1)
    keep_e = (re >= 0) & (pos_in_e < capacity)
    slot_e = jnp.where(keep_e, pos_in_e, capacity)
    eid = jnp.where(re >= 0, re, 0)
    expert_in = jnp.zeros((E_loc, capacity, D), dt).at[
        jnp.where(keep_e, eid, E_loc), slot_e].set(rx, mode="drop")

    gate = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", expert_in, _w(w_gate, dt)))
    up = jnp.einsum("ecd,edf->ecf", expert_in, _w(w_up, dt))
    expert_out = jnp.einsum("ecf,efd->ecd", gate * up, _w(w_down, dt))

    out_rows = jnp.where(
        keep_e[:, None],
        expert_out[eid, jnp.minimum(slot_e, capacity - 1)], 0.0)

    # ---- return trip + weighted combine -----------------------------
    back = out_rows.reshape(ep, s_cap, D)
    if axis_name is not None:
        back = jax.lax.all_to_all(back, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True)
    out_pair = jnp.where(
        keep[:, None], back[dest, jnp.minimum(slot, s_cap - 1)], 0.0)
    out = jnp.zeros((T_loc, D), dt).at[tok].add(
        out_pair * w_pair[:, None].astype(dt))

    frac_tokens = jnp.mean(
        jax.nn.one_hot(top_idx[:, 0], E, dtype=jnp.float32), axis=0)
    frac_probs = jnp.mean(probs, axis=0)
    if axis_name is not None:
        frac_tokens = jax.lax.pmean(frac_tokens, axis_name)
        frac_probs = jax.lax.pmean(frac_probs, axis_name)
    aux = _router_aux_loss(cfg, frac_tokens, frac_probs)
    return out, aux


def _moe_ragged(cfg: MoEConfig, x, router_w, w_gate, w_up, w_down):
    """Ragged dispatch entry: binds the ``ep`` mesh axis the way
    ``ring_attention`` binds ``cp`` — run directly if the axis is
    already manually bound, wrap in a partial-manual ``shard_map``
    (tokens sharded over ep per EP_RULES, experts over ep, all other
    mesh axes left to GSPMD) when called under plain jit with an
    ambient mesh, and degrade to the single-shard ragged math (still
    einsum-free) when no ep axis exists."""
    from polyaxon_tpu.ops.ring import _axis_bound, ambient_mesh

    B, S, D = x.shape
    tokens = x.reshape(B * S, D)

    if _axis_bound("ep"):
        out, aux = _moe_ragged_sharded(
            cfg, tokens, router_w, w_gate, w_up, w_down,
            ep=compat.axis_size("ep"), axis_name="ep")
        return out.reshape(B, S, D), aux

    mesh = ambient_mesh()
    ep = (dict(zip(mesh.axis_names, mesh.devices.shape)).get("ep", 1)
          if mesh is not None else 1)
    if ep == 1:
        out, aux = _moe_ragged_sharded(
            cfg, tokens, router_w, w_gate, w_up, w_down,
            ep=1, axis_name=None)
        return out.reshape(B, S, D), aux

    fn = compat.shard_map(
        functools.partial(_moe_ragged_sharded, cfg, ep=ep, axis_name="ep"),
        mesh=mesh,
        in_specs=(jax.sharding.PartitionSpec("ep", None),
                  jax.sharding.PartitionSpec(None, None),
                  jax.sharding.PartitionSpec("ep", None, None),
                  jax.sharding.PartitionSpec("ep", None, None),
                  jax.sharding.PartitionSpec("ep", None, None)),
        out_specs=(jax.sharding.PartitionSpec("ep", None),
                   jax.sharding.PartitionSpec()),
        axis_names={"ep"},
        check_vma=False,
    )
    out, aux = fn(tokens, router_w, w_gate, w_up, w_down)
    return out.reshape(B, S, D), aux


def moe_block(
    cfg: MoEConfig,
    x: jax.Array,  # [B, S, D]
    router_w: jax.Array,  # [D, E]
    w_gate: jax.Array,  # [E, D, F]
    w_up: jax.Array,
    w_down: jax.Array,  # [E, F, D]
    min_capacity: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """Returns (output [B,S,D], router aux loss scalar fp32).

    ``min_capacity`` floors the per-expert buffer; decode passes the
    group size T so serving never drops tokens (at decode T is the
    handful of live slots — capacity from the factor alone would be
    1-2 slots and silently diverge served outputs from training
    routing whenever >capacity rows picked one expert)."""
    B, S, D = x.shape
    E, K = cfg.n_experts, cfg.experts_per_token
    T = B * S
    capacity = max(int(math.ceil(T * cfg.capacity_factor * K / E)), K,
                   min_capacity)
    dt = cfg.dtype

    if cfg.dispatch not in ("dense", "ragged"):
        raise ValueError(f"unknown MoE dispatch `{cfg.dispatch}`")
    if (cfg.dispatch == "ragged" and cfg.router == "top_k"
            and min_capacity == 0):
        # Decode (min_capacity > 0) stays dense: its dispatch group is
        # a handful of slots, no ep mesh exists at serve time, and the
        # no-drop floor is what matters there.
        return _moe_ragged(cfg, x, router_w, w_gate, w_up, w_down)

    tokens = x.reshape(T, D)
    logits = (tokens @ _w(router_w, dt)).astype(jnp.float32)  # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)

    if cfg.router == "expert_choice":
        # Experts pick their top-`capacity` tokens: balanced by
        # construction, so no aux loss. Tokens outside every expert's
        # choice pass through the residual unchanged.
        g, idx = jax.lax.top_k(probs.T, min(capacity, T))  # [E, C]
        expert_in = tokens[idx]  # [E, C, D]
        gate = jax.nn.silu(
            jnp.einsum("ecd,edf->ecf", expert_in, _w(w_gate, dt)))
        up = jnp.einsum("ecd,edf->ecf", expert_in, _w(w_up, dt))
        expert_out = jnp.einsum("ecf,efd->ecd", gate * up, _w(w_down, dt))
        weighted = (g[..., None].astype(dt) * expert_out).reshape(-1, D)
        out = jnp.zeros((T, D), dt).at[idx.reshape(-1)].add(weighted)
        return out.reshape(B, S, D), jnp.zeros((), jnp.float32)
    if cfg.router != "top_k":
        raise ValueError(f"unknown MoE router `{cfg.router}`")

    top_probs, top_idx = jax.lax.top_k(probs, K)  # [T, K]
    top_probs = top_probs / jnp.sum(top_probs, axis=-1, keepdims=True)

    # Dense one-hot dispatch with capacity accounting. Per k-choice:
    # position of each token inside its expert's buffer = how many
    # earlier (token, choice) pairs picked that expert.
    onehot = jax.nn.one_hot(top_idx, E, dtype=jnp.float32)  # [T, K, E]
    oh_km = onehot.transpose(1, 0, 2)  # choice-major [K, T, E]
    flat = oh_km.reshape(K * T, E)
    positions = (jnp.cumsum(flat, axis=0) - flat)  # [K*T, E] slots used before
    pos_in_expert = jnp.sum(positions * flat, axis=-1).reshape(K, T)  # [K, T]
    keep = pos_in_expert < capacity

    # dispatch[t, e, c] = 1 where token t sits in slot c of expert e.
    slot_onehot = jax.nn.one_hot(
        pos_in_expert.astype(jnp.int32), capacity, dtype=jnp.float32)
    dispatch = jnp.einsum(
        "kte,ktc->tec", oh_km,
        slot_onehot * keep[..., None].astype(jnp.float32))
    combine = jnp.einsum(
        "kte,ktc,kt->tec", oh_km, slot_onehot,
        top_probs.T * keep.astype(jnp.float32))

    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dt), tokens)  # [E,C,D]
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, _w(w_gate, dt)))
    up = jnp.einsum("ecd,edf->ecf", expert_in, _w(w_up, dt))
    expert_out = jnp.einsum("ecf,efd->ecd", gate * up, _w(w_down, dt))
    out = jnp.einsum("tec,ecd->td", combine.astype(dt), expert_out)

    aux = _router_aux_loss(cfg, jnp.mean(onehot[:, 0, :], axis=0),
                           jnp.mean(probs, axis=0))
    return out.reshape(B, S, D), aux


def _layer(cfg: MoEConfig, carry, layer: dict, positions: jax.Array):
    x, aux_sum = carry
    B, S, D = x.shape
    H, KV, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    dt = cfg.dtype

    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = (h @ _w(layer["wq"], dt)).reshape(B, S, H, Hd)
    k = (h @ _w(layer["wk"], dt)).reshape(B, S, KV, Hd)
    v = (h @ _w(layer["wv"], dt)).reshape(B, S, KV, Hd)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    attn = dot_product_attention(q, k, v, causal=True, impl=cfg.attention_impl)
    x = x + attn.reshape(B, S, H * Hd) @ _w(layer["wo"], dt)

    h = rms_norm(x, layer["moe_norm"], cfg.norm_eps)
    moe_out, aux = moe_block(
        cfg, h, layer["router"], layer["w_gate"], layer["w_up"], layer["w_down"])
    return (x + moe_out, aux_sum + aux)


def hidden_states(
    cfg: MoEConfig,
    params: dict,
    tokens: jax.Array,
    positions: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Token ids → (final-norm hidden [B,S,D], mean router aux loss)."""
    dt = cfg.dtype
    B, S = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    x = _embed_rows(params["embed"], tokens, dt)

    body = functools.partial(_layer, cfg)
    if cfg.remat == "full":
        body = jax.checkpoint(body)
    elif cfg.remat == "dots":
        body = jax.checkpoint(
            body, policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims)

    def scan_body(carry, layer_params):
        return body(carry, layer_params, positions), None

    (x, aux_sum), _ = jax.lax.scan(
        scan_body, (x, jnp.zeros((), jnp.float32)), params["layers"])
    return rms_norm(x, params["final_norm"], cfg.norm_eps), aux_sum / cfg.n_layers


def forward(
    cfg: MoEConfig,
    params: dict,
    tokens: jax.Array,
    positions: Optional[jax.Array] = None,
) -> tuple[jax.Array, jax.Array]:
    """Token ids → (logits [B,S,vocab] fp32, mean router aux loss)."""
    x, aux = hidden_states(cfg, params, tokens, positions)
    logits = (x @ _w(params["lm_head"], cfg.dtype)).astype(jnp.float32)
    return logits, aux


# ---------------------------------------------------------------- decode
def init_cache(cfg: MoEConfig, batch: int, max_len: int) -> dict:
    """KV cache [L, B, C, KV, Hd] per tensor, compute dtype — the same
    layout as the llama cache (full-length: MoE configs carry no
    sliding window)."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def _prompt_pass(cfg: MoEConfig, params: dict, prompt: jax.Array):
    """Shared causal prompt sweep (one body for both prefill flavours,
    same contract as llama's): (final hidden x [B, P, D], k_all, v_all
    [L, B, P, KV, Hd]). The MoE FFN replaces the dense MLP; routing
    runs over the B·P prompt tokens exactly as in training."""
    dt = cfg.dtype
    B, P = prompt.shape
    H, KV, Hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    positions = jnp.broadcast_to(jnp.arange(P, dtype=jnp.int32)[None], (B, P))
    x = _embed_rows(params["embed"], prompt, dt)

    def layer_step(x, layer):
        h = rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = (h @ _w(layer["wq"], dt)).reshape(B, P, H, Hd)
        k = (h @ _w(layer["wk"], dt)).reshape(B, P, KV, Hd)
        v = (h @ _w(layer["wv"], dt)).reshape(B, P, KV, Hd)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        attn = dot_product_attention(q, k, v, causal=True,
                                     impl=cfg.attention_impl)
        x = x + attn.reshape(B, P, H * Hd) @ _w(layer["wo"], dt)
        h = rms_norm(x, layer["moe_norm"], cfg.norm_eps)
        moe_out, _ = moe_block(cfg, h, layer["router"], layer["w_gate"],
                               layer["w_up"], layer["w_down"])
        return x + moe_out, (k, v)

    x, (k_all, v_all) = jax.lax.scan(layer_step, x, params["layers"])
    return x, k_all, v_all


def prefill(
    cfg: MoEConfig,
    params: dict,
    prompt: jax.Array,  # [B, P] int32
    max_len: int,
) -> tuple[jax.Array, dict]:
    """One batched causal pass over the prompt, filling the KV cache:
    (last-position logits [B, V] fp32, cache)."""
    _check_decodable(cfg)
    dt = cfg.dtype
    B = prompt.shape[0]
    x, k_all, v_all = _prompt_pass(cfg, params, prompt)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, -1] @ _w(params["lm_head"], dt)).astype(jnp.float32)
    cache = init_cache(cfg, B, max_len)
    cache = {
        "k": jax.lax.dynamic_update_slice(
            cache["k"], k_all, (0, 0, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(
            cache["v"], v_all, (0, 0, 0, 0, 0)),
    }
    return logits, cache


def _check_decodable(cfg: MoEConfig) -> None:
    """Expert-choice routing selects tokens ACROSS the dispatch group,
    so a decode-time group (the current tokens only) cannot reproduce
    training-time selection — generation would silently diverge.
    Refuse rather than mis-serve; serve top_k-routed configs."""
    if cfg.router != "top_k":
        raise ValueError(
            f"MoE decode/generation requires router='top_k'; "
            f"'{cfg.router}' routes by group-wide selection that decode "
            "groups cannot reproduce")


def decode_step_ragged(
    cfg: MoEConfig,
    params: dict,
    cache: dict,
    tokens: jax.Array,  # [B] int32
    pos: jax.Array,  # [B] int32 per-row position (-1 = idle)
) -> tuple[jax.Array, dict]:
    """One autoregressive step with PER-ROW positions (continuous
    batching). Built on the same ``cached_attn_step`` kernel as the
    llama family — the families differ only in the FFN sublayer. The
    router sees the B current tokens as its dispatch group: top-k
    selection is per-token, so decode routing matches training routing
    for the same hidden state. Capacity is floored at the group size
    (``min_capacity=B`` below) so decode NEVER drops: at B live slots
    the factor-derived capacity would be 1-2 and any routing skew
    would silently diverge served outputs from training."""
    from polyaxon_tpu.models.llama import cached_attn_step, ragged_cache_coords

    _check_decodable(cfg)
    dt = cfg.dtype
    C = cache["k"].shape[2]
    positions, slot, valid = ragged_cache_coords(pos, C)
    x = _embed_rows(params["embed"], tokens, dt)[:, None, :]  # [B, 1, D]

    def layer_step(x, inputs):
        layer, k_cache, v_cache = inputs  # caches [B, C, KV, Hd]
        x, k_cache, v_cache = cached_attn_step(
            cfg, layer, x, k_cache, v_cache, positions, slot, valid)
        h = rms_norm(x, layer["moe_norm"], cfg.norm_eps)
        moe_out, _ = moe_block(cfg, h, layer["router"], layer["w_gate"],
                               layer["w_up"], layer["w_down"],
                               min_capacity=h.shape[0])
        return x + moe_out, (k_cache, v_cache)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = lm_logits(x[:, 0], params["lm_head"], dt,
                       chunk=cfg.lm_logits_chunk)
    return logits, {"k": new_k, "v": new_v}


def decode_step(
    cfg: MoEConfig,
    params: dict,
    cache: dict,
    tokens: jax.Array,  # [B] int32
    pos: jax.Array,  # scalar int32 position being written
) -> tuple[jax.Array, dict]:
    """Scalar-position decode: the all-rows-in-lockstep special case of
    ``decode_step_ragged`` (one body, same ring-cache semantics as
    llama)."""
    B = tokens.shape[0]
    return decode_step_ragged(
        cfg, params, cache, tokens,
        jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,)))


def decode_chunk(
    cfg: MoEConfig,
    params: dict,
    cache: dict,  # full-length cache: slot == position
    tokens: jax.Array,  # [B, c] int32
    pos0: jax.Array,  # [B] int32
) -> tuple[jax.Array, dict]:
    """Speculative-verify chunk for MoE targets: llama's
    ``chunk_attn_step`` with the expert FFN in the MLP slot. Routing
    sees the B·c chunk tokens as its dispatch group with no-drop
    capacity (same rule as ``decode_step_ragged``). MoE configs carry
    no sliding window, so the slot==position invariant holds."""
    from polyaxon_tpu.models.llama import chunk_attn_step

    _check_decodable(cfg)
    dt = cfg.dtype
    B, c = tokens.shape
    C = cache["k"].shape[2]
    positions = pos0[:, None] + jnp.arange(c)[None, :]
    x = _embed_rows(params["embed"], tokens, dt)
    cols = jnp.arange(C)[None, None, :]
    valid = (cols <= positions[:, :, None])[:, None]  # [B, 1, c, C]

    def layer_step(x, inputs):
        layer, k_cache, v_cache = inputs
        x, k_cache, v_cache = chunk_attn_step(
            cfg, layer, x, k_cache, v_cache, positions, valid)
        h = rms_norm(x, layer["moe_norm"], cfg.norm_eps)
        moe_out, _ = moe_block(cfg, h, layer["router"], layer["w_gate"],
                               layer["w_up"], layer["w_down"],
                               min_capacity=B * c)
        return x + moe_out, (k_cache, v_cache)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = lm_logits(x, params["lm_head"], dt,
                       chunk=cfg.lm_logits_chunk)
    return logits, {"k": new_k, "v": new_v}


def decode_step_paged(
    cfg: MoEConfig,
    params: dict,
    cache: dict,  # {"k"/"v": [L, P, page, KV, Hd]}
    tokens: jax.Array,  # [B] int32
    pos: jax.Array,  # [B] int32 per-row position (-1 = idle)
    tables: jax.Array,  # [B, maxp] int32 page ids (-1 = unallocated)
) -> tuple[jax.Array, dict]:
    """Paged-pool ragged decode (llama's block-table semantics, the
    expert FFN in the MLP slot) — parity with ``decode_step_ragged``
    for rows whose pages cover 0..p."""
    from polyaxon_tpu.models.llama import paged_attn_step, paged_coords

    _check_decodable(cfg)
    dt = cfg.dtype
    page = cache["k"].shape[2]
    positions, write_page, write_off, valid = paged_coords(pos, tables, page)
    x = _embed_rows(params["embed"], tokens, dt)[:, None, :]

    def layer_step(x, inputs):
        layer, k_pages, v_pages = inputs
        x, k_pages, v_pages = paged_attn_step(
            cfg, layer, x, k_pages, v_pages, positions,
            write_page, write_off, tables, valid)
        h = rms_norm(x, layer["moe_norm"], cfg.norm_eps)
        moe_out, _ = moe_block(cfg, h, layer["router"], layer["w_gate"],
                               layer["w_up"], layer["w_down"],
                               min_capacity=h.shape[0])
        return x + moe_out, (k_pages, v_pages)

    x, (new_k, new_v) = jax.lax.scan(
        layer_step, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = lm_logits(x[:, 0], params["lm_head"], dt,
                       chunk=cfg.lm_logits_chunk)
    return logits, {"k": new_k, "v": new_v}


def paged_init_cache(cfg: MoEConfig, n_pages: int, page_size: int) -> dict:
    """Paged pool (MoE configs carry no sliding window)."""
    shape = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def paged_prefill_kv(cfg: MoEConfig, params: dict, prompt: jax.Array):
    """Raw per-position KV for the paged insert ([L, P, KV, Hd], single
    row) — same ``_prompt_pass`` body as ``prefill``."""
    _check_decodable(cfg)
    _, k_all, v_all = _prompt_pass(cfg, params, prompt)
    return k_all[:, 0], v_all[:, 0]


def paged_prefill_suffix_kv(cfg: MoEConfig, params: dict,
                            suffix: jax.Array, k_prefix: jax.Array,
                            v_prefix: jax.Array, m: jax.Array):
    """Suffix-only prefill after a radix prefix-cache hit (llama's
    ``suffix_attn_step`` with the expert FFN in the MLP slot): computes
    KV only for the S novel tokens at absolute positions m..m+S-1,
    attending the matched prefix pages. Routing sees the suffix tokens
    as its dispatch group with no-drop capacity."""
    from polyaxon_tpu.models.llama import _suffix_mask, suffix_attn_step

    _check_decodable(cfg)
    dt = cfg.dtype
    B, S = suffix.shape
    m_pad = k_prefix.shape[1]
    positions = jnp.broadcast_to(
        m + jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    valid = _suffix_mask(S, m_pad, m)
    x = _embed_rows(params["embed"], suffix, dt)

    def layer_step(x, inputs):
        layer, kp, vp = inputs
        x, k, v = suffix_attn_step(
            cfg, layer, x, kp[None], vp[None], positions, valid)
        h = rms_norm(x, layer["moe_norm"], cfg.norm_eps)
        moe_out, _ = moe_block(cfg, h, layer["router"], layer["w_gate"],
                               layer["w_up"], layer["w_down"],
                               min_capacity=B * S)
        return x + moe_out, (k, v)

    _, (k_all, v_all) = jax.lax.scan(
        layer_step, x, (params["layers"], k_prefix, v_prefix))
    return k_all[:, 0], v_all[:, 0]


# Continuous-batching hooks: admission/validation semantics are the
# llama decoder-only ones; cache init/prefill are moe's own; the paged
# inserts are pure indexing shared verbatim.
from polyaxon_tpu.models.llama import (  # noqa: E402  (re-exported hooks)
    cb_admission,
    cb_validate,
    insert_cache_row,
    paged_insert_prefill,
    paged_insert_suffix,
)


def cb_init_cache(cfg: MoEConfig, slots: int, max_len: int) -> dict:
    return init_cache(cfg, slots, max_len)


def cb_prefill(cfg: MoEConfig, params: dict, prompt: jax.Array,
               max_len: int) -> dict:
    _, cache = prefill(cfg, params, prompt, max_len)
    return cache


def generate(
    cfg: MoEConfig,
    params: dict,
    prompt: jax.Array,  # [B, P] int32
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_p: float = 1.0,
    top_k: int = 0,
    rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Greedy (temperature 0) or sampled continuation: [B, max_new] —
    the same serving contract as llama.generate (all sampling knobs
    may be traced scalars; top_p/top_k filter in-program via
    models/common.py sample_logits)."""
    B, P = prompt.shape
    sampling = isinstance(temperature, jax.Array) or temperature > 0
    if sampling and rng is None:
        raise ValueError("sampling (temperature > 0) needs an rng key")
    rng = rng if rng is not None else jax.random.key(0)

    logits, cache = prefill(cfg, params, prompt, P + max_new_tokens)

    def sample(logits, key):
        if sampling:
            return sample_logits(logits, key, temperature, top_p, top_k)
        return jnp.argmax(logits, axis=-1)

    def decode_loop(carry, t):
        cache, logits, key = carry
        key, sub = jax.random.split(key)
        token = sample(logits, sub).astype(jnp.int32)
        logits, cache = decode_step(cfg, params, cache, token, P + t)
        return (cache, logits, key), token

    (_, logits, _), tokens = jax.lax.scan(
        decode_loop, (cache, logits, rng), jnp.arange(max_new_tokens))
    return tokens.T  # [B, max_new]


def apply(
    cfg: MoEConfig,
    variables: Variables,
    batch: Batch,
    train: bool = True,
    rng: Optional[jax.Array] = None,
):
    tokens = batch["tokens"]
    if batch.get("segments") is not None:
        raise ValueError(
            "moe models do not support packed sequences (segments) yet; "
            "use an unpacked dataset or a llama-family model")
    inputs = shift_right(tokens)
    # Chunked lm-head loss (common.chunked_lm_loss): full [B,S,V] fp32
    # logits are never materialized.
    x, aux = hidden_states(cfg, variables["params"], inputs)
    head = variables["params"]["lm_head"].astype(cfg.dtype)
    ce, acc = chunked_lm_loss(x, head, tokens, batch.get("mask"),
                              chunk=cfg.loss_chunk)
    loss = ce + cfg.router_aux_coef * aux
    # ``loss_unweighted``: the mask-independent component, exposed so
    # gradient accumulation can weight it per-microbatch (1/k) instead
    # of by valid-token count (runtime/step.py grads_of).
    return loss, {"loss": loss, "ce_loss": ce, "router_aux": aux,
                  "loss_unweighted": cfg.router_aux_coef * aux,
                  "accuracy": acc}, variables["state"]


def model_def(name: str, **overrides) -> ModelDef:
    cfg = dataclasses.replace(CONFIGS[name], **overrides)
    return ModelDef(
        name=name,
        init=functools.partial(init, cfg),
        apply=functools.partial(apply, cfg),
        logical_axes=functools.partial(logical_axes, cfg),
        unit="tokens",
        uniform_metrics=("router_aux",),
    )
