from polyaxon_tpu.fs.store import (
    FsspecStore,
    LocalStore,
    MemoryStore,
    Store,
    StoreError,
    TransientStoreError,
    get_store,
    is_transient_store_error,
    register_store,
)

__all__ = [
    "FsspecStore",
    "LocalStore",
    "MemoryStore",
    "Store",
    "StoreError",
    "TransientStoreError",
    "get_store",
    "is_transient_store_error",
    "register_store",
]
