"""Weighted fair-share admission + priority preemption (ISSUE 2).

Replaces the agent's FIFO ``queued[:capacity]`` slice with a policy
pass in the Borg/Kubernetes shape (PAPERS.md): desired-state queues and
quotas enforced by an idempotent per-tick decision, priority preemption
as the pressure valve. Every decision is recomputed from store state,
so a restarted agent converges to the same admissions.

Ordering: eligible QUEUED runs are admitted by

    (queue priority desc, project fair-share deficit desc, age asc)

where the deficit of project *p* is ``weight_p / Σweights − share_p``
over the runs currently live plus the ones tentatively admitted earlier
in the same pass — classic weighted fair queueing, so two projects
flooding one queue converge to their quota weights.

Scale (ISSUE 8, sized by the fleet sim): the live view is INCREMENTAL —
a ``Store.transition`` listener feeds status deltas into an in-memory
``_LiveEntry`` map instead of a per-pass O(live+queued) rebuild, and a
periodic full rebuild (``POLYAXON_TPU_ADMISSION_REBUILD_TICKS``, default
50 passes) cross-checks the map against the store, counting any
divergence into ``polyaxon_admission_live_divergence_total`` (the sim
asserts it stays zero across a whole compressed day). The ranking loop
groups candidates by (queue, project): members of a group share every
component of the rank key except age — and within a group candidates
already sit in age order — so each round picks the global head by
scanning GROUP heads, O(candidates · groups) per pass instead of the
old full re-sort per admission, with byte-identical admission order.

Preemption: a run that stays admissible but capacity-starved for
``POLYAXON_TPU_STARVATION_TICKS`` consecutive passes picks ONE victim —
the lowest-effective-priority RUNNING run on a *preemptible* queue —
which the agent evicts (kill → PREEMPTED → PR 1 backoff requeue).
Quota walls never trigger preemption: exceeding tenants wait, loudly
(a ``reason=QuotaExceeded`` condition is pinned on the blocked run).

Chaos seam ``admission``: a fault ``{"seam": "admission", "op":
"<queue>"}`` starves that queue's candidates for ``times`` decisions,
so drills can prove starvation stays bounded and observable.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from collections import deque

from polyaxon_tpu import chaos
from polyaxon_tpu.controlplane.store import RunRecord
from polyaxon_tpu.lifecycle import V1Statuses
from polyaxon_tpu.scheduling.catalog import (
    DEFAULT_QUEUE,
    RunSchedInfo,
    sched_info,
)

logger = logging.getLogger(__name__)

# Statuses that occupy capacity/quota (anything the executor may own).
LIVE_STATUSES = [
    V1Statuses.SCHEDULED,
    V1Statuses.STARTING,
    V1Statuses.RUNNING,
    V1Statuses.PROCESSING,
    V1Statuses.WARNING,
    V1Statuses.STOPPING,
]
_LIVE_SET = frozenset(LIVE_STATUSES)

_PIPELINE_KINDS = {"matrix", "dag", "schedule"}


def _starvation_ticks() -> int:
    try:
        return max(1, int(os.environ.get("POLYAXON_TPU_STARVATION_TICKS", "3")))
    except ValueError:
        return 3


def _rebuild_ticks() -> int:
    try:
        return max(1, int(os.environ.get(
            "POLYAXON_TPU_ADMISSION_REBUILD_TICKS", "50")))
    except ValueError:
        return 50


@dataclasses.dataclass
class AdmissionDecision:
    """One pass's verdict. ``admitted`` is ordered and may be longer
    than capacity: the agent starts entries until capacity is filled,
    skipping ones whose slice placement is still pending — so a single
    unplaceable run can never waste a slot a placeable one needs
    (head-of-line fix)."""

    admitted: list[tuple[RunRecord, RunSchedInfo]]
    victims: list[str]  # run uuids to preempt for starved high-priority work
    blocked: dict[str, str]  # run uuid -> reason (QuotaExceeded, ...)


@dataclasses.dataclass
class _LiveEntry:
    """The admission-relevant slice of one live run — everything a pass
    or victim selection reads, so neither ever refetches the record."""

    uuid: str
    project: str
    queue: str
    chips: int
    priority: int  # priority-class rank (catalog.RunSchedInfo.priority)
    status: V1Statuses
    started_at: str | None
    created_at: str


class AdmissionController:
    def __init__(self, plane, *, starvation_ticks: int | None = None,
                 incremental: bool = True,
                 rebuild_ticks: int | None = None):
        self.plane = plane
        self.store = plane.store
        self.starvation_ticks = starvation_ticks or _starvation_ticks()
        self._starved: dict[str, int] = {}  # uuid -> consecutive starved passes
        # Last reason pinned per still-queued run: re-pinning is skipped
        # without the old per-run last_condition query every pass.
        self._pinned: dict[str, str] = {}
        # ``incremental=False`` is the bench/deopt baseline: rebuild the
        # live view from the store every pass and rank with the original
        # full re-sort loop.
        self.incremental = incremental
        self.rebuild_ticks = rebuild_ticks or _rebuild_ticks()
        self._live: dict[str, _LiveEntry] = {}
        self._live_lock = threading.Lock()
        self._seeded = False
        self._passes = 0
        self.rebuild_checks = 0
        self.divergence_total = 0
        if self.incremental:
            self.store.add_transition_listener(self._on_transition)

    # ------------------------------------------------------------ helpers
    def _queue_row(self, queues: dict[str, dict], name: str) -> dict:
        row = queues.get(name)
        if row is not None:
            return row
        # Unknown queue (legacy run / deleted queue): schedule like the
        # implicit default — neutral priority, uncapped, non-preemptible.
        return {"name": name or DEFAULT_QUEUE, "priority": 0,
                "concurrency": None, "preemptible": False}

    def _pin_blocked(self, record: RunRecord, reason: str, message: str) -> None:
        """Surface WHY a run is still queued, once per block streak —
        re-pinning every tick would flood the condition history. The
        in-memory streak cache keeps repeat passes query-free; the store
        check only runs on the first sighting (e.g. agent restart)."""
        if self._pinned.get(record.uuid) == reason:
            return
        last = self.store.last_condition(record.uuid)
        if last is not None and last.get("reason") == reason:
            self._pinned[record.uuid] = reason
            return
        self.store.add_condition(
            record.uuid, V1Statuses.QUEUED.value, reason=reason,
            message=message)
        self._pinned[record.uuid] = reason

    # -------------------------------------------------- incremental live view
    def _entry_from_record(self, record: RunRecord) -> _LiveEntry:
        info = sched_info(record)
        return _LiveEntry(
            uuid=record.uuid, project=record.project, queue=info.queue,
            chips=info.chips, priority=info.priority, status=record.status,
            started_at=record.started_at, created_at=record.created_at)

    def _on_transition(self, event: dict) -> None:
        """Store delta feed: keep the live map exact without scans."""
        new = event["new"]
        uuid = event["uuid"]
        with self._live_lock:
            entry = self._live.get(uuid)
            if new in _LIVE_SET:
                if entry is not None:
                    entry.status = new
                    if new == V1Statuses.RUNNING and not entry.started_at:
                        entry.started_at = event["ts"]
                    return
            elif entry is not None:
                del self._live[uuid]
                return
            elif new not in _LIVE_SET:
                return
        # Entering the live set for the first time: one point lookup
        # (outside the map lock; transitions into live are bounded by
        # executor capacity per tick, not by queue depth).
        try:
            record = self.store.get_run(uuid)
        except KeyError:
            return
        if record.kind in _PIPELINE_KINDS:
            return
        entry = self._entry_from_record(record)
        entry.status = new
        if new == V1Statuses.RUNNING and not entry.started_at:
            entry.started_at = event["ts"]
        with self._live_lock:
            # Re-check: a racing terminal transition may have landed.
            if record.status in _LIVE_SET or new in _LIVE_SET:
                self._live[uuid] = entry

    def _rebuild_live(self) -> dict[str, _LiveEntry]:
        return {
            r.uuid: self._entry_from_record(r)
            for r in self.store.list_runs(
                statuses=LIVE_STATUSES,
                exclude_kinds=sorted(_PIPELINE_KINDS),
                limit=1000000)
        }

    def _live_view(self) -> dict[str, _LiveEntry]:
        """Current live entries. Incremental mode serves the in-memory
        map, re-seeding on first use and cross-checking it against a
        full store rebuild every ``rebuild_ticks`` passes — divergence
        is counted (metric + ``divergence_total``), logged, and healed
        by adopting the rebuilt view."""
        if not self.incremental:
            return self._rebuild_live()
        self._passes += 1
        if not self._seeded:
            # Seed OUTSIDE the lock: the scan is O(fleet) and every
            # store transition's _notify blocks on _live_lock (while
            # holding Store._lock), so holding it across list_runs
            # stalls every writer for the whole scan. Deltas that land
            # mid-rebuild win by uuid; drift in the other direction is
            # healed by the periodic divergence cross-check below.
            rebuilt = self._rebuild_live()
            with self._live_lock:
                if not self._seeded:
                    for uuid, entry in self._live.items():
                        rebuilt[uuid] = entry
                    self._live = rebuilt
                    self._seeded = True
        elif self._passes % self.rebuild_ticks == 0:
            rebuilt = self._rebuild_live()
            self.rebuild_checks += 1
            with self._live_lock:
                current = {
                    u: (e.project, e.queue, e.chips, e.status.value)
                    for u, e in self._live.items()}
            fresh = {u: (e.project, e.queue, e.chips, e.status.value)
                     for u, e in rebuilt.items()}
            diverged = (set(current.items()) ^ set(fresh.items()))
            if diverged:
                self.divergence_total += len(diverged)
                from polyaxon_tpu.obs import metrics as obs_metrics

                obs_metrics.admission_divergence().inc(len(diverged))
                logger.warning(
                    "admission live-view divergence: %d entries disagree "
                    "with the store rebuild (delta feed bug?) — adopting "
                    "the rebuilt view", len(diverged))
            with self._live_lock:
                self._live = rebuilt
        with self._live_lock:
            return dict(self._live)

    def usage_snapshot(self) -> dict[str, dict[str, int]]:
        """{project: {"runs": n, "chips": n}} over the current live
        map — exactly the counts ``_admissible`` enforces quotas
        against, so the ``polyaxon_project_usage`` gauges (and the
        oracle's ``quota_violation`` invariant) see the same truth.
        O(live), no store scan, no pass-cadence side effects."""
        with self._live_lock:
            entries = list(self._live.values())
        usage: dict[str, dict[str, int]] = {}
        for entry in entries:
            row = usage.setdefault(entry.project, {"runs": 0, "chips": 0})
            row["runs"] += 1
            row["chips"] += entry.chips
        return usage

    # --------------------------------------------------------------- pass
    def plan(self, queued: list[RunRecord], *, capacity: int,
             active: set[str] | None = None) -> AdmissionDecision:
        """Decide this tick's admissions (ordered) and preemptions.

        ``queued``: eligible QUEUED run records (non-pipeline kinds).
        ``capacity``: free executor slots. ``active``: run uuids the
        executor currently owns (the only evictable victims).
        """
        if not queued:
            # Idle ticks stay cheap (no catalog/usage queries), and an
            # empty queue means nothing can be starved.
            self._starved.clear()
            self._pinned.clear()
            return AdmissionDecision(admitted=[], victims=[], blocked={})
        t0 = time.perf_counter()
        try:
            return self._plan(queued, capacity=capacity, active=active)
        finally:
            from polyaxon_tpu.obs import metrics as obs_metrics

            obs_metrics.admission_pass_hist().observe(
                time.perf_counter() - t0)

    def _plan(self, queued: list[RunRecord], *, capacity: int,
              active: set[str] | None = None) -> AdmissionDecision:
        queues = {q["name"]: q for q in self.store.list_queues()}
        quotas = {q["project"]: q for q in self.store.list_quotas()}
        live = self._live_view()

        # Usage (runs + chips per project, runs per queue), tentatively
        # extended as candidates are admitted within this pass.
        runs_by_project: dict[str, int] = {}
        chips_by_project: dict[str, int] = {}
        runs_by_queue: dict[str, int] = {}
        for entry in live.values():
            runs_by_project[entry.project] = (
                runs_by_project.get(entry.project, 0) + 1)
            chips_by_project[entry.project] = (
                chips_by_project.get(entry.project, 0) + entry.chips)
            runs_by_queue[entry.queue] = runs_by_queue.get(entry.queue, 0) + 1

        candidates = []
        for i, r in enumerate(queued):
            info = sched_info(r)
            info.queue_priority = self._queue_row(queues, info.queue)["priority"]
            candidates.append((i, r, info))
        blocked: dict[str, str] = {}

        def weight(project: str) -> float:
            quota = quotas.get(project)
            w = float(quota.get("weight") or 1.0) if quota else 1.0
            return max(w, 1e-9)

        active_projects = ({e.project for e in live.values()}
                           | {r.project for r in queued})
        weights = {p: weight(p) for p in active_projects}
        total_weight = sum(weights.values()) or 1.0
        total_live = sum(runs_by_project.values())

        usage = (runs_by_project, chips_by_project, runs_by_queue)
        if self.incremental:
            admitted = self._rank_grouped(
                candidates, queues, quotas, weights, total_weight,
                total_live, usage, blocked)
        else:
            admitted = self._rank_legacy(
                candidates, queues, quotas, weights, total_weight,
                usage, blocked)

        victims = self._select_victims(
            admitted[max(capacity, 0):], queues, live, active or set())

        # Admission outcomes feed the unified registry: per-reason
        # blocked counts, admissions (capped at real capacity — the
        # overflow tail is ranked, not admitted), and evictions.
        from polyaxon_tpu.obs import metrics as obs_metrics

        outcomes = obs_metrics.admission_outcomes()
        n_admitted = len(admitted[:max(capacity, 0)])
        if n_admitted:
            outcomes.inc(n_admitted, outcome="admitted")
        for reason in blocked.values():
            outcomes.inc(outcome=reason)
        if victims:
            outcomes.inc(len(victims), outcome="victim")

        # Starvation counters/pin streaks only live for still-queued runs.
        queued_uuids = {r.uuid for r in queued}
        for uuid in list(self._starved):
            if uuid not in queued_uuids:
                del self._starved[uuid]
        for uuid in list(self._pinned):
            if uuid not in queued_uuids:
                del self._pinned[uuid]
        return AdmissionDecision(admitted=admitted, victims=victims,
                                 blocked=blocked)

    # ------------------------------------------------------------- ranking
    def _admissible(self, record: RunRecord, info: RunSchedInfo,
                    queue: dict, quotas: dict, usage, plan,
                    blocked: dict[str, str]) -> bool:
        """Examine one rank-order head: True → admit; False → the run
        was blocked (and recorded). Shared verbatim by both rankers so
        chaos firing order and pin semantics cannot drift."""
        runs_by_project, chips_by_project, runs_by_queue = usage
        if plan is not None and plan.fire(
                "admission", info.queue, detail=record.uuid) is not None:
            blocked[record.uuid] = "ChaosStarved"
            return False
        cap = queue.get("concurrency")
        if cap is not None and runs_by_queue.get(info.queue, 0) >= cap:
            blocked[record.uuid] = "QueueSaturated"
            self._pin_blocked(
                record, "QueueSaturated",
                f"queue `{info.queue}` at concurrency cap {cap}")
            return False
        quota = quotas.get(record.project)
        if quota is not None:
            max_runs = quota.get("max_runs")
            max_chips = quota.get("max_chips")
            used_runs = runs_by_project.get(record.project, 0)
            used_chips = chips_by_project.get(record.project, 0)
            if max_runs is not None and used_runs >= max_runs:
                blocked[record.uuid] = "QuotaExceeded"
                self._pin_blocked(
                    record, "QuotaExceeded",
                    f"project `{record.project}` at max_runs="
                    f"{max_runs} ({used_runs} live)")
                return False
            if (max_chips is not None
                    and used_chips + info.chips > max_chips):
                blocked[record.uuid] = "QuotaExceeded"
                self._pin_blocked(
                    record, "QuotaExceeded",
                    f"project `{record.project}` chips quota "
                    f"{used_chips}+{info.chips} > {max_chips}")
                return False
        return True

    def _rank_grouped(self, candidates, queues, quotas, weights,
                      total_weight, total_live, usage, blocked):
        """Admission ordering via (queue, project) groups.

        Every member of a group shares queue priority and project
        deficit, and group members sit in age order — so the globally
        best candidate is always some group's HEAD, found by scanning
        group heads (O(groups)) instead of re-sorting all remaining
        candidates (the old O(n log n) per admission). Admission order,
        block verdicts, and chaos firing order match the legacy ranker
        exactly; the fairness/starvation suites run against both."""
        runs_by_project, chips_by_project, runs_by_queue = usage
        groups: dict[tuple[str, str], deque] = {}
        for item in candidates:  # already in age order
            groups.setdefault((item[2].queue, item[1].project),
                              deque()).append(item)
        qprio = {key: self._queue_row(queues, key[0])["priority"]
                 for key in groups}
        plan = chaos.active_plan()
        admitted: list[tuple[RunRecord, RunSchedInfo]] = []
        while groups:
            best_key, best_rank = None, None
            for key, members in groups.items():
                project = key[1]
                share = (runs_by_project.get(project, 0) / total_live
                         if total_live else 0.0)
                deficit = weights[project] / total_weight - share
                rank = (-qprio[key], -deficit, members[0][0])
                if best_rank is None or rank < best_rank:
                    best_key, best_rank = key, rank
            members = groups[best_key]
            _, record, info = members.popleft()
            if not members:
                del groups[best_key]
            queue = self._queue_row(queues, info.queue)
            if not self._admissible(record, info, queue, quotas, usage,
                                    plan, blocked):
                continue
            admitted.append((record, info))
            runs_by_project[record.project] = (
                runs_by_project.get(record.project, 0) + 1)
            chips_by_project[record.project] = (
                chips_by_project.get(record.project, 0) + info.chips)
            runs_by_queue[info.queue] = runs_by_queue.get(info.queue, 0) + 1
            total_live += 1
        return admitted

    def _rank_legacy(self, candidates, queues, quotas, weights,
                     total_weight, usage, blocked):
        """The original full-re-sort ranking loop (pre-ISSUE-8), kept
        as the bench/deopt baseline the budget gate must fail on."""
        runs_by_project, chips_by_project, runs_by_queue = usage
        plan = chaos.active_plan()
        admitted: list[tuple[RunRecord, RunSchedInfo]] = []

        def deficit(project: str) -> float:
            total_live = sum(runs_by_project.values())
            share = (runs_by_project.get(project, 0) / total_live
                     if total_live else 0.0)
            return weights[project] / total_weight - share

        remaining = list(candidates)
        while remaining:
            # Re-rank each round: admissions shift the fair-share
            # deficits, which is exactly what makes this converge.
            remaining.sort(key=lambda item: (
                -self._queue_row(queues, item[2].queue)["priority"],
                -deficit(item[1].project),
                item[0],  # age: store order is (created_at, rowid)
            ))
            entry = remaining[0]
            _, record, info = entry
            remaining.remove(entry)
            queue = self._queue_row(queues, info.queue)
            if not self._admissible(record, info, queue, quotas, usage,
                                    plan, blocked):
                continue
            admitted.append((record, info))
            runs_by_project[record.project] = (
                runs_by_project.get(record.project, 0) + 1)
            chips_by_project[record.project] = (
                chips_by_project.get(record.project, 0) + info.chips)
            runs_by_queue[info.queue] = runs_by_queue.get(info.queue, 0) + 1
        return admitted

    # --------------------------------------------------------- preemption
    def _select_victims(self, overflow, queues,
                        live: dict[str, _LiveEntry],
                        active: set[str]) -> list[str]:
        """Pick victims for admissible-but-capacity-starved runs.

        One victim per starved run per tick, strictly lower effective
        priority, on a preemptible queue, currently owned by the
        executor — the gentlest eviction that unblocks the starved run.
        The victim pool is sorted once per pass (eff asc, youngest
        first within a tier), so the best victim for any starved run is
        the pool head iff its effective priority is strictly lower."""
        victims: list[str] = []
        if not overflow:
            self._starved.clear()
            return victims
        pool: list[tuple[tuple[int, int], str, _LiveEntry]] = []
        for entry in live.values():
            if entry.uuid not in active:
                continue
            if entry.status != V1Statuses.RUNNING:
                continue
            cqueue = self._queue_row(queues, entry.queue)
            if not cqueue["preemptible"]:
                continue
            eff = (cqueue["priority"], entry.priority)
            pool.append((eff, entry.started_at or entry.created_at, entry))
        # Lowest priority first; among equals the YOUNGEST start first
        # (least progress lost) — hence the descending timestamp.
        pool.sort(key=lambda item: item[1], reverse=True)
        pool.sort(key=lambda item: item[0])
        pool_dq = deque(pool)
        overflow_uuids = {r.uuid for r, _ in overflow}
        for record, info in overflow:
            ticks = self._starved.get(record.uuid, 0) + 1
            self._starved[record.uuid] = ticks
            if ticks < self.starvation_ticks:
                continue
            if not pool_dq:
                continue
            starved_eff = info.effective(
                self._queue_row(queues, info.queue)["priority"])
            eff, _, victim = pool_dq[0]
            if eff >= starved_eff:
                continue  # nothing strictly lower-priority to evict
            pool_dq.popleft()
            victims.append(victim.uuid)
            self._starved[record.uuid] = 0
            victim_record = self.store.get_run(victim.uuid)
            meta = dict(victim_record.meta or {})
            sched = dict(meta.get("scheduling") or {})
            sched["evicted_for"] = record.uuid
            meta["scheduling"] = sched
            self.store.update_run(victim.uuid, meta=meta)
            logger.info("admission: preempting %s (eff=%s) for starved %s "
                        "(eff=%s)", victim.uuid, eff, record.uuid,
                        starved_eff)
        # Drop counters for runs that were admitted within capacity.
        for uuid in list(self._starved):
            if uuid not in overflow_uuids:
                self._starved.pop(uuid, None)
        return victims
