"""Ring attention: context-parallel attention over the ``cp`` mesh axis.

Net-new surface vs the reference (SURVEY.md §5.7: long-context is
absent upstream — it ships no model math at all). v2 design:

- Every device holds one contiguous sequence shard of Q, K, V
  (``seq → cp`` in the CP rule table). K/V rotate around the ICI ring
  via ``lax.ppermute`` — each step overlaps the attention kernel for
  the current block with the DMA of the next.
- **Zigzag placement for causal masks.** A contiguous causal layout is
  ~2× wasteful: device 0's queries see one block while device cp-1's
  see all of them, and SPMD lockstep bills every device for the worst
  case. Instead each shard is split into two half-chunks and
  redistributed (two ppermutes) so device ``i`` holds global chunks
  ``i`` and ``2·cp-1-i``. Every ring step then needs exactly TWO dense
  block attentions per device — fully-post-diagonal blocks are never
  computed (skipped, not masked), and the load is perfectly balanced.
  The inverse permutation restores contiguous layout on the output.
- **Flash per block.** Each visible block runs
  ``flash_attention_with_lse`` (the Pallas kernel on real TPU, the
  einsum+lse reference for non-tiling block sizes), and the per-block
  partials merge exactly through (o, lse) online-softmax combination
  in f32. The S×S score matrix never exists on any chip.
- GQA K/V travel the ring UNexpanded (kv heads only); the flash kernel
  expands groups in its index maps, so ring bandwidth is divided by
  ``n_heads/n_kv_heads``.
- The loop is a ``lax.scan`` of differentiable pieces (custom-vjp flash
  blocks, ppermute, lse merges), so the whole ring is reverse-
  differentiable: ppermute transposes to the inverse permutation and
  the backward pass runs the ring the other way.

``ring_attention`` can be called either inside an existing
``shard_map`` (axis already bound) or under plain jit, where it wraps
itself in ``jax.shard_map`` over the ambient mesh's ``cp`` axis with
all other axes left to GSPMD (partial-manual sharding).
"""

from __future__ import annotations

import functools
import logging
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from polyaxon_tpu.parallel import compat

NEG_INF = -1e30

_warned_einsum_fallback = False


def _warn_einsum_fallback(s_loc: int) -> None:
    """The contiguous masked fallback does ~2× the attention FLOPs of
    zigzag (post-diagonal blocks are masked, not skipped) and einsum-
    not-flash math. Engaging it must be loud (VERDICT r2 weak #6):
    a user one `seq % (2*cp) == 0` reshape away from the fast path
    should find out from the log, not a profile."""
    global _warned_einsum_fallback
    if _warned_einsum_fallback:
        return
    _warned_einsum_fallback = True
    warnings.warn(
        f"ring_attention: local sequence length {s_loc} is odd — falling "
        f"back to the contiguous masked-einsum ring (~2x the attention "
        f"FLOPs of the zigzag path, no flash kernel). For CAUSAL "
        f"attention the global ring_attention entry pads this away "
        f"automatically (the pad relies on the causal mask, so it does "
        f"not apply non-causal); inside shard_map, pad the sequence so "
        f"seq/cp is even.",
        RuntimeWarning, stacklevel=3)


def _axis_bound(axis_name: str) -> bool:
    """True when ``axis_name`` is a bound manual-collective axis here."""
    try:
        jax.lax.axis_index(axis_name)
        return True
    except (NameError, KeyError, ValueError):
        return False


def ambient_mesh():
    """The mesh entered via ``with mesh:`` (as the runtime loop does).

    Reads the resource env through ``jax._src.mesh`` directly: the
    public re-export (``jax.interpreters.pxla.thread_resources``) is
    deprecated since 0.8.2, and ``get_abstract_mesh()`` is only
    populated by ``jax.sharding.use_mesh``, not by ``with mesh:``.
    """
    try:
        from jax._src import mesh as mesh_lib

        mesh = mesh_lib.thread_resources.env.physical_mesh
        if mesh is not None and not mesh.empty:
            return mesh
    except Exception as exc:
        logging.getLogger(__name__).debug(
            "thread_resources mesh probe failed (jax internals moved?): %s",
            exc)
    try:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is not None and not mesh.empty:
            return mesh
    except Exception as exc:
        logging.getLogger(__name__).debug(
            "get_abstract_mesh probe failed: %s", exc)
    return None


def _merge(o_a, lse_a, o_b, lse_b):
    """Exact online-softmax combination of two partial attentions.
    o: [B, S, H, D] f32; lse: [B, H, S] f32."""
    lse_new = jnp.logaddexp(lse_a, lse_b)
    w_a = jnp.exp(lse_a - lse_new).transpose(0, 2, 1)[..., None]
    w_b = jnp.exp(lse_b - lse_new).transpose(0, 2, 1)[..., None]
    return o_a * w_a + o_b * w_b, lse_new


def _block_attn(q, k, v, *, causal, scale):
    """One visible block through flash (Pallas on TPU, einsum+lse
    reference when the block doesn't tile), partials in f32."""
    from polyaxon_tpu.ops.flash import flash_attention_with_lse

    o, lse = flash_attention_with_lse(q, k, v, causal=causal,
                                      softmax_scale=scale)
    return o.astype(jnp.float32), lse


def _ring_causal_zigzag(q, k, v, *, scale, axis_name):
    """Causal ring attention with zigzag placement (module docstring)."""
    cp = compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_loc = q.shape[1]
    half = s_loc // 2
    rotate = [(i, (i + 1) % cp) for i in range(cp)]

    # --- redistribute contiguous halves into zigzag placement -------
    # Device i holds global half-chunks (2i, 2i+1); zigzag wants
    # (i, 2cp-1-i). Chunk c goes to device c if c < cp else 2cp-1-c;
    # per-parity that is one ppermute for first halves (A) and one for
    # second halves (B). Even devices receive their LOW chunk via A,
    # odd devices via B.
    perm_a = [(i, 2 * i if 2 * i < cp else 2 * cp - 1 - 2 * i)
              for i in range(cp)]
    perm_b = [(i, 2 * i + 1 if 2 * i + 1 < cp else 2 * cp - 2 - 2 * i)
              for i in range(cp)]
    even = (idx % 2) == 0

    def to_zigzag(x):
        ra = jax.lax.ppermute(x[:, :half], axis_name, perm_a)
        rb = jax.lax.ppermute(x[:, half:], axis_name, perm_b)
        lo = jnp.where(even, ra, rb)
        hi = jnp.where(even, rb, ra)
        return lo, hi

    q_lo, q_hi = to_zigzag(q)
    k_lo, k_hi = to_zigzag(k)
    v_lo, v_hi = to_zigzag(v)

    attn = functools.partial(_block_attn, scale=scale)

    def rot4(k_lo, k_hi, v_lo, v_hi):
        return tuple(jax.lax.ppermute(x, axis_name, rotate)
                     for x in (k_lo, k_hi, v_lo, v_hi))

    # --- step 0: the diagonal chunks this device already holds ------
    # low = global chunk idx, high = global chunk 2cp-1-idx. The high
    # chunk always sees the low chunk fully (2cp-1-idx > idx).
    # Rotation 1 is issued FIRST: it is independent of the diagonal
    # attention, so the ICI hop hides under the compute (pipelined
    # ring — SURVEY §7 hard-part 3; same shape as _ring_dense).
    kv1 = rot4(k_lo, k_hi, v_lo, v_hi)
    acc_lo = attn(q_lo, k_lo, v_lo, causal=True)
    o_hh, l_hh = attn(q_hi, k_hi, v_hi, causal=True)
    o_hl, l_hl = attn(q_hi, k_lo, v_lo, causal=False)
    acc_hi = _merge(o_hh, l_hh, o_hl, l_hl)

    # --- ring steps 1..cp-1: exactly two dense blocks per step, the
    # NEXT rotation in flight while the current blocks are attended
    # (the final iteration's permute is unused: ~1/cp extra bandwidth,
    # hidden under that step's compute) ---------------------------------
    def step(carry, s):
        (k_lo, k_hi, v_lo, v_hi), (acc_lo, acc_hi) = carry
        kv_nxt = rot4(k_lo, k_hi, v_lo, v_hi)
        src = (idx - s) % cp  # kv in hand holds chunks (src, 2cp-1-src)

        # Always visible: q chunk 2cp-1-idx vs kv chunk src (< cp).
        o1, l1 = attn(q_hi, k_lo, v_lo, causal=False)
        acc_hi = _merge(*acc_hi, o1, l1)

        # The second visible block depends on the diagonal side:
        # idx > src → q_lo sees kv_lo (chunk idx > chunk src);
        # idx < src → q_hi sees kv_hi (2cp-1-idx > 2cp-1-src).
        # Fully-post-diagonal blocks are never computed at all.
        take_low = idx > src
        q2 = jnp.where(take_low, q_lo, q_hi)
        k2 = jnp.where(take_low, k_lo, k_hi)
        v2 = jnp.where(take_low, v_lo, v_hi)
        o2, l2 = attn(q2, k2, v2, causal=False)
        lo_upd = _merge(*acc_lo, o2, l2)
        hi_upd = _merge(*acc_hi, o2, l2)
        acc_lo = tuple(jnp.where(take_low, a, b)
                       for a, b in zip(lo_upd, acc_lo))
        acc_hi = tuple(jnp.where(take_low, b, a)
                       for a, b in zip(hi_upd, acc_hi))
        return (kv_nxt, (acc_lo, acc_hi)), None

    ((_, (acc_lo, acc_hi)), _) = jax.lax.scan(
        step, (kv1, (acc_lo, acc_hi)),
        jnp.arange(1, cp))

    # --- inverse zigzag: restore contiguous output layout -----------
    o_lo = acc_lo[0].astype(q.dtype)
    o_hi = acc_hi[0].astype(q.dtype)
    inv_a = [(d, s) for (s, d) in perm_a]
    inv_b = [(d, s) for (s, d) in perm_b]
    send_a = jnp.where(even, o_lo, o_hi)  # the chunk that arrived via A
    send_b = jnp.where(even, o_hi, o_lo)
    back_a = jax.lax.ppermute(send_a, axis_name, inv_a)  # chunk 2i
    back_b = jax.lax.ppermute(send_b, axis_name, inv_b)  # chunk 2i+1
    return jnp.concatenate([back_a, back_b], axis=1)


def _ring_dense(q, k, v, *, scale, axis_name):
    """Non-causal ring: every block visible, one flash call per step.

    Pipelined (SURVEY §7 hard-part 3): each step attends to the block
    IN HAND while the next block's ppermute is already in flight — the
    two are data-independent, so XLA's async collective-permute
    (start/done pair) hides the ICI hop under the attention compute.
    The permute issued by the final iteration is unused (~1/cp extra
    bandwidth, itself hidden under that step's compute).
    """
    cp = compat.axis_size(axis_name)
    rotate = [(i, (i + 1) % cp) for i in range(cp)]
    attn = functools.partial(_block_attn, scale=scale, causal=False)

    # Rotation 1 flies while block 0 (the local block) is attended.
    k1 = jax.lax.ppermute(k, axis_name, rotate)
    v1 = jax.lax.ppermute(v, axis_name, rotate)
    acc = attn(q, k, v)

    def step(carry, _):
        (k_cur, v_cur), acc = carry
        k_nxt = jax.lax.ppermute(k_cur, axis_name, rotate)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, rotate)
        o, lse = attn(q, k_cur, v_cur)  # independent of the permutes
        return ((k_nxt, v_nxt), _merge(*acc, o, lse)), None

    (((_, _), acc), _) = jax.lax.scan(
        step, ((k1, v1), acc), jnp.arange(1, cp))
    return acc[0].astype(q.dtype)


def _ring_einsum_causal(q, k, v, *, scale, axis_name):
    """Contiguous-layout causal fallback for shapes the zigzag split
    cannot cover (odd local sequence length). Blocks ahead of the
    diagonal are masked, not skipped."""
    from polyaxon_tpu.ops.attention import repeat_kv

    cp = compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    n_rep = h // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)

    q_f = q.astype(jnp.float32)
    q_pos = idx * s_loc + jnp.arange(s_loc)  # global query positions
    local_pos = jnp.arange(s_loc)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    def step(carry, s):
        (k_cur, v_cur), (o, m, l) = carry
        src = (idx - s) % cp  # which block this kv shard is
        k_pos = src * s_loc + local_pos

        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q_f, k_cur.astype(jnp.float32)) * scale
        mask = q_pos[:, None] >= k_pos[None, :]  # [Sq, Sk]
        logits = jnp.where(mask[None, None], logits, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))  # [B,H,Sq]
        p = jnp.where(mask[None, None],
                      jnp.exp(logits - m_new[..., None]), 0.0)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_cur.astype(jnp.float32))
        o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv

        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return ((k_nxt, v_nxt), (o_new, m_new, l_new)), None

    o0 = jnp.zeros((b, s_loc, h, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    ((_, (o, _, l)), _) = jax.lax.scan(
        step, ((k, v), (o0, m0, l0)), jnp.arange(cp))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = o / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_attention_sharded(
    q: jax.Array,  # [B, S_loc, H, D] local shard
    k: jax.Array,  # [B, S_loc, KV, D]
    v: jax.Array,
    *,
    causal: bool,
    scale: float,
    axis_name: str,
) -> jax.Array:
    if not causal:
        return _ring_dense(q, k, v, scale=scale, axis_name=axis_name)
    if q.shape[1] % 2:
        _warn_einsum_fallback(q.shape[1])
        return _ring_einsum_causal(q, k, v, scale=scale,
                                   axis_name=axis_name)
    return _ring_causal_zigzag(q, k, v, scale=scale, axis_name=axis_name)


def ring_attention(
    q: jax.Array,  # [B, S, H, D] (global, seq sharded over cp)
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    softmax_scale: Optional[float] = None,
    axis_name: str = "cp",
    mesh=None,
) -> jax.Array:
    scale = softmax_scale if softmax_scale is not None else q.shape[-1] ** -0.5
    if _axis_bound(axis_name):
        return _ring_attention_sharded(
            q, k, v, causal=causal, scale=scale, axis_name=axis_name
        )

    mesh = mesh if mesh is not None else ambient_mesh()
    if mesh is None or axis_name not in mesh.axis_names:
        raise ValueError(
            f"ring_attention needs mesh axis `{axis_name}`: call inside "
            "shard_map, pass mesh=, or enter `with mesh:` (the runtime "
            "loop does) with a cp axis in the mesh"
        )
    # Odd local length cannot split into zigzag halves. From the global
    # entry we can fix that instead of falling back to the ~2x masked-
    # einsum path: pad the sequence TAIL by cp rows (shards stay equal
    # at S_loc+1 — now even — and the pads sit at the highest global
    # positions, which causal attention guarantees no real query ever
    # attends), run zigzag, slice the pads back off. Only direct
    # in-shard_map callers still hit the warned fallback.
    S = q.shape[1]
    cp = mesh.shape[axis_name]
    pad = cp if causal and (S // cp) % 2 else 0
    if pad:
        widths = ((0, 0), (0, pad), (0, 0), (0, 0))
        q = jnp.pad(q, widths)
        k = jnp.pad(k, widths)
        v = jnp.pad(v, widths)
    # Seq shards over cp; the batch dim keeps its dp/fsdp sharding
    # through the shard_map (an unmentioned batch axis would all-gather
    # Q/K/V at the boundary and attend dp-redundantly — the audit
    # measured that spelling at 3.2x the step time on dp2xcp4; see
    # docs/performance.md "Communication audit").
    spec = P(compat.batch_axes_in(mesh), axis_name, None, None)
    fn = compat.shard_map(
        functools.partial(
            _ring_attention_sharded, causal=causal, scale=scale, axis_name=axis_name
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    out = fn(q, k, v)
    return out[:, :S] if pad else out
