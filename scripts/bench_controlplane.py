#!/usr/bin/env python
"""Control-plane bench entry point (ISSUE 8).

Wraps the fleet simulator (``polyaxon_tpu.sim``) the way
``perf_sweep.py`` wraps the communication audit: build the standard
load-point curve with a per-point metrics-registry snapshot, gate it
against ``polyaxon_tpu/sim/budgets.json``, and optionally run the
before/after A/B the PR description quotes:

  # the CI-shaped run (quick points, registry snapshots, budget gate)
  python scripts/bench_controlplane.py --check

  # full curve incl. the 10k-queued point, refresh committed artifact
  python scripts/bench_controlplane.py --mode full --write-curve

  # measured A/B: legacy six-scan+rebuild vs single-pass+incremental
  python scripts/bench_controlplane.py --ab

  # whole compressed day, asserts zero admission divergence
  python scripts/bench_controlplane.py --day

The A/B measures the *scheduler tick* at the 10k-queued point (the
ISSUE 8 acceptance unit) and the *admission pass* at 1k queued — the
legacy admission ranker is O(n² log n) and takes minutes per pass at
10k, which is itself the headline finding: the old control plane could
not have survived a 10k-deep queue at all.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from polyaxon_tpu.sim import budgets as sim_budgets  # noqa: E402
from polyaxon_tpu.sim import curve as sim_curve  # noqa: E402
from polyaxon_tpu.sim.fleet import FleetSim  # noqa: E402


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr)


def run_ab(seed: int = 0) -> dict:
    """Before/after at the acceptance load points."""
    from polyaxon_tpu.obs import metrics as obs_metrics

    report: dict = {}

    # Scheduler tick at 10k queued: legacy six-scan vs single pass.
    for label, legacy in (("legacy", True), ("optimized", False)):
        obs_metrics.REGISTRY.reset()
        sim = FleetSim(capacity=0, seed=seed, legacy_scan=legacy,
                       incremental=True)
        try:
            _log(f"A/B sched_tick_10k/{label}: loading 10k queued runs ...")
            sim.submit_queued_jobs(10000)
            report[f"sched_tick_10k_{label}"] = (
                sim.measure_scheduler_ticks(10))
            _log(f"A/B sched_tick_10k/{label}: "
                 f"{report[f'sched_tick_10k_{label}']}")
        finally:
            sim.close()

    # Full reconcile tick at 10k queued (optimized admission only: the
    # legacy ranker cannot finish a 10k pass in CI-compatible time).
    obs_metrics.REGISTRY.reset()
    sim = FleetSim(capacity=0, seed=seed)
    try:
        _log("A/B reconcile_10k/optimized: loading 10k queued runs ...")
        sim.submit_queued_jobs(10000)
        report["reconcile_10k_optimized"] = sim.measure_ticks(10)
    finally:
        sim.close()

    # Admission pass at 1k queued: legacy full-rebuild+re-sort ranker
    # vs incremental grouped ranker.
    for label, incremental in (("legacy", False), ("optimized", True)):
        obs_metrics.REGISTRY.reset()
        sim = FleetSim(capacity=0, seed=seed, incremental=incremental)
        try:
            _log(f"A/B admission_1k/{label}: loading 1k queued runs ...")
            sim.submit_queued_jobs(1000)
            report[f"admission_1k_{label}"] = sim.measure_ticks(5)
            _log(f"A/B admission_1k/{label}: "
                 f"tick p50 {report[f'admission_1k_{label}']['tick_p50_ms']}ms")
        finally:
            sim.close()

    s_leg = report["sched_tick_10k_legacy"]["sched_tick_p50_ms"]
    s_opt = report["sched_tick_10k_optimized"]["sched_tick_p50_ms"]
    a_leg = report["admission_1k_legacy"]["tick_p50_ms"]
    a_opt = report["admission_1k_optimized"]["tick_p50_ms"]
    report["speedups"] = {
        "sched_tick_10k_p50": round(s_leg / max(s_opt, 1e-9), 2),
        "admission_tick_1k_p50": round(a_leg / max(a_opt, 1e-9), 2),
    }
    return report


def run_day(seed: int = 0) -> dict:
    from polyaxon_tpu.sim.traces import make_trace

    sim = FleetSim(capacity=1000, seed=seed, rebuild_ticks=25)
    try:
        report = sim.run_trace(make_trace("day", seed=seed),
                               max_wall=1800.0)
    finally:
        sim.close()
    if report["divergence_total"]:
        raise SystemExit(
            f"FAIL: admission live-view diverged "
            f"{report['divergence_total']} times over the sim day")
    if not report["rebuild_checks"]:
        raise SystemExit("FAIL: no rebuild consistency checks ran")
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--mode", choices=["quick", "full"],
                        default="quick")
    parser.add_argument("--check", action="store_true")
    parser.add_argument("--write-curve", action="store_true")
    parser.add_argument("--deopt", action="store_true")
    parser.add_argument("--ab", action="store_true",
                        help="run the before/after A/B instead of a curve")
    parser.add_argument("--day", action="store_true",
                        help="replay the compressed 100k-run day")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--json", dest="json_out")
    args = parser.parse_args(argv)

    if args.ab:
        result = run_ab(seed=args.seed)
    elif args.day:
        result = run_day(seed=args.seed)
    else:
        result = sim_curve.build_curve(
            args.mode, seed=args.seed, legacy=args.deopt,
            deopt=args.deopt, snapshot=True, progress=_log)
    print(json.dumps(result, indent=2))
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(result, fh, indent=2)
    if args.write_curve and not (args.ab or args.day):
        # The committed artifact stays snapshot-free (diff noise).
        slim = {"_meta": result["_meta"],
                "points": {k: {kk: vv for kk, vv in v.items()
                               if kk != "registry"}
                           for k, v in result["points"].items()}}
        path = sim_budgets.write_curve(slim)
        _log(f"curve written: {path}")
    if args.check and not (args.ab or args.day):
        violations = sim_budgets.check_curve(
            result, sim_budgets.load_budgets(), args.mode)
        for v in violations:
            print(f"BUDGET VIOLATION: {v}", file=sys.stderr)
        if violations:
            return 1
        _log(f"within budget ({args.mode})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
