#!/bin/sh
# Full CI sweep: Python suites (8-device virtual CPU mesh), native
# sanitizers, and the bench smoke contract.
set -e
cd "$(dirname "$0")/.."
echo "== pytest"
python -m pytest tests/ -q
echo "== native ASan/UBSan"
make -C native sanitize
printf 'ADD a 4x4 0\nREQ r 2x2 0 0\nTICK 0 30\nQUIT\n' | ./native/build/sliced_san >/dev/null
echo "== native TSan stress"
make -C native tsan
TSAN_OPTIONS=halt_on_error=1 ./native/build/sliced_tsan
echo "== bench smoke"
# Contract check only (one JSON line): forced onto CPU so CI does not
# depend on the TPU tunnel; the driver benches real hardware itself.
JAX_PLATFORMS=cpu python bench.py --smoke
echo "CI OK"
