"""polycheck static-analysis gate tests (ISSUE 9).

Golden fixtures under ``tests/fixtures/polycheck/`` plant exactly one
violation per rule (plus a negative control per family); the tests
assert the exact rule and line so an analyzer regression that stops
seeing a class of bug fails loudly, not silently. The lockdep drills
exercise the RUNTIME side: a synthetic AB-BA as the positive control,
then the real store + admission controller hammered from threads with
the shim installed, asserting zero observed cycles.
"""

import os
import textwrap
import threading

import pytest

from polyaxon_tpu.analysis import core
from polyaxon_tpu.analysis.__main__ import main as polycheck_main

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures", "polycheck")


def analyze_fixture(name: str, virtual_path: str):
    """Analyze one fixture as if it lived at ``virtual_path`` in the
    package (path-scoped rules key off the path prefix)."""
    with open(os.path.join(FIXDIR, name)) as fh:
        sf = core.SourceFile(virtual_path, fh.read())
    return core.analyze([sf])


def rule_lines(findings, rule):
    return sorted((f.line, f.qualname) for f in findings if f.rule == rule)


class TestGoldenConcurrency:
    def test_lock_order_inversion(self):
        findings = analyze_fixture(
            "lock_inversion.py", "polyaxon_tpu/fixture_locks.py")
        inversions = [f for f in findings if f.rule == "lock-order"]
        assert len(inversions) == 1
        # Anchored at the first edge of the cycle (forward's inner with).
        assert inversions[0].line == 10
        assert "_alpha" in inversions[0].message
        assert "_beta" in inversions[0].message

    def test_lock_self_deadlock(self):
        findings = analyze_fixture(
            "lock_self_deadlock.py", "polyaxon_tpu/fixture_self.py")
        assert rule_lines(findings, "lock-self-deadlock") == [(9, "reenter")]

    def test_lock_held_across_blocking_call(self):
        findings = analyze_fixture(
            "lock_blocking.py", "polyaxon_tpu/fixture_blocking.py")
        assert rule_lines(findings, "lock-blocking-call") == [
            (10, "slow_update")]

    def test_transaction_scoped_scan_is_exempt(self):
        findings = analyze_fixture(
            "txn_scan_ok.py", "polyaxon_tpu/fixture_txn_scan.py")
        assert [f for f in findings if f.family == "concurrency"] == []


class TestGoldenHotpath:
    def test_host_sync_in_jitted_step(self):
        findings = analyze_fixture(
            "jit_host_sync.py", "polyaxon_tpu/fixture_jit.py")
        assert rule_lines(findings, "hotpath-host-sync") == [(7, "step")]

    def test_tracer_branch(self):
        findings = analyze_fixture(
            "jit_tracer_branch.py", "polyaxon_tpu/fixture_branch.py")
        # Only the `if delta > 0` branch fires; `cfg is None` is static.
        assert rule_lines(findings, "hotpath-tracer-branch") == [
            (13, "step")]

    def test_wallclock_and_unseeded_random_in_runtime(self):
        findings = analyze_fixture(
            "runtime_wallclock_random.py",
            "polyaxon_tpu/runtime/fixture_rng.py")
        assert rule_lines(findings, "hotpath-wallclock") == [
            (10, "make_batch")]
        assert rule_lines(findings, "hotpath-unseeded-random") == [
            (11, "make_batch")]

    def test_runtime_rules_scoped_to_runtime_paths(self):
        # The same source outside runtime/ is not replay-relevant.
        findings = analyze_fixture(
            "runtime_wallclock_random.py", "polyaxon_tpu/fixture_rng.py")
        assert [f for f in findings if f.family == "hotpath"] == []


class TestGoldenInvariants:
    def test_silent_swallow(self):
        findings = analyze_fixture(
            "swallow.py", "polyaxon_tpu/fixture_swallow.py")
        # `quiet` swallows silently; `traced` logs at debug and passes.
        assert rule_lines(findings, "invariant-swallow") == [(11, "quiet")]

    def test_uncataloged_metric(self):
        findings = analyze_fixture(
            "metric_catalog.py", "polyaxon_tpu/fixture_metric.py")
        hits = [f for f in findings if f.rule == "invariant-metric-catalog"]
        assert len(hits) == 1
        assert hits[0].line == 8
        assert "polycheck_fixture_not_cataloged_total" in hits[0].message

    def test_store_batch(self):
        findings = analyze_fixture(
            "store_batch.py", "polyaxon_tpu/fixture_batch.py")
        # Anchored at the FIRST mutation outside transaction(); the
        # transaction-wrapped twin stays silent.
        assert rule_lines(findings, "invariant-store-batch") == [
            (6, "promote")]

    def test_daemon_drain(self):
        findings = analyze_fixture(
            "daemon_drain.py", "polyaxon_tpu/fixture_daemon.py")
        assert rule_lines(findings, "invariant-daemon-drain") == [
            (7, "spawn")]


class TestPragmas:
    def test_reasoned_pragmas_suppress_unreasoned_are_findings(self):
        findings = analyze_fixture(
            "pragma_suppress.py", "polyaxon_tpu/fixture_pragma.py")
        # Above-line and trailing reasoned pragmas silence their rules.
        assert rule_lines(findings, "lock-blocking-call") == []
        swallows = rule_lines(findings, "invariant-swallow")
        # Only the handler guarded by the REASON-LESS pragma still fires
        # (a malformed pragma must not suppress)...
        assert swallows == [(26, "unreasoned")]
        # ...and the malformed pragma is itself a finding.
        assert rule_lines(findings, "pragma-syntax") == [(27, "")]

    def test_unknown_rule_is_a_finding(self):
        sf = core.SourceFile(
            "polyaxon_tpu/fixture_unknown.py",
            "# polycheck: ignore[no-such-rule] -- why\nx = 1\n")
        findings = core.analyze([sf])
        assert [f.rule for f in findings] == ["pragma-syntax"]
        assert "unknown" in findings[0].message


class TestFindingIds:
    SRC = textwrap.dedent("""\
        def quiet(risky):
            try:
                return risky()
            except Exception:
                pass
        """)

    def test_stable_across_line_drift(self):
        a = core.analyze([core.SourceFile("polyaxon_tpu/fx.py", self.SRC)])
        b = core.analyze([core.SourceFile(
            "polyaxon_tpu/fx.py", "# pad\n# pad\n# pad\n" + self.SRC)])
        assert len(a) == len(b) == 1
        assert a[0].line != b[0].line
        assert a[0].id == b[0].id

    def test_identical_snippets_get_distinct_ids(self):
        src = textwrap.dedent("""\
            def f(r):
                try:
                    r()
                except Exception:
                    pass
                try:
                    r()
                except Exception:
                    pass
            """)
        findings = core.analyze([core.SourceFile("polyaxon_tpu/fx.py", src)])
        assert len(findings) == 2
        assert findings[0].id != findings[1].id


class TestBaseline:
    def _finding(self, rule="hotpath-wallclock"):
        return core.Finding(
            rule=rule, path="polyaxon_tpu/runtime/x.py", line=10,
            message="m", qualname="f", snippet="stamp = time.time()")

    def test_baselined_finding_passes_new_finding_fails(self, tmp_path):
        f = self._finding()
        path = str(tmp_path / "baseline.json")
        core.write_baseline(
            [{"id": f.id, "rule": f.rule, "reason": "legacy"}], path)
        result = core.check([f], baseline_path=path)
        assert result.ok and result.baselined == [f]
        fresh = self._finding()
        fresh.snippet = "other = time.time()"
        result = core.check([f, fresh], baseline_path=path)
        assert not result.ok and result.new == [fresh]

    def test_stale_entry_fails(self, tmp_path):
        f = self._finding()
        path = str(tmp_path / "baseline.json")
        core.write_baseline(
            [{"id": f.id, "rule": f.rule, "reason": "legacy"}], path)
        result = core.check([], baseline_path=path)
        assert not result.ok and result.stale_baseline == [f.id]

    @pytest.mark.parametrize("rule", ["lock-order", "lock-blocking-call",
                                      "invariant-swallow"])
    def test_no_baseline_families_rejected(self, tmp_path, rule):
        path = str(tmp_path / "baseline.json")
        core.write_baseline(
            [{"id": f"{rule}:x:abc", "rule": rule, "reason": "nope"}], path)
        with pytest.raises(core.BaselineError):
            core.load_baseline(path)

    def test_reasonless_entry_rejected(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        core.write_baseline(
            [{"id": "hotpath-wallclock:x:abc",
              "rule": "hotpath-wallclock"}], path)
        with pytest.raises(core.BaselineError):
            core.load_baseline(path)

    def test_committed_baseline_has_zero_suppressions(self):
        # ISSUE 9 acceptance: every finding was FIXED or pragma'd at the
        # site with a reason — the shipped baseline hides nothing.
        assert core.load_baseline() == {}


class TestCliGate:
    def test_committed_tree_is_clean(self):
        assert polycheck_main(["--check"]) == 0

    def test_injected_lock_inversion_fails_the_gate(self):
        assert polycheck_main(["--check", "--inject-lock-inversion"]) == 1

    def test_injected_uncataloged_metric_fails_the_gate(self):
        assert polycheck_main(["--check", "--inject-uncataloged-metric"]) == 1

    def test_list_rules(self, capsys):
        assert polycheck_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for family in core.RULE_FAMILIES:
            assert f"{family}:" in out


# A package-named module body, exec'd so the shim's creation-site
# filter (locks created BY polyaxon_tpu code) applies to the drill.
_ABBA_SRC = textwrap.dedent("""\
    import threading
    lock_a = threading.Lock()
    lock_b = threading.Lock()


    def fwd():
        with lock_a:
            with lock_b:
                pass


    def bwd():
        with lock_b:
            with lock_a:
                pass


    def make_lock():
        return threading.Lock()


    def call_through(factory):
        return factory()
    """)


class TestLockdep:
    def _exec_drill(self):
        ns = {"__name__": "polyaxon_tpu._lockdep_drill_fixture"}
        exec(compile(_ABBA_SRC, "<lockdep-drill>", "exec"), ns)
        return ns

    def test_positive_control_abba_is_caught(self):
        from polyaxon_tpu.analysis import lockdep as ld

        with ld.lockdep():
            ns = self._exec_drill()
            ns["fwd"]()
            ns["bwd"]()
        assert ld.edge_count() >= 2
        cycles = ld.cycles()
        assert cycles, "AB-BA inversion not observed by the shim"
        assert "_lockdep_drill_fixture" in cycles[0].render()

    def test_third_party_created_locks_pass_through(self):
        """Only the IMMEDIATE creator frame decides instrumentation: a
        lock a third-party library creates while servicing a
        polyaxon_tpu call must come back as a real threading lock, not
        a shim — otherwise orbax/fsspec internal lock protocols get
        charged to the polyaxon_tpu call site and read as false AB-BA
        cycles (observed live with orbax async checkpointing)."""
        import threading

        from polyaxon_tpu.analysis import lockdep as ld

        vendor_ns = {"__name__": "vendored_thirdparty_lib"}
        exec(compile(
            "import threading\n"
            "def make_lock():\n"
            "    return threading.Lock()\n",
            "<vendor>", "exec"), vendor_ns)
        with ld.lockdep():
            ns = self._exec_drill()
            # polyaxon_tpu frame calling into "third party" code that
            # creates the lock -- the creator is the vendor frame.
            vendored = ns["call_through"](vendor_ns["make_lock"])
            ours = ns["make_lock"]()
        assert not isinstance(vendored, ld._LockShim)
        assert isinstance(ours, ld._LockShim)

    def test_well_ordered_nesting_is_clean(self):
        from polyaxon_tpu.analysis import lockdep as ld

        with ld.lockdep():
            ns = self._exec_drill()
            ns["fwd"]()
            ns["fwd"]()
        assert ld.edge_count() >= 1
        assert ld.cycles() == []

    def test_drill_store_admission_concurrent_no_cycles(self, tmp_path):
        """The real control plane under the shim: concurrent writers
        driving the store's lifecycle ladder (whose transition listeners
        run INSIDE the store lock) against admission passes taking the
        live-view lock. Zero observed cycles is the contract the static
        lock-order rule mirrors."""
        from polyaxon_tpu.analysis import lockdep as ld

        component = {
            "kind": "component", "name": "drill",
            "run": {"kind": "job", "container": {"command": ["true"]}},
        }
        with ld.lockdep():
            # Built INSIDE the shim so Store._lock / the admission
            # live-view lock are instrumented instances.
            from polyaxon_tpu.controlplane import ControlPlane
            from polyaxon_tpu.lifecycle import V1Statuses
            from polyaxon_tpu.scheduling import AdmissionController

            plane = ControlPlane(str(tmp_path / "home"))
            admission = AdmissionController(plane)
            uuids = []
            for _ in range(6):
                record = plane.submit(component)
                plane.compile_run(record.uuid)
                uuids.append(record.uuid)
            errors: list[BaseException] = []

            def ladder(targets):
                try:
                    for uuid in targets:
                        for status in (V1Statuses.SCHEDULED,
                                       V1Statuses.STARTING,
                                       V1Statuses.RUNNING,
                                       V1Statuses.SUCCEEDED):
                            plane.store.transition(uuid, status, force=True)
                except BaseException as exc:  # surfaced after join
                    errors.append(exc)

            def admit():
                try:
                    for _ in range(10):
                        queued = plane.list_runs(
                            statuses=[V1Statuses.QUEUED])
                        admission.plan(queued, capacity=2, active=set())
                except BaseException as exc:
                    errors.append(exc)

            threads = [
                threading.Thread(target=ladder, args=(uuids[:3],)),
                threading.Thread(target=ladder, args=(uuids[3:],)),
                threading.Thread(target=admit),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in threads)
        assert errors == []
        # The drill must have OBSERVED nesting (listener under the store
        # lock at minimum) — an empty graph would mean the shim missed
        # the package locks, not that the code is clean.
        assert ld.edge_count() >= 1
        assert ld.cycles() == []
