from polyaxon_tpu.compiler.compile import CompilerError, ENV_JAXJOB_SPEC, compile_operation
from polyaxon_tpu.compiler.plan import (
    COORDINATOR_PLACEHOLDER,
    V1InitPhase,
    V1LaunchPlan,
    V1ProcessSpec,
    V1ResourceRequest,
    V1SidecarSpec,
)

__all__ = [
    "COORDINATOR_PLACEHOLDER",
    "CompilerError",
    "ENV_JAXJOB_SPEC",
    "V1InitPhase",
    "V1LaunchPlan",
    "V1ProcessSpec",
    "V1ResourceRequest",
    "V1SidecarSpec",
    "compile_operation",
]
