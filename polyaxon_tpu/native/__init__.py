from polyaxon_tpu.native.sliced import Gang, SlicePool, SlicedError, ensure_built

__all__ = ["Gang", "SlicePool", "SlicedError", "ensure_built"]
