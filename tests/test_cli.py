"""CLI surface tests via click's runner (fast paths only — the heavy
execution paths are covered in test_controlplane)."""

import json
import os

import pytest
from click.testing import CliRunner

from polyaxon_tpu.cli.main import cli


@pytest.fixture()
def runner(tmp_path, monkeypatch):
    monkeypatch.setenv("POLYAXON_TPU_HOME", str(tmp_path / "home"))
    return CliRunner()


FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "mnist.yaml")


class TestCheck:
    def test_check_valid(self, runner):
        result = runner.invoke(cli, ["check", "-f", FIXTURE, "-P", "lr=0.05"])
        assert result.exit_code == 0, result.output
        data = json.loads(result.output)
        assert data["params"]["lr"]["value"] == 0.05

    def test_check_missing_file(self, runner):
        result = runner.invoke(cli, ["check", "-f", "nope.yaml"])
        assert result.exit_code != 0
        assert "not found" in result.output

    def test_check_bad_param(self, runner):
        result = runner.invoke(cli, ["check", "-f", FIXTURE, "-P", "bogus=1"])
        assert result.exit_code != 0
        assert "bogus" in result.output


class TestRunAndOps:
    def test_submit_and_inspect(self, runner):
        result = runner.invoke(cli, ["run", "-f", FIXTURE, "-p", "demo"])
        assert result.exit_code == 0, result.output
        uid = result.output.split("Run created: ")[1].split()[0]

        result = runner.invoke(cli, ["ops", "ls", "-p", "demo"])
        assert uid in result.output

        result = runner.invoke(cli, ["ops", "get", "-uid", uid])
        data = json.loads(result.output)
        assert data["status"] == "created"

        result = runner.invoke(cli, ["ops", "statuses", "-uid", uid])
        assert "created" in result.output

    def test_ops_events(self, runner, tmp_path):
        result = runner.invoke(cli, ["run", "-f", FIXTURE])
        uid = result.output.split("Run created: ")[1].split()[0]
        # Write a typed event into the run's artifacts dir directly.
        from polyaxon_tpu.cli.main import get_plane

        rd = get_plane().streams.run_dir(uid)
        os.makedirs(os.path.join(rd, "events", "histogram"), exist_ok=True)
        with open(os.path.join(rd, "events", "histogram", "w.jsonl"), "w") as fh:
            fh.write(json.dumps({"step": 1, "counts": [2, 2], "edges": [0, 1, 2]}) + "\n")
        result = runner.invoke(cli, ["ops", "events", "-uid", uid,
                                     "--kind", "histogram"])
        assert result.exit_code == 0, result.output
        assert json.loads(result.output)["w"][0]["counts"] == [2, 2]

    def test_ops_artifacts_download(self, runner, tmp_path):
        result = runner.invoke(cli, ["run", "-f", FIXTURE])
        uid = result.output.split("Run created: ")[1].split()[0]
        from polyaxon_tpu.cli.main import get_plane

        rd = get_plane().streams.run_dir(uid)
        os.makedirs(rd, exist_ok=True)
        with open(os.path.join(rd, "outputs.json"), "w") as fh:
            fh.write('{"x": 1}')
        dest = tmp_path / "dl"
        dest.mkdir()
        result = runner.invoke(cli, ["ops", "artifacts", "-uid", uid,
                                     "--download", "outputs.json",
                                     "-o", str(dest)])
        assert result.exit_code == 0, result.output
        assert (dest / "outputs.json").read_text() == '{"x": 1}'
        # Traversal through --download is a clean CLI error, not a crash.
        result = runner.invoke(cli, ["ops", "artifacts", "-uid", uid,
                                     "--download", "../../etc/passwd"])
        assert result.exit_code != 0
        assert result.exception is None or isinstance(
            result.exception, SystemExit)
        # A not-yet-existing trailing-slash destination means "into dir".
        result = runner.invoke(cli, ["ops", "artifacts", "-uid", uid,
                                     "--download", "outputs.json",
                                     "-o", str(tmp_path / "newdir") + os.sep])
        assert result.exit_code == 0, result.output
        assert (tmp_path / "newdir" / "outputs.json").exists()

    def test_ops_lineage_graph(self, runner):
        """`plx ops lineage --graph` prints cross-run edges (a consumer
        whose param runs-refs this run) plus artifact/output edges."""
        result = runner.invoke(cli, ["run", "-f", FIXTURE])
        uid = result.output.split("Run created: ")[1].split()[0]
        from polyaxon_tpu.cli.main import get_plane

        plane = get_plane()
        # Outputs recorded for the producer.
        rd = plane.streams.run_dir(uid)
        os.makedirs(rd, exist_ok=True)
        with open(os.path.join(rd, "outputs.json"), "w") as fh:
            fh.write('{"accuracy": 0.5}')
        plane.submit({
            "kind": "operation", "name": "grapher",
            "params": {"acc": {"ref": f"runs.{uid}",
                               "value": "outputs.accuracy"}},
            "component": {
                "inputs": [{"name": "acc", "type": "float",
                            "isOptional": True, "value": 0.0}],
                "run": {"kind": "job", "container": {
                    "command": ["python", "-c", "print(1)"]}},
            },
        })
        result = runner.invoke(cli, ["ops", "lineage", "-uid", uid,
                                     "--graph"])
        assert result.exit_code == 0, result.output
        assert "--param:acc-->" in result.output
        assert "grapher" in result.output
        assert "--output--> accuracy" in result.output
        # Unknown uid: clean CLI error, not a traceback.
        result = runner.invoke(cli, ["ops", "lineage", "-uid", "ghost",
                                     "--graph"])
        assert result.exit_code != 0
        assert result.exception is None or isinstance(
            result.exception, SystemExit)

    def test_projects(self, runner):
        assert runner.invoke(cli, ["projects", "create", "--name", "p9"]).exit_code == 0
        result = runner.invoke(cli, ["projects", "ls"])
        assert "p9" in result.output

    def test_queue_and_quota_smoke(self, runner):
        """`plx queue` / `plx quota` happy path: add, list with depth,
        inspect a queued run, remove (ISSUE 2 smoke case)."""
        result = runner.invoke(cli, ["queue", "add", "prod",
                                     "--priority", "10"])
        assert result.exit_code == 0, result.output
        assert json.loads(result.output)["priority"] == 10
        result = runner.invoke(cli, ["quota", "set", "demo",
                                     "--max-runs", "2", "--weight", "2"])
        assert result.exit_code == 0, result.output

        # A queued run shows up as queue depth and in inspect.
        result = runner.invoke(cli, ["run", "-f", FIXTURE, "-p", "demo"])
        uid = result.output.split("Run created: ")[1].split()[0]
        from polyaxon_tpu.cli.main import get_plane

        get_plane().compile_run(uid)
        result = runner.invoke(cli, ["queue", "ls"])
        assert result.exit_code == 0, result.output
        assert "prod" in result.output and "default" in result.output
        result = runner.invoke(cli, ["queue", "inspect", "default"])
        assert result.exit_code == 0, result.output
        assert uid in result.output
        result = runner.invoke(cli, ["quota", "ls"])
        assert result.exit_code == 0, result.output
        assert "demo" in result.output

        assert runner.invoke(cli, ["queue", "rm", "prod"]).exit_code == 0
        result = runner.invoke(cli, ["queue", "rm", "default"])
        assert result.exit_code != 0  # the implicit queue is permanent

    def test_models_listing(self, runner):
        result = runner.invoke(cli, ["models"])
        assert "llama3_8b" in result.output
        assert "mnist_cnn" in result.output

    def test_param_json_parsing(self, runner):
        result = runner.invoke(
            cli, ["run", "-f", FIXTURE, "-P", "lr=0.5", "-P", "epochs=3"]
        )
        assert result.exit_code == 0, result.output


class TestOpsTrials:
    def test_trials_table_and_pipeline_filter(self, runner, tmp_path,
                                              monkeypatch):
        """`plx ops trials` prints the bracket/rung table of a sweep;
        `ops ls --pipeline` scopes to its children."""
        import textwrap

        from polyaxon_tpu.agent import Agent
        from polyaxon_tpu.cli.main import get_plane

        script = textwrap.dedent(
            """
            import json, os
            d = os.environ["POLYAXON_RUN_ARTIFACTS_PATH"]
            os.makedirs(d + "/events/metric", exist_ok=True)
            score = (float(os.environ["LR"]) - 0.3) ** 2
            with open(d + "/events/metric/score.jsonl", "a") as fh:
                fh.write(json.dumps({"step": 1, "value": score}) + "\\n")
            """
        ).strip()
        monkeypatch.setenv("POLYAXON_TPU_HOME", str(tmp_path / "home"))
        plane = get_plane()
        # ASHA with a single rung: metric-driven sweep, no promotions —
        # exercises the metric lookup and best-first ordering.
        record = plane.submit({
            "kind": "operation",
            "matrix": {
                "kind": "asha", "numRuns": 3, "maxIterations": 1,
                "minResource": 1, "eta": 2, "seed": 2, "concurrency": 4,
                "resource": {"name": "epochs", "type": "int"},
                "metric": {"name": "score", "optimization": "minimize"},
                "params": {"lr": {"kind": "uniform",
                                  "value": {"low": 0.0, "high": 1.0}}},
            },
            "component": {
                "kind": "component", "name": "t",
                "inputs": [
                    {"name": "lr", "type": "float", "toEnv": "LR"},
                    {"name": "epochs", "type": "int", "value": 1,
                     "isOptional": True},
                ],
                "run": {"kind": "job",
                        "container": {"command": ["python", "-c", script]}},
            },
        })
        Agent(plane).run_until_done(record.uuid, timeout=120)

        result = runner.invoke(cli, ["ops", "trials", "-uid", record.uuid])
        assert result.exit_code == 0, result.output
        assert "bracket 0 rung 0" in result.output
        assert result.output.count("succeeded") == 3
        # Best metric first: the score column must come out ascending.
        scores = [float(line.split()[2])
                  for line in result.output.splitlines()
                  if "succeeded" in line]
        assert scores == sorted(scores) and len(scores) == 3

        listed = runner.invoke(cli, ["ops", "ls", "--pipeline", record.uuid])
        assert listed.exit_code == 0, listed.output
        assert listed.output.count("\n") == 3  # exactly the children


class TestConvert:
    def test_hf_to_orbax_to_serving(self, runner, tmp_path, monkeypatch):
        """HF safetensors → plx convert → Orbax → load_params: the
        converted checkpoint must reproduce transformers' forward
        logits (the interop chain users take to serve HF weights)."""
        import dataclasses

        import numpy as np

        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        from safetensors.numpy import save_file

        import jax.numpy as jnp

        from polyaxon_tpu.models import llama

        monkeypatch.setenv("POLYAXON_TPU_HOME", str(tmp_path / "home"))
        cfg = dataclasses.replace(llama.CONFIGS["llama_tiny"],
                                  dtype=jnp.float32, max_seq_len=64)
        hf_cfg = transformers.LlamaConfig(
            vocab_size=cfg.vocab_size, hidden_size=cfg.dim,
            intermediate_size=cfg.ffn_dim, num_hidden_layers=cfg.n_layers,
            num_attention_heads=cfg.n_heads,
            num_key_value_heads=cfg.n_kv_heads,
            max_position_embeddings=64, rope_theta=cfg.rope_theta,
            rms_norm_eps=cfg.norm_eps, attention_bias=False,
            tie_word_embeddings=False)
        torch.manual_seed(0)
        hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()
        sd = {k: v.numpy() for k, v in hf_model.state_dict().items()}
        save_file(sd, str(tmp_path / "model.safetensors"))

        out_dir = str(tmp_path / "ck")
        result = runner.invoke(cli, [
            "convert", "--model", "llama_tiny",
            "--from-hf", str(tmp_path / "model.safetensors"),
            "--out", out_dir])
        assert result.exit_code == 0, result.output
        assert "converted llama_tiny" in result.output

        from polyaxon_tpu.serving import load_params

        _, params = load_params("llama_tiny", out_dir)
        tokens = np.array([[5, 17, 42, 7]], np.int32)
        ours = llama.forward(cfg, params, jnp.asarray(tokens))
        with torch.no_grad():
            theirs = hf_model(torch.tensor(tokens.astype(np.int64))).logits
        np.testing.assert_allclose(np.asarray(ours), theirs.numpy(),
                                   atol=2e-3, rtol=2e-3)

        # Re-running into the same --out is a clean CLI error, not an
        # orbax StepAlreadyExists traceback.
        again = runner.invoke(cli, [
            "convert", "--model", "llama_tiny",
            "--from-hf", str(tmp_path / "model.safetensors"),
            "--out", out_dir])
        assert again.exit_code != 0
        assert "already contains a checkpoint" in again.output

    def test_convert_rejects_unknown_model(self, runner, tmp_path):
        result = runner.invoke(cli, [
            "convert", "--model", "resnet50",
            "--from-hf", str(tmp_path), "--out", str(tmp_path / "o")])
        assert result.exit_code != 0
        assert "llama-family" in result.output


class TestOpsCompare:
    def test_compare_params_and_final_metrics(self, runner, tmp_path,
                                              monkeypatch):
        """`plx ops compare A B`: differing params + final metric per
        run, side by side — the CLI twin of the dashboard compare."""
        import textwrap

        from polyaxon_tpu.agent import Agent
        from polyaxon_tpu.cli.main import get_plane

        script = textwrap.dedent(
            """
            import json, os
            d = os.environ["POLYAXON_RUN_ARTIFACTS_PATH"]
            os.makedirs(d + "/events/metric", exist_ok=True)
            score = (float(os.environ["LR"]) - 0.3) ** 2
            with open(d + "/events/metric/score.jsonl", "a") as fh:
                fh.write(json.dumps({"step": 1, "value": score}) + "\\n")
            """
        ).strip()
        monkeypatch.setenv("POLYAXON_TPU_HOME", str(tmp_path / "home"))
        plane = get_plane()
        component = {
            "kind": "component", "name": "t",
            "inputs": [{"name": "lr", "type": "float", "toEnv": "LR"},
                       {"name": "fixed", "type": "int", "value": 7,
                        "isOptional": True}],
            "run": {"kind": "job",
                    "container": {"command": ["python", "-c", script]}},
        }
        agent = Agent(plane)
        a = plane.submit(component, params={"lr": 0.1}, name="run-a")
        b = plane.submit(component, params={"lr": 0.5}, name="run-b")
        agent.run_until_done(a.uuid, timeout=60)
        agent.run_until_done(b.uuid, timeout=60)

        result = runner.invoke(cli, ["ops", "compare", a.uuid, b.uuid])
        assert result.exit_code == 0, result.output
        out = result.output
        # lr differs and is tabulated; `fixed` is identical -> omitted.
        assert "lr" in out and "fixed" not in out
        assert "0.1" in out and "0.5" in out
        # Final metric values per run: (0.1-0.3)^2 and (0.5-0.3)^2.
        assert "0.04" in out and "score" in out
        assert "run-a" in out and "run-b" in out

    def test_compare_needs_two_runs(self, runner, tmp_path, monkeypatch):
        monkeypatch.setenv("POLYAXON_TPU_HOME", str(tmp_path / "home"))
        result = runner.invoke(cli, ["ops", "compare", "deadbeef"])
        assert result.exit_code != 0
