"""Collective accounting and overlap measurement over compiled HLO.

The sharded program GSPMD emits makes every byte of inter-device
traffic explicit as a collective instruction; parsing the
post-optimization module therefore gives an exact op census and a
shape-derived traffic estimate without running a single step. Wire
bytes use the standard ring-algorithm costs **per participant**:

    all-reduce          2 * B * (g-1)/g     (reduce-scatter + all-gather)
    all-gather          B_out * (g-1)/g     (B_out = gathered result)
    reduce-scatter      B_out * (g-1)       (receives (g-1)/g of input)
    all-to-all          B * (g-1)/g         (keeps 1/g locally)
    collective-permute  B                   (one hop per pair)

where ``g`` is the replica-group size. These are estimates of traffic
*volume* — topology (ICI hop count, DCN crossings) is out of scope; the
budget gate cares about op counts and byte deltas, both of which these
formulas rank faithfully.

Overlap measurement (ISSUE 12): counting collectives says nothing about
whether their latency is *hidden* — the same program swings multiples
depending on whether XLA schedules its collectives against independent
compute or serializes them (GSPMD §3.4; DeepSpeed-Ulysses makes the
same point for all-to-alls). Async collectives appear in three textual
encodings, all handled here:

- the classic ``-start``/``-done`` pair: the transfer is in flight
  between the two instructions, so everything scheduled between them
  is by construction independent of the payload (the ``-start`` result
  tuple is only consumable by its ``-done``);
- a sync-form instruction annotated with ``frontend_attributes={...
  async_collective_name=...}``: in flight until its first consumer;
- the TPU latency-hiding scheduler's **continuation fusions** in
  scheduled modules (``is_scheduled=true``): a
  ``%async-collective-start[.N] = (...) fusion(..., calls=%fc)`` whose
  callee issues the collective, paired by NAME SUFFIX with an
  ``%async-collective-done[.N]`` fusion that retires it. The transfer
  is in flight strictly between the two fusions.

Either way the *overlap window* of an async collective is the
instruction span from issue to retirement (first consumer for the
first two forms, the suffix-matched done fusion for the third), and
the compute FLOPs scheduled inside that span bound how much of the
transfer can hide. An unannotated sync collective in the schedule
spine has an empty window — 0 overlap. A collective fused WITH compute
(a plain fusion whose callee contains one) overlaps its own fusion's
compute: its window is that single fusion.

Census dedup rules for scheduled TPU modules (each logical transfer
appears in up to three fused computations): a transfer is counted AT
its ``async-collective-start*`` fusion only; ``async-collective-done*``
fusions and computations named ``async_collective_fusion*`` (the
compute-side continuations, which repeat the collective a third time)
are never censused. The schedule *spine* is every computation that is
not a fusion callee (``calls=`` target) — while bodies, branch
computations and ENTRY stay spine, so their collectives count exactly
once.

The time model converts both sides to seconds with two documented
v5e-class constants (``PEAK_FLOPS_PER_S``, ``ICI_BYTES_PER_S``):
``coll_time = wire_bytes / ICI_BYTES_PER_S`` and ``window_compute =
window_flops / PEAK_FLOPS_PER_S``; the hidden fraction of one op is
``min(coll_time, window_compute) / coll_time`` and a schedule's
``overlap_ratio`` is the hidden fraction of its TOTAL collective time.
The constants are a ranking model, not a profiler: budgets are floors
measured with the same model, so only consistency matters — but the
ratio is also dimensionally honest (a 1 MiB all-gather cannot be
"hidden" by two scalar adds).

FLOP attribution inside windows: ``dot`` counts
``2 * result_elements * K`` (K = the lhs contracting-dim product);
``convolution`` — which is what scheduled TPU modules turn every
matmul into — counts ``2 * result_elements * K`` with K = the product
of rhs dims whose ``dim_labels`` char is not ``o`` (input-feature and
kernel-spatial dims); ``fusion``/``call`` recurse into their callee
(memoized per computation); every other payload op counts its result
elements. Bookkeeping ops (parameter, constant, tuple plumbing,
bitcast, copies, custom-calls) and other collectives count zero (a
collective inside another's window is communication that overlaps on
its own account, not compute hiding this one).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

# Time-model constants (v5e class; see module docstring — a consistent
# ranking model shared by measurement and budget floors, not a profiler).
PEAK_FLOPS_PER_S = 1.97e14   # bf16 peak per chip
ICI_BYTES_PER_S = 4.5e10     # per-chip interconnect bandwidth

# f8 variants first so "f8e4m3fn" doesn't half-match "f8".
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# `%name = <result-type> <op>(`. The result type is everything between
# `=` and the op token — matched that way because TPU HLO layouts embed
# colons and parens (`bf16[4,2048]{2,1,0:T(2,128)(2,1)S(1)}`) that
# defeat any character-class spelling. The op token is the FIRST
# whitespace-preceded `word(` after the `=` (layout parens like
# `T(2,128)` follow `:` or `)`, never whitespace, so they can't match).
_ASSIGN_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w\.\-]+)\s*=\s*(?P<rest>.+)$")
_GENERIC_OP_RE = re.compile(r"(?:^|\s)(?P<op>[a-zA-Z][\w\-]*)\(")
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_GROUPS_RE = re.compile(r"replica_groups=\{\{([^}]*)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[")
_PAIRS_RE = re.compile(r"source_target_pairs=\{\{")
_REF_RE = re.compile(r"%([\w\.\-]+)")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([\d,]*)\}")
_CALLS_RE = re.compile(r"calls=%?([\w\.\-]+)")
_TO_APPLY_RE = re.compile(r"to_apply=%?([\w\.\-]+)")
_DIM_LABELS_RE = re.compile(r"dim_labels=([\w\d]+)_([\w\d]+)->([\w\d]+)")
_HEADER_NAME_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w\.\-]+)")

_COLLECTIVE_OP_TOKENS = frozenset(
    list(COLLECTIVE_KINDS) + [k + "-start" for k in COLLECTIVE_KINDS])

# Continuation-fusion naming in scheduled TPU modules (see module
# docstring census rules). Instruction-name prefixes for the paired
# start/done fusions; computation-name prefix for the compute-side
# continuations that must never be censused.
_ASYNC_START_PREFIX = "async-collective-start"
_ASYNC_DONE_PREFIX = "async-collective-done"
_ASYNC_CONT_COMP_PREFIX = "async_collective_fusion"

# Window ops that carry no arithmetic payload: plumbing, layout
# changes, async copy halves, opaque custom-calls (their cost is not
# shape-derivable; undercounting is the conservative direction for a
# floor), and collectives themselves.
_ZERO_FLOP_OPS = frozenset(
    ["parameter", "constant", "tuple", "get-tuple-element", "bitcast",
     "copy", "copy-start", "copy-done", "after-all", "partition-id",
     "replica-id", "opt-barrier", "broadcast", "iota", "reshape",
     "transpose", "slice", "dynamic-slice", "dynamic-update-slice",
     "pad", "send", "send-done", "recv", "recv-done", "custom-call"]
    + list(_COLLECTIVE_OP_TOKENS)
    + [k + "-done" for k in COLLECTIVE_KINDS])


@dataclasses.dataclass
class CollectiveOp:
    kind: str            # canonical kind (no -start suffix)
    name: str            # HLO instruction name
    result_bytes: int    # total bytes of the result shape(s)
    group_size: int      # replica-group participants
    wire_bytes: float    # estimated bytes on the wire per participant
    line: str            # the source line (diagnostics / report detail)
    is_async: bool = False      # -start form, annotated, or fused
    window_ops: int = 0         # instructions inside the overlap window
    window_flops: float = 0.0   # attributed compute FLOPs in the window
    overlap_ratio: float = 0.0  # hidden fraction of this op's wire time


@dataclasses.dataclass
class _Instr:
    name: str
    op: str
    result_type: str
    operands: tuple
    line: str
    args: str = ""  # raw operand span (shape extraction for dot/conv)


def _shape_bytes_list(type_str: str) -> list[int]:
    out = []
    for dtype, dims in _SHAPE_RE.findall(type_str):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue  # token[], opaque[] — carry no payload
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append(n * size)
    return out


def _result_bytes(type_str: str, async_start: bool) -> int:
    """Payload bytes of a collective's result type.

    Sync form: the (possibly tuple) result IS the payload — sum it.
    ``-start`` form: the result tuple aliases (source, destination,
    context scalars); summing would double-count the transfer, so take
    the largest member (the destination — equal to the sync form's
    result for every kind)."""
    sizes = _shape_bytes_list(type_str)
    if not sizes:
        return 0
    return max(sizes) if async_start else sum(sizes)


def _group_size(line: str, n_devices: Optional[int]) -> int:
    m = _GROUPS_RE.search(line)
    if m:
        first = [p for p in m.group(1).split(",") if p.strip()]
        return max(len(first), 1)
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return max(int(m.group(2)), 1)
    if _PAIRS_RE.search(line):
        return 2  # permute: pairwise
    return max(n_devices or 1, 1)


def _wire_bytes(kind: str, result_bytes: int, g: int) -> float:
    if g <= 1:
        return 0.0
    if kind == "all-reduce":
        return 2.0 * result_bytes * (g - 1) / g
    if kind == "all-gather":
        return result_bytes * (g - 1) / g
    if kind == "reduce-scatter":
        return float(result_bytes) * (g - 1)
    if kind == "all-to-all":
        return result_bytes * (g - 1) / g
    if kind == "collective-permute":
        return float(result_bytes)
    raise ValueError(f"unknown collective kind {kind!r}")


def _operand_span(rest: str, open_idx: int) -> str:
    """The operand list inside the op's balanced parens — attributes
    after the close paren (``to_apply=%sum``, ``calls=%fused``) must
    not read as dataflow consumers."""
    depth = 0
    for i in range(open_idx, len(rest)):
        c = rest[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return rest[open_idx + 1:i]
    return rest[open_idx + 1:]


def _parse_instruction(line: str) -> Optional[_Instr]:
    assign = _ASSIGN_RE.match(line)
    if not assign:
        return None
    rest = assign.group("rest")
    m = _GENERIC_OP_RE.search(rest)
    if not m:
        return None
    args = _operand_span(rest, m.end() - 1)
    return _Instr(
        name=assign.group("name"),
        op=m.group("op"),
        result_type=rest[: m.start()],
        operands=tuple(_REF_RE.findall(args)),
        line=line,
        args=args,
    )


def _computation_blocks(hlo_text: str) -> list[tuple[str, list[_Instr]]]:
    """(name, instruction list) per computation, in textual order
    (= schedule order for ``is_scheduled=true`` modules — the form the
    overlap windows are measured on). Header lines (`%comp (args) ->
    type {`) carry no `=` so they never parse as instructions; bare
    fixture text without braces lands in one implicit ``""`` block."""
    blocks: list[tuple[str, list[_Instr]]] = []
    orphans: list[_Instr] = []
    current: Optional[list[_Instr]] = None
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.startswith("}"):
            current = None
            continue
        instr = _parse_instruction(line)
        if instr is None:
            if stripped.endswith("{") and "HloModule" not in stripped:
                header = _HEADER_NAME_RE.match(stripped)
                current = []
                blocks.append((header.group(1) if header else "", current))
            continue
        (orphans if current is None else current).append(instr)
    if orphans:
        blocks.append(("", orphans))
    return [(name, block) for name, block in blocks if block]


def _num_elements(type_str: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n
    return total


def _operand_dims(args: str, index: int) -> Optional[list[int]]:
    """Dims of the index-th shaped operand (operand shapes appear in
    call order inside the operand span)."""
    shapes = _SHAPE_RE.findall(args)
    if index >= len(shapes):
        return None
    return [int(d) for d in shapes[index][1].split(",") if d]


class _ModuleGraph:
    """Computation index for one HLO module: fusion-callee detection
    (spine = not a ``calls=`` target), memoized per-computation FLOPs
    with fusion/call recursion, and deduped inner-collective lookup."""

    def __init__(self, blocks: list[tuple[str, list[_Instr]]]):
        self.comps: dict[str, list[_Instr]] = {}
        for name, block in blocks:
            self.comps.setdefault(name, block)
        self.fusion_targets: set[str] = set()
        for _, block in blocks:
            for instr in block:
                if instr.op == "fusion":
                    m = _CALLS_RE.search(instr.line)
                    if m:
                        self.fusion_targets.add(m.group(1))
        self._flops_memo: dict[str, float] = {}

    def instr_flops(self, instr: _Instr) -> float:
        """Attributed compute FLOPs of one instruction (module
        docstring: dot/conv = 2·result·K, fusion/call recurse, other
        payload ops = result elements, plumbing/collectives = 0)."""
        if instr.op in ("fusion", "call"):
            m = _CALLS_RE.search(instr.line) or _TO_APPLY_RE.search(instr.line)
            return self.comp_flops(m.group(1)) if m else 0.0
        if instr.op in _ZERO_FLOP_OPS:
            return 0.0
        elems = _num_elements(instr.result_type)
        if instr.op == "dot":
            m = _CONTRACT_RE.search(instr.line)
            lhs_dims = _operand_dims(instr.args, 0)
            if m is not None and lhs_dims is not None:
                k = 1
                for idx in (int(d) for d in m.group(1).split(",") if d):
                    if 0 <= idx < len(lhs_dims):
                        k *= lhs_dims[idx]
                return 2.0 * elems * k
            return 2.0 * elems
        if instr.op == "convolution":
            # K = product of rhs dims whose dim_labels char != 'o'
            # (input-feature + kernel-spatial): each output element is
            # a K-term dot product. Covers the `bf0_0oi->b0f` spelling
            # scheduled TPU modules lower every matmul to.
            m = _DIM_LABELS_RE.search(instr.line)
            rhs_dims = _operand_dims(instr.args, 1)
            if m is not None and rhs_dims is not None:
                k = 1
                for label, dim in zip(m.group(2), rhs_dims):
                    if label != "o":
                        k *= dim
                return 2.0 * elems * k
            return 2.0 * elems
        return float(elems)

    def comp_flops(self, name: str) -> float:
        if name in self._flops_memo:
            return self._flops_memo[name]
        self._flops_memo[name] = 0.0  # cycle guard (malformed input)
        block = self.comps.get(name)
        if block is not None:
            self._flops_memo[name] = sum(
                self.instr_flops(instr) for instr in block)
        return self._flops_memo[name]

    def inner_collectives(
            self, name: str, _seen: Optional[set] = None) -> list[_Instr]:
        """Collective instructions reachable from computation ``name``
        through nested fusions — EXCLUDING ``async_collective_fusion*``
        computations, whose collectives are compute-side repeats of a
        transfer censused at its start fusion (module docstring)."""
        if _seen is None:
            _seen = set()
        if (name in _seen or name not in self.comps
                or name.startswith(_ASYNC_CONT_COMP_PREFIX)):
            return []
        _seen.add(name)
        out: list[_Instr] = []
        for instr in self.comps[name]:
            if instr.op in _COLLECTIVE_OP_TOKENS:
                out.append(instr)
            elif instr.op == "fusion":
                m = _CALLS_RE.search(instr.line)
                if m:
                    out.extend(self.inner_collectives(m.group(1), _seen))
        return out


def _first_consumer(block: list[_Instr], i: int) -> int:
    name = block[i].name
    for j in range(i + 1, len(block)):
        if name in block[j].operands:
            return j
    return len(block)


def _make_op(graph: _ModuleGraph, coll: _Instr, is_async: bool,
             window: list[_Instr], n_devices: Optional[int]) -> CollectiveOp:
    async_start = coll.op.endswith("-start")
    kind = coll.op[: -len("-start")] if async_start else coll.op
    result_bytes = _result_bytes(coll.result_type, async_start)
    g = _group_size(coll.line, n_devices)
    wire = _wire_bytes(kind, result_bytes, g)
    window_flops = 0.0
    ratio = 0.0
    if is_async:
        window_flops = sum(graph.instr_flops(w) for w in window)
        coll_s = wire / ICI_BYTES_PER_S
        if coll_s > 0:
            ratio = min(coll_s, window_flops / PEAK_FLOPS_PER_S) / coll_s
    return CollectiveOp(
        kind=kind,
        name=coll.name,
        result_bytes=result_bytes,
        group_size=g,
        wire_bytes=wire,
        line=coll.line.strip(),
        is_async=is_async,
        window_ops=len(window) if is_async else 0,
        window_flops=window_flops,
        overlap_ratio=round(ratio, 6),
    )


def parse_collectives(hlo_text: str,
                      n_devices: Optional[int] = None) -> list[CollectiveOp]:
    """All logical collective transfers in a post-optimization HLO
    module, each annotated with its overlap-window measurement.

    Census (module docstring dedup rules): plain collectives in spine
    computations (async ``-start``/``-done`` pairs counted once at the
    ``-start``); transfers wrapped in continuation fusions counted at
    their ``async-collective-start*`` fusion with the window running to
    the suffix-matched ``async-collective-done*``; other fusions whose
    callees contain collectives counted with the fusion itself as the
    window (the transfer overlaps its own fusion's compute)."""
    blocks = _computation_blocks(hlo_text)
    graph = _ModuleGraph(blocks)
    ops: list[CollectiveOp] = []
    for comp_name, block in blocks:
        if comp_name in graph.fusion_targets:
            continue  # fusion callee: censused via its caller
        for i, instr in enumerate(block):
            if instr.op in _COLLECTIVE_OP_TOKENS:
                is_async = (instr.op.endswith("-start")
                            or "async_collective_name" in instr.line)
                window = block[i + 1:_first_consumer(block, i)]
                ops.append(_make_op(graph, instr, is_async, window, n_devices))
                continue
            if instr.op != "fusion":
                continue
            if instr.name.startswith(_ASYNC_DONE_PREFIX):
                continue  # retirement half: censused at its -start twin
            m = _CALLS_RE.search(instr.line)
            inner = graph.inner_collectives(m.group(1)) if m else []
            if not inner:
                continue
            if instr.name.startswith(_ASYNC_START_PREFIX):
                done = _ASYNC_DONE_PREFIX + instr.name[
                    len(_ASYNC_START_PREFIX):]
                j = next((k for k in range(i + 1, len(block))
                          if block[k].name == done), None)
                if j is None:
                    j = _first_consumer(block, i)
                window = block[i + 1:j]
                for coll in inner:
                    ops.append(_make_op(graph, coll, True, window, n_devices))
            else:
                # Collective fused with compute: the transfer's window
                # is its own fusion (its compute can hide it; a
                # compute-free wrapper honestly measures 0).
                for coll in inner:
                    ops.append(_make_op(graph, coll, True, [instr], n_devices))
    return ops


def summarize_collectives(ops: list[CollectiveOp]) -> dict:
    """Aggregate an op list into the budget-comparable report shape."""
    counts: dict[str, int] = {}
    bytes_by_kind: dict[str, int] = {}
    for op in ops:
        counts[op.kind] = counts.get(op.kind, 0) + 1
        bytes_by_kind[op.kind] = (
            bytes_by_kind.get(op.kind, 0) + int(op.wire_bytes))
    return {
        "counts": dict(sorted(counts.items())),
        "wire_bytes_by_kind": dict(sorted(bytes_by_kind.items())),
        "est_wire_bytes_per_step": int(sum(o.wire_bytes for o in ops)),
        "n_collectives": len(ops),
    }


def summarize_overlap(ops: list[CollectiveOp]) -> dict:
    """Schedule-level overlap report: the hidden fraction of TOTAL
    estimated collective time (sync collectives contribute full time
    and zero hiding). A program with no wire traffic has nothing to
    hide — ratio 1.0 by convention, so the budget gate never fails a
    schedule for being communication-free."""
    coll_s = 0.0
    hidden_s = 0.0
    async_by_kind: dict[str, int] = {}
    n_async = n_sync = 0
    for op in ops:
        t = op.wire_bytes / ICI_BYTES_PER_S
        if t <= 0:
            continue
        coll_s += t
        if op.is_async:
            n_async += 1
            async_by_kind[op.kind] = async_by_kind.get(op.kind, 0) + 1
            hidden_s += min(t, op.window_flops / PEAK_FLOPS_PER_S)
        else:
            n_sync += 1
    return {
        "overlap_ratio": round(hidden_s / coll_s, 4) if coll_s else 1.0,
        "n_async_collectives": n_async,
        "n_sync_collectives": n_sync,
        "async_by_kind": dict(sorted(async_by_kind.items())),
        "coll_time_us": round(coll_s * 1e6, 3),
        "hidden_time_us": round(hidden_s * 1e6, 3),
    }
