"""Interval re-execution wrapper for watchdog runs.

``python -m polyaxon_tpu.utils.watchloop <interval_seconds> -- cmd ...``
runs the command, sleeps, and repeats until SIGTERM/SIGINT (the
executor's stop path). A failing iteration ends the loop with the
child's exit code so the run transitions to failed.
"""

from __future__ import annotations

import signal
import subprocess
import sys
import time


def main(argv: list[str]) -> int:
    if len(argv) < 3 or argv[1] != "--":
        print("usage: watchloop <interval_seconds> -- cmd ...", file=sys.stderr)
        return 2
    interval = float(argv[0])
    cmd = argv[2:]

    stopping = False
    child: subprocess.Popen | None = None

    def _stop(signum, frame):
        nonlocal stopping
        stopping = True
        # Forward to the active child so a long-running iteration ends
        # promptly instead of outliving the stop request.
        if child is not None and child.poll() is None:
            child.terminate()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)

    while not stopping:
        child = subprocess.Popen(cmd)
        if stopping and child.poll() is None:
            # Signal landed between the loop check and the assignment —
            # the handler had nothing to terminate, so do it here.
            child.terminate()
        rc = child.wait()
        if stopping:
            return 0  # stop requested mid-iteration: clean shutdown
        if rc != 0:
            return rc
        # Sleep in small increments so a stop signal lands promptly.
        deadline = time.monotonic() + interval
        while not stopping and time.monotonic() < deadline:
            time.sleep(min(0.5, max(deadline - time.monotonic(), 0.01)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
