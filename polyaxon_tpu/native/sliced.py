"""ctypes bindings for the native slice daemon (native/sliced/).

The C++ pool is the framework's operator equivalent (SURVEY.md §2a):
ICI-topology-aware gang placement over TPU slices, heartbeat liveness,
preemption, restart policy. This wrapper auto-builds ``libsliced.so``
with the repo Makefile on first use (g++ is part of the toolchain
contract; pybind11 is not available, hence ctypes — see the environment
notes) and exposes a thin, typed API for the agent.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from dataclasses import dataclass, field
from typing import Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "build", "libsliced.so")

_BUF_LEN = 1 << 16
_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class SlicedError(RuntimeError):
    pass


def ensure_built() -> str:
    """Build libsliced.so if missing; return its path."""
    with _build_lock:
        if not os.path.exists(_LIB_PATH):
            # polycheck: ignore[lock-blocking-call] -- the build mutex exists to serialize this one-shot compile; it nests no other lock and waiters need the .so anyway
            result = subprocess.run(
                ["make", "-C", _NATIVE_DIR, "build/libsliced.so"],
                capture_output=True, text=True,
            )
            if result.returncode != 0:
                raise SlicedError(
                    f"native build failed:\n{result.stdout}\n{result.stderr}"
                )
    return _LIB_PATH


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(ensure_built())
    lib.sliced_new.restype = ctypes.c_void_p
    lib.sliced_free.argtypes = [ctypes.c_void_p]
    lib.sliced_add_slice.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.sliced_remove_slice.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.sliced_free_chips.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.sliced_request_gang.restype = ctypes.c_longlong
    lib.sliced_request_gang.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_int]
    lib.sliced_release_gang.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
    lib.sliced_gang_info.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_char_p, ctypes.c_int]
    lib.sliced_heartbeat.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int, ctypes.c_double]
    lib.sliced_preempt_slice.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.sliced_tick.argtypes = [
        ctypes.c_void_p, ctypes.c_double, ctypes.c_double, ctypes.c_char_p,
        ctypes.c_int]
    _lib = lib
    return lib


@dataclass
class Gang:
    gang_id: int
    state: str
    slice: str
    topology: str
    offset: tuple[int, ...]
    shape: tuple[int, ...]
    chips: tuple[int, ...]
    restarts: int
    run_uuid: str


@dataclass
class Event:
    gang_id: int
    kind: str  # PLACED | LOST | RESTART | FAILED | PREEMPTED
    detail: str = ""


class SlicePool:
    """Owned handle on a native pool instance."""

    def __init__(self):
        self._lib = _load()
        self._handle = self._lib.sliced_new()

    def close(self) -> None:
        if self._handle:
            self._lib.sliced_free(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception as exc:
            # Raising in __del__ is unusable noise at interpreter
            # teardown, but the leak is worth one debug line.
            logging.getLogger(__name__).debug(
                "SlicePool.__del__ close failed: %s", exc)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---------------------------------------------------------- inventory
    def add_slice(self, name: str, topology: str, *, preemptible: bool = False) -> None:
        rc = self._lib.sliced_add_slice(
            self._handle, name.encode(), topology.encode(), int(preemptible))
        if rc != 0:
            raise SlicedError(f"add_slice({name!r}, {topology!r}) failed")

    def remove_slice(self, name: str) -> None:
        if self._lib.sliced_remove_slice(self._handle, name.encode()) != 0:
            raise SlicedError(f"unknown slice {name!r}")

    def free_chips(self, name: str) -> int:
        free = self._lib.sliced_free_chips(self._handle, name.encode())
        if free < 0:
            raise SlicedError(f"unknown slice {name!r}")
        return free

    # --------------------------------------------------------------- gangs
    def request_gang(self, run_uuid: str, topology: str, *, priority: int = 0,
                     max_restarts: int = 0) -> int:
        gang_id = self._lib.sliced_request_gang(
            self._handle, run_uuid.encode(), topology.encode(), priority,
            max_restarts)
        if gang_id == -1:
            raise SlicedError(f"malformed topology {topology!r}")
        if gang_id == -2:
            raise SlicedError(
                f"topology {topology!r} can never fit any registered slice")
        return int(gang_id)

    def release_gang(self, gang_id: int) -> None:
        if self._lib.sliced_release_gang(self._handle, gang_id) != 0:
            raise SlicedError(f"unknown gang {gang_id}")

    def gang(self, gang_id: int) -> Gang:
        buf = ctypes.create_string_buffer(_BUF_LEN)
        if self._lib.sliced_gang_info(self._handle, gang_id, buf, _BUF_LEN) < 0:
            raise SlicedError(f"unknown gang {gang_id}")
        fields = dict(
            part.split("=", 1) for part in buf.value.decode().split(";") if part
        )
        ints = lambda s: tuple(int(x) for x in s.split(",")) if s else ()
        return Gang(
            gang_id=gang_id,
            state=fields["state"],
            slice=fields.get("slice", ""),
            topology=fields.get("topology", ""),
            offset=ints(fields.get("offset", "")),
            shape=ints(fields.get("shape", "")),
            chips=ints(fields.get("chips", "")),
            restarts=int(fields.get("restarts", "0")),
            run_uuid=fields.get("run", ""),
        )

    # ------------------------------------------------------------- signals
    def heartbeat(self, gang_id: int, proc: int, now: float) -> bool:
        return self._lib.sliced_heartbeat(self._handle, gang_id, proc, now) == 0

    def preempt_slice(self, name: str) -> int:
        evicted = self._lib.sliced_preempt_slice(self._handle, name.encode())
        if evicted < 0:
            raise SlicedError(f"unknown slice {name!r}")
        return evicted

    # ----------------------------------------------------------- reconcile
    def tick(self, now: float, *, heartbeat_timeout: float = 30.0) -> list[Event]:
        length = _BUF_LEN
        while True:
            buf = ctypes.create_string_buffer(length)
            if self._lib.sliced_tick(
                    self._handle, now, heartbeat_timeout, buf, length) >= 0:
                break
            # Events stay queued on overflow; retry with more room.
            length *= 4
            if length > (1 << 24):
                raise SlicedError("tick event buffer exceeded 16MB")
        events = []
        for line in buf.value.decode().splitlines():
            parts = line.split(" ", 2)
            events.append(Event(
                gang_id=int(parts[0]), kind=parts[1],
                detail=parts[2] if len(parts) > 2 else ""))
        return events
