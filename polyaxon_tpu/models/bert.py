"""BERT masked-LM pretraining model (BASELINE config 3's capability,
rebuilt JAX-native instead of delegating to torch-xla in a container)."""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from polyaxon_tpu.models import encoder
from polyaxon_tpu.models.common import (
    Batch,
    ModelDef,
    Variables,
    cross_entropy_loss,
    layer_norm,
    scaled_init,
    truncated_normal_init,
)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30_522
    dim: int = 1024          # bert-large
    n_layers: int = 24
    n_heads: int = 16
    ffn_dim: int = 4096
    max_seq_len: int = 512
    type_vocab_size: int = 2
    dtype: Any = jnp.bfloat16
    remat: str = "none"

    def encoder_config(self) -> encoder.EncoderConfig:
        return encoder.EncoderConfig(
            dim=self.dim, n_layers=self.n_layers, n_heads=self.n_heads,
            ffn_dim=self.ffn_dim, dtype=self.dtype, remat=self.remat,
        )


CONFIGS: dict[str, BertConfig] = {
    "bert_large": BertConfig(),
    "bert_base": BertConfig(dim=768, n_layers=12, n_heads=12, ffn_dim=3072),
    "bert_tiny": BertConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                            ffn_dim=128, max_seq_len=64),
}


def init(cfg: BertConfig, rng: jax.Array) -> Variables:
    keys = jax.random.split(rng, 6)
    params = {
        "tok_embed": truncated_normal_init(keys[0], (cfg.vocab_size, cfg.dim)),
        "pos_embed": truncated_normal_init(keys[1], (cfg.max_seq_len, cfg.dim)),
        "type_embed": truncated_normal_init(keys[2], (cfg.type_vocab_size, cfg.dim)),
        "embed_ln_scale": jnp.ones((cfg.dim,)),
        "embed_ln_bias": jnp.zeros((cfg.dim,)),
        "layers": encoder.init_layers(cfg.encoder_config(), keys[3]),
        "mlm_dense": scaled_init(keys[4], (cfg.dim, cfg.dim), fan_in=cfg.dim),
        "mlm_bias": jnp.zeros((cfg.dim,)),
        "mlm_ln_scale": jnp.ones((cfg.dim,)),
        "mlm_ln_bias": jnp.zeros((cfg.dim,)),
        "mlm_out_bias": jnp.zeros((cfg.vocab_size,)),
    }
    return {"params": params, "state": {}}


def logical_axes(cfg: BertConfig) -> Variables:
    return {
        "params": {
            "tok_embed": ("vocab", "embed"),
            "pos_embed": ("seq", "embed"),
            "type_embed": (None, "embed"),
            "embed_ln_scale": ("embed",),
            "embed_ln_bias": ("embed",),
            "layers": encoder.layers_logical_axes(),
            "mlm_dense": ("embed", "embed"),
            "mlm_bias": ("embed",),
            "mlm_ln_scale": ("embed",),
            "mlm_ln_bias": ("embed",),
            "mlm_out_bias": ("vocab",),
        },
        "state": {},
    }


def forward(cfg: BertConfig, params: dict, tokens: jax.Array,
            type_ids: Optional[jax.Array] = None) -> jax.Array:
    dt = cfg.dtype
    B, S = tokens.shape
    x = params["tok_embed"].astype(dt)[tokens]
    x = x + params["pos_embed"].astype(dt)[None, :S]
    if type_ids is not None:
        x = x + params["type_embed"].astype(dt)[type_ids]
    x = layer_norm(x, params["embed_ln_scale"], params["embed_ln_bias"])
    x = encoder.encode(cfg.encoder_config(), params["layers"], x)
    # MLM head: dense + gelu + LN, tied output embedding.
    h = jax.nn.gelu(x @ params["mlm_dense"].astype(dt) + params["mlm_bias"].astype(dt))
    h = layer_norm(h, params["mlm_ln_scale"], params["mlm_ln_bias"])
    logits = h @ params["tok_embed"].astype(dt).T + params["mlm_out_bias"].astype(dt)
    return logits.astype(jnp.float32)


def apply(cfg: BertConfig, variables: Variables, batch: Batch, train: bool = True,
          rng: Optional[jax.Array] = None):
    """``batch``: tokens [B,S] (with [MASK] ids already substituted),
    labels [B,S] (-1 at unmasked positions), optional type_ids."""
    logits = forward(cfg, variables["params"], batch["tokens"], batch.get("type_ids"))
    loss, acc = cross_entropy_loss(logits, batch["labels"])
    return loss, {"loss": loss, "accuracy": acc}, variables["state"]


def model_def(name: str, **overrides) -> ModelDef:
    cfg = dataclasses.replace(CONFIGS[name], **overrides)
    return ModelDef(
        name=name,
        init=functools.partial(init, cfg),
        apply=functools.partial(apply, cfg),
        logical_axes=functools.partial(logical_axes, cfg),
        unit="tokens",
    )
