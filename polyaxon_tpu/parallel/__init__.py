from polyaxon_tpu.parallel.bootstrap import ProcessGroup, initialize, read_env_contract
from polyaxon_tpu.parallel.mesh import (
    AXIS_ORDER,
    build_mesh,
    mesh_summary,
    parse_mesh_axes,
    single_device_mesh,
)
from polyaxon_tpu.parallel.sharding import (
    STRATEGY_RULES,
    batch_spec,
    logical_to_spec,
    merge_rules,
    param_bytes,
    rules_for_mesh,
    tree_shardings,
)

__all__ = [
    "AXIS_ORDER",
    "ProcessGroup",
    "STRATEGY_RULES",
    "batch_spec",
    "build_mesh",
    "initialize",
    "logical_to_spec",
    "merge_rules",
    "mesh_summary",
    "param_bytes",
    "parse_mesh_axes",
    "read_env_contract",
    "rules_for_mesh",
    "single_device_mesh",
    "tree_shardings",
]
