"""Deterministic fault injection for the control plane (ISSUE 1).

A :class:`ChaosPlan` is a seed-addressed list of faults, each pinned to
one of four seams the orchestration spine crosses on every run:

- ``store``      — artifact-store I/O: raise a typed transient (or
                   permanent) ``StoreError`` on the Nth matching op;
- ``gang``       — executor gangs: kill a member (SIGKILL for
                   subprocess gangs, an injected exception for the
                   in-process fast path), optionally gated on the run
                   having written ``min_checkpoints`` checkpoint steps;
- ``init``       — stall a named init phase for ``seconds``;
- ``checkpoint`` — corrupt the LATEST checkpoint step's bytes on disk
                   right before a restore, so the fallback path runs;
- ``tick``       — swallow the Nth scheduler tick (a stalled control
                   plane), proving ticks are idempotent;
- ``slice-loss`` — elastic gangs (ISSUE 14): op ``kill`` takes a slice
                   away mid-train (an elastic gang files a *shrink*
                   resize; a non-elastic gang is preempted), op
                   ``restore`` returns the capacity (files a *grow*).
                   ``min_checkpoints`` gates like the gang seam, and a
                   ``restore`` is only eligible after a ``kill`` fired;
- ``tier0-loss`` — tiered checkpointing (ISSUE 16): drop the in-memory
                   tier-0 replica AND its local-disk spill right before
                   a restore, so the store-fallback path is drilled,
                   not assumed (``runtime.tiers`` consults this seam).

Activation: tests call :func:`polyaxon_tpu.chaos.install`; operators
point ``POLYAXON_TPU_CHAOS_PLAN`` at a JSON file (or inline JSON) or
pass ``--chaos-plan`` to ``plx agent``/``plx server``. Every firing is
appended to ``plan.consumed`` so a test can assert the whole plan was
exercised. Counters are per-process (subprocess gang members that
inherit the env var keep their own counts).

Plan JSON::

    {"seed": 7, "faults": [
      {"seam": "store", "op": "read_bytes", "at": 1, "times": 1,
       "config": {"error": "transient"}},
      {"seam": "gang", "op": "kill", "config": {"min_checkpoints": 2}},
      {"seam": "checkpoint", "op": "corrupt_latest"},
      {"seam": "tick", "op": "skip", "at": 3},
      {"seam": "slice-loss", "op": "kill", "config": {"min_checkpoints": 2}},
      {"seam": "slice-loss", "op": "restore", "config": {"min_checkpoints": 4}},
      {"seam": "tier0-loss", "op": "drop"}
    ]}

``at`` is 1-based over MATCHING events; ``times`` consecutive events
fire starting there. ``op: "*"`` matches every op of the seam.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

logger = logging.getLogger(__name__)

ENV_CHAOS_PLAN = "POLYAXON_TPU_CHAOS_PLAN"


class ChaosKill(RuntimeError):
    """Raised inside an in-process gang member to simulate its death."""


@dataclass
class Fault:
    seam: str
    op: str = "*"
    at: int = 1
    times: int = 1
    config: dict = field(default_factory=dict)
    # runtime counters
    seen: int = 0
    fired: int = 0

    def matches(self, seam: str, op: str) -> bool:
        return self.seam == seam and self.op in ("*", op)

    @property
    def exhausted(self) -> bool:
        return self.fired >= self.times

    @classmethod
    def from_dict(cls, data: dict) -> "Fault":
        known = {"seam", "op", "at", "times", "config"}
        extra = {k: v for k, v in data.items() if k not in known}
        config = dict(data.get("config") or {})
        config.update(extra)  # allow flat {"error": ...} style entries
        return cls(seam=data["seam"], op=data.get("op", "*"),
                   at=int(data.get("at", 1)), times=int(data.get("times", 1)),
                   config=config)


class ChaosPlan:
    def __init__(self, faults: list[Fault], seed: int = 0):
        self.faults = faults
        self.seed = seed
        self.consumed: list[dict] = []
        self._lock = threading.Lock()

    # ----------------------------------------------------------- loading
    @classmethod
    def from_dict(cls, data: dict) -> "ChaosPlan":
        return cls([Fault.from_dict(f) for f in data.get("faults", [])],
                   seed=int(data.get("seed", 0)))

    @classmethod
    def load(cls, source: str) -> "ChaosPlan":
        """``source`` is a JSON file path or inline JSON."""
        text = source
        if not source.lstrip().startswith("{"):
            with open(source) as fh:
                text = fh.read()
        return cls.from_dict(json.loads(text))

    # ------------------------------------------------------------ firing
    def fire(self, seam: str, op: str, detail: str = "") -> Optional[Fault]:
        """Record one event at (seam, op); return the fault that fires
        on it, if any. Each fault counts matching events independently,
        so two faults can trigger on different Ns of the same seam."""
        with self._lock:
            for fault in self.faults:
                if not fault.matches(seam, op) or fault.exhausted:
                    continue
                fault.seen += 1
                if fault.seen >= fault.at:
                    fault.fired += 1
                    self.consumed.append(
                        {"seam": seam, "op": op, "detail": detail,
                         "event": fault.seen})
                    logger.warning("chaos: firing %s/%s (event %d) %s",
                                   seam, op, fault.seen, detail)
                    # Annotate the lifecycle phase the fault hit
                    # (obs.trace): a drill reads as events on the run's
                    # timeline instead of log archaeology. add_event is
                    # a no-op outside an active span and never raises.
                    try:
                        from polyaxon_tpu.obs import trace as _trace

                        _trace.add_event(f"chaos.{seam}", op=op,
                                         detail=detail, event=fault.seen)
                    except ImportError:  # pragma: no cover
                        pass
                    # Bracket the fault's active stretch as a named
                    # history window (``chaos.<seam>``): opened on its
                    # first firing, closed when its budget exhausts, so
                    # during-window oracle invariants can scope to the
                    # drill. Fail-open like the trace annotation.
                    try:
                        from polyaxon_tpu.obs import history as _history

                        hist = _history.default_history()
                        if fault.fired == 1:
                            hist.mark_window(f"chaos.{seam}", start=True)
                        if fault.exhausted:
                            hist.mark_window(f"chaos.{seam}", end=True)
                    # polycheck: ignore[invariant-swallow] -- window markers are telemetry garnish on the fault path; a broken history ring must never mask the fault being injected
                    except Exception:  # noqa: BLE001
                        pass
                    return fault
        return None

    def has_faults(self, seam: str) -> bool:
        return any(f.seam == seam and not f.exhausted for f in self.faults)

    @property
    def done(self) -> bool:
        """Every declared fault has fired its full budget."""
        return all(f.exhausted for f in self.faults)

    # ------------------------------------------------- seam: gangs/init
    def gang_kill_due(self, run_uuid: str, ckpt_dir: str) -> bool:
        """True (once per fault budget) when a gang-kill fault is due
        for this run. ``min_checkpoints`` gates the kill on the run
        having already persisted that many checkpoint steps, so the
        restart can prove resume actually resumes."""
        pending = [f for f in self.faults
                   if f.matches("gang", "kill") and not f.exhausted]
        if not pending:
            return False
        fault = pending[0]
        need = int(fault.config.get("min_checkpoints", 0))
        if need and _checkpoint_steps(ckpt_dir) < need:
            return False  # not an eligible event yet: don't count it
        return self.fire("gang", "kill", detail=run_uuid) is not None

    def maybe_kill_gang(self, run_uuid: str, ckpt_dir: str) -> None:
        """In-process gang seam: raise :class:`ChaosKill` when due."""
        if self.gang_kill_due(run_uuid, ckpt_dir):
            raise ChaosKill(
                f"chaos: gang member of run {run_uuid} killed by fault plan")

    def slice_loss_due(self, run_uuid: str, ckpt_dir: str) -> Optional[str]:
        """Return ``"kill"`` or ``"restore"`` when a slice-loss fault is
        due for this run (once per fault budget), else None.

        ``min_checkpoints`` gates each fault on the run having persisted
        that many checkpoint steps — a resize needs something to restore
        — and a ``restore`` (capacity returned → grow) is only eligible
        after a ``kill`` has fired, so a plan cannot regrow a gang it
        never shrank. Ineligible events are not counted (the
        ``gang_kill_due`` rule)."""
        pending = [f for f in self.faults
                   if f.seam == "slice-loss" and not f.exhausted]
        if not pending:
            return None
        killed = any(f.seam == "slice-loss" and f.op == "kill" and f.fired
                     for f in self.faults)
        for fault in pending:
            op = "kill" if fault.op == "*" else fault.op
            if op == "restore" and not killed:
                continue
            need = int(fault.config.get("min_checkpoints", 0))
            if need and _checkpoint_steps(ckpt_dir) < need:
                continue  # not an eligible event yet: don't count it
            if self.fire("slice-loss", op, detail=run_uuid) is not None:
                return op
            return None
        return None

    def tier0_loss_due(self, directory: str) -> bool:
        """True (once per fault budget) when a ``tier0-loss`` fault is
        due for this checkpoint directory. The caller
        (:func:`runtime.tiers.tier0_loss_due`) drops the in-memory
        replica and the local spill so the restore must walk down to
        the persistent store — the fallback drill."""
        pending = [f for f in self.faults
                   if f.matches("tier0-loss", "drop") and not f.exhausted]
        if not pending:
            return False
        return self.fire("tier0-loss", "drop", detail=directory) is not None

    def maybe_stall_init(self, phase_kind: str) -> float:
        """Stall seam for executor init phases; returns seconds slept."""
        fault = self.fire("init", phase_kind)
        if fault is None:
            return 0.0
        seconds = float(fault.config.get("seconds", 0.1))
        time.sleep(seconds)
        return seconds

    # ------------------------------------------------- seam: checkpoint
    def corrupt_checkpoint(self, directory: str,
                           steps: list[int]) -> Optional[int]:
        """Corrupt the newest step's files on disk (returns the step),
        if a ``checkpoint/corrupt_latest`` fault is due."""
        if not steps:
            return None
        fault = self.fire("checkpoint", "corrupt_latest",
                          detail=str(max(steps)))
        if fault is None:
            return None
        target = max(steps)
        step_dir = os.path.join(directory, str(target))
        corrupted = 0
        for root, _, files in os.walk(step_dir):
            for name in files:
                path = os.path.join(root, name)
                try:
                    with open(path, "wb") as fh:
                        fh.write(b"\x00CHAOS-CORRUPTED\x00")
                    corrupted += 1
                except OSError:
                    continue
        logger.warning("chaos: corrupted checkpoint step %s (%d files)",
                       target, corrupted)
        return target


from polyaxon_tpu.fs.store import Store as _Store  # noqa: E402 — no cycle:
# fs.store only imports chaos lazily inside get_store()


class ChaosStore(_Store):
    """Store wrapper injecting plan faults on primitive ops.

    Installed by ``fs.get_store`` only while a plan with store faults
    is active. Subclasses ``Store`` so the DERIVED surface
    (``download_dir``, ``sync_dir``, ``read_text``, ...) runs through
    the hooked primitives below — a fault plan targeting ``read_bytes``
    fires no matter which entry point the caller used. Retry layers
    (FsspecStore internals, the init/sidecar call sites) sit OUTSIDE
    this wrapper, so injected transient faults exercise the real retry
    paths.
    """

    def __init__(self, inner: Any, plan: ChaosPlan):
        self._inner = inner
        self._plan = plan
        self.scheme = getattr(inner, "scheme", "chaos")

    def _hook(self, op: str, detail: str = "") -> None:
        fault = self._plan.fire("store", op, detail=detail)
        if fault is None:
            return
        from polyaxon_tpu.fs.store import StoreError, TransientStoreError

        if fault.config.get("error", "transient") == "permanent":
            raise StoreError(
                f"chaos: injected permanent store fault on {op} {detail}")
        raise TransientStoreError(
            f"chaos: injected transient store fault on {op} {detail}")

    def read_bytes(self, key: str) -> bytes:
        self._hook("read_bytes", key)
        return self._inner.read_bytes(key)

    def write_bytes(self, key: str, data: bytes) -> None:
        self._hook("write_bytes", key)
        return self._inner.write_bytes(key, data)

    def exists(self, key: str) -> bool:
        self._hook("exists", key)
        return self._inner.exists(key)

    def delete(self, key: str) -> None:
        self._hook("delete", key)
        return self._inner.delete(key)

    def list(self, prefix: str = "") -> list:
        self._hook("list", prefix)
        return self._inner.list(prefix)

    def upload_file(self, local_path: str, key: str) -> None:
        self._hook("upload_file", key)
        return self._inner.upload_file(local_path, key)

    def download_file(self, key: str, local_path: str) -> str:
        self._hook("download_file", key)
        return self._inner.download_file(key, local_path)

    def __getattr__(self, name: str):
        return getattr(self._inner, name)


def _checkpoint_steps(ckpt_dir: str) -> int:
    """Count orbax step directories (digit-named dirs) under a
    checkpoints dir; 0 when the dir does not exist yet."""
    try:
        return sum(1 for name in os.listdir(ckpt_dir)
                   if name.isdigit()
                   and os.path.isdir(os.path.join(ckpt_dir, name)))
    except OSError:
        return 0


# ------------------------------------------------------------ activation
_ACTIVE: Optional[ChaosPlan] = None
_ENV_CHECKED = False


def install(plan: ChaosPlan) -> ChaosPlan:
    global _ACTIVE
    _ACTIVE = plan
    return plan


def uninstall() -> None:
    global _ACTIVE, _ENV_CHECKED
    _ACTIVE = None
    _ENV_CHECKED = False


def active_plan() -> Optional[ChaosPlan]:
    """The installed plan, else one lazily loaded from the env var
    (checked once per process), else None."""
    global _ACTIVE, _ENV_CHECKED
    if _ACTIVE is not None:
        return _ACTIVE
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        source = os.environ.get(ENV_CHAOS_PLAN)
        if source:
            try:
                _ACTIVE = ChaosPlan.load(source)
            except (OSError, ValueError, KeyError) as exc:
                logger.error("ignoring unloadable chaos plan %r: %s",
                             source, exc)
    return _ACTIVE
