"""Fleet-scoped telemetry (ISSUE 20): component-stamped series behind
scoped registry views, federated reads (sum/max/bucket-merge) judged
over every replica's series, series GC as release discipline, the TTFT
skew rollup, trace-context propagation router → engine (ONE tree per
trace id through an eviction→readmit arc), and the fleet-wide request
lookup fan-out."""

import dataclasses
import threading
import time

import pytest

from polyaxon_tpu.obs import metrics as obs_metrics
from polyaxon_tpu.obs import oracle as obs_oracle
from polyaxon_tpu.obs import reqtrace
from polyaxon_tpu.obs import rules as obs_rules
from polyaxon_tpu.obs.analyze import request_phases
from polyaxon_tpu.obs.trace import Span, build_timeline
from polyaxon_tpu.serving.fleet import ServingFleet
from polyaxon_tpu.serving.router import FleetRouter
from polyaxon_tpu.sim import fleet_serve


def _reg():
    return obs_metrics.MetricsRegistry()


def _conv(c, n=8):
    return [c * 131 + j for j in range(n)]


# ==================================================== scoped recording
class TestScopedSeries:
    def test_scoped_view_stamps_component(self):
        reg = _reg()
        c = reg.counter("t_requests_total", "", ("klass",))
        c.inc(2, klass="a")
        reg.scoped("r0").counter("t_requests_total", "",
                                 ("klass",)).inc(3, klass="a")
        snap = c.snapshot()
        assert snap["series"] == {"a": 2.0, "a,r0": 3.0}
        # unscoped and scoped reads stay disjoint
        assert c.value(klass="a") == 2.0
        assert reg.scoped("r0").counter(
            "t_requests_total", "", ("klass",)).value(klass="a") == 3.0
        assert c.components() == {"", "r0"}

    def test_snapshot_labels_append_component_only_when_scoped(self):
        reg = _reg()
        g = reg.gauge("t_depth", "", ("q",))
        g.set(1, q="x")
        assert g.snapshot()["labels"] == ["q"]  # unscoped-only: unchanged
        reg.scoped("r1").gauge("t_depth", "", ("q",)).set(2, q="x")
        assert g.snapshot()["labels"] == ["q", "component"]

    def test_render_carries_component_label(self):
        reg = _reg()
        h = reg.histogram("t_lat", "", buckets=(0.1, 1.0))
        reg.scoped("r2").histogram("t_lat", "",
                                   buckets=(0.1, 1.0)).observe(0.05)
        text = "\n".join(h.render())
        assert 'component="r2"' in text
        h.observe(0.05)  # unscoped series renders without the label
        assert "t_lat_bucket{le=\"0.1\"} 1" in "\n".join(h.render())

    def test_scoped_view_survives_registry_reset(self):
        """Views are stateless (base instrument re-resolved per call) —
        the bench resets the registry after warmup and the replica's
        view must keep recording into the fresh instruments."""
        reg = _reg()
        view = reg.scoped("r0")
        view.counter("t_total", "").inc()
        reg._metrics.clear()  # the reset() core, sans global hooks
        view.counter("t_total", "").inc(5)
        # ("": 0.0 is the no-label instrument's seeded unscoped series)
        assert reg.counter("t_total", "").snapshot()["series"] == {
            "": 0.0, "r0": 5.0}

    def test_overflow_fold_preserves_component(self):
        """The cardinality-cap fold keeps the component suffix so a
        replica's accounting survives an overflowing base label."""
        reg = _reg()
        c = reg.counter("t_cap_total", "", ("user",), max_series=2)
        view = reg.scoped("r0")
        sc = view.counter("t_cap_total", "", ("user",), max_series=2)
        sc.inc(user="u1")
        sc.inc(user="u2")
        sc.inc(user="u3")  # folds — but stays r0's
        totals = c.total_by_component()
        assert totals == {"r0": 3.0}


# ========================================================= federation
class TestFederation:
    def _ttft(self, reg):
        return obs_metrics.serving_ttft_hist(reg)

    def test_federate_sums_counters_and_maxes_gauges(self):
        reg = _reg()
        reg.counter("t_total", "", ("klass",)).inc(2, klass="a")
        reg.scoped("r0").counter("t_total", "",
                                 ("klass",)).inc(3, klass="a")
        reg.scoped("r1").counter("t_total", "",
                                 ("klass",)).inc(5, klass="a")
        reg.gauge("t_g", "").set(1)
        reg.scoped("r0").gauge("t_g", "").set(7)
        reg.scoped("r1").gauge("t_g", "").set(3)
        fed = reg.federate()
        assert fed["t_total"]["series"] == {"a": 10.0}
        assert fed["t_total"]["components"] == ["", "r0", "r1"]
        assert fed["t_g"]["series"] == {"": 7.0}  # worst-series view

    def test_federate_merges_histogram_buckets(self):
        reg = _reg()
        h = reg.histogram("t_h", "", buckets=(0.1, 1.0))
        h.observe(0.05)
        reg.scoped("r0").histogram("t_h", "",
                                   buckets=(0.1, 1.0)).observe(0.5)
        reg.scoped("r1").histogram("t_h", "",
                                   buckets=(0.1, 1.0)).observe(5.0)
        merged = reg.federate()["t_h"]["series"][""]
        assert merged["count"] == 3
        assert merged["buckets"] == {"0.1": 1, "1": 1, "+Inf": 1}
        assert merged["sum"] == pytest.approx(5.55)

    def test_quantile_merged_is_the_federated_distribution(self):
        reg = _reg()
        hist = self._ttft(reg)
        for _ in range(4):
            obs_metrics.serving_ttft_hist(
                reg.scoped("r0")).observe(0.04, **{"class": "interactive"})
            obs_metrics.serving_ttft_hist(
                reg.scoped("r1")).observe(0.04, **{"class": "interactive"})
        # per-component and federated agree when the replicas agree
        by_comp = hist.quantile_by_component(0.5)
        assert set(by_comp) == {"r0", "r1"}
        merged = hist.quantile_merged(0.5, **{"class": "interactive"})
        assert merged == pytest.approx(by_comp["r0"])
        # ...and for an unscoped-only registry merged == plain quantile
        solo = _reg()
        sh = self._ttft(solo)
        sh.observe(0.04, **{"class": "interactive"})
        assert (sh.quantile_merged(0.5, **{"class": "interactive"})
                == sh.quantile(0.5, **{"class": "interactive"}))

    def test_match_series_component_is_a_wildcard(self):
        """A {class: interactive} selector keeps matching every
        replica's series once the fleet records scoped — the property
        that lets existing rules/invariants judge federated."""
        names = ("class",)
        assert obs_metrics.match_series(
            names, "interactive,r0", {"class": "interactive"})
        assert obs_metrics.match_series(
            names, "interactive", {"class": "interactive"})
        assert not obs_metrics.match_series(
            names, "batch,r0", {"class": "interactive"})
        # the component dimension is addressable when named
        assert obs_metrics.match_series(
            names, "interactive,r0", {"component": "r0"})
        assert not obs_metrics.match_series(
            names, "interactive,r0", {"component": ""})
        assert obs_metrics.match_series(names, "interactive,r0", None)

    def test_oracle_selection_merges_scoped_series(self):
        """The metric_during judgment path: a labels selector that
        doesn't name the component merges every replica's sample into
        one federated histogram."""
        reg = _reg()
        for comp, v in (("r0", 0.1), ("r1", 0.3)):
            for _ in range(4):
                obs_metrics.serving_ttft_hist(
                    reg.scoped(comp)).observe(v, **{"class": "interactive"})
        family = obs_metrics.serving_ttft_hist(reg).snapshot()
        sample = obs_oracle._select_series(
            family, {"class": "interactive"})
        assert sample["count"] == 8
        assert sample["sum"] == pytest.approx(1.6)

    def test_catalog_carries_fleet_telemetry_entries(self):
        rule_ids = {r.id for r in obs_rules.load_ruleset()}
        assert "fleet-replica-skew" in rule_ids
        assert "serving-ttft-slo-burn" in rule_ids
        inv_ids = {i.id for i in obs_oracle.load_invariants()}
        assert "serving-ttft-federated-during-scaleup" in inv_ids


# ============================================== series GC on release
class TestSeriesRemoval:
    def test_counter_and_histogram_remove_parity_with_gauge_unset(self):
        reg = _reg()
        c = reg.counter("t_c", "", ("k",))
        c.inc(k="x")
        c.remove(k="x")
        assert c.snapshot()["series"] == {}
        h = reg.histogram("t_h", "", ("k",), buckets=(1.0,))
        h.observe(0.5, k="x")
        h.remove(k="x")
        assert h.snapshot()["series"] == {}
        assert h.quantile(0.5, k="x") is None  # no value, not stale

    def test_scoped_remove_leaves_other_components(self):
        reg = _reg()
        for comp in ("r0", "r1"):
            reg.scoped(comp).counter("t_c", "", ("k",)).inc(k="x")
        reg.scoped("r0").counter("t_c", "", ("k",)).remove(k="x")
        assert reg.counter("t_c", "", ("k",)).components() == {"r1"}

    def test_drop_component_sweeps_every_instrument(self):
        reg = _reg()
        view = reg.scoped("r3")
        view.counter("t_c", "", ("k",)).inc(k="a")
        view.counter("t_c", "", ("k",)).inc(k="b")
        view.gauge("t_g", "").set(2)
        view.histogram("t_h", "", buckets=(1.0,)).observe(0.5)
        reg.scoped("r4").gauge("t_g", "").set(9)
        assert reg.drop_component("r3") == 4  # exact eviction accounting
        for name in ("t_c", "t_g", "t_h"):
            assert "r3" not in reg.get(name).components()
        # "" is the no-label gauge's seeded unscoped series — drop
        # only swept r3's
        assert reg.gauge("t_g", "").components() == {"", "r4"}
        assert reg.drop_component("") == 0  # unscoped is never swept

    def test_dropped_component_leaves_federated_reads(self):
        reg = _reg()
        for comp, v in (("r0", 0.04), ("r1", 30.0)):
            obs_metrics.serving_ttft_hist(
                reg.scoped(comp)).observe(v, **{"class": "interactive"})
        hist = obs_metrics.serving_ttft_hist(reg)
        assert "r1" in hist.quantile_by_component(0.99)
        reg.drop_component("r1")
        assert set(hist.quantile_by_component(0.99)) == {"r0"}
        # the dead replica's slow tail no longer weights the federation
        assert hist.quantile_merged(
            0.99, **{"class": "interactive"}) < 1.0


# ======================================================= skew rollup
class TestFleetRollups:
    def _observe(self, reg, comp, value, n=4):
        for _ in range(n):
            obs_metrics.serving_ttft_hist(
                reg.scoped(comp)).observe(value, **{"class": "interactive"})

    def test_rollup_unset_below_two_components(self):
        reg = _reg()
        self._observe(reg, "r0", 0.04)
        obs_metrics.publish_fleet_rollups(reg)
        assert obs_metrics.fleet_ttft_skew(reg).snapshot()["series"] == {}

    def test_rollup_fires_on_hot_outlier_and_recovers(self):
        reg = _reg()
        self._observe(reg, "r0", 0.04)
        self._observe(reg, "r1", 0.05)
        self._observe(reg, "r2", 30.0)
        obs_metrics.publish_fleet_rollups(reg)
        gauge = obs_metrics.fleet_ttft_skew(reg)
        assert gauge.value() > 3.0  # the fleet-replica-skew threshold
        # the outlier releases: the ratio recomputes over survivors...
        reg.drop_component("r2")
        obs_metrics.publish_fleet_rollups(reg)
        assert 0 < gauge.value() < 3.0
        # ...and with one survivor the ratio is undefined, not stale
        reg.drop_component("r1")
        obs_metrics.publish_fleet_rollups(reg)
        assert gauge.snapshot()["series"] == {}

    def test_rollup_accepts_scoped_view(self):
        """A rollup is a fleet-wide read by definition — handing it a
        replica's view must unwrap to the base registry."""
        reg = _reg()
        self._observe(reg, "r0", 0.04)
        self._observe(reg, "r1", 0.05)
        obs_metrics.publish_fleet_rollups(reg.scoped("r0"))
        assert obs_metrics.fleet_ttft_skew(reg).value() > 0


# ===================================================== fleet plumbing
class _Result:
    def __init__(self, rid=None):
        self.id = rid
        self.done = threading.Event()
        self.done.set()

    def wait(self, timeout=None):
        return [1]


class _TraceFake:
    """Fake engine exposing the full trace-propagation surface."""

    def __init__(self, registry=None):
        self._obs = registry
        self.submits = []

    def generate(self, rows, max_new_tokens, **kw):
        return [[0]] * len(rows)

    def submit(self, tokens, max_new_tokens, *, request_id=None,
               trace_parent=None, route_record=None, klass="batch",
               **kw):
        self.submits.append({
            "tokens": list(tokens), "request_id": request_id,
            "trace_parent": trace_parent, "route_record": route_record,
            "klass": klass})
        if self._obs is not None:
            obs_metrics.serving_ttft_hist(self._obs).observe(
                0.02 + 0.01 * len(self.submits), **{"class": klass})
            if klass == "best-effort":
                obs_metrics.serving_preemptions_total(self._obs).inc(
                    **{"class": klass, "reason": "slots"})
        return _Result(request_id)

    def health(self):
        return {"status": "ok", "queued": 0, "active": 0}

    def stats(self):
        return {"prefill_tokens_total": 0, "prefill_tokens_skipped": 0,
                "kv_invariant_violations": 0,
                "requests_served": len(self.submits)}

    def stop(self):
        pass


class _LegacyFake(_TraceFake):
    """Strict-signature submit: no trace kwargs (pre-ISSUE-20 engine)."""

    def submit(self, tokens, max_new_tokens):  # noqa: D102
        self.submits.append({"tokens": list(tokens)})
        return _Result()


def _fake_fleet(cls=_TraceFake, *, replicas=2, mute_first=False, **kw):
    reg = _reg()
    engines = {}

    def factory(registry=None):
        view = (None if (mute_first and not engines)
                else registry)
        eng = cls(view)
        engines[getattr(registry, "component", f"e{len(engines)}")] = eng
        return eng

    # The router is built with the default (global) registry on
    # purpose: ServingFleet rebinds exactly that case to a `router`
    # view of ITS base registry — the assertion that `router` series
    # land scoped in `reg` is the rebind working.
    fleet = ServingFleet(
        factory, replicas=replicas, standby=0, max_replicas=replicas + 1,
        prewarm=False, router=FleetRouter(seed=1),
        registry=reg, cooldown=0.0, idle_hold=0.0, **kw)
    fleet.start()
    return fleet, engines, reg


class TestFleetTracePropagation:
    def test_submit_propagates_trace_context(self):
        fleet, engines, _ = _fake_fleet()
        try:
            req, decision = fleet.submit(_conv(3), 4, klass="interactive")
            eng = engines[decision.replica]
            sub = eng.submits[-1]
            assert sub["request_id"] == req.id
            record = sub["route_record"]
            assert record["name"] == "route"
            assert record["component"] == "router"
            assert record["trace_id"] == req.id
            assert record["end"] is not None  # closed pre-hop
            assert sub["trace_parent"] == record["span_id"]
            attrs = record["attributes"]
            assert attrs["decision"] == decision.reason
            assert attrs["replica"] == decision.replica
            # candidate telemetry names every ready replica
            assert set(attrs["candidates"]) == {"r0", "r1"}
        finally:
            fleet.stop()

    def test_caller_request_id_wins(self):
        fleet, engines, _ = _fake_fleet()
        try:
            req, decision = fleet.submit(
                _conv(4), 4, request_id="feedc0de", klass="batch")
            assert req.id == "feedc0de"
            assert (engines[decision.replica].submits[-1]["route_record"]
                    ["trace_id"] == "feedc0de")
        finally:
            fleet.stop()

    def test_legacy_engine_falls_back_without_trace_kwargs(self):
        fleet, engines, _ = _fake_fleet(_LegacyFake)
        try:
            req, decision = fleet.submit(_conv(5), 4)
            assert engines[decision.replica].submits[-1]["tokens"] == \
                _conv(5)
        finally:
            fleet.stop()


class TestPerReplicaSeries:
    def test_preemption_and_ttft_series_separate_by_replica(self):
        """Satellite: metrics recorded under fleet routing carry the
        admitting replica's component — totals reconcile exactly
        against what each engine actually served."""
        fleet, engines, reg = _fake_fleet()
        try:
            for i in range(16):  # distinct conversations spread by hash
                fleet.submit(_conv(i), 4, klass="best-effort")
            totals = obs_metrics.serving_preemptions_total(
                reg).total_by_component()
            assert "" not in totals  # nothing leaked unscoped
            by_engine = {rid: sum(1 for s in e.submits
                                  if s.get("klass") == "best-effort")
                         for rid, e in engines.items() if e.submits}
            assert len(by_engine) == 2, "seed must exercise both replicas"
            assert totals == {rid: float(n)
                              for rid, n in by_engine.items()}
            per = fleet.per_replica_telemetry()
            assert set(per) == set(by_engine)
            for rid, row in per.items():
                assert row["preemptions"] == by_engine[rid]
                assert row["ttft_p50_ms"] > 0
            snap = fleet.fleet_snapshot()
            assert snap["components"] == sorted(by_engine)
            assert snap["ttft_skew"] is not None  # >= 2 components
            # the router's own series landed under its component
            assert "router" in obs_metrics.fleet_routed_total(
                reg).components()
        finally:
            fleet.stop()

    def test_fleet_snapshot_skew_undefined_below_two_replicas(self):
        fleet, _, _ = _fake_fleet(replicas=1)
        try:
            fleet.submit(_conv(1), 4, klass="interactive")
            assert fleet.fleet_snapshot()["ttft_skew"] is None
        finally:
            fleet.stop()

    def test_scale_down_drops_released_replica_series(self):
        """Release discipline: the victim's scoped series AND the
        fleet-recorded queue-depth series about it both vanish."""
        fleet, engines, reg = _fake_fleet(replicas=3)
        try:
            for i in range(16):
                fleet.submit(_conv(i), 4, klass="interactive")
            fleet.poll()
            depth = obs_metrics.fleet_replica_queue_depth(reg)
            assert "r2" in {obs_metrics.series_key_labels(
                ("replica",), k)["replica"]
                for k in depth.snapshot()["series"]}
            ev = fleet.scale_down(timeout=5.0)
            assert ev["replica"] == "r2"
            assert fleet.wait_settled(timeout=10.0)
            hist = obs_metrics.serving_ttft_hist(reg)
            assert "r2" not in hist.components()
            assert "r2" not in {obs_metrics.series_key_labels(
                ("replica",), k)["replica"]
                for k in depth.snapshot()["series"]}
            # survivors keep their series
            assert hist.components()
            assert hist.components() <= {"r0", "r1"}
        finally:
            fleet.stop()

    def test_stop_unsets_skew_rollup(self):
        fleet, _, reg = _fake_fleet()
        try:
            for i in range(8):
                fleet.submit(_conv(i), 4, klass="interactive")
            fleet.poll()
        finally:
            fleet.stop()
        assert obs_metrics.fleet_ttft_skew(
            reg).snapshot()["series"] == {}

    def test_telemetry_gaps_catch_a_muted_replica(self):
        """The mute-replica gate: a replica built without its scoped
        view serves traffic but is absent from the federated
        per-component breakdown — exactly what flips CI."""
        fleet, engines, _ = _fake_fleet(mute_first=True)
        try:
            for i in range(16):
                fleet.submit(_conv(i), 4, klass="interactive")
            assert all(e.submits for e in engines.values()), \
                "both replicas must serve for the gap to be provable"
            assert fleet_serve.telemetry_gaps(fleet) == ["r0"]
        finally:
            fleet.stop()

    def test_no_gaps_when_every_replica_records_scoped(self):
        fleet, engines, _ = _fake_fleet()
        try:
            for i in range(16):
                fleet.submit(_conv(i), 4, klass="interactive")
            assert fleet_serve.telemetry_gaps(fleet) == []
        finally:
            fleet.stop()


# ================================================ fleet request lookup
class _RingFake(_TraceFake):
    def __init__(self, registry=None):
        super().__init__(registry)
        self.ring = reqtrace.TimelineRing()

    def recent_requests(self):
        return self.ring.summaries()

    def request_timeline(self, request_id):
        return self.ring.timeline(request_id)


class TestFleetRequestLookup:
    def _trace(self, rid, start, klass="interactive"):
        t = reqtrace.RequestTrace(rid, klass=klass)
        t.root.start = start
        t.finish()
        return t

    def test_recent_requests_fans_out_and_stamps_replica(self):
        fleet, engines, _ = _fake_fleet(_RingFake)
        try:
            engines["r0"].ring.add(self._trace("aa01", 100.0))
            engines["r1"].ring.add(self._trace("bb02", 200.0))
            rows = fleet.recent_requests()
            assert [(r["request_id"], r["replica"]) for r in rows] == [
                ("bb02", "r1"), ("aa01", "r0")]  # newest first
        finally:
            fleet.stop()

    def test_request_timeline_searches_every_ring(self):
        fleet, engines, _ = _fake_fleet(_RingFake)
        try:
            engines["r1"].ring.add(self._trace("cc03", 50.0))
            tl = fleet.request_timeline("cc03")
            assert tl is not None and tl["trace_id"] == "cc03"
            assert fleet.request_timeline("dead") is None
        finally:
            fleet.stop()

    def test_lookup_skips_engines_without_rings(self):
        fleet, _, _ = _fake_fleet(_TraceFake)  # no recent_requests
        try:
            assert fleet.recent_requests() == []
            assert fleet.request_timeline("anything") is None
        finally:
            fleet.stop()


# ============================================= cross-component timeline
class TestCrossComponentTimeline:
    def _arc(self):
        """A routed request that gets evicted and readmitted — the
        span shapes the engine records, driven directly."""
        rid = reqtrace.new_request_id()
        route = Span(trace_id=rid, name="route", component="router",
                     attributes={"decision": "affinity", "replica": "r1",
                                 "candidates": {"r0": 0, "r1": 2}})
        route.end = time.time()
        tr = reqtrace.RequestTrace(
            rid, klass="best-effort", component="r1",
            parent_id=route.span_id, extra_records=[route.to_record()])
        tr.start_phase("queue_wait")
        tr.start_phase("prefill")
        tr.event("preempted", reason="slots", slot=0)
        tr.start_phase("queue_wait", requeued=True)
        tr.start_phase("prefill")
        tr.start_phase("decode")
        tr.event("first_token")
        tr.finish(tokens_out=4)
        return rid, tr

    def test_route_span_parents_the_request_tree(self):
        rid, tr = self._arc()
        tl = build_timeline(tr.records(), trace_id=rid)
        assert tl["span_count"] == 7  # route + request + 5 phases
        assert len(tl["spans"]) == 1, "ONE tree — no orphan roots"
        root = tl["spans"][0]
        assert (root["name"], root["component"]) == ("route", "router")
        assert len(root["children"]) == 1
        request = root["children"][0]
        assert (request["name"], request["component"]) == ("request", "r1")
        names = [c["name"] for c in request["children"]]
        assert names.count("queue_wait") == 2
        assert names.count("prefill") == 2
        assert names.count("decode") == 1
        requeued = [c for c in request["children"]
                    if c["name"] == "queue_wait"
                    and (c.get("attributes") or {}).get("requeued")]
        assert len(requeued) == 1
        # every engine-side hop names the replica, not generic serving
        assert all(c["component"] == "r1"
                   for c in request["children"])

    def test_request_phases_reports_route_and_replica(self):
        rid, tr = self._arc()
        summary = request_phases(build_timeline(tr.records(),
                                                trace_id=rid))
        assert summary["request_id"] == rid
        assert summary["route"] == {"decision": "affinity",
                                    "replica": "r1"}
        assert summary["replica"] == "r1"
        # route is an upstream decision, never an engine phase
        assert set(summary["phases_ms"]) == {"queue_wait", "prefill",
                                             "decode"}
        assert summary["events"]["preempted"] == 1
        assert summary["ttft_ms"] is not None


# =============================================== real engine, end to end
class TestRealEngineFleetArc:
    def test_one_trace_id_one_fleet_timeline_through_eviction(self):
        """Acceptance: a request routed by the fleet, evicted by a
        higher class, and readmitted yields ONE timeline whose route
        span parents the engine's request tree through the
        eviction→readmit arc — with the replica's identity on the
        engine spans and on the preemption/TTFT series."""
        from polyaxon_tpu.serving.fleet import engine_factory

        reg = _reg()
        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]
        fleet = ServingFleet(
            engine_factory("llama_tiny", slots=1, kv="paged",
                           page_size=4, max_len=64),
            replicas=1, standby=0, max_replicas=1, prewarm=True,
            warmup_rows=[prompt], router=FleetRouter(seed=0,
                                                     registry=reg),
            registry=reg, cooldown=1.0, idle_hold=1.0)
        fleet.start()
        try:
            be, d_be = fleet.submit(prompt, 24, klass="best-effort")
            while not be.out:  # live and decoding before the rival
                time.sleep(0.005)
            ia, d_ia = fleet.submit([7, 7, 7], 2, klass="interactive")
            ia.wait(timeout=300)
            be.wait(timeout=300)
            assert be.preemptions >= 1
            assert d_be.replica == d_ia.replica == "r0"

            tl = fleet.request_timeline(be.id)
            assert tl is not None and tl["trace_id"] == be.id
            assert len(tl["spans"]) == 1
            root = tl["spans"][0]
            assert (root["name"], root["component"]) == ("route",
                                                         "router")
            assert root["attributes"]["replica"] == "r0"
            request = next(c for c in root["children"]
                           if c["name"] == "request")
            assert request["component"] == "r0"
            summary = request_phases(tl)
            assert summary["route"]["replica"] == "r0"
            assert summary["replica"] == "r0"
            assert summary["events"].get("preempted", 0) >= 1
            assert summary["phases_ms"].get("queue_wait", 0) >= 0
            requeued = [s for s in request["children"]
                        if s["name"] == "queue_wait"
                        and (s.get("attributes") or {}).get("requeued")]
            assert requeued, "readmit must reopen queue_wait in-tree"

            # the series side of the same story: everything the engine
            # recorded carries its component
            assert obs_metrics.serving_preemptions_total(
                reg).total_by_component().get("r0", 0) >= 1
            assert obs_metrics.serving_ttft_hist(
                reg).components() == {"r0"}
            snap = fleet.fleet_snapshot()
            assert snap["per_replica"]["r0"]["preemptions"] >= 1
            assert snap["ttft_skew"] is None  # one replica: undefined
            assert fleet_serve.telemetry_gaps(fleet) == []
        finally:
            fleet.stop()
