from polyaxon_tpu.serving.server import ServingServer, load_params

__all__ = ["ServingServer", "load_params"]
