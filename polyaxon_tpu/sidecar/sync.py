"""Incremental rsync-like tree sync (mtime+size) with a watch loop.

The destination is either a local/mounted directory (the TPU-VM
default) or any artifact-store URL (``gs://``, ``s3://``, ...) — the
upstream sidecar ships to fsspec stores the same way (SURVEY.md §3.3).
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from typing import Optional
from urllib.parse import urlparse

logger = logging.getLogger(__name__)

# Per-path once-only + time-limited summary, shared with the store
# path: the 5 s hot loop re-hits the same broken destination every
# pass, and unbounded identical warnings are their own outage.
_warned_paths: set[str] = set()
_last_summary_warn = 0.0
_SUMMARY_INTERVAL_S = 60.0


def warn_sync_failures(failed: int, first_error: str) -> None:
    """Summary warning for a sync pass with failures, at most one per
    minute process-wide."""
    global _last_summary_warn
    now = time.monotonic()
    if now - _last_summary_warn >= _SUMMARY_INTERVAL_S:
        _last_summary_warn = now
        logger.warning(
            "sync pass: %d file(s) failed to ship (will retry; first "
            "error: %s)", failed, first_error)


def warn_sync_file(path: str, dest: str, exc: Exception) -> None:
    """Per-file warning, once per source path per process."""
    if path not in _warned_paths:
        _warned_paths.add(path)
        logger.warning("sync failed for %s -> %s: %s", path, dest, exc)


def _should_copy(src: str, dest: str) -> bool:
    if not os.path.exists(dest):
        return True
    s, d = os.stat(src), os.stat(dest)
    return s.st_mtime > d.st_mtime or s.st_size != d.st_size


def sync_tree(src_root: str, dest_root: str) -> int:
    """Copy changed files; returns number synced. Append-heavy files
    (jsonl/logs) are whole-file copied — sizes here are small relative to
    checkpoints, which orbax already writes store-side.

    Only a vanished source (FileNotFoundError) is silently retried; a
    failing DESTINATION (read-only/full volume) is logged loudly — the
    same contract as ``Store.sync_dir`` — and retried next pass."""
    synced = 0
    failed = 0
    first_error = ""
    for dirpath, _, filenames in os.walk(src_root):
        rel = os.path.relpath(dirpath, src_root)
        dest_dir = os.path.join(dest_root, rel) if rel != "." else dest_root
        for name in filenames:
            if name.endswith((".tmp", ".lock")):
                continue
            src = os.path.join(dirpath, name)
            dest = os.path.join(dest_dir, name)
            if _should_copy(src, dest):
                try:
                    os.makedirs(dest_dir, exist_ok=True)
                    shutil.copy2(src, dest)
                    synced += 1
                except FileNotFoundError:
                    continue  # source vanished/rotating mid-walk
                except OSError as exc:
                    failed += 1
                    first_error = first_error or f"{exc}"
                    warn_sync_file(src, dest, exc)
                    continue
    if failed:
        warn_sync_failures(failed, first_error)
    return synced


class SidecarSync:
    def __init__(self, run_dir: str, store_dir: str, interval_seconds: float = 5.0,
                 run_uuid: Optional[str] = None):
        self.run_dir = run_dir
        self.store_dir = store_dir
        self.interval = interval_seconds
        # Lifecycle tracing: run dirs are <artifacts_root>/<uuid>, so
        # the basename is the trace id when none is given; the remote
        # parent (the agent's `execute` span) rides the env contract.
        self.run_uuid = run_uuid or os.path.basename(
            os.path.abspath(run_dir))
        from polyaxon_tpu.obs import trace as obs_trace

        _, self._trace_parent = obs_trace.parse_trace_parent(
            os.environ.get(obs_trace.ENV_TRACE_PARENT))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # A URL destination ships through the store layer with the
        # incremental mtime state Store.sync_dir keeps; a plain path
        # (or file://) stays on the local fast path below.
        parsed = urlparse(store_dir)
        if parsed.scheme and parsed.scheme != "file":
            from polyaxon_tpu.fs import get_store

            self._store = get_store(store_dir)
            self._store_state: dict[str, float] = {}
        else:
            self._store = None
            if parsed.scheme == "file":
                self.store_dir = parsed.path

    def sync_once(self) -> int:
        t0 = time.time()
        if self._store is not None:
            from polyaxon_tpu.fs import is_transient_store_error
            from polyaxon_tpu.utils.retries import with_retries

            # Transient store failures (throttles, injected chaos
            # faults — typed StoreErrors that sync_dir's per-file
            # OSError net does not catch) retry the pass in place;
            # sync_dir is incremental, so a re-pass only re-ships what
            # the failed pass missed.
            synced = with_retries(
                lambda: self._store.sync_dir(self.run_dir,
                                             state=self._store_state),
                transient=is_transient_store_error, key=self.run_dir)
        else:
            synced = sync_tree(self.run_dir, self.store_dir)
        if synced:
            self._record_sync_span(t0, synced)
        return synced

    def _record_sync_span(self, t0: float, synced: int) -> None:
        """`sync` span per pass that shipped files, then ship the span
        file itself IN this pass (recording its mtime) — otherwise the
        span write would make the next pass non-empty and the sidecar
        would emit sync spans about syncing sync spans forever."""
        from polyaxon_tpu.obs import trace as obs_trace

        try:
            span_path = obs_trace.record_completed(
                self.run_dir, self.run_uuid, "sync", component="sidecar",
                start=t0, end=time.time(), parent_id=self._trace_parent,
                attributes={"files": synced,
                            "dest": ("store" if self._store is not None
                                     else "local")})
            rel = os.path.relpath(span_path, self.run_dir)
            if self._store is not None:
                key = rel.replace(os.sep, "/")
                self._store.upload_file(span_path, key)
                self._store_state[span_path] = os.path.getmtime(span_path)
            else:
                dest = os.path.join(self.store_dir, rel)
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                shutil.copy2(span_path, dest)  # mtime preserved → no re-copy
        except Exception as exc:  # noqa: BLE001 — tracing must never
            # break the sync loop (incl. chaos-injected StoreErrors on
            # the span-file ship; the file re-ships next pass).
            warn_sync_file(self.run_dir, "span/lifecycle", exc)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sync_once()
            except Exception as exc:  # noqa: BLE001 — keep the loop alive
                warn_sync_failures(1, f"{type(exc).__name__}: {exc}")

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, name="plx-sidecar", daemon=True)
            self._thread.start()

    def stop(self, final_sync: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if final_sync:
            self.sync_once()
