"""Fleet-scale control-plane simulator (ISSUE 8).

Drives the REAL ``Scheduler`` + ``AdmissionController`` + ``Store``
(nothing under test is mocked) through arrival traces composed from the
workloads the repo already supports — tune sweeps, cron/interval
schedules, DAG pipelines, serving deploys, restart/backoff churn,
preemption storms — with only the executor/slice layer replaced by a
synthetic agent whose placement, run-duration, and failure behavior is
configurable and seeded.

Outputs the committed ``fleet_curve.json`` (tick latency and store cost
vs load) gated by ``budgets.json`` in CI, exactly like the PR 4
collective audit. See docs/scheduling.md § "Fleet-scale simulation".
"""

from polyaxon_tpu.sim.executor import SyntheticExecutor
from polyaxon_tpu.sim.fleet import FleetSim
from polyaxon_tpu.sim.traces import TraceEvent, make_trace

__all__ = ["SyntheticExecutor", "FleetSim", "TraceEvent", "make_trace"]
