"""Chaos harness + retry/backoff layer (ISSUE 1): seed-driven fault
plans injected at the store/gang/checkpoint/tick seams, and the
recovery machinery they prove out — restart policies with persisted
backoff, typed store retries, checkpoint restore fallback, init
timeouts, gang reaping, and serving load-shedding."""

import json
import os
import sys
import time
import urllib.error
import urllib.request

import pytest

from polyaxon_tpu import chaos
from polyaxon_tpu.agent import Agent
from polyaxon_tpu.controlplane import ControlPlane
from polyaxon_tpu.lifecycle import V1Statuses


@pytest.fixture(autouse=True)
def _fast_backoff(monkeypatch):
    """Sub-second backoff + retry delays so fault drills stay quick,
    and a clean chaos slate around every test."""
    monkeypatch.setenv("POLYAXON_TPU_BACKOFF_BASE", "0.05")
    monkeypatch.setenv("POLYAXON_TPU_BACKOFF_MAX", "2")
    monkeypatch.setenv("POLYAXON_TPU_STORE_RETRY_BASE", "0.01")
    chaos.uninstall()
    yield
    chaos.uninstall()


@pytest.fixture()
def plane(tmp_path):
    return ControlPlane(str(tmp_path / "home"))


@pytest.fixture()
def agent(plane):
    return Agent(plane, max_concurrent=4)


def drive(agent, plane, uuid, until, timeout=120.0, poll=0.03):
    """Reconcile until ``until(record)`` or fail the test."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        agent.reconcile_once()
        record = plane.get_run(uuid)
        if until(record):
            return record
        time.sleep(poll)
    raise AssertionError(
        f"run {uuid} never satisfied the predicate; last status "
        f"{plane.get_run(uuid).status}: {plane.get_statuses(uuid)}")


# =================================================================== retries
class TestRetries:
    def test_transient_retries_then_succeeds(self):
        from polyaxon_tpu.utils.retries import with_retries

        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("blip")
            return "ok"

        assert with_retries(flaky, attempts=3, base=0.001) == "ok"
        assert len(calls) == 3

    def test_permanent_raises_immediately(self):
        from polyaxon_tpu.utils.retries import with_retries

        calls = []

        def broken():
            calls.append(1)
            raise FileNotFoundError("gone")

        with pytest.raises(FileNotFoundError):
            with_retries(broken, attempts=5, base=0.001)
        assert len(calls) == 1

    def test_exhausted_reraises_last_error(self):
        from polyaxon_tpu.utils.retries import with_retries

        with pytest.raises(TimeoutError):
            with_retries(lambda: (_ for _ in ()).throw(TimeoutError("t")),
                         attempts=2, base=0.001)

    def test_backoff_is_monotone_and_deterministic(self):
        from polyaxon_tpu.utils.retries import backoff_delay

        delays = [backoff_delay(i, base=0.5, key="run:restarts")
                  for i in range(5)]
        assert all(b > a for a, b in zip(delays, delays[1:]))
        again = [backoff_delay(i, base=0.5, key="run:restarts")
                 for i in range(5)]
        assert delays == again  # same key → same jitter: idempotent ticks
        other = [backoff_delay(i, base=0.5, key="other") for i in range(5)]
        assert delays != other  # different runs decorrelate


# ================================================================ fault plan
class TestChaosPlan:
    def test_nth_event_and_times_window(self):
        plan = chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "store", "op": "read_bytes", "at": 2, "times": 2}]})
        fired = [plan.fire("store", "read_bytes") is not None
                 for _ in range(5)]
        assert fired == [False, True, True, False, False]
        assert plan.done

    def test_wildcard_op_and_seam_isolation(self):
        plan = chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "store", "op": "*", "at": 1}]})
        assert plan.fire("tick", "skip") is None  # other seam untouched
        assert plan.fire("store", "write_bytes") is not None
        assert plan.done

    def test_env_var_activation(self, monkeypatch, tmp_path):
        path = tmp_path / "plan.json"
        path.write_text(json.dumps(
            {"faults": [{"seam": "tick", "op": "skip"}]}))
        monkeypatch.setenv(chaos.ENV_CHAOS_PLAN, str(path))
        chaos.uninstall()  # force the env re-read
        plan = chaos.active_plan()
        assert plan is not None and plan.has_faults("tick")


# =============================================================== store seam
class TestStoreFaults:
    def test_transient_fault_is_retried_through(self, tmp_path):
        from polyaxon_tpu.fs import (
            get_store,
            is_transient_store_error,
        )
        from polyaxon_tpu.utils.retries import with_retries

        chaos.install(chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "store", "op": "read_bytes", "at": 1, "times": 2}]}))
        store = get_store("memory://chaos-unit")
        store.write_bytes("k", b"v")
        with pytest.raises(Exception):
            store.read_bytes("k")  # first direct read: injected fault
        # The retry layer absorbs the remaining fault budget.
        assert with_retries(lambda: store.read_bytes("k"),
                            transient=is_transient_store_error,
                            base=0.01) == b"v"
        assert chaos.active_plan().done

    def test_permanent_fault_is_not_retried(self):
        from polyaxon_tpu.fs import (
            StoreError,
            get_store,
            is_transient_store_error,
        )
        from polyaxon_tpu.utils.retries import with_retries

        chaos.install(chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "store", "op": "read_bytes", "at": 1, "times": 3,
             "config": {"error": "permanent"}}]}))
        store = get_store("memory://chaos-perm")
        store.write_bytes("k", b"v")
        with pytest.raises(StoreError):
            with_retries(lambda: store.read_bytes("k"),
                         transient=is_transient_store_error, base=0.01)
        # Permanent → one attempt, not three: two fault budget left.
        assert not chaos.active_plan().done

    def test_derived_ops_route_through_hooks(self, tmp_path):
        """download_dir on a wrapped store must hit the read_bytes hook
        (the real init-phase entry point), not bypass it."""
        from polyaxon_tpu.fs import TransientStoreError, get_store

        seed = get_store("memory://chaos-derived")
        seed.write_bytes("data/a.txt", b"a")
        chaos.install(chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "store", "op": "download_file", "at": 1}]}))
        store = get_store("memory://chaos-derived")
        with pytest.raises(TransientStoreError):
            store.download_dir("", str(tmp_path / "out"))
        assert chaos.active_plan().done


# =========================================================== checkpoint seam
class TestCheckpointFallback:
    def _state(self, step: int):
        import numpy as np

        return {"step": np.asarray(step, np.int32),
                "params": {"w": np.arange(8, dtype=np.float32) + step}}

    def test_corrupt_latest_falls_back_to_older(self, tmp_path):
        from polyaxon_tpu.polyflow.runs import V1JaxCheckpointing
        from polyaxon_tpu.runtime.checkpoint import CheckpointManager

        mgr = CheckpointManager(
            str(tmp_path / "ckpt"),
            V1JaxCheckpointing(enabled=True, async_save=False))
        mgr.save(2, self._state(2), force=True)
        mgr.save(4, self._state(4), force=True)
        mgr.wait()
        assert mgr.latest_step() == 4

        chaos.install(chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "checkpoint", "op": "corrupt_latest"}]}))
        restored = mgr.restore(self._state(0))
        assert int(restored["step"]) == 2
        assert mgr.last_restore_skipped == [4]
        # The corrupt step was culled so the next save/restore is clean.
        assert mgr.latest_step() == 2
        mgr.close()
        assert chaos.active_plan().done

    def test_all_steps_corrupt_raises(self, tmp_path):
        from polyaxon_tpu.polyflow.runs import V1JaxCheckpointing
        from polyaxon_tpu.runtime.checkpoint import CheckpointManager

        mgr = CheckpointManager(
            str(tmp_path / "ckpt"),
            V1JaxCheckpointing(enabled=True, async_save=False))
        mgr.save(2, self._state(2), force=True)
        mgr.wait()
        chaos.install(chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "checkpoint", "op": "corrupt_latest"}]}))
        with pytest.raises(RuntimeError, match="no restorable checkpoint"):
            mgr.restore(self._state(0))
        mgr.close()


# ================================================================ tick seam
class TestTickSeam:
    def test_swallowed_tick_is_recovered_by_the_next(self, plane):
        from polyaxon_tpu.controlplane.scheduler import Scheduler

        chaos.install(chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "tick", "op": "skip", "at": 1}]}))
        record = plane.submit({
            "kind": "component",
            "run": {"kind": "job",
                    "container": {"command": ["python", "-c", "print(1)"]}},
        })
        sched = Scheduler(plane)
        assert sched.tick() == 0  # injected stall: nothing happens
        assert plane.get_run(record.uuid).status == V1Statuses.CREATED
        assert sched.tick() >= 1  # identical state, next tick advances
        assert plane.get_run(record.uuid).status == V1Statuses.QUEUED
        assert chaos.active_plan().done


# ====================================================== restart policy (AC2)
class TestRestartPolicyBackoff:
    def test_on_failure_consumes_retries_then_exhausts(self, plane, agent):
        """Acceptance: restart_policy=on_failure consumes retries with
        monotonically growing meta["backoff"] delays and ends FAILED
        reason=RetriesExhausted once the budget is spent."""
        record = plane.submit({
            "kind": "operation",
            "termination": {"maxRetries": 2},
            "component": {
                "run": {
                    "kind": "job",
                    "environment": {"restartPolicy": "on_failure"},
                    "container": {"command": [
                        "python", "-c", "raise SystemExit(3)"]},
                },
            },
        })

        def exhausted(rec):
            reasons = [c.get("reason")
                       for c in plane.get_statuses(rec.uuid)]
            return "RetriesExhausted" in reasons

        final = drive(agent, plane, record.uuid, exhausted, timeout=90)
        assert final.status == V1Statuses.FAILED
        assert final.retries == 2
        backoff = final.meta["backoff"]
        assert backoff["exhausted"] is True
        assert backoff["restarts"] == 2
        delays = backoff["delays"]
        assert len(delays) == 2
        assert delays[1] > delays[0]  # monotone growth, audited in meta
        conditions = [c["type"] for c in plane.get_statuses(record.uuid)]
        assert conditions.count("retrying") == 2
        assert conditions.count("failed") >= 3  # 1 initial + 2 restarts

    def test_never_policy_does_not_restart(self, plane, agent):
        record = plane.submit({
            "kind": "component",
            "run": {
                "kind": "job",
                "environment": {"restartPolicy": "never"},
                "container": {"command": [
                    "python", "-c", "raise SystemExit(1)"]},
            },
        })
        final = drive(agent, plane, record.uuid, lambda r: r.is_done,
                      timeout=60)
        for _ in range(3):
            agent.reconcile_once()
        conditions = [c["type"] for c in plane.get_statuses(record.uuid)]
        assert final.status == V1Statuses.FAILED
        assert "retrying" not in conditions

    def test_requeue_waits_for_not_before(self, plane, agent, monkeypatch):
        """A RETRYING run must not be re-popped before its backoff gate:
        with a long base delay, immediate ticks leave it RETRYING."""
        monkeypatch.setenv("POLYAXON_TPU_BACKOFF_BASE", "30")
        record = plane.submit({
            "kind": "component",
            "run": {
                "kind": "job",
                "environment": {"restartPolicy": "on_failure"},
                "container": {"command": [
                    "python", "-c", "raise SystemExit(1)"]},
            },
        })
        final = drive(
            agent, plane, record.uuid,
            lambda r: r.status == V1Statuses.RETRYING, timeout=60)
        for _ in range(5):
            agent.reconcile_once()
        record = plane.get_run(record.uuid)
        assert record.status == V1Statuses.RETRYING  # gate holds
        assert record.meta["backoff"]["not_before"] > final.updated_at


# ============================================================= init failures
class TestInitTimeout:
    def test_hung_build_fails_run_with_init_timeout(self, plane, agent,
                                                    monkeypatch):
        monkeypatch.setenv("POLYAXON_TPU_BUILD_TIMEOUT", "0.4")
        record = plane.submit({
            "kind": "component",
            "run": {"kind": "job",
                    "container": {"command": ["python", "-c", "print(1)"]}},
        })
        plane.compile_run(record.uuid)
        # Splice a hung build phase into the compiled plan (the builder
        # path a hubRef build: section produces).
        plan_dict = dict(plane.get_run(record.uuid).launch_plan)
        plan_dict["init"] = [{
            "kind": "build",
            "config": {"command": [sys.executable, "-c",
                                   "import time; time.sleep(30)"],
                       "hubRef": "slow-builder"},
        }] + list(plan_dict.get("init") or [])
        plane.store.update_run(record.uuid, launch_plan=plan_dict)

        t0 = time.monotonic()
        final = drive(agent, plane, record.uuid, lambda r: r.is_done,
                      timeout=60)
        assert final.status == V1Statuses.FAILED
        assert time.monotonic() - t0 < 25  # not the build's 30s sleep
        last = plane.get_statuses(record.uuid)[-1]
        assert last["reason"] == "InitTimeout"
        assert "hung" in (last.get("message") or "")

    def test_hung_git_clone_raises_init_timeout(self, tmp_path,
                                                monkeypatch):
        import subprocess as sp

        from polyaxon_tpu.agent.executor import InitTimeoutError

        src = tmp_path / "repo"
        src.mkdir()
        sp.run(["git", "init", "-q", str(src)], check=True)
        (src / "f.txt").write_text("x")
        monkeypatch.setenv("POLYAXON_TPU_GIT_TIMEOUT", "0.001")

        class _Plan:
            artifacts_dir = str(tmp_path / "arts")

        class _Phase:
            config = {"url": str(src)}
            path = "code"

        os.makedirs(_Plan.artifacts_dir, exist_ok=True)
        from polyaxon_tpu.agent.executor import LocalExecutor

        executor = LocalExecutor.__new__(LocalExecutor)
        with pytest.raises(InitTimeoutError, match="hung"):
            executor._init_git(_Plan, _Phase)

    def test_chaos_init_stall_is_survivable(self, plane, agent):
        """The init stall seam delays a phase without breaking it: the
        run still succeeds and the fault is consumed."""
        chaos.install(chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "init", "op": "auth",
             "config": {"seconds": 0.2}}]}))
        record = plane.submit({
            "kind": "component",
            "run": {"kind": "job",
                    "container": {"command": ["python", "-c", "print(1)"]}},
        })
        final = drive(agent, plane, record.uuid, lambda r: r.is_done,
                      timeout=60)
        assert final.status == V1Statuses.SUCCEEDED
        assert chaos.active_plan().done


# ============================================================= gang reaping
class TestGangReaping:
    SLEEPER = {
        "kind": "component",
        "run": {
            "kind": "jaxjob",
            "numProcesses": 2,
            "container": {"command": [
                "python", "-c", "import time; time.sleep(60)"]},
        },
    }

    def _wait_active(self, agent, plane, uuid, timeout=30):
        deadline = time.monotonic() + timeout
        while uuid not in agent.executor.active_runs:
            assert time.monotonic() < deadline, "gang never started"
            agent.reconcile_once()
            time.sleep(0.05)

    def test_signal_killed_member_reaps_survivors_and_fails(self, plane,
                                                            agent):
        record = plane.submit(self.SLEEPER)
        self._wait_active(agent, plane, record.uuid)
        gang = agent.executor._gangs[record.uuid]
        assert len(gang.procs) == 2
        gang.procs[0].kill()  # SIGKILL one member → exit code -9
        t0 = time.monotonic()
        final = drive(agent, plane, record.uuid, lambda r: r.is_done,
                      timeout=30)
        assert final.status == V1Statuses.FAILED
        assert time.monotonic() - t0 < 25  # survivor did not sleep out 60s
        last = plane.get_statuses(record.uuid)[-1]
        assert "exit code -9" in (last.get("message") or "")
        assert all(p.poll() is not None for p in gang.procs)

    def test_stopping_wins_over_preemption_at_reap(self, plane, agent):
        """poll() precedence pin: a STOPPING run whose gang also took a
        preemption reaps STOPPED — operator intent over weather."""
        record = plane.submit(self.SLEEPER)
        self._wait_active(agent, plane, record.uuid)
        plane.stop(record.uuid)  # → STOPPING
        assert agent.executor.preempt(record.uuid)  # kills + preempt mark
        final = drive(agent, plane, record.uuid, lambda r: r.is_done,
                      timeout=30)
        assert final.status == V1Statuses.STOPPED
        conditions = [c["type"] for c in plane.get_statuses(record.uuid)]
        assert "preempted" not in conditions

    def test_chaos_kill_seam_fails_subprocess_gang(self, plane, agent):
        """The gang seam's own kill path: the plan SIGKILLs one member
        and the normal reap fails the run with the signal code."""
        chaos.install(chaos.ChaosPlan.from_dict({"faults": [
            {"seam": "gang", "op": "kill"}]}))
        record = plane.submit(self.SLEEPER)
        self._wait_active(agent, plane, record.uuid)
        final = drive(agent, plane, record.uuid, lambda r: r.is_done,
                      timeout=30)
        assert final.status == V1Statuses.FAILED
        last = plane.get_statuses(record.uuid)[-1]
        assert "exit code -9" in (last.get("message") or "")
        assert chaos.active_plan().done


# ==================================================== the chaos gauntlet (AC1)
class TestChaosJaxjobGauntlet:
    def test_one_run_survives_store_fault_kill_and_corrupt_ckpt(
            self, plane, tmp_path):
        """Acceptance: ONE jaxjob run rides through (a) a transient
        store fault during artifact init, (b) a gang-member kill after
        two checkpoints exist, and (c) a corrupted latest checkpoint on
        resume — and still reaches SUCCEEDED with restored_from_step
        set from the OLDER checkpoint."""
        from polyaxon_tpu.fs import get_store

        seed_store = get_store("memory://chaos-gauntlet")
        seed_store.write_bytes("vocab.txt", b"tokens")

        chaos.install(chaos.ChaosPlan.from_dict({"seed": 7, "faults": [
            {"seam": "store", "op": "*", "at": 1, "times": 1},
            {"seam": "gang", "op": "kill",
             "config": {"min_checkpoints": 2}},
            {"seam": "checkpoint", "op": "corrupt_latest"},
        ]}))

        record = plane.submit({
            "kind": "operation",
            "termination": {"maxRetries": 2},
            "component": {
                "name": "gauntlet",
                "run": {
                    "kind": "jaxjob",
                    "numProcesses": 1,
                    "environment": {"restartPolicy": "on_failure"},
                    "init": [{"artifacts": {
                        "path": "memory://chaos-gauntlet"}}],
                    "mesh": {"axes": {"dp": 8}},
                    "checkpointing": {"enabled": True, "intervalSteps": 2,
                                      "asyncSave": False,
                                      "restoreOnStart": True},
                    "runtime": {
                        "model": "llama_tiny",
                        "dataset": "lm_synthetic",
                        "steps": 6,
                        "seq_len": 64,
                        "global_batch_size": 8,
                    },
                },
            },
        })
        agent = Agent(plane, in_process=True)

        def settled(rec):
            if rec.status == V1Statuses.SUCCEEDED:
                return True
            reasons = [c.get("reason") for c in plane.get_statuses(rec.uuid)]
            assert "RetriesExhausted" not in reasons, reasons
            return False

        final = drive(agent, plane, record.uuid, settled, timeout=420)
        assert final.status == V1Statuses.SUCCEEDED

        plan = chaos.active_plan()
        assert plan.done, f"unconsumed faults; fired: {plan.consumed}"
        seams = [c["seam"] for c in plan.consumed]
        assert seams.count("store") == 1
        assert seams.count("gang") == 1
        assert seams.count("checkpoint") == 1

        # The kill consumed exactly one restart, through the backoff gate.
        assert final.retries == 1
        assert len(final.meta["backoff"]["delays"]) == 1
        conditions = [c["type"] for c in plane.get_statuses(record.uuid)]
        assert "retrying" in conditions

        # Resume restored from the OLDER checkpoint (label 2 → state
        # step 3), skipping the corrupted latest (label 4), and surfaced
        # both the outputs audit and a WARNING condition.
        outputs = plane.streams.get_outputs(record.uuid)
        assert outputs["steps"] == 6
        assert outputs["restored_from_step"] == 3
        assert outputs["restore_skipped_steps"] == [4]
        warning = [c for c in plane.get_statuses(record.uuid)
                   if c["type"] == "warning"]
        assert warning and warning[-1]["reason"] == "CheckpointFallback"
        assert "4" in warning[-1]["message"]

        # The transiently-faulted artifact download still landed.
        arts_dir = plane.run_artifacts_dir(record.uuid)
        assert os.path.exists(os.path.join(
            arts_dir, "inputs", "artifacts", "vocab.txt"))


# ======================================================== serving degradation
class TestServingBackpressure:
    def test_queue_cap_503_and_healthz_depth(self):
        from polyaxon_tpu.serving import ServingServer

        with ServingServer("llama_tiny", batching="continuous", slots=1,
                           max_pending=1) as server:
            # Saturate: one request decoding in the slot, one queued.
            r1 = server.engine.submit([5, 6, 7], 32)
            deadline = time.monotonic() + 120
            while server.engine.stats()["queued"] > 0:
                assert time.monotonic() < deadline, "r1 never admitted"
                time.sleep(0.02)  # wait for r1 to occupy the only slot
            r2 = server.engine.submit([5, 6, 7], 32)
            body = json.dumps({"tokens": [[5, 6, 7]],
                               "max_new_tokens": 32}).encode()
            req = urllib.request.Request(
                server.url + "/v1/generate", data=body, method="POST",
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(req, timeout=30)
            assert exc_info.value.code == 503
            assert int(exc_info.value.headers["Retry-After"]) >= 1
            payload = json.loads(exc_info.value.read())
            assert "queue is full" in payload["error"]

            with urllib.request.urlopen(server.url + "/healthz",
                                        timeout=30) as resp:
                health = json.load(resp)
            assert health["status"] == "ok"
            assert health["engine"] == "continuous"
            assert health["slots"] == 1
            assert health["max_pending"] == 1
            assert health["queued"] >= 1  # the capped queue is visible

            out1 = r1.wait(timeout=300)
            out2 = r2.wait(timeout=300)
            assert len(out1) == 32 and len(out2) == 32

            # Drained: the same request is admitted again.
            with urllib.request.urlopen(req, timeout=300) as resp:
                out = json.load(resp)
            assert len(out["tokens"][0]) == 32
