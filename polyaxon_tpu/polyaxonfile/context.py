"""Template interpolation: ``{{ params.x }}`` / ``{{ globals.* }}``.

Parity target: the reference's context resolution (SURVEY.md §3.1 [K]):
container command/args/env and IO values may reference bound params and
run globals with jinja-style expressions. Rendered with a sandboxed
jinja2 environment (jinja2 is available in-env [E]).
"""

from __future__ import annotations

import os
from typing import Any, Mapping, Optional

from jinja2 import StrictUndefined, Undefined
from jinja2.sandbox import SandboxedEnvironment

_ENV = SandboxedEnvironment(
    undefined=StrictUndefined,
    keep_trailing_newline=True,
)
_LENIENT_ENV = SandboxedEnvironment(undefined=Undefined, keep_trailing_newline=True)


class ContextError(ValueError):
    pass


def default_globals(
    *,
    run_uuid: str = "",
    run_name: str = "",
    project_name: str = "",
    owner_name: str = "default",
    iteration: Optional[int] = None,
    base_path: str = "",
) -> dict[str, Any]:
    """The ``globals.*`` namespace exposed to templates — mirrors the
    reference's run context contract (uuid/name/paths/iteration [K])."""
    artifacts_path = os.path.join(base_path, run_uuid) if base_path else ""
    return {
        "owner_name": owner_name,
        "project_name": project_name,
        "project_unique_name": f"{owner_name}.{project_name}" if project_name else "",
        "uuid": run_uuid,
        "name": run_name,
        "iteration": iteration,
        "context_path": "/plx-context",
        "artifacts_path": artifacts_path,
        "run_artifacts_path": artifacts_path,
        "run_outputs_path": os.path.join(artifacts_path, "outputs") if artifacts_path else "",
    }


def render_value(value: Any, context: Mapping[str, Any], *, strict: bool = True) -> Any:
    """Recursively render jinja expressions inside strings/lists/dicts.

    A string that is exactly one ``{{ expr }}`` preserves the expression's
    native type (so ``"{{ params.lr }}"`` with lr=0.1 yields a float, not
    the string "0.1") — matching the reference's param-substitution
    behavior for typed IO.
    """
    if isinstance(value, str):
        if "{{" not in value and "{%" not in value:
            return value
        env = _ENV if strict else _LENIENT_ENV
        stripped = value.strip()
        if stripped.startswith("{{") and stripped.endswith("}}") and stripped.count("{{") == 1:
            expr = stripped[2:-2].strip()
            try:
                result = env.compile_expression(expr, undefined_to_none=False)(**context)
            except Exception as exc:
                raise ContextError(f"Failed to resolve `{value}`: {exc}") from exc
            if isinstance(result, Undefined):
                if strict:
                    raise ContextError(f"Unresolved expression `{value}`")
                return None
            return result
        try:
            return env.from_string(value).render(**context)
        except Exception as exc:
            raise ContextError(f"Failed to render `{value}`: {exc}") from exc
    if isinstance(value, list):
        return [render_value(item, context, strict=strict) for item in value]
    if isinstance(value, tuple):
        return tuple(render_value(item, context, strict=strict) for item in value)
    if isinstance(value, dict):
        return {k: render_value(v, context, strict=strict) for k, v in value.items()}
    return value
