"""Polyflow IR — the typed spec universe (SURVEY.md §2 "Polyflow IR")."""

from polyaxon_tpu.polyflow.component import V1Component
from polyaxon_tpu.polyflow.environment import (
    GPU_RESOURCE,
    TPU_RESOURCE,
    V1Cache,
    V1Container,
    V1EnvVar,
    V1Environment,
    V1Hook,
    V1Init,
    V1Notification,
    V1Plugins,
    V1ResourceSpec,
    V1Termination,
    V1TpuTopology,
)
from polyaxon_tpu.polyflow.io import IOTypes, V1IO, V1Param, validate_params_against_io
from polyaxon_tpu.polyflow.matrix import (
    V1Asha,
    V1Bayes,
    V1FailureEarlyStopping,
    V1GridSearch,
    V1Hyperband,
    V1Hyperopt,
    V1HpChoice,
    V1HpLinSpace,
    V1HpLogSpace,
    V1HpLogUniform,
    V1HpPChoice,
    V1HpRange,
    V1HpUniform,
    V1Iterative,
    V1Mapping,
    V1MetricEarlyStopping,
    V1OptimizationMetric,
    V1OptimizationResource,
    V1RandomSearch,
)
from polyaxon_tpu.polyflow.operation import (
    V1Build,
    V1EventTrigger,
    V1Join,
    V1Operation,
    V1PatchStrategy,
    V1TriggerPolicy,
)
from polyaxon_tpu.polyflow.runs import (
    RunSpec,
    V1CleanerJob,
    V1Dag,
    V1DaskJob,
    V1JAXJob,
    V1JaxCheckpointing,
    V1Job,
    V1KFReplica,
    V1MPIJob,
    V1MeshSpec,
    V1NotifierJob,
    V1PyTorchJob,
    V1RayJob,
    V1RunKind,
    V1Service,
    V1TFJob,
    V1Tuner,
    V1WatchdogJob,
)
from polyaxon_tpu.polyflow.schedules import (
    V1CronSchedule,
    V1DateTimeSchedule,
    V1IntervalSchedule,
)

__all__ = [name for name in dir() if name.startswith("V1") or name in
           ("IOTypes", "RunSpec", "TPU_RESOURCE", "GPU_RESOURCE", "validate_params_against_io")]
