"""Device-mesh construction from JAXJob topology + mesh specs.

This is where the reference's replica-count orchestration (SURVEY.md §2b
[K]: Polyaxon only wires replica specs and rendezvous env; all real
parallelism is delegated) becomes an owned, first-class layer: a
``V1MeshSpec`` resolves against the slice topology into a
``jax.sharding.Mesh`` whose ICI-heavy axes (fsdp/tp/sp/cp/ep) sit on
intra-slice device dimensions and whose DCN axes (usually dp) span
slices — the hierarchy `jax.experimental.mesh_utils` encodes.

Axis convention (outermost → innermost):
    dp    data parallel (pure replication of params; gradients psum)
    pp    pipeline stages (DCN-friendly cuts)
    fsdp  fully-sharded data parallel (params/opt-state sharded; the
          [B] target config for Llama-3-8B over ICI)
    cp    context parallel (ring attention over sequence blocks)
    sp    sequence parallel (activation sharding fused with tp)
    ep    expert parallel (MoE dispatch axis)
    tp    tensor parallel (innermost — highest-bandwidth ICI)
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

logger = logging.getLogger(__name__)

from polyaxon_tpu.polyflow.environment import V1TpuTopology
from polyaxon_tpu.polyflow.runs import V1MeshSpec

# Canonical axis order: ICI-bandwidth-hungry axes innermost.
AXIS_ORDER: tuple[str, ...] = ("dp", "pp", "fsdp", "cp", "sp", "ep", "tp")

# Aliases accepted in specs (upstream-ish vocabulary → canonical).
AXIS_ALIASES = {"data": "dp", "model": "tp", "expert": "ep", "seq": "sp"}


def canonical_axes(axes: dict[str, int]) -> dict[str, int]:
    out: dict[str, int] = {}
    for name, size in axes.items():
        canon = AXIS_ALIASES.get(name, name)
        if canon in out:
            raise ValueError(f"Duplicate mesh axis `{name}` (alias of `{canon}`)")
        out[canon] = size
    return out


def order_axes(axes: dict[str, int]) -> dict[str, int]:
    """Order axes canonically; unknown axes keep their given order, last."""
    known = {k: axes[k] for k in AXIS_ORDER if k in axes}
    unknown = {k: v for k, v in axes.items() if k not in AXIS_ORDER}
    return {**known, **unknown}


def parse_mesh_axes(spec: str) -> dict[str, int]:
    """Parse a CLI-style mesh string — ``"tp=4,dp=2"`` / ``"fsdp=-1"``
    (-1 = absorb remaining devices) — into an axes dict. Raises
    ``ValueError`` with an actionable message; entry points convert it
    to their own usage-error style."""
    axes: dict[str, int] = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        name = name.strip()
        if name in axes:
            raise ValueError(f"mesh axis {name!r} given twice")
        try:
            axes[name] = int(size)
        except ValueError:
            raise ValueError(
                f"mesh axes expect name=size pairs "
                f"(e.g. 'tp=4,dp=2'), got {part.strip()!r}") from None
        if not name:
            raise ValueError(f"mesh axis in {part.strip()!r} has no name")
    return axes


def build_mesh(
    mesh_spec: Optional[V1MeshSpec] = None,
    topology: Optional[V1TpuTopology] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axes: Optional[dict[str, int]] = None,
) -> Mesh:
    """Build a ``Mesh`` from a spec (or raw ``axes``) over ``devices``.

    Single-slice: ``mesh_utils.create_device_mesh`` maps the logical mesh
    onto the ICI torus. Multi-slice (``topology.slices > 1`` and
    ``dcn_axes``): ``create_hybrid_device_mesh`` places the DCN axes
    across slice granules so only those axes pay DCN latency
    (SURVEY.md §2c).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)

    if axes is None:
        if mesh_spec is None:
            axes = {"dp": n}
        else:
            axes = mesh_spec.resolved_axes(n)
    axes = order_axes(canonical_axes(axes))

    sizes = [s for s in axes.values()]
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(f"Mesh axes {axes} need {total} devices, have {n}")

    dcn_axes = set()
    if mesh_spec is not None and mesh_spec.dcn_axes:
        dcn_axes = {AXIS_ALIASES.get(a, a) for a in mesh_spec.dcn_axes}
    slices = topology.slices if topology is not None else 1

    names = tuple(axes.keys())
    if slices > 1 and dcn_axes:
        ici_shape = [1 if name in dcn_axes else size for name, size in axes.items()]
        dcn_shape = [size if name in dcn_axes else 1 for name, size in axes.items()]
        try:
            device_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape,
                dcn_shape,
                devices=devices,
                allow_split_physical_axes=bool(mesh_spec and mesh_spec.allow_split_physical_axes),
            )
            logger.info("hybrid mesh: dcn_axes=%s over %d hardware slices",
                        sorted(dcn_axes), slices)
        except ValueError:
            # Devices without slice_index (CPU mesh, emulator): emulate the
            # slice granularity by putting DCN axes slowest-varying so each
            # contiguous device block is one "slice".
            perm = sorted(range(len(names)), key=lambda i: names[i] not in dcn_axes)
            permuted_sizes = [sizes[i] for i in perm]
            arr = np.asarray(devices).reshape(permuted_sizes)
            inverse = np.argsort(perm)
            device_array = arr.transpose(tuple(inverse))
            logger.info(
                "hybrid mesh: dcn_axes=%s over %d emulated slices "
                "(devices lack slice_index; DCN axes placed slowest-varying)",
                sorted(dcn_axes), slices)
    else:
        try:
            device_array = mesh_utils.create_device_mesh(
                sizes,
                devices=devices,
                allow_split_physical_axes=bool(mesh_spec and mesh_spec.allow_split_physical_axes),
            )
        except Exception:
            # CPU meshes / odd emulated topologies: fall back to a plain
            # row-major reshape (no ICI assignment to optimize anyway).
            device_array = np.asarray(devices).reshape(sizes)
    return Mesh(device_array, names)


def single_device_mesh(axis: str = "dp") -> Mesh:
    return Mesh(np.asarray(jax.devices()[:1]).reshape((1,)), (axis,))


def mesh_summary(mesh: Mesh) -> dict:
    return {
        "axes": dict(zip(mesh.axis_names, mesh.devices.shape)),
        "n_devices": int(mesh.devices.size),
        "platform": mesh.devices.flat[0].platform,
        "device_kind": getattr(mesh.devices.flat[0], "device_kind", "unknown"),
    }
