"""Local slice executor: materializes launch plans as processes.

This is the local provider behind the agent (SURVEY.md §2 "Agent", §7
step 5): the reconcile target that upstream delegates to k8s+operator.
It owns gang semantics in miniature — all processes of a plan start
together, the gang fails/stops together, and preemption (real eviction
on TPU-VMs, injected in tests) kills the gang and reports PREEMPTED so
the scheduler can requeue without consuming retries.

Modes per process:
- runnable command (python/binaries on PATH) → subprocess, stdout/err →
  ``logs/main-<i>.log`` in the run dir;
- ``in_process=True`` (tests/CLI fast path, single-process jaxjob
  gangs) → execute the builtin runtime in a thread, skipping the
  ~20s+ JAX re-import/compile of a fresh interpreter.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import threading
import traceback
from dataclasses import dataclass, field
from typing import Any, Optional
from urllib.parse import urlparse

from polyaxon_tpu import chaos
from polyaxon_tpu.compiler import COORDINATOR_PLACEHOLDER, ENV_JAXJOB_SPEC
from polyaxon_tpu.compiler.plan import V1LaunchPlan
from polyaxon_tpu.controlplane.service import ControlPlane
from polyaxon_tpu.lifecycle import V1Statuses
from polyaxon_tpu.obs import flight as obs_flight
from polyaxon_tpu.obs import trace as obs_trace
from polyaxon_tpu.runtime import elastic as elastic_mod


class InitTimeoutError(RuntimeError):
    """A build/clone init phase overran its wall-clock budget; the run
    fails with ``reason="InitTimeout"`` instead of the timeout
    propagating through the agent tick."""


def _init_timeout(env_var: str, default: float) -> float:
    try:
        return float(os.environ.get(env_var, default))
    except ValueError:
        return default


def _safe_join(root: str, rel: str) -> str:
    """Join a user-controlled relative path under ``root``, refusing
    absolute paths and ``..`` escapes (and ``root`` itself)."""
    joined = os.path.realpath(os.path.join(root, rel))
    root_real = os.path.realpath(root)
    if not joined.startswith(root_real + os.sep):
        raise RuntimeError(
            f"init path {rel!r} escapes the run's artifacts dir")
    return joined


@dataclass
class _Gang:
    run_uuid: str
    plan: V1LaunchPlan
    procs: list[subprocess.Popen] = field(default_factory=list)
    thread: Optional[threading.Thread] = None
    thread_error: Optional[str] = None
    thread_done: bool = False
    preempted: bool = False
    stop_event: threading.Event = field(default_factory=threading.Event)
    reaping: bool = False  # a member died; survivors were signalled
    warning: Optional[str] = None  # non-fatal anomaly → WARNING condition
    # Lifecycle tracing (obs.trace): the `execute` span covers the gang
    # from start() to its reap; subprocess children parent under it via
    # POLYAXON_TRACE_PARENT, the in-process runtime via a passed tracer.
    tracer: Optional[obs_trace.RunTracer] = None
    span: Optional[obs_trace.Span] = None
    # Elastic resize channel (runtime.elastic): present only for
    # in-process jaxjob gangs whose checkpointing makes a cross-mesh
    # restore possible; slice loss files a shrink here instead of a kill.
    elastic: Optional[elastic_mod.ElasticController] = None
    failed_resizes_dumped: int = 0  # postmortems already written
    # Restore audit from the runtime (ISSUE 16): which tier satisfied
    # the run's restore, mirrored into meta["checkpoint"] on poll so
    # ops surfaces read the store, not the thread.
    checkpoint_audit: Optional[dict] = None
    checkpoint_flushed: bool = False


class LocalExecutor:
    def __init__(self, plane: ControlPlane, *, in_process: bool = False):
        self.plane = plane
        self.store = plane.store
        self.in_process = in_process
        self._gangs: dict[str, _Gang] = {}
        # Persistent-compile-cache opt-in (POLYAXON_TPU_COMPILE_CACHE=1
        # without an explicit dir): resolve to ONE shared dir under the
        # agent's artifacts root, so every gang this agent launches —
        # in-process threads and subprocesses alike (both read the env)
        # — shares warm XLA executables and a preemption-requeued run
        # skips recompilation.
        from polyaxon_tpu.runtime import compile_cache

        if (os.environ.get(compile_cache.ENV_CACHE, "").strip() == "1"
                and not os.environ.get(compile_cache.ENV_CACHE_DIR)):
            os.environ[compile_cache.ENV_CACHE_DIR] = os.path.join(
                plane.artifacts_root, compile_cache.SHARED_CACHE_DIRNAME)

    # ------------------------------------------------------------------ init
    def _run_init_phases(self, plan: V1LaunchPlan) -> None:
        """Local init phases (SURVEY §3.3): auth context stub, artifact
        copies, tpu metadata discovery (local → loopback coordinator)."""
        os.makedirs(plan.artifacts_dir, exist_ok=True)
        os.makedirs(plan.outputs_dir, exist_ok=True)
        os.makedirs(os.path.join(plan.artifacts_dir, "logs"), exist_ok=True)
        fault_plan = chaos.active_plan()
        for phase in plan.init:
            if fault_plan is not None:
                fault_plan.maybe_stall_init(phase.kind)
            if phase.kind == "build":
                self._init_build(plan, phase)
            elif phase.kind == "auth":
                with open(os.path.join(plan.artifacts_dir, ".auth"), "w") as fh:
                    json.dump({"run_uuid": plan.run_uuid, "mode": "local"}, fh)
            elif phase.kind == "artifacts":
                src = phase.config.get("path") or phase.path
                scheme = urlparse(src).scheme if src else ""
                if scheme == "file":
                    src = urlparse(src).path  # → plain local path below
                elif src and scheme:
                    # Store URL (gs://, s3://, ...): download the whole
                    # prefix through the fs layer (upstream's artifacts
                    # initializer over fsspec — SURVEY §3.3).
                    from polyaxon_tpu.fs import (
                        StoreError,
                        get_store,
                        is_transient_store_error,
                    )
                    from polyaxon_tpu.utils.retries import with_retries

                    store = get_store(src)
                    name = (os.path.basename(urlparse(src).path.rstrip("/"))
                            or "artifacts")
                    dest = _safe_join(
                        os.path.join(plan.artifacts_dir, "inputs"), name)
                    # Retried as a unit: one transient store blip must
                    # not fail the run (download_dir re-copies already-
                    # fetched files, so the retry stays correct).
                    if with_retries(lambda: store.download_dir("", dest),
                                    transient=is_transient_store_error,
                                    key=plan.run_uuid) == 0:
                        # A single-object URL lists empty: fetch it as
                        # one file instead.
                        try:
                            with_retries(
                                lambda: store.download_file("", dest),
                                transient=is_transient_store_error,
                                key=plan.run_uuid)
                        except StoreError as exc:
                            raise StoreError(
                                f"artifacts init phase found no objects "
                                f"at {src!r}") from exc
                    continue
                if src and os.path.exists(src):
                    dest = os.path.join(plan.artifacts_dir, "inputs",
                                        os.path.basename(src))
                    os.makedirs(os.path.dirname(dest), exist_ok=True)
                    if os.path.isdir(src):
                        shutil.copytree(src, dest, dirs_exist_ok=True)
                    else:
                        shutil.copy2(src, dest)
            elif phase.kind == "file":
                content = phase.config.get("content", "")
                name = phase.config.get("filename", "file")
                path = _safe_join(os.path.join(plan.artifacts_dir, "inputs"), name)
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(path, "w") as fh:
                    fh.write(content)
            elif phase.kind == "git":
                self._init_git(plan, phase)
            elif phase.kind == "tpu_metadata":
                with open(os.path.join(plan.artifacts_dir, "tpu-metadata.json"), "w") as fh:
                    json.dump({"coordinator": "127.0.0.1", "topology": "local"}, fh)
            # dockerfile needs docker: recorded, skipped locally.

    def _init_build(self, plan: V1LaunchPlan, phase) -> None:
        """Execute the compiled ``build:`` section (upstream gates the
        main run on a separate build run; here the builder's command
        runs as the FIRST init phase, so a build failure fails the run
        with its log before any main process starts). Output lands in
        ``logs/build.log`` next to the main-process logs."""
        cmd = phase.config.get("command") or []
        if not cmd:
            raise RuntimeError("build init phase has no command")
        env = dict(os.environ)
        env.update(phase.config.get("env") or {})
        log_path = os.path.join(plan.artifacts_dir, "logs", "build.log")
        timeout = _init_timeout("POLYAXON_TPU_BUILD_TIMEOUT", 3600)
        try:
            with open(log_path, "ab") as log_handle:
                proc = subprocess.run(
                    [str(c) for c in cmd], env=env, cwd=plan.artifacts_dir,
                    stdout=log_handle, stderr=subprocess.STDOUT,
                    timeout=timeout)
        except subprocess.TimeoutExpired as exc:
            raise InitTimeoutError(
                f"build `{phase.config.get('hubRef')}` hung past "
                f"{timeout:.0f}s and was killed") from exc
        if proc.returncode != 0:
            tail = ""
            try:
                with open(log_path, "rb") as fh:
                    tail = fh.read()[-400:].decode(errors="replace")
            except OSError:
                pass
            raise RuntimeError(
                f"build `{phase.config.get('hubRef')}` failed "
                f"rc={proc.returncode}: {tail}")

    def _init_git(self, plan: V1LaunchPlan, phase) -> None:
        """Git initializer (upstream init.git): clone url@revision into the
        run context. Works against local paths and any remote git supports;
        failures raise so the run fails with the real git error."""
        url = phase.config.get("url")
        if not url:
            raise RuntimeError(
                "git init phase has no `url` (inline or via its connection)")
        revision = phase.config.get("revision")
        # A dash-prefixed "revision" would be parsed as a git option
        # (e.g. `--force` turns the checkout into a silent no-op).
        if revision and str(revision).startswith("-"):
            raise RuntimeError(f"invalid git revision {revision!r}")
        # The user-controlled path must stay inside the run's artifacts
        # dir — we rmtree it below, so absolute/`..` escapes are rejected,
        # and resolving to the artifacts root itself is refused too.
        dest = _safe_join(plan.artifacts_dir, phase.path or "repo")
        # Idempotent like every other init phase: a preemption-requeued
        # run restarts against the same artifacts dir.
        if os.path.exists(dest):
            shutil.rmtree(dest)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        # `--` stops git from parsing a dash-prefixed url as an option.
        timeout = _init_timeout("POLYAXON_TPU_GIT_TIMEOUT", 600)
        try:
            clone = subprocess.run(
                ["git", "clone", "--quiet", "--", url, dest],
                capture_output=True, text=True, timeout=timeout)
        except subprocess.TimeoutExpired as exc:
            raise InitTimeoutError(
                f"git clone {url} hung past {timeout:.0f}s and was "
                "killed") from exc
        if clone.returncode != 0:
            raise RuntimeError(f"git clone {url} failed: {clone.stderr.strip()}")
        if revision:
            try:
                checkout = subprocess.run(
                    ["git", "-C", dest, "checkout", "--quiet", revision, "--"],
                    capture_output=True, text=True,
                    timeout=min(timeout, 120))
            except subprocess.TimeoutExpired as exc:
                raise InitTimeoutError(
                    f"git checkout {revision} hung and was killed") from exc
            if checkout.returncode != 0:
                raise RuntimeError(
                    f"git checkout {revision} failed: {checkout.stderr.strip()}")

    # ----------------------------------------------------------------- start
    def start(self, run_uuid: str) -> bool:
        """queued → scheduled → starting → running; spawns the gang."""
        record = self.store.get_run(run_uuid)
        plan_dict = record.launch_plan
        if not plan_dict:
            # polycheck: ignore[invariant-store-batch] -- lifecycle gates separated by gang spawn: FAILED/RUNNING mark externally observable progress and cannot batch with the scheduled hop below
            self.store.transition(run_uuid, V1Statuses.FAILED, reason="NoLaunchPlan")
            return False
        plan = V1LaunchPlan.from_dict(plan_dict)
        # One commit for the pre-spawn hop: a crash between them would
        # strand the run in SCHEDULED with no gang to reap it.
        with self.store.transaction():
            self.store.transition(run_uuid, V1Statuses.SCHEDULED)
            self.store.transition(run_uuid, V1Statuses.STARTING)

        gang = _Gang(run_uuid=run_uuid, plan=plan)
        # Arm the flight recorder before any span lands: the registry
        # baseline taken here is what turns the postmortem's metric
        # section into DELTAS (what moved while this gang lived).
        obs_flight.RECORDER.mark_start(run_uuid)
        gang.tracer = obs_trace.RunTracer(
            plan.artifacts_dir, run_uuid, component="agent")
        gang.span = gang.tracer.start_span(
            "execute", attributes={"kind": plan.run_kind,
                                   "processes": plan.num_processes,
                                   "in_process": self.in_process})
        try:
            # Init runs inside a child span AS the current span, so the
            # deep seams it crosses (chaos store faults, with_retries
            # attempts, init stalls) annotate it (obs.trace.add_event).
            with gang.tracer.span("init", parent=gang.span) as init_span:
                init_span.set(phases=[p.kind for p in plan.init])
                self._run_init_phases(plan)
            if self.in_process and self._can_run_in_process(plan):
                gang.elastic = self._make_elastic(plan)
                gang.thread = threading.Thread(
                    target=self._run_in_process, args=(gang,), daemon=True
                )
                gang.thread.start()
            else:
                for proc_spec in plan.processes:
                    env = dict(os.environ)
                    env.update(proc_spec.env)
                    # Trace propagation rides the same env plumbing as
                    # the graft/tracking contract: the child's runtime
                    # spans parent under this gang's `execute` span.
                    env[obs_trace.ENV_TRACE_PARENT] = (
                        obs_trace.format_trace_parent(run_uuid,
                                                      gang.span.span_id))
                    for key, value in list(env.items()):
                        if isinstance(value, str) and COORDINATOR_PLACEHOLDER in value:
                            env[key] = value.replace(COORDINATOR_PLACEHOLDER, "127.0.0.1")
                    cmd = list(proc_spec.command) + list(proc_spec.args)
                    if not cmd:
                        raise RuntimeError("Process has no command")
                    if shutil.which(cmd[0]) is None and not os.path.exists(cmd[0]):
                        raise RuntimeError(
                            f"Command `{cmd[0]}` is not executable on this host "
                            f"(image `{proc_spec.image}` delegation needs a cluster provider)"
                        )
                    log_path = os.path.join(plan.artifacts_dir, "logs",
                                            f"main-{proc_spec.index}.log")
                    log_handle = open(log_path, "ab")
                    try:
                        proc = subprocess.Popen(
                            cmd, env=env, stdout=log_handle, stderr=subprocess.STDOUT,
                            cwd=proc_spec.working_dir or None, start_new_session=True,
                        )
                    except Exception:
                        log_handle.close()
                        raise
                    proc._plx_log_handle = log_handle  # closed in poll()
                    gang.procs.append(proc)
        except Exception as exc:
            # Kill any half-started gang members — a partial gang must not
            # keep running unowned (gang semantics: start together or not
            # at all).
            for proc in gang.procs:
                try:
                    proc.kill()
                except OSError:
                    pass
                handle = getattr(proc, "_plx_log_handle", None)
                if handle and not handle.closed:
                    handle.close()
            reason = ("InitTimeout" if isinstance(exc, InitTimeoutError)
                      else "StartError")
            self._finish_gang_span(gang, status="error",
                                   error=f"{reason}: {exc}")
            self.store.transition(run_uuid, V1Statuses.FAILED,
                                  reason=reason, message=str(exc)[:500])
            # A run that died in init gets its black box too.
            obs_flight.RECORDER.dump(run_uuid, plan.artifacts_dir,
                                     status=V1Statuses.FAILED.value,
                                     reason=reason, message=str(exc)[:500])
            return False
        self._gangs[run_uuid] = gang
        self.store.transition(run_uuid, V1Statuses.RUNNING)
        return True

    def _finish_gang_span(self, gang: _Gang, *, status: str = "ok",
                          error: Optional[str] = None, **attrs) -> None:
        """Close the gang's `execute` span + its writer handle (the
        EventWriter-close contract: a reaped gang pins no fds)."""
        if gang.tracer is None:
            return
        try:
            if gang.span is not None:
                gang.span.set(**attrs)
                gang.tracer.finish(gang.span, status=status, error=error)
        finally:
            gang.tracer.close()
            gang.tracer = gang.span = None

    def _can_run_in_process(self, plan: V1LaunchPlan) -> bool:
        return (
            plan.run_kind == "jaxjob"
            and plan.num_processes == 1
            and ENV_JAXJOB_SPEC in plan.processes[0].env
        )

    def _make_elastic(self, plan: V1LaunchPlan) -> Optional[
            elastic_mod.ElasticController]:
        """A resize channel for gangs that can actually survive one:
        jaxjob with checkpointing + restore-on-start (the segment
        boundary is a forced save and a cross-mesh restore)."""
        from polyaxon_tpu.polyflow.runs import V1JAXJob

        try:
            job = V1JAXJob.from_dict(
                json.loads(plan.processes[0].env[ENV_JAXJOB_SPEC]))
        except (KeyError, ValueError):
            return None
        if not elastic_mod.elastic_capable(job):
            return None
        try:
            prior = ((self.store.get_run(plan.run_uuid).meta or {})
                     .get("elastic") or {}).get("attempts")
        except KeyError:
            prior = None
        return elastic_mod.ElasticController(plan.run_uuid,
                                             prior_attempts=prior)

    def request_resize(self, run_uuid: str, direction: str, *,
                       reason: str = "",
                       target_devices: Optional[int] = None) -> bool:
        """File a resize against a live elastic gang. False means the
        gang cannot resize (no channel, budget exhausted, already
        resizing, dead thread) — callers fall back to :meth:`preempt`."""
        gang = self._gangs.get(run_uuid)
        if (gang is None or gang.elastic is None or gang.preempted
                or gang.thread is None or not gang.thread.is_alive()):
            return False
        granted = gang.elastic.request(direction, reason=reason,
                                       target_devices=target_devices)
        if granted and gang.span is not None:
            gang.span.add_event("resize_requested", direction=direction,
                                reason=reason)
        return granted

    def shrunk_elastic_runs(self) -> list[str]:
        """Live gangs currently training on a shrunk mesh — the set the
        agent offers a grow to when slice capacity returns."""
        return [uuid for uuid, gang in self._gangs.items()
                if gang.elastic is not None and gang.elastic.shrunk
                and not gang.preempted
                and gang.thread is not None and gang.thread.is_alive()]

    def _run_in_process(self, gang: _Gang) -> None:
        from polyaxon_tpu.polyflow.runs import V1JAXJob
        from polyaxon_tpu.runtime.loop import run_jaxjob
        from polyaxon_tpu.tracking.run import Run

        plan = gang.plan
        spec = json.loads(plan.processes[0].env[ENV_JAXJOB_SPEC])
        job = V1JAXJob.from_dict(spec)
        tracking = Run(plan.run_uuid, plan.artifacts_dir)
        # The runtime thread gets its OWN tracer (thread-owned writer
        # handle) parented under the gang's `execute` span — the same
        # shape the subprocess path gets via POLYAXON_TRACE_PARENT.
        tracer = obs_trace.RunTracer(
            plan.artifacts_dir, plan.run_uuid, component="runtime",
            parent_id=gang.span.span_id if gang.span is not None else None)
        ckpt_dir = os.path.join(plan.artifacts_dir, "checkpoints")

        def should_stop() -> bool:
            # Chaos gang seam for the in-process fast path: a thread
            # has no pid to SIGKILL, so a due kill-fault raises inside
            # the step loop — the same abrupt member death, observed
            # through the same FAILED reap. `preempted` stops the loop
            # too: an in-process gang has no process to kill, so the
            # preempt signal must reach the step loop itself.
            fault_plan = chaos.active_plan()
            if fault_plan is not None:
                fault_plan.maybe_kill_gang(plan.run_uuid, ckpt_dir)
                if gang.elastic is not None and not gang.elastic.resizing:
                    # Slice-loss seam, consulted per step so the drill
                    # is deterministic against checkpoint counts: "kill"
                    # files a shrink (denied → budget exhausted → plain
                    # preemption), "restore" files a grow. NOT consulted
                    # mid-resize: the request would be denied and the
                    # fired fault swallowed — the next step retries.
                    op = fault_plan.slice_loss_due(plan.run_uuid, ckpt_dir)
                    if op == "kill":
                        if not gang.elastic.request(
                                "shrink", reason="ChaosSliceLoss"):
                            gang.preempted = True
                    elif op == "restore":
                        gang.elastic.request(
                            "grow", reason="ChaosCapacityReturned")
            return gang.stop_event.is_set() or gang.preempted

        try:
            tracking.log_status(V1Statuses.RUNNING)
            if gang.elastic is not None:
                result = elastic_mod.run_elastic(
                    job, controller=gang.elastic,
                    artifacts_dir=plan.artifacts_dir,
                    on_metrics=tracking.log_metrics_cb(),
                    should_stop=should_stop, tracer=tracer)
            else:
                result = run_jaxjob(job, artifacts_dir=plan.artifacts_dir,
                                    on_metrics=tracking.log_metrics_cb(),
                                    should_stop=should_stop, tracer=tracer)
            if result.restore_skipped_steps:
                gang.warning = (
                    f"restored checkpoint step {result.restored_from_step} "
                    f"after skipping corrupt step(s) "
                    f"{result.restore_skipped_steps}")
            if result.restored_from_step is not None:
                gang.checkpoint_audit = {
                    "restored_from_step": result.restored_from_step,
                    "restore_tier": result.restore_tier,
                    **({"restore_skipped_steps":
                        result.restore_skipped_steps}
                       if result.restore_skipped_steps else {}),
                }
            tracking.log_outputs(
                steps=result.steps, throughput=result.throughput,
                wall_time=result.wall_time, param_count=result.param_count,
                # Same resume-audit field as the subprocess entrypoint
                # (runtime/launch.py): None means cold start.
                restored_from_step=result.restored_from_step,
                **({"restore_tier": result.restore_tier}
                   if result.restore_tier is not None else {}),
                **({"restore_skipped_steps": result.restore_skipped_steps}
                   if result.restore_skipped_steps else {}),
                **{f"final_{k}": v for k, v in result.final_metrics.items()},
            )
            if gang.stop_event.is_set():
                tracking.log_status(V1Statuses.STOPPED, reason="StopRequested")
            elif gang.preempted:
                pass  # the poll reap owns the PREEMPTED transition
            else:
                tracking.log_succeeded()
        except elastic_mod.ResizeAborted as exc:
            # A shrink that could not prewarm (or whose budget ran out)
            # degrades to the EXISTING preemption path: the poll reap
            # transitions PREEMPTED and the scheduler backoff-requeues.
            gang.preempted = True
            with open(os.path.join(plan.artifacts_dir, "logs", "main-0.log"), "a") as fh:
                fh.write(f"elastic resize aborted: {exc}\n")
        except Exception as exc:
            gang.thread_error = f"{type(exc).__name__}: {exc}"
            with open(os.path.join(plan.artifacts_dir, "logs", "main-0.log"), "a") as fh:
                fh.write(traceback.format_exc())
            tracking.log_failed(reason=type(exc).__name__, message=str(exc)[:2000])
        finally:
            tracer.close()
            tracking.close()
            gang.thread_done = True

    # ------------------------------------------------------------------ poll
    def poll(self) -> int:
        """Reap finished gangs → terminal statuses. Returns actions.

        Precedence is STOPPING > preempted > exit status: a gang whose
        run was asked to stop reaps STOPPED even if a preemption landed
        while it was dying (the operator's intent wins over weather).
        """
        fault_plan = chaos.active_plan()
        if fault_plan is not None:
            # Chaos gang seam for subprocess gangs: SIGKILL one member
            # of a due gang; the normal reap path must terminate the
            # survivors and fail the run with the signal code.
            for run_uuid, gang in list(self._gangs.items()):
                live = [p for p in gang.procs if p.poll() is None]
                ckpt_dir = os.path.join(gang.plan.artifacts_dir,
                                        "checkpoints")
                if live and fault_plan.gang_kill_due(run_uuid, ckpt_dir):
                    try:
                        live[0].kill()
                    except OSError:
                        pass
            # Chaos slice-loss seam for gangs WITHOUT a resize channel
            # (subprocess, or checkpointing off): losing a slice is a
            # plain preemption — the pre-elastic behavior, kept as the
            # degradation floor. Elastic gangs consult the seam from
            # their own step loop (deterministic against checkpoints).
            for run_uuid, gang in list(self._gangs.items()):
                if gang.elastic is not None:
                    continue
                ckpt_dir = os.path.join(gang.plan.artifacts_dir,
                                        "checkpoints")
                if fault_plan.slice_loss_due(run_uuid, ckpt_dir) == "kill":
                    self.preempt(run_uuid)
        actions = 0
        for run_uuid, gang in list(self._gangs.items()):
            # Mirror the resize audit into meta["elastic"] on every poll
            # while the gang is LIVE: the scheduler's resizing-hold and
            # the ops surfaces read the store, not the controller.
            self._flush_elastic(run_uuid, gang)
            self._flush_checkpoint(run_uuid, gang)
        for run_uuid, gang in list(self._gangs.items()):
            status = self._gang_status(gang)
            if status is None:
                continue
            del self._gangs[run_uuid]
            # Final audit flush: the thread may have finished an attempt
            # between the live flush above and its exit.
            self._flush_elastic(run_uuid, gang)
            self._flush_checkpoint(run_uuid, gang)
            record = self.store.get_run(run_uuid)
            if record.status == V1Statuses.STOPPING:
                self._finish_gang_span(gang, final="stopped")
                # polycheck: ignore[invariant-store-batch] -- exclusive per-gang reap branches: exactly one terminal write runs per gang (the WARNING+terminal pair below batches separately)
                self.store.transition(run_uuid, V1Statuses.STOPPED)
                obs_flight.RECORDER.discard(run_uuid)  # operator intent
            elif gang.preempted:
                self._finish_gang_span(gang, status="error",
                                       error="preempted", final="preempted")
                self.store.transition(run_uuid, V1Statuses.PREEMPTED,
                                      reason="SlicePreempted", force=True)
                # Preemption is a death the operator did not ask for:
                # dump the black box (the backoff requeue keeps the ring
                # alive, so a later fatal reap overwrites with more).
                obs_flight.RECORDER.dump(
                    run_uuid, gang.plan.artifacts_dir,
                    status=V1Statuses.PREEMPTED.value,
                    reason="SlicePreempted")
            else:
                target = V1Statuses.SUCCEEDED if status == 0 else V1Statuses.FAILED
                self._finish_gang_span(
                    gang, status="ok" if status == 0 else "error",
                    error=(None if status == 0 else
                           gang.thread_error or f"exit code {status}"),
                    final=target.value, exit_code=status)
                with self.store.transaction():
                    if gang.warning:
                        # Non-fatal anomaly (e.g. checkpoint fallback):
                        # pinned as a WARNING condition so operators see
                        # it without the run dying — committed with the
                        # terminal hop so a crash between them cannot
                        # strand the run live in WARNING.
                        self.store.transition(
                            run_uuid, V1Statuses.WARNING,
                            reason="CheckpointFallback",
                            message=gang.warning[:500], force=True)
                    self.store.transition(
                        run_uuid, target,
                        reason="Completed" if status == 0 else "ProcessFailed",
                        message=gang.thread_error or (None if status == 0
                                                      else f"exit code {status}"),
                    )
                if target == V1Statuses.FAILED:
                    # The reap that declared the run dead writes its
                    # postmortem: ring of recent spans/notes, metric
                    # deltas since gang start, and every log tail.
                    obs_flight.RECORDER.dump(
                        run_uuid, gang.plan.artifacts_dir,
                        status=target.value, reason="ProcessFailed",
                        message=gang.thread_error
                        or f"exit code {status}")
                else:
                    obs_flight.RECORDER.discard(run_uuid)
            actions += 1
        return actions

    def _flush_elastic(self, run_uuid: str, gang: _Gang) -> None:
        """Write the controller's audit into ``meta["elastic"]`` when it
        changed, and dump a postmortem for every newly FAILED resize
        attempt — a failed resize is evidence worth keeping on disk even
        when the run survives it (grow failures don't kill the run)."""
        if gang.elastic is None:
            return
        snap = gang.elastic.snapshot(consume_dirty=True)
        if snap is None:
            return
        try:
            record = self.store.get_run(run_uuid)
        except KeyError:
            return
        meta = dict(record.meta or {})
        meta["elastic"] = snap
        self.store.update_run(run_uuid, meta=meta)
        failed = sum(1 for a in snap["attempts"]
                     if a["outcome"] == "failed")
        if failed > gang.failed_resizes_dumped:
            gang.failed_resizes_dumped = failed
            last = next(a for a in reversed(snap["attempts"])
                        if a["outcome"] == "failed")
            obs_flight.RECORDER.dump(
                run_uuid, gang.plan.artifacts_dir,
                status=V1Statuses.RUNNING.value, reason="ResizeFailed",
                message=(f"{last['direction']} {last['from_devices']}→"
                         f"{last['to_devices']} devices: "
                         f"{last.get('error', '')}")[:500])

    def _flush_checkpoint(self, run_uuid: str, gang: _Gang) -> None:
        """Write the runtime's restore audit into ``meta["checkpoint"]``
        once it exists: ``restore_tier`` ("0" memory / "1" spill / "2"
        store) + ``restored_from_step`` (+ any culled steps), so `plx ops
        report` and the drills can assert WHERE a rerun resumed from."""
        if gang.checkpoint_audit is None or gang.checkpoint_flushed:
            return
        try:
            record = self.store.get_run(run_uuid)
        except KeyError:
            return
        meta = dict(record.meta or {})
        meta["checkpoint"] = dict(gang.checkpoint_audit)
        self.store.update_run(run_uuid, meta=meta)
        gang.checkpoint_flushed = True

    def _gang_status(self, gang: _Gang) -> Optional[int]:
        """None while running; else first nonzero exit code of the gang.

        Gang liveness: the moment any member exits nonzero, survivors are
        terminated (they would otherwise block on the dead coordinator
        forever) and the gang is reaped on a later poll once all exited.
        """
        if gang.thread is not None:
            if not gang.thread_done and gang.thread.is_alive():
                return None
            return 1 if gang.thread_error else 0
        codes = []
        running = []
        for proc in gang.procs:
            code = proc.poll()
            if code is None:
                running.append(proc)
            else:
                codes.append(code)
        if running:
            if not gang.reaping and any(c != 0 for c in codes):
                gang.reaping = True
                for proc in running:
                    try:
                        proc.terminate()
                    except OSError:
                        pass
            return None
        for proc in gang.procs:
            handle = getattr(proc, "_plx_log_handle", None)
            if handle and not handle.closed:
                handle.close()
        if not codes:
            return 1
        # Any nonzero (incl. negative signal codes) fails the gang.
        return next((c for c in codes if c != 0), 0)

    # ------------------------------------------------------------- stop/kill
    def stop(self, run_uuid: str) -> None:
        gang = self._gangs.get(run_uuid)
        if gang is None:
            return
        if gang.span is not None:
            gang.span.add_event("stop_requested")
        gang.stop_event.set()  # in-process runtime loop checks this per step
        if gang.thread is not None and gang.thread.is_alive():
            # Drain: the loop exits at the next step boundary; a
            # bounded join lets its final status/checkpoint writes land
            # before teardown (daemon threads die mid-write at exit).
            gang.thread.join(timeout=30)
        for proc in gang.procs:
            try:
                proc.terminate()
            except OSError:
                pass

    def preempt(self, run_uuid: str) -> bool:
        """Simulate slice preemption (fault-injection hook — SURVEY §5.3:
        test-only in the fake provider; real eviction signals map here)."""
        gang = self._gangs.get(run_uuid)
        if gang is None:
            return False
        if gang.span is not None:
            gang.span.add_event("preempt")
        gang.preempted = True
        for proc in gang.procs:
            try:
                proc.kill()
            except OSError:
                pass
        return True

    @property
    def active_runs(self) -> list[str]:
        return list(self._gangs)
