"""The JAXJob training loop: mesh → data → compiled step → metrics/
checkpoints. Single code path from the 1-chip emulator to multi-host
slices (only the mesh and the env contract change — SURVEY.md §7 step 2).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Optional

import contextlib

import jax
import numpy as np

from polyaxon_tpu.models import get_model
from polyaxon_tpu.obs import flight as obs_flight
from polyaxon_tpu.obs import metrics as obs_metrics
from polyaxon_tpu.obs import trace as obs_trace
from polyaxon_tpu.parallel import build_mesh, rules_for_mesh
from polyaxon_tpu.parallel.sharding import param_bytes
from polyaxon_tpu.polyflow.runs import V1JAXJob, V1JaxCheckpointing
from polyaxon_tpu.runtime import data as data_lib
from polyaxon_tpu.runtime.checkpoint import (CheckpointManager,
                                             TieredCheckpointManager)
from polyaxon_tpu.runtime.config import RuntimeConfig
from polyaxon_tpu.runtime.optim import build_optimizer
from polyaxon_tpu.runtime.step import build_eval_step, build_init, build_train_step

logger = logging.getLogger(__name__)

MetricsCallback = Callable[[int, dict[str, float]], None]


@dataclasses.dataclass
class TrainResult:
    steps: int
    final_metrics: dict[str, float]
    throughput: float  # units/sec (tokens or examples)
    unit: str
    units_per_step: int
    wall_time: float
    param_count: int
    restored_from_step: Optional[int] = None
    # Checkpoint steps whose bytes were skipped as corrupt during the
    # restore that produced restored_from_step (newest first; empty on
    # a clean restore or cold start).
    restore_skipped_steps: list[int] = dataclasses.field(default_factory=list)
    # Tier that satisfied the restore ("0" in-memory replica, "1" local
    # spill, "2" store); None on a cold start.
    restore_tier: Optional[str] = None
    # Host time blocked on `next(batches)`, averaged per timed step —
    # ~0 when the prefetcher keeps up, ≈ generation+transfer time when
    # the input pipeline is the bottleneck.
    input_wait_ms: float = 0.0
    # Wall time of the warm-up train_step dispatch+completion (XLA
    # compile dominates); drops to executable-load time on a
    # persistent-compile-cache hit.
    compile_time_s: float = 0.0


def _model_config_cls(model_name: str):
    from polyaxon_tpu.models import bert, llama, mnist, moe, resnet, t5, vit

    for mod in (llama, moe, vit, bert, resnet, mnist, t5):
        if model_name in mod.CONFIGS:
            return type(mod.CONFIGS[model_name])
    raise ValueError(f"Unknown model `{model_name}`")


def _dataset_kwargs(cfg: RuntimeConfig, model_cfg, per_host_batch: int) -> dict:
    kwargs: dict[str, Any] = {"batch_size": per_host_batch, "seed": cfg.seed}
    extras = dict(cfg.__pydantic_extra__ or {})
    for key in ("path", "tokenizer", "image_size", "num_classes",
                "mask_rate"):
        if key in extras:
            kwargs[key] = extras[key]
    if cfg.seq_len:
        kwargs["seq_len"] = cfg.seq_len
    elif hasattr(model_cfg, "max_seq_len"):
        kwargs["seq_len"] = min(model_cfg.max_seq_len, 2048)
    if hasattr(model_cfg, "vocab_size"):
        kwargs["vocab_size"] = model_cfg.vocab_size
    if hasattr(model_cfg, "image_size") and "image_size" not in kwargs:
        kwargs["image_size"] = model_cfg.image_size
    if hasattr(model_cfg, "num_classes") and "num_classes" not in kwargs:
        kwargs["num_classes"] = model_cfg.num_classes
    return kwargs


def _span(tracer: Optional["obs_trace.RunTracer"], name: str, **attrs):
    """Span when tracing is on, nullcontext (yielding None) when off —
    keeps every instrumentation site a one-line `with`."""
    if tracer is None:
        return contextlib.nullcontext()
    return tracer.span(name, attributes=attrs or None)


def run_jaxjob(
    job: V1JAXJob,
    *,
    artifacts_dir: Optional[str] = None,
    on_metrics: Optional[MetricsCallback] = None,
    devices: Optional[list] = None,
    mesh_axes: Optional[dict[str, int]] = None,
    should_stop: Optional[Callable[[], bool]] = None,
    tracer: Optional[obs_trace.RunTracer] = None,
) -> TrainResult:
    """Execute a builtin-runtime JAXJob in-process.

    Lifecycle tracing: with an ``artifacts_dir`` the loop emits
    runtime/jit_compile/restore/step/checkpoint/eval spans. An explicit
    ``tracer`` (the in-process executor passes one parented under its
    `execute` span) is used as-is and left open for its owner; without
    one a tracer is built from the env contract (the subprocess path —
    the executor stamps ``POLYAXON_TRACE_PARENT``) and closed by the
    loop's ExitStack.
    """
    if not job.runtime:
        raise ValueError("run_jaxjob requires a jaxjob with a `runtime` section")
    cfg = RuntimeConfig.model_validate(job.runtime)

    close_tracer = False
    if tracer is None and artifacts_dir:
        tracer = obs_trace.RunTracer.from_env(artifacts_dir,
                                              component="runtime")
        close_tracer = True

    from polyaxon_tpu.runtime import compile_cache

    with compile_cache.compilation_cache(
            compile_cache.resolve_cache_dir(cfg.compile_cache_dir)):
        return _run_jaxjob(job, cfg, artifacts_dir=artifacts_dir,
                           on_metrics=on_metrics, devices=devices,
                           mesh_axes=mesh_axes,
                           should_stop=should_stop, tracer=tracer,
                           close_tracer=close_tracer)


def _run_jaxjob(
    job: V1JAXJob,
    cfg: RuntimeConfig,
    *,
    artifacts_dir: Optional[str],
    on_metrics: Optional[MetricsCallback],
    devices: Optional[list],
    should_stop: Optional[Callable[[], bool]],
    mesh_axes: Optional[dict[str, int]] = None,
    tracer: Optional[obs_trace.RunTracer] = None,
    close_tracer: bool = False,
) -> TrainResult:
    # An explicit `mesh_axes` overrides the spec's resolved axes — the
    # elastic resize path compiles the SAME job for a shrunk/regrown
    # device subset whose axis product no longer matches the spec.
    mesh = build_mesh(job.mesh, job.get_topology(), devices=devices,
                      axes=mesh_axes)
    rules = rules_for_mesh(mesh)
    logger.info("mesh axes=%s devices=%d", dict(zip(mesh.axis_names, mesh.devices.shape)),
                mesh.devices.size)

    config_cls = _model_config_cls(cfg.model)
    overrides = cfg.model_overrides(config_cls)
    model_def = get_model(cfg.model, **overrides)
    model_cfg = dataclasses.replace(_get_cfg(cfg.model), **overrides)

    n_devices = mesh.devices.size
    if cfg.global_batch_size:
        global_batch = cfg.global_batch_size
    else:
        global_batch = (cfg.batch_size or 8) * n_devices
    if global_batch % jax.process_count():
        raise ValueError(
            f"global batch {global_batch} must divide process count {jax.process_count()}"
        )
    per_host_batch = global_batch // jax.process_count()

    dataset_name = cfg.dataset or data_lib.dataset_for_model(cfg.model)
    ds_kwargs = _dataset_kwargs(cfg, model_cfg, per_host_batch)

    optimizer = build_optimizer(cfg)
    if cfg.lora_rank:
        from polyaxon_tpu.models.lora import lora_model_def, wrap_optimizer

        model_def = lora_model_def(model_def, cfg.lora_rank,
                                   cfg.lora_alpha,
                                   cfg.lora_targets)
        optimizer = wrap_optimizer(optimizer)
        logger.info("lora: rank=%d alpha=%s targets=%s", cfg.lora_rank,
                    cfg.lora_alpha, cfg.lora_targets or "default")

    # The prefetch producer registers its close() here: stop, drain,
    # join on EVERY exit — normal completion, should_stop, or a raise
    # anywhere in the loop — so no thread outlives its run. The tracer's
    # EventWriter rides the same stack when this loop owns it.
    with mesh, contextlib.ExitStack() as cleanup:
        run_span = None
        if tracer is not None:
            if close_tracer:
                cleanup.callback(tracer.close)
            run_span = cleanup.enter_context(tracer.span(
                "runtime", attributes={"model": cfg.model,
                                       "steps": cfg.steps,
                                       "devices": mesh.devices.size}))
        init_fn = build_init(model_def, optimizer, mesh, rules)
        # polycheck: ignore[hotpath-host-sync] -- config scalar from the job spec, not a device value; one-shot setup before the loop
        accum = max(int(cfg.grad_accum_steps or 1), 1)
        if accum > 1:
            if global_batch % accum:
                raise ValueError(
                    f"grad_accum_steps {accum} must divide the global "
                    f"batch {global_batch}")
            from polyaxon_tpu.parallel.sharding import batch_spec

            spec = batch_spec(mesh, rules)
            batch_axes = spec[0] if len(spec) else None
            if isinstance(batch_axes, str):
                batch_axes = (batch_axes,)
            sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
            shards = 1
            for axis in batch_axes or ():
                shards *= sizes[axis]
            if (global_batch // accum) % max(shards, 1):
                raise ValueError(
                    f"microbatch {global_batch // accum} (global batch "
                    f"{global_batch} / grad_accum_steps {accum}) must stay "
                    f"divisible by the {shards}-way batch sharding")
        train_step = build_train_step(model_def, optimizer, mesh, rules,
                                      accum_steps=accum)

        rng = jax.random.key(cfg.seed)
        state = init_fn(rng)
        n_params = sum(x.size for x in jax.tree.leaves(state["params"]))
        logger.info("model=%s params=%.2fM bytes=%.1fMB", cfg.model, n_params / 1e6,
                    param_bytes(state["params"]) / 1e6)

        ckpt: Optional[CheckpointManager] = None
        restored_from = None
        restore_skipped: list[int] = []
        restore_tier: Optional[str] = None
        ckpt_spec = job.checkpointing or V1JaxCheckpointing(enabled=False)
        if artifacts_dir and ckpt_spec.enabled:
            ckpt = TieredCheckpointManager(f"{artifacts_dir}/checkpoints",
                                           ckpt_spec)
            if ckpt_spec.restore_on_start and ckpt.latest_step() is not None:
                with _span(tracer, "restore") as sp:
                    state = ckpt.restore(state)
                    restored_from = int(state["step"])
                    restore_skipped = list(ckpt.last_restore_skipped)
                    restore_tier = ckpt.last_restore_tier
                    if sp is not None:
                        sp.set(restored_from_step=restored_from,
                               skipped_steps=restore_skipped,
                               restore_tier=restore_tier)

        seq = ds_kwargs.get("seq_len", 1)
        units_per_step = global_batch * (seq if model_def.unit == "tokens" else 1)

        start_step = int(state["step"])
        if start_step >= cfg.steps:
            if ckpt:
                ckpt.close()
            return TrainResult(
                steps=start_step,
                final_metrics={},
                throughput=0.0,
                unit=model_def.unit,
                units_per_step=0,
                wall_time=0.0,
                # polycheck: ignore[hotpath-host-sync] -- n_params is a host-side sum of static leaf sizes; no device sync
                param_count=int(n_params),
                restored_from_step=restored_from,
                restore_skipped_steps=restore_skipped,
                restore_tier=restore_tier,
            )
        # Data streams are index-addressable (batch i = f(seed, i)), so a
        # restored run resumes the stream at its step instead of replaying
        # from batch 0 — the iterator is built only after restore (which
        # also makes prefetch resume-exact for free: batches that were
        # prefetched but unconsumed at interrupt are simply regenerated).
        host_iter = data_lib.get_dataset(dataset_name, start_batch=start_step,
                                         **ds_kwargs)
        batches = data_lib.shard_batches(host_iter, mesh, rules)
        prefetcher: Optional[data_lib.PrefetchIterator] = None
        if cfg.prefetch > 0:
            # Overlap the host with the device: batch i+k generates and
            # commits to its NamedSharding on a background thread while
            # the device runs step i.
            batches = prefetcher = data_lib.PrefetchIterator(
                batches, depth=cfg.prefetch)
            cleanup.callback(prefetcher.close)
        # Periodic held-out evaluation: a FIXED batch set drawn from the
        # same dataset family at a disjoint seed (or from `eval_path`
        # when given — e.g. a separate validation corpus for lm_text),
        # so every eval scores the same data and curves are comparable.
        eval_step = run_eval = None
        if cfg.eval_every:
            eval_step = build_eval_step(model_def)
            eval_kwargs = dict(ds_kwargs)
            extras = dict(cfg.__pydantic_extra__ or {})
            if extras.get("eval_path"):
                eval_kwargs["path"] = extras["eval_path"]
            eval_kwargs["seed"] = cfg.seed + 104_729  # disjoint stream
            eval_kwargs["start_batch"] = 0
            n_eval = max(cfg.eval_steps, 1)
            # Materialize the fixed batch set ONCE: rebuilding the
            # dataset pipeline per eval would re-pay its construction
            # cost (e.g. lm_text's corpus mmap + vocab scan) at every
            # cadence point.
            _eval_iter = data_lib.shard_batches(
                data_lib.get_dataset(dataset_name, **eval_kwargs),
                mesh, rules)
            eval_batches = [next(_eval_iter) for _ in range(n_eval)]
            del _eval_iter

            def run_eval(state) -> dict[str, float]:
                sums: dict[str, float] = {}
                for batch in eval_batches:
                    for k, v in eval_step(state, batch).items():
                        sums[k] = sums.get(k, 0.0) + float(v)
                return {f"eval_{k}": v / n_eval for k, v in sums.items()}

        final_metrics: dict[str, float] = {}
        last_eval: dict[str, float] = {}
        evaled_at = -1  # state["step"] value the last eval scored
        step_rng = jax.random.key(cfg.seed + 17)
        # Warm up compile outside the timed window; the dispatch-to-
        # ready wall of this first step IS the compile cost (execution
        # of one step rides along, noise next to XLA), emitted as
        # compile_time_s so cache-hit restarts are attributable.
        first_batch = next(batches)
        with _span(tracer, "jit_compile") as sp:
            t_compile = time.perf_counter()
            state, metrics = train_step(state, first_batch, step_rng)
            # polycheck: ignore[hotpath-host-sync] -- deliberate: the dispatch-to-ready wall of this first step IS the measured compile cost
            jax.block_until_ready(metrics["loss"])
            compile_time_s = time.perf_counter() - t_compile
            if sp is not None:
                sp.set(compile_time_s=round(compile_time_s, 3))

        # Per-step MFU self-reporting (SURVEY §5.1): every emission
        # carries tokens/sec + achieved TFLOPs/chip, and MFU when both
        # the analytic FLOPs/token and the chip's peak are known
        # (CPU mesh → flops known, peak unknown → mfu omitted).
        from polyaxon_tpu.runtime.flops import peak_flops, train_flops_per_token

        n_chips = int(mesh.devices.size)
        # polycheck: ignore[hotpath-host-sync] -- n_params is a host-side sum of static leaf sizes; one-shot setup before the loop
        flops_unit = (train_flops_per_token(cfg.model, seq, int(n_params))
                      if model_def.unit == "tokens" else None)
        peak = peak_flops(getattr(jax.devices()[0], "device_kind", ""))
        t_emit = time.perf_counter()
        # polycheck: ignore[hotpath-wallclock] -- observability timestamp: span wall-clock twin of t_emit; never feeds training state or replay
        t_emit_wall = time.time()  # wall twin of t_emit for step spans
        # The warm-up step above consumed batch `start_step` and
        # advanced the state — it is a REAL training step, so the first
        # emission window starts at 1, making step windows contiguous
        # from `start_step` across restore/resize segment boundaries
        # (the oracle's loss_continuity invariant reads these windows).
        steps_since_emit = 1
        emitted_compile = False
        wait_window = 0.0  # host seconds blocked on data, per emission
        wait_total = 0.0   # ... over all timed steps

        t0 = time.perf_counter()
        timed_steps = 0
        off_clock = 0.0  # eval + sync-checkpoint seconds, excluded
        for step in range(start_step + 1, cfg.steps):
            if should_stop is not None and should_stop():
                logger.info("stop requested at step %d", step)
                break
            profiling = cfg.profile_steps and step in cfg.profile_steps and artifacts_dir
            if profiling:
                jax.profiler.start_trace(f"{artifacts_dir}/profile")
            t_wait = time.perf_counter()
            batch = next(batches)
            dt_wait = time.perf_counter() - t_wait
            wait_window += dt_wait
            wait_total += dt_wait
            state, metrics = train_step(state, batch, step_rng)
            timed_steps += 1
            steps_since_emit += 1
            if profiling:
                # polycheck: ignore[hotpath-host-sync] -- deliberate: bound the profiler trace at a completed step; profiled steps are off the timed window
                jax.block_until_ready(metrics["loss"])
                jax.profiler.stop_trace()
            if on_metrics and (step % cfg.log_every == 0 or step == cfg.steps - 1):
                # polycheck: ignore[hotpath-host-sync] -- deliberate emission-window materialization at log_every cadence, off the per-step path
                vals = {k: float(v) for k, v in metrics.items()}
                # Rolling window since the last emission; block so the
                # window covers completed device work, not dispatch.
                # polycheck: ignore[hotpath-host-sync] -- deliberate emission-window sync (see comment above): throughput must cover completed device work
                jax.block_until_ready(metrics["loss"])
                window = time.perf_counter() - t_emit
                if window > 0 and steps_since_emit:
                    ups = units_per_step * steps_since_emit / window
                    vals[f"{model_def.unit}_per_sec"] = ups
                    vals["step_time_ms"] = 1e3 * window / steps_since_emit
                    # Host time blocked on next(batches), per step:
                    # ~0 when prefetch keeps up; ≈ generation+transfer
                    # when the input pipeline is the bottleneck.
                    vals["input_wait_ms"] = (1e3 * wait_window
                                             / steps_since_emit)
                    if flops_unit:
                        achieved = ups * flops_unit / n_chips
                        vals["tflops_per_sec_per_chip"] = achieved / 1e12
                        if peak:
                            vals["mfu"] = achieved / peak
                if not emitted_compile:
                    # One-shot: the warm-up compile wall, so a metric
                    # stream can attribute a cheap restart to the
                    # persistent compile cache.
                    vals["compile_time_s"] = compile_time_s
                    emitted_compile = True
                # The emission window is one `step` span on the
                # timeline (reusing the already-derived step_time_ms /
                # input_wait_ms) and one histogram sample — per-window,
                # not per-step, so tracing cost stays off the hot path.
                if steps_since_emit and window > 0:
                    obs_metrics.training_step_hist().observe(
                        window / steps_since_emit)
                if tracer is not None and steps_since_emit:
                    tracer.record_completed(
                        # polycheck: ignore[hotpath-wallclock] -- observability timestamp: span end on the wall-clock timeline, per-window not per-step
                        "step", start=t_emit_wall, end=time.time(),
                        parent_id=(run_span.span_id if run_span is not None
                                   else None),
                        attributes={
                            "from_step": step - steps_since_emit + 1,
                            "to_step": step,
                            "steps": steps_since_emit,
                            **{k: round(vals[k], 3) for k in
                               ("step_time_ms", "input_wait_ms", "loss")
                               if k in vals},
                        })
                steps_since_emit = 0
                wait_window = 0.0
                if tracer is not None:
                    # The flight ring keeps the last emissions a dying
                    # run saw — the postmortem's "final instruments".
                    obs_flight.RECORDER.note(
                        tracer.trace_id, "metrics", step=step,
                        # polycheck: ignore[hotpath-host-sync] -- vals already holds host floats (materialized at the emission sync above); no new device sync
                        **{k: round(float(v), 5) for k, v in vals.items()})
                on_metrics(step, vals)
                # Stamp AFTER the callback: tracking I/O must not
                # deflate the next window's reported throughput.
                t_emit = time.perf_counter()
                # polycheck: ignore[hotpath-wallclock] -- observability timestamp: re-stamp the span wall twin after tracking I/O
                t_emit_wall = time.time()
            if eval_step is not None and step % cfg.eval_every == 0:
                # Drain queued train dispatches BEFORE stamping the
                # exclusion window, or their device time would be
                # charged to eval and inflate reported throughput/MFU.
                # polycheck: ignore[hotpath-host-sync] -- deliberate: drain queued train dispatches so their device time is not charged to eval (see comment above)
                jax.block_until_ready(metrics["loss"])
                t_eval = time.perf_counter()
                with _span(tracer, "eval", step=step):
                    last_eval = run_eval(state)
                evaled_at = int(state["step"])
                if on_metrics:
                    on_metrics(step, last_eval)
                # Off the training clock, like checkpoint saves — for
                # both the per-emission window AND the run-level wall.
                dt_eval = time.perf_counter() - t_eval
                t_emit += dt_eval
                # polycheck: ignore[hotpath-wallclock] -- observability timestamp: restart the span wall twin after the eval exclusion window
                t_emit_wall = time.time()
                off_clock += dt_eval
            if ckpt and ckpt.should_save(step):
                t_save = time.perf_counter()
                with _span(tracer, "checkpoint", step=step):
                    ckpt.save(step, state)
                # Exclude (synchronous) checkpoint time too — an MFU
                # dip every save interval would make real regressions
                # indistinguishable from checkpoint cadence.
                dt_save = time.perf_counter() - t_save
                t_emit += dt_save
                # polycheck: ignore[hotpath-wallclock] -- observability timestamp: restart the span wall twin after the checkpoint exclusion window
                t_emit_wall = time.time()
                off_clock += dt_save
        # polycheck: ignore[hotpath-host-sync] -- deliberate end-of-run drain: the wall stamp below must cover all device work
        jax.block_until_ready(state["params"])
        # Run-level throughput matches the emitted stream: eval and
        # sync-save time are off the training clock in both.
        wall = time.perf_counter() - t0 - off_clock
        # polycheck: ignore[hotpath-host-sync] -- post-loop materialization of the final metrics; the loop is over
        final_metrics = {k: float(v) for k, v in metrics.items()}
        if eval_step is not None:
            # Outputs always carry an eval of the FINISHED params; skip
            # the extra pass (and the duplicate metric point) when the
            # cadence already scored them.
            if evaled_at != int(state["step"]):
                last_eval = run_eval(state)
                if on_metrics:
                    on_metrics(max(int(state["step"]) - 1, 0), last_eval)
            final_metrics.update(last_eval)
        final_step = int(state["step"])

        # Flush the partial un-emitted window (an early stop — resize,
        # preemption, stop request — lands between emissions): without
        # this span the last `steps_since_emit` trained steps would be
        # a gap in the step-window stream and loss_continuity could not
        # hold across a resize boundary.
        if tracer is not None and steps_since_emit:
            window = time.perf_counter() - t_emit
            flush_to = final_step - 1
            attrs = {
                "from_step": flush_to - steps_since_emit + 1,
                "to_step": flush_to,
                "steps": steps_since_emit,
            }
            if window > 0:
                attrs["step_time_ms"] = round(
                    1e3 * window / steps_since_emit, 3)
                attrs["input_wait_ms"] = round(
                    1e3 * wait_window / steps_since_emit, 3)
                obs_metrics.training_step_hist().observe(
                    window / steps_since_emit)
            if "loss" in final_metrics:
                attrs["loss"] = round(final_metrics["loss"], 3)
            tracer.record_completed(
                # polycheck: ignore[hotpath-wallclock] -- observability timestamp: one span end after the loop has exited
                "step", start=t_emit_wall, end=time.time(),
                parent_id=(run_span.span_id if run_span is not None
                           else None),
                attributes=attrs)

        if ckpt:
            with _span(tracer, "checkpoint", step=final_step, final=True):
                ckpt.save(final_step, state, force=True)
            ckpt.close()

    throughput = units_per_step * timed_steps / wall if wall > 0 and timed_steps else 0.0
    return TrainResult(
        steps=final_step,
        final_metrics=final_metrics,
        throughput=throughput,
        unit=model_def.unit,
        units_per_step=units_per_step,
        wall_time=wall,
        # polycheck: ignore[hotpath-host-sync] -- n_params is a host-side sum of static leaf sizes; no device sync
        param_count=int(n_params),
        restored_from_step=restored_from,
        restore_skipped_steps=restore_skipped,
        restore_tier=restore_tier,
        input_wait_ms=1e3 * wait_total / timed_steps if timed_steps else 0.0,
        compile_time_s=compile_time_s,
    )


def _get_cfg(model_name: str):
    from polyaxon_tpu.models import bert, llama, mnist, moe, resnet, t5, vit

    for mod in (llama, moe, vit, bert, resnet, mnist, t5):
        if model_name in mod.CONFIGS:
            return mod.CONFIGS[model_name]
    raise ValueError(f"Unknown model `{model_name}`")
