"""Process entrypoint for compiled JAXJob runs.

The launch plan sets ``POLYAXON_JAXJOB_SPEC`` + the tracking/bootstrap
env contract; every gang process runs ``python -m
polyaxon_tpu.runtime.launch`` (SURVEY.md §3.3 in-pod stack, with the
main-process half owned by the framework instead of user code).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import traceback

from polyaxon_tpu.compiler.compile import ENV_JAXJOB_SPEC
from polyaxon_tpu.lifecycle import V1Statuses
from polyaxon_tpu.parallel import bootstrap
from polyaxon_tpu.polyflow.runs import V1JAXJob
from polyaxon_tpu.runtime.loop import run_jaxjob
from polyaxon_tpu.tracking.run import ENV_ARTIFACTS_PATH, ENV_RUN_UUID, Run

logger = logging.getLogger(__name__)


def main() -> int:
    # force=True: the module imports above pull in jax, whose absl
    # bridge may already have attached a root handler — without force,
    # basicConfig is a silent no-op and root stays at WARNING, so no
    # framework INFO line (mesh shape, bootstrap, step logs) ever
    # reaches the gang's log files.
    logging.basicConfig(
        level=os.environ.get("POLYAXON_LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        force=True,
    )
    from polyaxon_tpu.parallel import overlap
    from polyaxon_tpu.utils import apply_jax_platforms_override

    apply_jax_platforms_override()
    # Pin the latency-hiding scheduler before the backend initializes
    # (bootstrap.initialize below) so collective overlap — and with it
    # the budgeted overlap_ratio floors — cannot silently regress with
    # a libtpu default flip. No-op off-TPU (parallel/overlap.py).
    overlap.pin_runtime_flags()
    spec_json = os.environ.get(ENV_JAXJOB_SPEC)
    if not spec_json:
        print(f"{ENV_JAXJOB_SPEC} is not set", file=sys.stderr)
        return 2
    job = V1JAXJob.from_dict(json.loads(spec_json))

    run_uuid = os.environ.get(ENV_RUN_UUID, "local")
    artifacts_dir = os.environ.get(ENV_ARTIFACTS_PATH) or os.path.join(
        os.getcwd(), ".plx-runs", run_uuid
    )
    os.makedirs(artifacts_dir, exist_ok=True)

    group = bootstrap.initialize()
    is_lead = group.process_id == 0

    tracking = None
    if is_lead:
        tracking = Run(run_uuid, artifacts_dir, collect_system_metrics=True)
        tracking.log_status(V1Statuses.RUNNING)

    try:
        result = run_jaxjob(
            job,
            artifacts_dir=artifacts_dir,
            on_metrics=(tracking.log_metrics_cb() if tracking else None),
        )
        if tracking:
            tracking.log_outputs(
                steps=result.steps,
                throughput=result.throughput,
                throughput_unit=f"{result.unit}/sec",
                wall_time=result.wall_time,
                param_count=result.param_count,
                # Preemption-requeue proof: a requeued attempt reports
                # where its checkpoint restore landed (None → cold
                # start), so the plane can audit that resume actually
                # resumed instead of silently burning the budget from
                # step 0 (SURVEY §5.4).
                restored_from_step=result.restored_from_step,
                **({"restore_skipped_steps": result.restore_skipped_steps}
                   if result.restore_skipped_steps else {}),
                **{f"final_{k}": v for k, v in result.final_metrics.items()},
            )
            tracking.log_succeeded()
        return 0
    except Exception as exc:
        traceback.print_exc()
        if tracking:
            tracking.log_failed(reason=type(exc).__name__, message=str(exc)[:2000])
        return 1
    finally:
        if tracking:
            tracking.close()


if __name__ == "__main__":
    sys.exit(main())
