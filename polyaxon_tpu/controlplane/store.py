"""SQLite-backed run/project store — the reference's haupt DB collapsed
to an embedded, dependency-free layer (SURVEY.md §2 "API server" [K],
§7: "control plane + scheduler, single binary, SQLite").

WAL mode so the scheduler/agent threads and CLI reads interleave safely.

Hot-path design (ISSUE 8, sized by the fleet simulator in
``polyaxon_tpu/sim``):

- ``RunRecord`` is a lazy row view: the JSON columns (``spec``,
  ``resolved_spec``, ``launch_plan``, ``params``, ``tags``, ``meta``)
  decode on first attribute access and cache. A 10k-deep queue scan
  that only reads ``uuid``/``status``/``kind`` never pays ~0.1 ms/row
  of deserialization.
- ``scan_runs`` folds the scheduler's per-tick status scans into ONE
  query (optionally kind-filtered per partition, so non-pipeline
  QUEUED/RUNNING rows are never even fetched); ``list_run_uuids`` is
  the key-only projection for terminal sweeps that diff against
  in-memory sets before touching any payload.
- ``transaction()`` batches every write inside the block into a single
  commit (one WAL fsync per tick instead of one per transition).
- ``add_transition_listener`` is the admission controller's delta feed:
  each status change is pushed to subscribers so the live view updates
  incrementally instead of being rebuilt O(live+queued) every pass.
- every connection is wrapped in a counting proxy: ``stats`` exposes
  per-store query/row counts (the sim budget gate and the query-count
  regression test read these) and each statement's latency lands in the
  ``polyaxon_runstore_op_seconds`` histogram.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import sqlite3
import threading
import time
import uuid as _uuid
from typing import Any, Callable, Optional, Sequence

from polyaxon_tpu.lifecycle import V1Statuses, can_transition, now

logger = logging.getLogger(__name__)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS projects (
    name TEXT PRIMARY KEY,
    description TEXT,
    created_at TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS runs (
    uuid TEXT PRIMARY KEY,
    project TEXT NOT NULL,
    name TEXT,
    description TEXT,
    kind TEXT,
    managed_by TEXT DEFAULT 'agent',
    status TEXT NOT NULL,
    spec TEXT,
    resolved_spec TEXT,
    launch_plan TEXT,
    params TEXT,
    tags TEXT,
    meta TEXT,
    parent_uuid TEXT,
    pipeline_uuid TEXT,
    iteration INTEGER,
    retries INTEGER DEFAULT 0,
    created_at TEXT NOT NULL,
    updated_at TEXT NOT NULL,
    started_at TEXT,
    finished_at TEXT
);
CREATE INDEX IF NOT EXISTS idx_runs_status ON runs(status);
CREATE INDEX IF NOT EXISTS idx_runs_project ON runs(project);
CREATE INDEX IF NOT EXISTS idx_runs_pipeline ON runs(pipeline_uuid);
-- Composite index for the list_runs hot path: status equality then the
-- (created_at, rowid) order — rowid is the implicit last index column,
-- so the PR 2 same-second tie-break is served straight off the index
-- with no sort step (asserted by a query-plan test).
CREATE INDEX IF NOT EXISTS idx_runs_status_created
    ON runs(status, created_at);
CREATE TABLE IF NOT EXISTS conditions (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    run_uuid TEXT NOT NULL,
    type TEXT NOT NULL,
    reason TEXT,
    message TEXT,
    created_at TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_conditions_run ON conditions(run_uuid);
CREATE TABLE IF NOT EXISTS queues (
    name TEXT PRIMARY KEY,
    priority INTEGER NOT NULL DEFAULT 0,
    concurrency INTEGER,
    preemptible INTEGER NOT NULL DEFAULT 0,
    description TEXT,
    created_at TEXT NOT NULL,
    updated_at TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS quotas (
    project TEXT PRIMARY KEY,
    max_runs INTEGER,
    max_chips INTEGER,
    weight REAL NOT NULL DEFAULT 1.0,
    created_at TEXT NOT NULL,
    updated_at TEXT NOT NULL
);
"""

_JSON_COLUMNS = ("spec", "resolved_spec", "launch_plan", "params",
                 "tags", "meta")


def _loads(text: Optional[str]):
    return json.loads(text) if text else None


class RunRecord:
    """One ``runs`` row. JSON columns decode lazily on first access —
    most scans only touch ``uuid``/``status``/``kind``/timestamps and
    never pay for the (large) serialized spec."""

    __slots__ = ("uuid", "project", "name", "description", "kind",
                 "managed_by", "cache_key", "status", "parent_uuid",
                 "pipeline_uuid", "iteration", "retries", "created_at",
                 "updated_at", "started_at", "finished_at",
                 "_raw", "_decoded")

    def __init__(self, *, uuid: str, project: str, name: Optional[str],
                 kind: Optional[str], status: V1Statuses,
                 parent_uuid: Optional[str], pipeline_uuid: Optional[str],
                 iteration: Optional[int], retries: int, created_at: str,
                 updated_at: str, started_at: Optional[str],
                 finished_at: Optional[str], description: Optional[str] = None,
                 managed_by: str = "agent", cache_key: Optional[str] = None,
                 raw_json: Optional[dict] = None):
        self.uuid = uuid
        self.project = project
        self.name = name
        self.description = description
        self.kind = kind
        self.managed_by = managed_by
        self.cache_key = cache_key
        self.status = status
        self.parent_uuid = parent_uuid
        self.pipeline_uuid = pipeline_uuid
        self.iteration = iteration
        self.retries = retries
        self.created_at = created_at
        self.updated_at = updated_at
        self.started_at = started_at
        self.finished_at = finished_at
        self._raw = raw_json or {}
        self._decoded: dict[str, Any] = {}

    def _json_field(self, field: str):
        try:
            return self._decoded[field]
        except KeyError:
            pass
        value = _loads(self._raw.get(field))
        if value is None:
            if field == "tags":
                value = []
            elif field == "meta":
                value = {}
        self._decoded[field] = value
        return value

    @property
    def spec(self) -> Optional[dict]:
        return self._json_field("spec")

    @property
    def resolved_spec(self) -> Optional[dict]:
        return self._json_field("resolved_spec")

    @property
    def launch_plan(self) -> Optional[dict]:
        return self._json_field("launch_plan")

    @property
    def params(self) -> Optional[dict]:
        return self._json_field("params")

    @property
    def tags(self) -> list:
        return self._json_field("tags")

    @property
    def meta(self) -> dict:
        return self._json_field("meta")

    @property
    def is_done(self) -> bool:
        return self.status in V1Statuses.terminal_values()

    def __repr__(self) -> str:  # debugging aid; JSON stays undecoded
        return (f"RunRecord(uuid={self.uuid!r}, project={self.project!r}, "
                f"kind={self.kind!r}, status={self.status.value!r})")


class _TrackedConnection:
    """Thin proxy over ``sqlite3.Connection`` that counts statements
    into ``Store.stats`` and times them into the
    ``polyaxon_runstore_op_seconds`` histogram. All other attributes
    delegate, so cursors/rowcount/transaction semantics are untouched."""

    __slots__ = ("_raw", "_store")

    def __init__(self, raw: sqlite3.Connection, store: "Store"):
        self._raw = raw
        self._store = store

    def execute(self, sql: str, params: Sequence = ()):  # hot path
        store = self._store
        store.stats["queries"] += 1
        hist = store._op_hist()
        if hist is None:
            return self._raw.execute(sql, params)
        t0 = time.perf_counter()
        try:
            return self._raw.execute(sql, params)
        finally:
            verb = sql.lstrip()[:7].split(None, 1)[0].lower()
            hist.observe(time.perf_counter() - t0, op=verb)

    def executescript(self, script: str):
        self._store.stats["queries"] += 1
        return self._raw.executescript(script)

    def __enter__(self):
        self._raw.__enter__()
        return self

    def __exit__(self, *exc):
        return self._raw.__exit__(*exc)

    def __getattr__(self, name):
        return getattr(self._raw, name)


class Store:
    def __init__(self, path: str = ":memory:"):
        self.path = path
        if path != ":memory:":
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._local = threading.local()
        self._lock = threading.RLock()
        # Test/bench-visible statement + materialized-record counters
        # (the sim budget gate and the query-count regression test).
        self.stats: dict[str, int] = {"queries": 0, "rows": 0}
        self._op_hist_cache = None
        self._listeners: list[Callable[[dict], None]] = []
        self._no_batch = False  # deoptimize(): disable txn batching
        with self._conn() as conn:
            conn.executescript(_SCHEMA)
            # Migration: cache_key column for run memoization (upstream
            # V1Cache semantics); older DBs lack it.
            try:
                conn.execute("ALTER TABLE runs ADD COLUMN cache_key TEXT")
                conn.execute(
                    "CREATE INDEX IF NOT EXISTS idx_runs_cache ON runs(cache_key)")
            except sqlite3.OperationalError:
                pass  # already migrated

    def _op_hist(self):
        if self._op_hist_cache is None:
            from polyaxon_tpu.obs import metrics as obs_metrics

            self._op_hist_cache = obs_metrics.runstore_op_hist()
        return self._op_hist_cache

    def _conn(self) -> _TrackedConnection:
        # ':memory:' DBs are per-connection, so a thread-local connection
        # would hand every thread an empty schema — share one connection
        # (all access is serialized by self._lock anyway).
        if self.path == ":memory:":
            conn = getattr(self, "_memory_conn", None)
            if conn is None:
                raw = sqlite3.connect(self.path, timeout=30.0, check_same_thread=False)
                raw.row_factory = sqlite3.Row
                raw.execute("PRAGMA foreign_keys=ON")
                conn = _TrackedConnection(raw, self)
                self._memory_conn = conn
            return conn
        conn = getattr(self._local, "conn", None)
        if conn is None:
            raw = sqlite3.connect(self.path, timeout=30.0, check_same_thread=False)
            raw.row_factory = sqlite3.Row
            raw.execute("PRAGMA journal_mode=WAL")
            # Belt over the connect timeout: writers in other PROCESSES
            # (CLI vs agent) spin inside sqlite instead of raising
            # immediately when the WAL write lock is briefly held.
            raw.execute("PRAGMA busy_timeout=30000")
            raw.execute("PRAGMA foreign_keys=ON")
            conn = _TrackedConnection(raw, self)
            self._local.conn = conn
        return conn

    # -- write batching ----------------------------------------------------
    @contextlib.contextmanager
    def transaction(self):
        """Batch every store write inside the block into ONE commit.

        The scheduler wraps each tick in this so N same-tick transitions
        cost one WAL fsync, not N. Reentrant (inner blocks join the
        outer commit); holds the store lock for the duration, which is
        what makes the batch atomic against other writer threads."""
        with self._lock:
            depth = getattr(self._local, "txn_depth", 0)
            if depth or self._no_batch:
                self._local.txn_depth = depth + 1
                try:
                    yield
                finally:
                    self._local.txn_depth = depth
                return
            conn = self._conn()
            self._local.txn_depth = 1
            try:
                with conn:
                    yield
            finally:
                self._local.txn_depth = 0

    @contextlib.contextmanager
    def _write(self):
        """One write op: joins an open ``transaction()`` batch if the
        calling thread has one, else commits immediately (old behavior)."""
        with self._lock:
            conn = self._conn()
            if getattr(self._local, "txn_depth", 0):
                yield conn
            else:
                with conn:
                    yield conn

    # -- delta feed --------------------------------------------------------
    def add_transition_listener(self, fn: Callable[[dict], None]) -> None:
        """Subscribe to status changes. ``fn`` receives
        ``{"uuid", "old", "new", "ts"}`` after each successful
        ``transition`` (inside the store lock, so events arrive in
        commit order). This is the admission controller's incremental
        live-view feed."""
        self._listeners.append(fn)

    def remove_transition_listener(self, fn: Callable[[dict], None]) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def _notify(self, event: dict) -> None:
        for fn in list(self._listeners):
            try:
                fn(event)
            except Exception:  # a broken subscriber must not wedge writes
                logger.exception("transition listener failed for %s", event)

    # -- test/bench hooks --------------------------------------------------
    def reset_stats(self) -> None:
        self.stats["queries"] = 0
        self.stats["rows"] = 0

    def deoptimize(self) -> None:
        """Bench hook (``--deopt``): drop the hot composite index and
        disable transaction batching — the 'before' configuration the
        sim budget gate must demonstrably fail on."""
        self._no_batch = True
        with self._write() as conn:
            conn.execute("DROP INDEX IF EXISTS idx_runs_status_created")

    # -- projects ---------------------------------------------------------
    def create_project(self, name: str, description: str = "") -> None:
        with self._write() as conn:
            conn.execute(
                "INSERT OR IGNORE INTO projects(name, description, created_at) VALUES (?,?,?)",
                (name, description, now().isoformat()),
            )

    def list_projects(self) -> list[dict]:
        rows = self._conn().execute("SELECT * FROM projects ORDER BY name").fetchall()
        return [dict(r) for r in rows]

    def has_project(self, name: str) -> bool:
        return self._conn().execute(
            "SELECT 1 FROM projects WHERE name=?", (name,)
        ).fetchone() is not None

    # -- runs -------------------------------------------------------------
    def create_run(
        self,
        *,
        project: str,
        spec: Optional[dict] = None,
        name: Optional[str] = None,
        description: Optional[str] = None,
        kind: Optional[str] = None,
        params: Optional[dict] = None,
        tags: Optional[list[str]] = None,
        meta: Optional[dict] = None,
        parent_uuid: Optional[str] = None,
        pipeline_uuid: Optional[str] = None,
        iteration: Optional[int] = None,
        run_uuid: Optional[str] = None,
    ) -> RunRecord:
        run_uuid = run_uuid or _uuid.uuid4().hex[:12]
        ts = now().isoformat()
        with self._write() as conn:
            conn.execute(
                """INSERT INTO runs(uuid, project, name, description, kind, status,
                    spec, params, tags, meta, parent_uuid, pipeline_uuid, iteration,
                    created_at, updated_at)
                   VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)""",
                (
                    run_uuid, project, name, description, kind,
                    V1Statuses.CREATED.value,
                    json.dumps(spec) if spec else None,
                    json.dumps(params) if params else None,
                    json.dumps(tags or []),
                    json.dumps(meta or {}),
                    parent_uuid, pipeline_uuid, iteration, ts, ts,
                ),
            )
            conn.execute(
                "INSERT INTO conditions(run_uuid, type, reason, message, created_at)"
                " VALUES (?,?,?,?,?)",
                (run_uuid, V1Statuses.CREATED.value, None, None, ts),
            )
        return self.get_run(run_uuid)

    def find_cached(self, cache_key: str, *, project: str,
                    ttl: Optional[int] = None) -> Optional[RunRecord]:
        """Newest SUCCEEDED run in ``project`` with this cache key
        (within ttl seconds). Project-scoped: memoization must never
        leak artifacts across project namespaces."""
        rows = self._conn().execute(
            "SELECT * FROM runs WHERE cache_key=? AND project=? AND status=? "
            "ORDER BY created_at DESC LIMIT 5",
            (cache_key, project, V1Statuses.SUCCEEDED.value),
        ).fetchall()
        for row in rows:
            record = self._to_record(row)
            if ttl and record.finished_at:
                import datetime as _dt

                finished = _dt.datetime.fromisoformat(record.finished_at)
                if (now() - finished).total_seconds() > ttl:
                    continue
            return record
        return None

    def _to_record(self, row: sqlite3.Row) -> RunRecord:
        self.stats["rows"] += 1
        return RunRecord(
            uuid=row["uuid"],
            project=row["project"],
            name=row["name"],
            description=row["description"],
            kind=row["kind"],
            managed_by=row["managed_by"],
            cache_key=row["cache_key"] if "cache_key" in row.keys() else None,
            status=V1Statuses(row["status"]),
            raw_json={field: row[field] for field in _JSON_COLUMNS},
            parent_uuid=row["parent_uuid"],
            pipeline_uuid=row["pipeline_uuid"],
            iteration=row["iteration"],
            retries=row["retries"],
            created_at=row["created_at"],
            updated_at=row["updated_at"],
            started_at=row["started_at"],
            finished_at=row["finished_at"],
        )

    def get_run(self, run_uuid: str) -> RunRecord:
        row = self._conn().execute("SELECT * FROM runs WHERE uuid=?", (run_uuid,)).fetchone()
        if row is None:
            raise KeyError(f"Run `{run_uuid}` not found")
        return self._to_record(row)

    def get_runs(self, uuids: Sequence[str]) -> list[RunRecord]:
        """Batch point-lookup, (created_at, rowid) ordered. Missing
        uuids are silently skipped (callers diff sets, not indexes)."""
        out: list[RunRecord] = []
        uuids = list(uuids)
        for i in range(0, len(uuids), 500):  # sqlite bind-var headroom
            chunk = uuids[i:i + 500]
            rows = self._conn().execute(
                f"SELECT * FROM runs WHERE uuid IN ({','.join('?' * len(chunk))}) "
                "ORDER BY created_at, rowid", chunk,
            ).fetchall()
            out.extend(self._to_record(r) for r in rows)
        return out

    def list_runs(
        self,
        *,
        project: Optional[str] = None,
        statuses: Optional[list[V1Statuses]] = None,
        pipeline_uuid: Optional[str] = None,
        parent_uuid: Optional[str] = None,
        kind: Optional[str] = None,
        kinds: Optional[Sequence[str]] = None,
        exclude_kinds: Optional[Sequence[str]] = None,
        limit: int = 1000,
        newest_first: bool = False,
    ) -> list[RunRecord]:
        clauses, args = [], []
        if project:
            clauses.append("project=?")
            args.append(project)
        if statuses:
            clauses.append(f"status IN ({','.join('?' * len(statuses))})")
            args.extend(s.value for s in statuses)
        if pipeline_uuid:
            clauses.append("pipeline_uuid=?")
            args.append(pipeline_uuid)
        if parent_uuid:
            clauses.append("parent_uuid=?")
            args.append(parent_uuid)
        if kind:
            clauses.append("kind=?")
            args.append(kind)
        if kinds:
            clauses.append(f"kind IN ({','.join('?' * len(kinds))})")
            args.extend(kinds)
        if exclude_kinds:
            # NULL kind must survive the exclusion (NOT IN drops NULLs).
            clauses.append(
                f"(kind IS NULL OR kind NOT IN "
                f"({','.join('?' * len(exclude_kinds))}))")
            args.extend(exclude_kinds)
        where = (" WHERE " + " AND ".join(clauses)) if clauses else ""
        # rowid tie-break: isoformat timestamps collide at same-second
        # submissions, and admission order must be insertion order then.
        order = ("created_at DESC, rowid DESC" if newest_first
                 else "created_at, rowid")
        rows = self._conn().execute(
            f"SELECT * FROM runs{where} ORDER BY {order} LIMIT ?", (*args, limit)
        ).fetchall()
        return [self._to_record(r) for r in rows]

    def list_run_uuids(
        self,
        *,
        statuses: list[V1Statuses],
        limit: int = 100000,
    ) -> list[str]:
        """Key-only projection of the status index: uuids in
        (created_at, rowid) order, no row payload, no JSON. Terminal
        sweeps (e.g. the FAILED restart pass) diff these against their
        in-memory seen-sets and fetch full records only for the
        residue — O(new failures), not O(all failures ever)."""
        rows = self._conn().execute(
            f"SELECT uuid FROM runs WHERE status IN "
            f"({','.join('?' * len(statuses))}) "
            "ORDER BY created_at, rowid LIMIT ?",
            (*[s.value for s in statuses], limit),
        ).fetchall()
        return [r["uuid"] for r in rows]

    def scan_runs(
        self,
        partitions: Sequence[tuple[Sequence[V1Statuses], Optional[Sequence[str]]]],
        *,
        limit: int = 100000,
    ) -> dict[V1Statuses, list[RunRecord]]:
        """The scheduler's one-query tick scan. Each partition is
        ``(statuses, kinds-or-None)``; a kind filter keeps rows of
        other kinds out of the result AT THE SQL LAYER (a 10k-queued
        backlog of plain jobs contributes zero rows to the pipeline
        partition). Results come back grouped by status, each group in
        (created_at, rowid) order; every requested status is present in
        the dict, possibly empty."""
        ors, args = [], []
        for statuses, kinds in partitions:
            clause = f"status IN ({','.join('?' * len(statuses))})"
            args.extend(s.value for s in statuses)
            if kinds:
                clause += f" AND kind IN ({','.join('?' * len(kinds))})"
                args.extend(kinds)
            ors.append(f"({clause})")
        out: dict[V1Statuses, list[RunRecord]] = {}
        for statuses, _ in partitions:
            for status in statuses:
                out.setdefault(status, [])
        rows = self._conn().execute(
            f"SELECT * FROM runs WHERE {' OR '.join(ors)} "
            "ORDER BY created_at, rowid LIMIT ?", (*args, limit),
        ).fetchall()
        for row in rows:
            out[V1Statuses(row["status"])].append(self._to_record(row))
        return out

    def count_runs(self, *, statuses: list[V1Statuses]) -> int:
        row = self._conn().execute(
            f"SELECT COUNT(*) AS n FROM runs WHERE status IN "
            f"({','.join('?' * len(statuses))})",
            [s.value for s in statuses],
        ).fetchone()
        return int(row["n"])

    def update_run(self, run_uuid: str, **fields: Any) -> None:
        allowed = {"name", "description", "kind", "spec", "resolved_spec",
                   "launch_plan", "params", "tags", "meta", "retries",
                   "iteration", "cache_key"}
        sets, args = ["updated_at=?"], [now().isoformat()]
        for key, value in fields.items():
            if key not in allowed:
                raise ValueError(f"Cannot update field `{key}`")
            if key in ("spec", "resolved_spec", "launch_plan", "params", "tags", "meta"):
                value = json.dumps(value) if value is not None else None
            sets.append(f"{key}=?")
            args.append(value)
        args.append(run_uuid)
        with self._write() as conn:
            conn.execute(f"UPDATE runs SET {', '.join(sets)} WHERE uuid=?", args)

    # -- lifecycle --------------------------------------------------------
    def transition(
        self,
        run_uuid: str,
        status: V1Statuses,
        *,
        reason: Optional[str] = None,
        message: Optional[str] = None,
        force: bool = False,
    ) -> bool:
        """Atomically advance a run's status; returns False if illegal."""
        ts = now().isoformat()
        with self._lock:
            with self._write() as conn:
                row = conn.execute("SELECT status FROM runs WHERE uuid=?", (run_uuid,)).fetchone()
                if row is None:
                    raise KeyError(f"Run `{run_uuid}` not found")
                current = V1Statuses(row["status"])
                if not force and not can_transition(current, status):
                    return False
                extra = ""
                args: list[Any] = [status.value, ts]
                if status == V1Statuses.RUNNING:
                    extra = ", started_at=COALESCE(started_at, ?)"
                    args.append(ts)
                elif status in V1Statuses.terminal_values():
                    extra = ", finished_at=?"
                    args.append(ts)
                args.append(run_uuid)
                conn.execute(
                    f"UPDATE runs SET status=?, updated_at=?{extra} WHERE uuid=?", args
                )
                conn.execute(
                    "INSERT INTO conditions(run_uuid, type, reason, message, created_at)"
                    " VALUES (?,?,?,?,?)",
                    (run_uuid, status.value, reason, message, ts),
                )
            # Still inside the store lock: subscribers observe events in
            # commit order (inside an open transaction() batch they see
            # this thread's uncommitted state, which is the same state
            # their own queries on this connection would read).
            self._notify({"uuid": run_uuid, "old": current, "new": status,
                          "ts": ts})
        return True

    def add_condition(
        self,
        run_uuid: str,
        type: str,  # noqa: A002 - mirrors the conditions column
        *,
        reason: Optional[str] = None,
        message: Optional[str] = None,
    ) -> None:
        """Pin a condition WITHOUT a status transition — used by the
        admission pass to surface why a run is still QUEUED (e.g.
        reason=QuotaExceeded) while the status itself stays put."""
        with self._write() as conn:
            conn.execute(
                "INSERT INTO conditions(run_uuid, type, reason, message, created_at)"
                " VALUES (?,?,?,?,?)",
                (run_uuid, type, reason, message, now().isoformat()),
            )

    def last_condition(self, run_uuid: str) -> Optional[dict]:
        row = self._conn().execute(
            "SELECT type, reason, message, created_at FROM conditions "
            "WHERE run_uuid=? ORDER BY id DESC LIMIT 1", (run_uuid,),
        ).fetchone()
        return dict(row) if row is not None else None

    def get_conditions(self, run_uuid: str) -> list[dict]:
        rows = self._conn().execute(
            "SELECT type, reason, message, created_at FROM conditions "
            "WHERE run_uuid=? ORDER BY id", (run_uuid,),
        ).fetchall()
        return [dict(r) for r in rows]

    # -- scheduling catalog (queues + quotas) ------------------------------
    def upsert_queue(
        self,
        name: str,
        *,
        priority: int = 0,
        concurrency: Optional[int] = None,
        preemptible: bool = False,
        description: str = "",
    ) -> dict:
        ts = now().isoformat()
        with self._write() as conn:
            conn.execute(
                """INSERT INTO queues(name, priority, concurrency, preemptible,
                       description, created_at, updated_at)
                   VALUES (?,?,?,?,?,?,?)
                   ON CONFLICT(name) DO UPDATE SET
                       priority=excluded.priority,
                       concurrency=excluded.concurrency,
                       preemptible=excluded.preemptible,
                       description=excluded.description,
                       updated_at=excluded.updated_at""",
                (name, int(priority), concurrency, int(preemptible),
                 description, ts, ts),
            )
        return self.get_queue(name)  # type: ignore[return-value]

    def get_queue(self, name: str) -> Optional[dict]:
        row = self._conn().execute(
            "SELECT * FROM queues WHERE name=?", (name,)).fetchone()
        if row is None:
            return None
        out = dict(row)
        out["preemptible"] = bool(out["preemptible"])
        return out

    def list_queues(self) -> list[dict]:
        rows = self._conn().execute(
            "SELECT * FROM queues ORDER BY priority DESC, name").fetchall()
        out = []
        for row in rows:
            queue = dict(row)
            queue["preemptible"] = bool(queue["preemptible"])
            out.append(queue)
        return out

    def delete_queue(self, name: str) -> bool:
        with self._write() as conn:
            cur = conn.execute("DELETE FROM queues WHERE name=?", (name,))
        return cur.rowcount > 0

    def set_quota(
        self,
        project: str,
        *,
        max_runs: Optional[int] = None,
        max_chips: Optional[int] = None,
        weight: float = 1.0,
    ) -> dict:
        ts = now().isoformat()
        with self._write() as conn:
            conn.execute(
                """INSERT INTO quotas(project, max_runs, max_chips, weight,
                       created_at, updated_at)
                   VALUES (?,?,?,?,?,?)
                   ON CONFLICT(project) DO UPDATE SET
                       max_runs=excluded.max_runs,
                       max_chips=excluded.max_chips,
                       weight=excluded.weight,
                       updated_at=excluded.updated_at""",
                (project, max_runs, max_chips, float(weight), ts, ts),
            )
        return self.get_quota(project)  # type: ignore[return-value]

    def get_quota(self, project: str) -> Optional[dict]:
        row = self._conn().execute(
            "SELECT * FROM quotas WHERE project=?", (project,)).fetchone()
        return dict(row) if row is not None else None

    def list_quotas(self) -> list[dict]:
        rows = self._conn().execute(
            "SELECT * FROM quotas ORDER BY project").fetchall()
        return [dict(r) for r in rows]

    def delete_quota(self, project: str) -> bool:
        with self._write() as conn:
            cur = conn.execute("DELETE FROM quotas WHERE project=?", (project,))
        return cur.rowcount > 0

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None
        mem = getattr(self, "_memory_conn", None)
        if mem is not None:
            mem.close()
            self._memory_conn = None
