"""Polytune manager unit tests: grid/random enumeration, Hyperband rung
math + preemption accounting, Bayes GP/acquisition behavior."""

import math

import numpy as np
import pytest

from polyaxon_tpu.polyflow.matrix import (
    V1Bayes,
    V1GridSearch,
    V1Hyperband,
    V1Hyperopt,
    V1Mapping,
    V1RandomSearch,
)
from polyaxon_tpu.tune import (
    BayesManager,
    GaussianProcess,
    GridSearchManager,
    HyperbandManager,
    HyperoptManager,
    MappingManager,
    Observation,
    RandomSearchManager,
    acquisition,
    top_k,
)


def _hb(max_iterations=81, eta=3) -> HyperbandManager:
    return HyperbandManager(
        V1Hyperband.from_dict(
            {
                "kind": "hyperband",
                "maxIterations": max_iterations,
                "eta": eta,
                "resource": {"name": "epochs", "type": "int"},
                "metric": {"name": "loss", "optimization": "minimize"},
                "params": {"lr": {"kind": "loguniform",
                                  "value": {"low": math.log(1e-5), "high": math.log(1e-1)}}},
                "seed": 11,
            }
        )
    )


class TestOneShotManagers:
    def test_grid_product(self):
        mgr = GridSearchManager(
            V1GridSearch.from_dict(
                {
                    "kind": "grid",
                    "params": {
                        "a": {"kind": "choice", "value": [1, 2]},
                        "b": {"kind": "choice", "value": ["x", "y", "z"]},
                    },
                }
            )
        )
        suggestions = mgr.get_suggestions()
        assert len(suggestions) == 6
        assert {"a": 1, "b": "z"} in suggestions

    def test_random_deterministic_seed(self):
        config = V1RandomSearch.from_dict(
            {
                "kind": "random",
                "numRuns": 5,
                "seed": 3,
                "params": {"lr": {"kind": "uniform", "value": {"low": 0, "high": 1}}},
            }
        )
        assert RandomSearchManager(config).get_suggestions() == \
               RandomSearchManager(config).get_suggestions()

    def test_mapping(self):
        mgr = MappingManager(V1Mapping.from_dict(
            {"kind": "mapping", "values": [{"a": 1}, {"a": 2}]}))
        assert mgr.get_suggestions() == [{"a": 1}, {"a": 2}]


class TestHyperband:
    def test_rung_shapes_paper_table(self):
        mgr = _hb(81, 3)
        assert mgr.brackets() == [4, 3, 2, 1, 0]
        assert mgr.rung_shape(4, 0) == (81, 1)
        assert mgr.rung_shape(4, 1) == (27, 3)
        assert mgr.rung_shape(4, 2) == (9, 9)
        assert mgr.rung_shape(4, 3) == (3, 27)
        assert mgr.rung_shape(4, 4) == (1, 81)
        assert mgr.rung_shape(0, 0) == (5, 81)

    def test_first_rung_and_promotion(self):
        mgr = _hb(9, 3)  # s_max=2
        rung0 = mgr.first_rung(2)
        assert rung0.n_configs == len(rung0.suggestions)
        obs = [
            Observation(params=p, metric=float(i), status="succeeded")
            for i, p in enumerate(rung0.suggestions)
        ]
        rung1 = mgr.next_rung(2, 0, obs)
        assert rung1 is not None
        # minimize → the best (lowest metric) configs survive
        surviving = rung1.suggestions
        assert obs[0].params in surviving
        assert len(surviving) == mgr.rung_shape(2, 1)[0]
        assert rung1.resource > rung0.resource

    def test_bracket_exhaustion(self):
        mgr = _hb(9, 3)
        obs = [Observation(params={"lr": 0.1}, metric=1.0)]
        assert mgr.next_rung(2, 2, obs) is None

    def test_failed_trials_rank_worst(self):
        metric = _hb().config.metric
        obs = [
            Observation(params={"lr": 1}, metric=5.0),
            Observation(params={"lr": 2}, metric=None, status="failed"),
            Observation(params={"lr": 3}, metric=1.0),
        ]
        best = top_k(obs, metric, 2)
        assert [o.params["lr"] for o in best] == [3, 1]

    def test_preempted_reissued_not_scored(self):
        mgr = _hb()
        obs = [
            Observation(params={"lr": 1}, metric=None, status="preempted"),
            Observation(params={"lr": 2}, metric=2.0),
        ]
        assert mgr.reissue_preempted(obs) == [{"lr": 1}]
        assert [o.params["lr"] for o in top_k(obs, mgr.config.metric, 2)] == [2]


class TestBayes:
    def _config(self, acq="ei"):
        return V1Bayes.from_dict(
            {
                "kind": "bayes",
                "numInitialRuns": 4,
                "maxIterations": 10,
                "seed": 5,
                "metric": {"name": "loss", "optimization": "minimize"},
                "utilityFunction": {"acquisitionFunction": acq},
                "params": {"x": {"kind": "uniform", "value": {"low": 0.0, "high": 1.0}}},
            }
        )

    def test_gp_interpolates(self):
        gp = GaussianProcess(kernel="matern", length_scale=0.3)
        x = np.array([[0.0], [0.5], [1.0]])
        y = np.array([0.0, 1.0, 0.0])
        gp.fit(x, y)
        mean, std = gp.predict(np.array([[0.5]]))
        assert abs(mean[0] - 1.0) < 0.05
        assert std[0] < 0.1
        _, std_far = gp.predict(np.array([[0.25]]))
        assert std_far[0] > std[0]

    def test_acquisition_shapes(self):
        mean = np.array([0.0, 1.0])
        std = np.array([1.0, 0.01])
        for kind in ("ucb", "ei", "poi"):
            scores = acquisition(kind, mean, std, best=0.5)
            assert scores.shape == (2,)
        # EI prefers high-mean low-uncertainty point that beats best
        ei = acquisition("ei", mean, std, best=0.5)
        assert ei[1] > 0

    def test_initial_then_model_based(self):
        mgr = BayesManager(self._config())
        initial = mgr.initial_suggestions()
        assert len(initial) == 4
        # Minimization objective: loss = (x - 0.3)^2
        obs = [
            Observation(params=p, metric=(p["x"] - 0.3) ** 2) for p in initial
        ]
        obs += [Observation(params={"x": 0.3}, metric=0.0),
                Observation(params={"x": 0.9}, metric=0.36)]
        suggestion = mgr.get_suggestions(obs, count=1)[0]
        assert 0.0 <= suggestion["x"] <= 1.0
        # The GP should focus near the optimum rather than the far edge.
        assert abs(suggestion["x"] - 0.3) < abs(0.9 - 0.3)

    def test_insufficient_observations_falls_back_to_random(self):
        mgr = BayesManager(self._config())
        out = mgr.get_suggestions([], count=3)
        assert len(out) == 3

    def test_done_accounting_ignores_preempted(self):
        mgr = BayesManager(self._config())
        obs = [Observation(params={"x": 0.1}, metric=1.0)] * 13
        assert not mgr.is_done(obs)
        obs += [Observation(params={"x": 0.2}, metric=1.0)]
        assert mgr.is_done(obs)
        preempted = obs[:13] + [Observation(params={"x": 0.3}, metric=None,
                                            status="preempted")]
        assert not mgr.is_done(preempted)


from tests.test_controlplane import TRIAL_COMPONENT  # noqa: E402


class TestHyperopt:
    def _config(self, algorithm="tpe", **kw):
        spec = {
            "kind": "hyperopt",
            "algorithm": algorithm,
            "numRuns": 20,
            "seed": 7,
            "metric": {"name": "loss", "optimization": "minimize"},
            "params": {"x": {"kind": "uniform", "value": {"low": 0.0, "high": 1.0}}},
        }
        spec.update(kw)
        return V1Hyperopt.from_dict(spec)

    def test_schema_validates_algorithm(self):
        with pytest.raises(Exception):
            self._config(algorithm="cmaes")
        cfg = self._config()
        assert cfg.startup_trials == 4

    def test_rand_is_plain_random(self):
        mgr = HyperoptManager(self._config(algorithm="rand"))
        obs = [Observation(params={"x": 0.5}, metric=0.0)] * 5
        out = mgr.get_suggestions(obs, count=6)
        assert len(out) == 6
        assert all(0.0 <= s["x"] <= 1.0 for s in out)

    def test_tpe_focuses_near_good_region(self):
        mgr = HyperoptManager(self._config())
        # loss = (x - 0.3)^2; spread observations across the range.
        obs = [Observation(params={"x": x}, metric=(x - 0.3) ** 2)
               for x in (0.05, 0.15, 0.28, 0.32, 0.5, 0.7, 0.85, 0.95)]
        suggestions = mgr.get_suggestions(obs, count=8)
        mean_dist = sum(abs(s["x"] - 0.3) for s in suggestions) / len(suggestions)
        assert mean_dist < 0.25  # uniform would average ~0.29; TPE tighter

    def test_tpe_handles_discrete_and_log_params(self):
        cfg = self._config(params={
            "layers": {"kind": "choice", "value": [2, 4, 8]},
            "lr": {"kind": "loguniform",
                   "value": {"low": math.log(1e-5), "high": math.log(1e-1)}},
        })
        mgr = HyperoptManager(cfg)
        obs = [
            Observation(params={"layers": 4, "lr": 1e-3}, metric=0.1),
            Observation(params={"layers": 4, "lr": 3e-3}, metric=0.12),
            Observation(params={"layers": 2, "lr": 1e-5}, metric=0.9),
            Observation(params={"layers": 8, "lr": 1e-1}, metric=1.0),
        ]
        for s in mgr.get_suggestions(obs, count=5):
            assert s["layers"] in (2, 4, 8)
            assert 1e-5 * 0.99 <= s["lr"] <= 1e-1 * 1.01

    def test_anneal_shrinks_toward_incumbent(self):
        mgr = HyperoptManager(self._config(algorithm="anneal"))
        best = Observation(params={"x": 0.4}, metric=0.0)
        far = Observation(params={"x": 0.95}, metric=1.0)
        # Many observations → low temperature → samples hug the incumbent.
        obs = [best, far] + [Observation(params={"x": 0.9}, metric=0.8)] * 30
        out = [mgr._anneal_one([best, far], len(obs)) for _ in range(10)]
        mean_dist = sum(abs(s["x"] - 0.4) for s in out) / len(out)
        assert mean_dist < 0.2

    def test_quantized_params_stay_on_grid(self):
        cfg = self._config(params={
            "bs": {"kind": "quniform", "value": {"low": 8, "high": 64, "q": 8}},
        })
        mgr = HyperoptManager(cfg)
        obs = [Observation(params={"bs": 16.0}, metric=0.1),
               Observation(params={"bs": 24.0}, metric=0.2),
               Observation(params={"bs": 56.0}, metric=0.9)]
        for s in mgr.get_suggestions(obs, count=6):
            assert s["bs"] % 8 == 0

    def test_seeded_rand_varies_across_ticks(self):
        """The scheduler rebuilds the manager per tick — a fixed seed must
        not replay the same RNG stream (duplicate trials)."""
        cfg = self._config(algorithm="rand")
        obs3 = [Observation(params={"x": 0.5}, metric=1.0)] * 3
        obs4 = obs3 + [Observation(params={"x": 0.6}, metric=1.0)]
        a = HyperoptManager(cfg).get_suggestions(obs3, count=1)[0]
        b = HyperoptManager(cfg).get_suggestions(obs4, count=1)[0]
        again = HyperoptManager(cfg).get_suggestions(obs3, count=1)[0]
        assert a != b          # new observations → new draw
        assert a == again      # still deterministic per round

    def test_negative_max_iterations_rejected(self):
        with pytest.raises(Exception, match="maxIterations"):
            self._config(maxIterations=-3)

    def test_max_iterations_caps_model_guided_trials(self):
        cfg = self._config(numRuns=50, maxIterations=5, numStartupTrials=4)
        assert cfg.total_budget == 9  # startup + capped iterations
        mgr = HyperoptManager(cfg)
        obs = [Observation(params={"x": 0.1}, metric=1.0)] * 9
        assert mgr.is_done(obs)
        assert V1Hyperopt.from_dict(
            {**self._config().to_dict(), "numRuns": 10}).total_budget == 10

    def test_done_counts_exclude_preempted(self):
        mgr = HyperoptManager(self._config(numRuns=3))
        obs = [Observation(params={"x": 0.1}, metric=1.0)] * 2
        assert not mgr.is_done(obs)
        assert not mgr.is_done(obs + [Observation(params={"x": 0.2}, metric=None,
                                                  status="preempted")])
        assert mgr.is_done(obs + [Observation(params={"x": 0.2}, metric=1.0)])


class TestIterativeAndEarlyStopping:
    """V1Iterative execution + early-stopping policies (scheduler-side)."""

    @pytest.fixture()
    def plane(self, tmp_path):
        from polyaxon_tpu.controlplane import ControlPlane

        return ControlPlane(str(tmp_path / "home"))

    @pytest.fixture()
    def agent(self, plane):
        from polyaxon_tpu.agent import Agent

        return Agent(plane, max_concurrent=8)

    def test_iterative_runs_sequentially(self, plane, agent):
        from polyaxon_tpu.lifecycle import V1Statuses

        record = plane.submit({
            "kind": "operation",
            "matrix": {
                "kind": "iterative",
                "maxIterations": 3,
                "seed": 3,
                "params": {"lr": {"kind": "uniform",
                                   "value": {"low": 0.0, "high": 1.0}}},
            },
            "component": TRIAL_COMPONENT,
        })
        status = agent.run_until_done(record.uuid, timeout=120)
        assert status == V1Statuses.SUCCEEDED
        children = plane.list_runs(pipeline_uuid=record.uuid)
        assert len(children) == 3
        # Sequential: each child created only after the previous finished.
        ordered = sorted(children, key=lambda c: c.created_at)
        for first, second in zip(ordered, ordered[1:]):
            assert first.finished_at <= second.created_at
        lrs = {c.meta["trial_params"]["lr"] for c in children}
        assert len(lrs) == 3  # per-iteration seeds differ

    def test_metric_early_stopping_succeeds_sweep(self, plane, agent):
        from polyaxon_tpu.lifecycle import V1Statuses

        record = plane.submit({
            "kind": "operation",
            "matrix": {
                "kind": "grid",
                "concurrency": 1,
                "earlyStopping": [{"kind": "metric_early_stopping",
                                    "metric": "score", "value": 0.05}],
                "params": {"lr": {"kind": "choice",
                                   "value": [0.3, 0.9, 0.8, 0.7]}},
            },
            "component": TRIAL_COMPONENT,
        })
        status = agent.run_until_done(record.uuid, timeout=120)
        assert status == V1Statuses.SUCCEEDED
        conditions = [c["reason"] for c in plane.get_statuses(record.uuid)]
        assert "MetricEarlyStopping" in conditions
        # lr=0.3 hits score 0 on the FIRST trial: the rest never ran.
        children = plane.list_runs(pipeline_uuid=record.uuid)
        assert len(children) < 4

    def test_failure_early_stopping_fails_sweep(self, plane, agent):
        from polyaxon_tpu.lifecycle import V1Statuses

        bad_component = {
            "kind": "component",
            "inputs": [{"name": "lr", "type": "float", "toEnv": "LR"}],
            "run": {"kind": "job", "container": {
                "command": ["python", "-c", "raise SystemExit(1)"]}},
        }
        record = plane.submit({
            "kind": "operation",
            "matrix": {
                "kind": "grid",
                "concurrency": 1,
                "earlyStopping": [{"kind": "failure_early_stopping",
                                    "percent": 50}],
                "params": {"lr": {"kind": "choice",
                                   "value": [0.1, 0.2, 0.3, 0.4]}},
            },
            "component": bad_component,
        })
        status = agent.run_until_done(record.uuid, timeout=120)
        assert status == V1Statuses.FAILED
        conditions = [c["reason"] for c in plane.get_statuses(record.uuid)]
        assert "FailureEarlyStopping" in conditions
        assert len(plane.list_runs(pipeline_uuid=record.uuid)) < 4

    def test_custom_tuner_rejected(self, plane, agent):
        from polyaxon_tpu.lifecycle import V1Statuses

        record = plane.submit({
            "kind": "operation",
            "matrix": {
                "kind": "iterative",
                "maxIterations": 2,
                "tuner": {"hubRef": "my-tuner"},
                "params": {"lr": {"kind": "uniform",
                                   "value": {"low": 0.0, "high": 1.0}}},
            },
            "component": TRIAL_COMPONENT,
        })
        status = agent.run_until_done(record.uuid, timeout=30)
        assert status == V1Statuses.FAILED
        conditions = [c["reason"] for c in plane.get_statuses(record.uuid)]
        assert "UnsupportedTuner" in conditions

    def test_unseeded_iterative_varies(self):
        import dataclasses

        from polyaxon_tpu.polyflow.matrix import V1Iterative
        from polyaxon_tpu.tune import IterativeManager

        matrix = V1Iterative.from_dict({
            "kind": "iterative", "maxIterations": 2,
            "params": {"lr": {"kind": "uniform",
                               "value": {"low": 0.0, "high": 1.0}}},
        })
        a = IterativeManager(matrix).get_suggestion(0)
        b = IterativeManager(matrix).get_suggestion(0)
        assert a != b  # OS entropy, not a fixed seed-0 stream


class TestAsha:
    def _matrix(self, **over):
        from polyaxon_tpu.polyflow.matrix import V1Asha

        spec = {
            "kind": "asha", "numRuns": 9, "maxIterations": 9,
            "minResource": 1, "eta": 3, "seed": 3,
            "resource": {"name": "epochs", "type": "int"},
            "metric": {"name": "loss", "optimization": "minimize"},
            "params": {"lr": {"kind": "loguniform",
                              "value": {"low": -9.2, "high": -2.3}}},
        }
        spec.update(over)
        return V1Asha.from_dict(spec)

    def test_rung_resources(self):
        assert self._matrix().rung_resources() == [1, 3, 9]
        # Cap rung: R not a power of eta → last rung clamps to R.
        assert self._matrix(maxIterations=5).rung_resources() == [1, 3, 5]
        assert self._matrix(minResource=2,
                            maxIterations=8).rung_resources() == [2, 6, 8]
        # Small eta + int resource: cast duplicates are dropped so no
        # promotion ever re-runs at an identical budget.
        rungs = self._matrix(eta=1.4, maxIterations=4).rung_resources()
        assert rungs == sorted(set(rungs)) == [1, 2, 3, 4]

    def test_validation(self):
        import pytest as _pytest

        with _pytest.raises(ValueError):
            self._matrix(numRuns=0)
        with _pytest.raises(ValueError):
            self._matrix(eta=1)
        with _pytest.raises(ValueError):
            self._matrix(minResource=20)  # > maxIterations
        with _pytest.raises(ValueError):
            self._matrix(minResource=0.5)  # casts to int 0

    def test_sampling_deterministic_per_index(self):
        from polyaxon_tpu.tune import AshaManager

        m1, m2 = AshaManager(self._matrix()), AshaManager(self._matrix())
        assert m1.sample_params(4) == m2.sample_params(4)
        assert m1.sample_params(4) != m1.sample_params(5)

    def test_promotable_top_fraction(self):
        from polyaxon_tpu.tune import AshaManager

        m = AshaManager(self._matrix())  # eta=3
        completed = [(f"u{i}", {"lr": i}, float(i)) for i in range(6)]
        # floor(6/3) = 2 best (minimize): u0, u1.
        assert m.promotable(completed) == ["u0", "u1"]
        # Fewer than eta completed → nothing promotes yet (async rule).
        assert m.promotable(completed[:2]) == []

    def test_promotable_maximize_and_failures(self):
        from polyaxon_tpu.tune import AshaManager

        m = AshaManager(self._matrix(
            metric={"name": "acc", "optimization": "maximize"}))
        completed = [("a", {}, 0.1), ("b", {}, 0.9),
                     ("fail", {}, None), ("c", {}, 0.5)]
        # floor(4/3) = 1 → the best by acc; failed trials never promote.
        assert m.promotable(completed) == ["b"]
        only_failed = [("x", {}, None), ("y", {}, None), ("z", {}, None)]
        assert m.promotable(only_failed) == []
