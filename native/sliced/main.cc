// Standalone slice daemon: a line protocol over stdin/stdout so the
// reconcile loop can run out-of-process (the production shape — the
// Python agent talks to it the way upstream's agent talks to its Go
// operator, but over a pipe instead of the k8s API).
//
// Protocol (one request per line, one reply line per request):
//   ADD <name> <topology> <preemptible:0|1>
//   REQ <run_uuid> <topology> <priority> <max_restarts>   -> gang id
//   REL <gang_id>
//   HB <gang_id> <proc> <now>
//   PRE <slice>
//   INFO <gang_id>
//   TICK <now> <timeout>      -> events, terminated by "."
//   QUIT
#include <iostream>
#include <sstream>
#include <string>

#include "pool.h"

int main() {
  sliced::Pool pool;
  std::string line;
  while (std::getline(std::cin, line)) {
    std::istringstream in(line);
    std::string cmd;
    in >> cmd;
    if (cmd == "QUIT") break;
    if (cmd == "ADD") {
      std::string name, topo;
      int preemptible = 0;
      in >> name >> topo >> preemptible;
      std::cout << (pool.AddSlice(name, topo, preemptible != 0) ? "ok" : "err")
                << "\n";
    } else if (cmd == "REQ") {
      std::string uuid, topo;
      int priority = 0, max_restarts = 0;
      in >> uuid >> topo >> priority >> max_restarts;
      std::cout << pool.RequestGang(uuid, topo, priority, max_restarts) << "\n";
    } else if (cmd == "REL") {
      long long id = 0;
      in >> id;
      std::cout << (pool.ReleaseGang(id) ? "ok" : "err") << "\n";
    } else if (cmd == "HB") {
      long long id = 0;
      int proc = 0;
      double now = 0;
      in >> id >> proc >> now;
      std::cout << (pool.Heartbeat(id, proc, now) ? "ok" : "err") << "\n";
    } else if (cmd == "PRE") {
      std::string name;
      in >> name;
      std::cout << pool.PreemptSlice(name) << "\n";
    } else if (cmd == "INFO") {
      long long id = 0;
      in >> id;
      const sliced::Gang* gang = pool.GetGang(id);
      if (gang == nullptr) {
        std::cout << "err\n";
      } else {
        std::cout << GangStateName(gang->state) << " "
                  << (gang->placement.slice.empty() ? "-"
                                                    : gang->placement.slice)
                  << " restarts=" << gang->restarts << "\n";
      }
    } else if (cmd == "TICK") {
      double now = 0, timeout = 30;
      in >> now >> timeout;
      pool.Tick(now, timeout);
      for (const auto& event : pool.DrainEvents())
        std::cout << event.gang_id << " " << event.kind << " " << event.detail
                  << "\n";
      std::cout << ".\n";
    } else {
      std::cout << "err unknown\n";
    }
    std::cout.flush();
  }
  return 0;
}
