"""Test bootstrap: force an 8-device virtual CPU mesh.

The axon PJRT plugin auto-registers via sitecustomize and pins
``jax_platforms="axon,cpu"``; flipping the env var alone is not enough
once ``register()`` has run, so we also update the config before any
backend initializes. Multi-chip sharding tests then run on 8 virtual CPU
devices exactly the way the driver's ``dryrun_multichip`` harness does.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

# NOTE: do NOT enable jax_compilation_cache_dir for this CPU-mesh suite.
# It was tried (4x warm-run speedup) and reverted: XLA:CPU persists AOT
# executables whose reload is unreliable on this host (cpu_aot_loader
# machine-feature mismatch warnings, then sharded executables hang at
# collective rendezvous until the 40s watchdog hard-aborts the whole
# pytest process). Reproduced deterministically on cache hits of the
# dp2xfsdp4 checkpoint tests, 2026-07-30.

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devices = jax.devices()
    assert len(devices) == 8, f"expected 8 virtual devices, got {len(devices)}"
    return devices


@pytest.fixture()
def tmp_store(tmp_path):
    """A throwaway artifacts-store root."""
    root = tmp_path / "store"
    root.mkdir()
    return str(root)
