// C ABI over the slice pool, consumed from Python via ctypes
// (polyaxon_tpu/native/sliced.py). String results are written into
// caller-provided buffers as `key=value;` / line records — no JSON
// dependency on either side of the boundary.
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>

#include "pool.h"

using sliced::Gang;
using sliced::GangStateName;
using sliced::Pool;

namespace {

struct Handle {
  Pool pool;
  std::mutex mu;  // the Python agent may poll from multiple threads
};

int WriteOut(const std::string& text, char* buf, int len) {
  if (buf == nullptr || len <= 0) return -1;
  if (static_cast<int>(text.size()) + 1 > len) return -1;
  std::memcpy(buf, text.c_str(), text.size() + 1);
  return static_cast<int>(text.size());
}

}  // namespace

extern "C" {

void* sliced_new() { return new Handle(); }

void sliced_free(void* h) { delete static_cast<Handle*>(h); }

int sliced_add_slice(void* h, const char* name, const char* topology,
                     int preemptible) {
  Handle* handle = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> lock(handle->mu);
  return handle->pool.AddSlice(name, topology, preemptible != 0) ? 0 : -1;
}

int sliced_remove_slice(void* h, const char* name) {
  Handle* handle = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> lock(handle->mu);
  return handle->pool.RemoveSlice(name) ? 0 : -1;
}

int sliced_free_chips(void* h, const char* name) {
  Handle* handle = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> lock(handle->mu);
  return handle->pool.FreeChips(name);
}

long long sliced_request_gang(void* h, const char* run_uuid,
                              const char* topology, int priority,
                              int max_restarts) {
  Handle* handle = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> lock(handle->mu);
  return handle->pool.RequestGang(run_uuid, topology, priority, max_restarts);
}

int sliced_release_gang(void* h, long long gang_id) {
  Handle* handle = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> lock(handle->mu);
  return handle->pool.ReleaseGang(gang_id) ? 0 : -1;
}

// gang info as `state=running;slice=a;topology=2x2;offset=0,0,0;
// shape=1,2,2;chips=0,1,8,9;restarts=0;run=uuid`
int sliced_gang_info(void* h, long long gang_id, char* buf, int len) {
  Handle* handle = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> lock(handle->mu);
  const Gang* gang = handle->pool.GetGang(gang_id);
  if (gang == nullptr) return -1;
  std::string out = "state=";
  out += GangStateName(gang->state);
  out += ";slice=" + gang->placement.slice;
  out += ";topology=" + gang->requested.str();
  out += ";offset=";
  for (int d = 0; d < sliced::kMaxDims; ++d) {
    if (d) out += ',';
    out += std::to_string(gang->placement.offset[d]);
  }
  out += ";shape=";
  for (int d = 0; d < sliced::kMaxDims; ++d) {
    if (d) out += ',';
    out += std::to_string(gang->placement.shape[d]);
  }
  out += ";chips=";
  for (size_t i = 0; i < gang->placement.chips.size(); ++i) {
    if (i) out += ',';
    out += std::to_string(gang->placement.chips[i]);
  }
  out += ";restarts=" + std::to_string(gang->restarts);
  out += ";run=" + gang->run_uuid;
  return WriteOut(out, buf, len);
}

int sliced_heartbeat(void* h, long long gang_id, int proc, double now) {
  Handle* handle = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> lock(handle->mu);
  return handle->pool.Heartbeat(gang_id, proc, now) ? 0 : -1;
}

int sliced_preempt_slice(void* h, const char* name) {
  Handle* handle = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> lock(handle->mu);
  return handle->pool.PreemptSlice(name);
}

// Reconcile + drain events; one `gang_id KIND detail` record per line.
// On buffer overflow returns -1 and KEEPS the events queued, so the
// caller can retry with a bigger buffer without losing signals.
int sliced_tick(void* h, double now, double heartbeat_timeout, char* buf,
                int len) {
  Handle* handle = static_cast<Handle*>(h);
  std::lock_guard<std::mutex> lock(handle->mu);
  handle->pool.Tick(now, heartbeat_timeout);
  std::string out;
  for (const auto& event : handle->pool.PendingEvents()) {
    out += std::to_string(event.gang_id) + " " + event.kind + " " +
           event.detail + "\n";
  }
  int written = WriteOut(out, buf, len);
  if (written >= 0) handle->pool.ClearEvents();
  return written;
}

}  // extern "C"
