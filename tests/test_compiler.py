"""Compiler golden tests — compile-to-plan is pure and deterministic,
so assert structurally (the reference's highest-value test pattern,
SURVEY.md §4 "Compiler golden tests")."""

import json
import sys

import pytest

from polyaxon_tpu.compiler import COORDINATOR_PLACEHOLDER, compile_operation
from polyaxon_tpu.compiler.compile import CompilerError, ENV_JAXJOB_SPEC
from polyaxon_tpu.polyaxonfile import check_polyaxonfile, resolve_operation_context


def _compile(source, *, params=None, run_uuid="u1", store_dir=None):
    op = check_polyaxonfile(source, params=params)
    resolved = resolve_operation_context(
        op, params=params or {}, run_uuid=run_uuid, project_name="proj",
        artifacts_root="/store",
    )
    return compile_operation(
        resolved, run_uuid=run_uuid, artifacts_root="/store", project="proj",
        store_dir=store_dir,
    )


class TestJaxjobPlan:
    def test_llama_fsdp_plan_golden(self):
        plan = _compile("tests/fixtures/llama3_8b.yaml")
        assert plan.run_kind == "jaxjob"
        assert plan.resources.accelerator == "v5e"
        assert plan.resources.topology == "8x8"
        assert plan.resources.chips == 64
        assert plan.resources.hosts == 16          # 64 chips / 4 per host
        assert plan.resources.resources == {"google.com/tpu": 4}
        assert plan.num_processes == 16
        p0 = plan.processes[0]
        env = p0.env
        assert env["POLYAXON_RUN_UUID"] == "u1"
        assert env["POLYAXON_RUN_ARTIFACTS_PATH"] == "/store/u1"
        assert env["POLYAXON_RUN_OUTPUTS_PATH"] == "/store/u1/outputs"
        assert env["POLYAXON_TPU_NUM_PROCESSES"] == "16"
        assert env["POLYAXON_TPU_PROCESS_ID"] == "0"
        assert COORDINATOR_PLACEHOLDER in env["POLYAXON_TPU_COORDINATOR"]
        assert plan.processes[7].env["POLYAXON_TPU_PROCESS_ID"] == "7"
        spec = json.loads(env[ENV_JAXJOB_SPEC])
        assert spec["runtime"]["model"] == "llama3_8b"
        assert spec["runtime"]["learning_rate"] == 0.0003
        assert p0.command[0] == sys.executable

    def test_plan_deterministic(self):
        a = _compile("tests/fixtures/llama3_8b.yaml").to_dict()
        b = _compile("tests/fixtures/llama3_8b.yaml").to_dict()
        assert a == b

    def test_sidecar_injected_with_store(self):
        plan = _compile("tests/fixtures/mnist.yaml", store_dir="/remote/store")
        kinds = [s.kind for s in plan.sidecars]
        assert "sync" in kinds
        sync = plan.sidecars[kinds.index("sync")]
        assert "--store-dir" in sync.command

    def test_auth_init_phase_default(self):
        plan = _compile("tests/fixtures/mnist.yaml")
        assert [p.kind for p in plan.init][:1] == ["auth"]


class TestWatchdogKind:
    def test_watchdog_interval_wraps_in_watchloop(self):
        plan = _compile({
            "kind": "component",
            "run": {"kind": "watchdog", "intervalSeconds": 30,
                    "container": {"command": ["python", "-c", "print('wd')"]}},
        })
        assert plan.run_kind == "watchdog"
        cmd = plan.processes[0].command
        assert cmd[:3] == ["python", "-m", "polyaxon_tpu.utils.watchloop"]
        assert cmd[3] == "30" and cmd[-1] == "print('wd')"

    def test_watchdog_without_interval_runs_once(self):
        plan = _compile({
            "kind": "component",
            "run": {"kind": "watchdog",
                    "container": {"command": ["python", "-c", "print('wd')"]}},
        })
        assert plan.processes[0].command[-1] == "print('wd')"
        assert "watchloop" not in " ".join(plan.processes[0].command[:3])


class TestKubeflowPlans:
    def test_tfjob_tf_config(self):
        plan = _compile("tests/fixtures/resnet_tfjob.yaml")
        assert plan.run_kind == "tfjob"
        assert plan.num_processes == 4
        tf_config = json.loads(plan.processes[2].env["TF_CONFIG"])
        assert tf_config["task"] == {"type": "worker", "index": 2}
        assert len(tf_config["cluster"]["worker"]) == 4
        assert plan.resources.chips == 16  # 4 replicas x 4 chips

    def test_pytorchjob_rendezvous(self):
        plan = _compile("tests/fixtures/bert_pytorchjob.yaml")
        assert plan.num_processes == 4  # 1 master + 3 workers
        master = [p for p in plan.processes if p.replica_name == "master"][0]
        worker = [p for p in plan.processes if p.replica_name == "worker"][-1]
        assert master.env["RANK"] == "0"
        assert worker.env["WORLD_SIZE"] == "4"
        assert worker.env["MASTER_ADDR"].startswith("master-0")

    def test_empty_replicas_rejected(self):
        with pytest.raises(CompilerError):
            _compile({"kind": "component", "run": {"kind": "tfjob"}})


class TestIOEnv:
    def test_to_env_params(self):
        plan = _compile(
            {
                "kind": "component",
                "inputs": [{"name": "lr", "type": "float", "toEnv": "TRAIN_LR"}],
                "run": {"kind": "job", "container": {"image": "x", "command": ["run"]}},
            },
            params={"lr": 0.25},
        )
        assert plan.processes[0].env["TRAIN_LR"] == "0.25"

    def test_dag_not_compilable(self):
        with pytest.raises(CompilerError):
            _compile(
                {
                    "kind": "component",
                    "run": {"kind": "dag", "operations": []},
                }
            )


class TestCaptureProfile:
    """plugins.captureProfile → profile_steps in the jaxjob runtime."""

    def _plan(self, capture, runtime={"model": "llama_tiny"}):
        from polyaxon_tpu.compiler import compile_operation
        from polyaxon_tpu.polyaxonfile import get_operation

        run = {"kind": "jaxjob"}
        if runtime is not None:
            run["runtime"] = dict(runtime)
        else:
            run["container"] = {"command": ["python", "train.py"]}
        op = get_operation({
            "kind": "operation",
            "plugins": {"captureProfile": capture},
            "component": {"run": run},
        })
        return compile_operation(op, run_uuid="u1", artifacts_root="/tmp/x")

    def _spec_steps(self, plan):
        import json

        from polyaxon_tpu.compiler.compile import ENV_JAXJOB_SPEC

        spec = json.loads(plan.processes[0].env[ENV_JAXJOB_SPEC])
        return spec["runtime"].get("profileSteps") or spec["runtime"].get(
            "profile_steps")

    def test_bool_and_empty_dict_enable_defaults(self):
        assert self._spec_steps(self._plan(True)) == [3]
        assert self._spec_steps(self._plan({})) == [3]

    def test_scalar_step_normalized(self):
        assert self._spec_steps(self._plan({"steps": 7})) == [7]

    def test_bad_steps_fail_compile(self):
        import pytest

        from polyaxon_tpu.compiler import CompilerError

        with pytest.raises(CompilerError, match="steps"):
            self._plan({"steps": "everything"})

    def test_container_jaxjob_rejected(self):
        import pytest

        from polyaxon_tpu.compiler import CompilerError

        with pytest.raises(CompilerError, match="builtin jaxjob runtime"):
            self._plan(True, runtime=None)

    def test_false_disables(self):
        assert self._spec_steps(self._plan(False)) is None


class TestBuildSection:
    """``build:`` compiles into a gating pre-run init phase (VERDICT r4
    missing #3; upstream gates the main run on a builder run resolved
    from the hub and patches the main image with the built destination —
    SURVEY §2 "Polyflow IR")."""

    BUILDER = {
        "kind": "component",
        "name": "kaniko-like",
        "inputs": [
            {"name": "destination", "type": "str", "toEnv": "BUILD_DEST"},
            {"name": "context", "type": "str", "isOptional": True,
             "value": "."},
        ],
        "run": {
            "kind": "job",
            "container": {
                "command": ["python", "-c"],
                "args": ["print('built {{ params.destination }}')"],
            },
        },
    }

    def _resolver(self, ref):
        from polyaxon_tpu.polyaxonfile import get_component

        if ref != "builder":
            raise ValueError(f"hub component `{ref}` not found")
        return get_component(dict(self.BUILDER))

    def _op(self, build):
        return check_polyaxonfile({
            "kind": "operation",
            "build": build,
            "component": {
                "run": {"kind": "job",
                        "container": {"image": "app:raw",
                                      "command": ["python", "-c", "1"]}},
            },
        })

    def _compile_with_build(self, build):
        op = self._op(build)
        resolved = resolve_operation_context(op, run_uuid="u1")
        return compile_operation(
            resolved, run_uuid="u1", artifacts_root="/store",
            hub_resolver=self._resolver)

    def test_build_phase_golden(self):
        plan = self._compile_with_build({
            "hubRef": "builder",
            "params": {"destination": {"value": "app:v3"}},
        })
        assert plan.init[0].kind == "build"   # gates everything, first
        cfg = plan.init[0].config
        assert cfg["hubRef"] == "builder"
        # params rendered into the builder's own command template
        assert cfg["command"] == ["python", "-c", "print('built app:v3')"]
        # toEnv routing works for the builder's IO too
        assert cfg["env"]["BUILD_DEST"] == "app:v3"
        # main processes run the BUILT image, not the raw one
        assert cfg["destination"] == "app:v3"
        assert all(p.image == "app:v3" for p in plan.processes)

    def test_build_run_patch_applies(self):
        plan = self._compile_with_build({
            "hubRef": "builder",
            "params": {"destination": {"value": "app:v3"}},
            "runPatch": {"container": {
                "args": ["print('patched')"]}},
        })
        assert plan.init[0].config["command"] == [
            "python", "-c", "print('patched')"]

    def test_unresolvable_build_ref_fails_compile(self):
        with pytest.raises(CompilerError, match="ghost"):
            self._compile_with_build({
                "hubRef": "ghost",
                "params": {"destination": {"value": "x"}}})

    def test_build_without_hub_ref_fails(self):
        with pytest.raises(CompilerError, match="hubRef"):
            self._compile_with_build({"params": {}})

    def test_no_build_no_phase(self):
        plan = _compile("tests/fixtures/mnist.yaml")
        assert all(p.kind != "build" for p in plan.init)
