#!/bin/sh
# CI sweep: Python suites (8-device virtual CPU mesh), native
# sanitizers, and the bench smoke contract.
#
# Default = the SMOKE tier (-m smoke: every subsystem's happy path,
# minutes not the full suite's ~40; tier curated in tests/conftest.py).
# Pass --full for the complete suite (pre-push / nightly).
set -e
cd "$(dirname "$0")/.."
# Static-analysis gate (ISSUE 9, docs/static-analysis.md): AST rules
# over polyaxon_tpu/** — lock-order inversions, locks held across
# blocking I/O, host syncs / wall clock / unseeded RNG in the step
# path, store writes outside transaction(), un-cataloged metrics,
# silent swallows, undrained daemon threads. Cheapest gate, so it runs
# first. New findings fail here; suppressions live AT THE SITE as
# reasoned `# polycheck: ignore[rule] -- why` pragmas (the committed
# baseline is empty and only shrinks).
echo "== polycheck (static analysis gate)"
python -m polyaxon_tpu.analysis --check
# The gate must be able to FAIL: each planted violation must flip
# --check to exit 1 (the --deopt / --inject-reshard self-test pattern)
# so a refactor that quietly breaks an analyzer fails the build.
if python -m polyaxon_tpu.analysis --check --inject-lock-inversion >/dev/null 2>&1; then
    echo "polycheck self-test FAILED: injected lock inversion passed the gate"
    exit 1
fi
if python -m polyaxon_tpu.analysis --check --inject-uncataloged-metric >/dev/null 2>&1; then
    echo "polycheck self-test FAILED: injected uncataloged metric passed the gate"
    exit 1
fi
python -m pytest tests/test_analysis.py -q -m 'not slow'
if [ "$1" = "--full" ]; then
    # Single-process full suite — the default since the XLA:CPU
    # collective-watchdog root cause was fixed and validated (two
    # consecutive green runs, tests/conftest.py NOTE 2; VERDICT r5 #7
    # promoted it). The old per-module loop survives below as
    # --full-modules: the crash-isolation fallback if a native
    # flake ever resurfaces (scripts/debug_fullsuite.sh remains the
    # diagnostic harness with faulthandler + RSS sampling).
    echo "== pytest (full, single process; --full-modules = per-module fallback)"
    python -m pytest tests/ -q
elif [ "$1" = "--full-modules" ]; then
    # Crash fallback: one pytest process per module bounds each
    # process's compile-cache/lifetime and isolates a native crash to
    # one module's rerun; accumulate failures instead of aborting at
    # the first failing module (set -e would otherwise mask later
    # modules' results).
    echo "== pytest (full, per-module processes)"
    rc=0
    failed=""
    for mod in tests/test_*.py; do
        echo "-- $mod"
        python -m pytest "$mod" -q || { rc=1; failed="$failed $mod"; }
    done
    if [ "$rc" -ne 0 ]; then
        echo "FAILED modules:$failed"
        exit "$rc"
    fi
else
    echo "== pytest (smoke tier; use --full for the whole suite)"
    python -m pytest tests/ -q -m smoke
fi
# Chaos stage: every fault plan is fixed-seed/counter-deterministic
# (tests/test_chaos.py), so this runs in tier-1 on every invocation —
# restart policies, store retries, checkpoint fallback, gang reaping,
# and serving load-shedding all exercised under injected faults.
echo "== chaos drills (fixed-seed fault plans)"
python -m pytest tests/test_chaos.py -q -m chaos
# Elastic-gang stage (ISSUE 14): chaos kills slices mid-train twice
# against the REAL agent/scheduler/runtime — the run must SUCCEED with
# both resizes (shrink then regrow) recorded as timeline spans and
# loss-curve continuity judged by the telemetry oracle; the
# budget-exhausted path must degrade cleanly to PREEMPTED → backoff
# requeue; the slow-marked prewarm-failure drills (induced PrewarmError
# on shrink and on grow) prove the fallback-to-requeue seam.
echo "== elastic gangs (shrink/regrow drills + prewarm fallbacks)"
python -m pytest tests/test_elastic.py -q -m elastic
# Multi-tier checkpointing stage (ISSUE 16): the cross-tier fallback
# ladder on the REAL TieredCheckpointManager — tier-0 hit, corrupt
# replica → local spill (with re-promotion), cheap tiers gone → store,
# all tiers corrupt at latest → older clean step — plus the atomic
# spill commit, the tier0-loss chaos seam, the restore-phase audit in
# the attribution report, and the acceptance timing claim (tier-0
# measurably beats the store round trip on the same checkpoint).
echo "== tiered checkpointing (fallback ladder + restore audit)"
python -m pytest tests/test_checkpoint_tiers.py -q
# Scheduling stage: multi-tenant admission invariants (queue priority,
# fair-share convergence, quota walls, bounded starvation, the
# preemption-for-priority drill) — deterministic and CPU-only.
echo "== scheduling invariants (queues/quotas/fair-share/preemption)"
python -m pytest tests/test_scheduling.py -q -m scheduling
# Host/device overlap stage: prefetch pipeline + vectorized generators
# on CPU — functional invariants (resume-exactness, drain-on-stop,
# per-(seed,i) determinism) plus the `perf`-marked relative-timing
# checks (prefetch-vs-sync throughput, compile-cache reuse).
echo "== input pipeline (prefetch/generators/compile-cache)"
python -m pytest tests/test_prefetch.py -q
# Alert-rule schema gate: the committed default ruleset
# (polyaxon_tpu/obs/rules.json) must load clean — unknown metric names
# (checked against the registry catalog), malformed windows, duplicate
# rule ids, bad kinds/ops all fail the build HERE, not as an alert
# that silently never fires in production.
echo "== obs rules (schema-validate the committed ruleset)"
python -c "from polyaxon_tpu.obs import rules; \
    raise SystemExit(rules._main(['--check']))"
# Telemetry-oracle schema gate (ISSUE 13): the committed invariant set
# (polyaxon_tpu/obs/oracle.json) must load clean — unknown kinds/ops,
# metric names outside the registry catalog, duplicate ids, bad
# quantiles/objectives all fail HERE, not as an invariant that
# silently never judges anything.
echo "== obs oracle (schema-validate the committed invariant set)"
python -c "from polyaxon_tpu.obs import oracle; \
    raise SystemExit(oracle._main(['--check']))"
# Observability stage: span/registry/timeline invariants plus the
# analysis plane (ISSUE 6) — alert-rule fire→hysteresis→resolve
# lifecycle, histogram_quantile goldens, label-cardinality cap,
# flight-recorder ring bounds + dump-on-FAILED — and the acceptance
# drills: an e2e jaxjob whose report's phase decomposition sums to the
# wall clock, and a chaos gauntlet that leaves a postmortem.json, a
# fired-then-resolved retry-storm alert, and an attributed report.
echo "== observability (spans / registry / rules / reports / flight)"
python -m pytest tests/test_obs.py tests/test_oracle.py -q -m obs
# Serving-request observability drill (ISSUE 10): concurrent streams
# against a real continuous server must leave queue→prefill→decode
# span timelines behind /requests/{id}/timeline, per-class TTFT/TPOT
# series on a line-parsed /metrics scrape, and shed-load accounting;
# the TTFT burn-rule fire→resolve episode rides the obs run above
# (TestServingObsDrill). The tracing-overhead parity check (on vs off
# within 5%) is slow-marked and runs under --full.
echo "== serving observability (request timelines / SLO series)"
python -m pytest "tests/test_serving.py::TestRequestObservability" -q
# Radix prefix-cache smoke (ISSUE 11): the real server under the
# shared-system-prompt mix, paged vs paged-nocache. --check-prefix
# fails the build unless the radix tree actually served prefill tokens
# (prefix_hit_rate > 0) AND the page refcount/CoW invariants came out
# clean after the run (kv_invariant_violations == 0) — a leak or
# double-free in the fork/evict/release lifecycle fails HERE, not as
# pool exhaustion hours into a soak.
echo "== radix prefix-cache smoke (hit rate + refcount invariants)"
JAX_PLATFORMS=cpu python scripts/bench_serve.py --model llama_tiny \
    --quick --workload shared-prefix --slots 2 --kv-page-size 8 \
    --configs paged,paged-nocache --check-prefix \
    --out /tmp/bench_serve_smoke.json
# Lane A/B smoke (ISSUE 18): interleaved vs disaggregated
# prefill/decode over the long-prompt-storm mix, paired per trial.
# --check-lanes fails the build unless pages actually moved
# prefill→decode (handoffs > 0), refcount invariants came out clean
# on BOTH arms, decode gap p99 stayed <= 1.15x interleaved (the
# whole point of the split), and prefill throughput held >= 0.90x
# (pacing, not starvation).
echo "== lane A/B smoke (disaggregated prefill/decode handoff)"
JAX_PLATFORMS=cpu python scripts/bench_serve.py --model llama_tiny \
    --quick --workload long-prompt-storm --slots 4 --kv-page-size 8 \
    --check-lanes --out /tmp/bench_serve_lanes.json
# The lane gate must be able to FAIL: zeroing the decode lane budget
# starves every request of its decode steps — nothing completes, and
# the run must exit 1.
if JAX_PLATFORMS=cpu python scripts/bench_serve.py --model llama_tiny \
    --quick --workload long-prompt-storm --slots 4 --kv-page-size 8 \
    --inject lane-starve --out /tmp/bench_serve_starve.json \
    >/dev/null 2>&1; then
    echo "lane self-test FAILED: a starved decode lane passed the gate"
    exit 1
fi
# Class-admission A/B (ISSUE 19): a thousand-plus concurrent
# mixed-class streams land on a slot-camped engine, three arms
# (interactive-only unloaded, class-aware admission + preemptive
# eviction, FIFO baseline). --check-classes fails the build unless
# interactive TTFT p99 stays <= 1.5x its unloaded value WITH
# best-effort preemptions > 0 (the policy actually fired), page
# refcount invariants clean on every arm, peak concurrency >= 1000,
# and the FIFO pair beaten (p99 lower, aggregate tok/s >= 0.90x).
echo "== class-admission A/B (thousand-stream preemption gate)"
JAX_PLATFORMS=cpu python scripts/bench_serve.py --model llama_tiny \
    --streams 1100 --check-classes --out /tmp/bench_serve_classes.json
# The class gate must be able to FAIL: disabling eviction leaves
# interactive TTFT at the natural-retirement wall with zero
# preemptions, and the run must exit 1.
if JAX_PLATFORMS=cpu python scripts/bench_serve.py --model llama_tiny \
    --streams 120 --check-classes --inject no-preempt \
    --out /tmp/bench_serve_nopreempt.json >/dev/null 2>&1; then
    echo "class self-test FAILED: disabled preemption passed the gate"
    exit 1
fi
# Fleet-sim stage (ISSUE 8): drive the REAL scheduler + admission +
# store through the quick load points (idle → storm, seconds not the
# full compressed day) and gate tick cost against
# polyaxon_tpu/sim/budgets.json — a refactor that reintroduces
# per-status scans or per-pass live rebuilds fails HERE on the
# deterministic per-tick query count, not at the next fleet incident.
# The module's fast tier (trace/budget/executor classes) rides along;
# full-curve and day-trace tests run under --full. Update budgets
# after an INTENTIONAL change: python -m polyaxon_tpu.sim
# --update-budgets.
echo "== fleet sim (control-plane tick budgets)"
JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim --quick --check --json '' >/dev/null
JAX_PLATFORMS=cpu python -m pytest tests/test_sim.py -q -m 'not slow'
# Mini-gauntlet (ISSUE 13): a compressed composed episode — low-prio
# train + preemptible tune churn + serving deploys + a preemption
# storm + a chaos plan — through the REAL scheduler/admission/store,
# judged EXCLUSIVELY by telemetry-oracle verdicts (obs/oracle.json):
# all runs terminal, phase accounting closes, zero unresolved alerts.
echo "== mini-gauntlet (oracle-judged fleet episode)"
JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim --gauntlet
# The oracle must be able to FAIL: suppressing the scheduler's
# preempted-run requeue path strands the storm's victims in PREEMPTED,
# and the all-runs-terminal invariant must flip the stage to exit 1.
if JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim --gauntlet \
    --inject stuck-requeue >/dev/null 2>&1; then
    echo "gauntlet self-test FAILED: stuck requeues passed the oracle"
    exit 1
fi
# ...and so must the elastic lane: wedging resize completion strands
# the shrink mid-flight (resizing=True forever), and the oracle's
# all-runs-terminal invariant must flip the stage to exit 1.
if JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim --gauntlet \
    --inject stuck-resize >/dev/null 2>&1; then
    echo "gauntlet self-test FAILED: stuck resize passed the oracle"
    exit 1
fi
# Cluster-day gauntlet (ISSUE 15): the compressed day — morning trace,
# Hyperband sweep lane, cron + DAG lanes, real-engine serving under
# continuous mixed-class traffic, store-fault chaos, and a MARKED
# mid-day preemption storm — judged exclusively by oracle verdicts,
# including metric_during (interactive serving p99 inside the storm
# window) and quota_violation (no sampled instant over quota). The
# full day profile is the slow-marked tier; CI runs the compressed
# form.
echo "== cluster-day gauntlet (window-scoped oracle verdicts)"
JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim --cluster-day --quick
# The quota invariant must be able to FAIL: bypassing admission's
# quota check while the limit gauges stay published must put sampled
# usage over the limit, and quota-violations-zero must flip the stage
# to exit 1.
if JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim --cluster-day --quick \
    --inject quota-breach >/dev/null 2>&1; then
    echo "cluster-day self-test FAILED: quota breach passed the oracle"
    exit 1
fi
# The checkpoint ladder must DEGRADE, not fail: dropping the tier-0
# replica and local spill on every restore (tier0-loss chaos) forces
# the whole day onto the store tier — the day must still PASS (the
# tier-0 restore-budget anchor rightly skips: no tier-0 samples land).
echo "== cluster-day tier0-loss drill (store fallback must carry the day)"
JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim --cluster-day --quick \
    --inject tier0-loss >/dev/null
# ...and the commit protocol must be able to FAIL: wedging tier-1
# commits (tmp written, rename withheld) strands every gang behind an
# uncommitted checkpoint, and all-runs-terminal must flip to exit 1.
if JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim --cluster-day --quick \
    --inject stuck-tier0-commit >/dev/null 2>&1; then
    echo "cluster-day self-test FAILED: wedged tier commits passed the oracle"
    exit 1
fi
# Incident replay (ISSUE 13): the committed preemption-storm
# postmortem converts deterministically into an arrival trace and
# replays through the real control plane; the oracle must see every
# run terminal and a clean alert board at the end.
echo "== incident replay (committed scenario, oracle-judged)"
JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim \
    --replay polyaxon_tpu/sim/scenarios/preemption-storm.json >/dev/null
# ISSUE 16 companion scenario: a mid-storm preemption whose rerun
# found both cheap checkpoint tiers gone and walked the ladder to the
# store (budget floor breached, alert fired→resolved) — replayed
# against a loaded fleet, the oracle must still come back clean.
JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim \
    --replay polyaxon_tpu/sim/scenarios/tier0-loss-during-storm.json >/dev/null
# ISSUE 17 companion scenario: an interactive traffic spike that
# drove a rule-fired scale-up (warm-standby promotion mid-spike) and
# a post-quiet drain + scale-down — replayed against a loaded fleet,
# the oracle must come back clean.
JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim \
    --replay polyaxon_tpu/sim/scenarios/traffic-spike-scale.json >/dev/null
# Serving fleet (ISSUE 17): real-engine replicas behind the
# prefix-affinity router + SLO-driven autoscaler — spike traffic in a
# marked window, rule-fired warm-standby promotion, drain-before-
# release scale-down; judged by the telemetry oracle (interactive
# TTFT p99 inside the scale-up window) plus the fleet-wide prefix
# hit-rate floor and per-replica KV invariants.
echo "== serving fleet (prefix-affinity router + SLO autoscaler)"
JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim --fleet-serve --quick
# The hit-rate gate must be able to FAIL: a router that round-robins
# (ignoring affinity AND the hash) sprays conversations across
# replicas; under the episode's deliberately tight per-replica KV
# budget every replica churns through everyone's prefixes and the
# fleet-wide hit rate collapses below the floor.
if JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim --fleet-serve --quick \
    --inject route-blind >/dev/null 2>&1; then
    echo "fleet-serve self-test FAILED: blind routing passed the gate"
    exit 1
fi
# ...and so must the scale-up SLO: skipping prewarm leaves the
# promoted standby's jit caches empty, its first in-window requests
# eat the XLA compiles, and serving-ttft-during-scaleup must flip the
# stage to exit 1.
if JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim --fleet-serve --quick \
    --inject cold-scale >/dev/null 2>&1; then
    echo "fleet-serve self-test FAILED: cold scale-up passed the TTFT oracle"
    exit 1
fi
# Fleet telemetry (ISSUE 20): every replica records through its own
# component-scoped registry view, the oracle judges the FEDERATED
# per-component series, and the coverage gate requires every replica
# that served to appear as a component. The red-team half: building
# one replica without its scoped view (it records unscoped — every
# aggregate SLO number still looks healthy) must flip the episode to
# exit 1 on federated-view coverage.
echo "== fleet telemetry (scoped views + federated coverage)"
if JAX_PLATFORMS=cpu python -m polyaxon_tpu.sim --fleet-serve --quick \
    --inject mute-replica >/dev/null 2>&1; then
    echo "fleet-telemetry self-test FAILED: muted replica passed the federated-view gate"
    exit 1
fi
# Communication-audit stage: compile every standard schedule's REAL
# train step on the 8-device virtual CPU mesh, census the collectives
# in the compiled HLO, and gate against polyaxon_tpu/perf/budgets.json
# — an accidental reshard (a rule-table typo, a manual schedule's spec
# gathering the batch) fails CI here instead of silently costing a
# multiple at the next measurement round. The module's fast tier
# (parser/gate/probe-containment) rides along; its slow-marked golden
# recompiles run under --full. Update budgets after an INTENTIONAL
# sharding change: python -m polyaxon_tpu.perf --update-budgets.
echo "== communication audit (collective budgets)"
python -m polyaxon_tpu.perf --check --json ''
python -m pytest tests/test_perf_audit.py -q -m 'not slow'
# Overlap-budget stage (ISSUE 12): compile the standard schedules
# against a TPU topology description with the latency-hiding scheduler
# pinned, measure each schedule's collective overlap_ratio from the
# scheduled HLO, and gate against the _overlap floors in
# perf/budgets.json — a knob/scheduler regression that re-serializes
# the fsdp all-gathers fails CI here, not at the next MFU measurement.
# Exit 3 = the probe itself found no workable topology (no TPU
# compiler on this host): recorded as a skip, not a red build. Update
# floors after an INTENTIONAL schedule change:
# python -m polyaxon_tpu.perf --audit --update-budgets.
echo "== overlap budget (async-collective latency hiding)"
overlap_rc=0
python -m polyaxon_tpu.perf --audit --check --json '' || overlap_rc=$?
if [ "$overlap_rc" -eq 3 ]; then
    echo "overlap budget: SKIPPED (no workable TPU topology on this host)"
elif [ "$overlap_rc" -ne 0 ]; then
    exit "$overlap_rc"
else
    # The gate must be able to FAIL: forcing the scheduler OFF must
    # flip --check to exit 1 (one schedule keeps the self-test cheap).
    if python -m polyaxon_tpu.perf --audit --check --schedules fsdp \
        --inject-serialize --json '' >/dev/null 2>&1; then
        echo "overlap self-test FAILED: serialized compile passed the gate"
        exit 1
    fi
fi
echo "== native ASan/UBSan"
make -C native sanitize
printf 'ADD a 4x4 0\nREQ r 2x2 0 0\nTICK 0 30\nQUIT\n' | ./native/build/sliced_san >/dev/null
echo "== native TSan stress"
make -C native tsan
TSAN_OPTIONS=halt_on_error=1 ./native/build/sliced_tsan
echo "== bench smoke"
# Contract check only (one JSON line): forced onto CPU so CI does not
# depend on the TPU tunnel; the driver benches real hardware itself.
JAX_PLATFORMS=cpu python bench.py --smoke
echo "CI OK"
