"""Host/device overlap: prefetching input pipeline, vectorized
synthetic generators, persistent compile cache (ISSUE 3).

Functional invariants (determinism, resume-exactness, drain-on-stop,
exception propagation) are exact; the relative-timing assertions
(throughput parity, cache-hit compile speedup) carry the `perf` marker
and retry internally because this 1-core host schedules noisily.
"""

import os
import threading

import numpy as np
import pytest

from polyaxon_tpu.polyflow import V1JAXJob
from polyaxon_tpu.runtime import data as data_lib, run_jaxjob


def _prefetch_threads():
    return [t for t in threading.enumerate()
            if t.name == "plx-data-prefetch" and t.is_alive()]


def _job(steps=6, mesh=None, **runtime_extra):
    runtime = {
        "model": "llama_tiny",
        "dataset": "lm_synthetic",
        "steps": steps,
        "learning_rate": 1e-3,
        "batch_size": 2,
        "seq_len": 32,
        "log_every": 2,
        **runtime_extra,
    }
    return V1JAXJob.from_dict({
        "kind": "jaxjob",
        "mesh": {"axes": mesh or {"dp": 2, "fsdp": 4}},
        "runtime": runtime,
    })


class TestVectorizedGenerators:
    """The searchsorted-Zipf and cumsum-packed generators must keep the
    stream contract the loop's resume depends on: batch i is a pure
    function of (seed, i)."""

    def test_lm_synthetic_deterministic_per_seed_i(self):
        kw = dict(batch_size=4, seq_len=64, vocab_size=32_000, seed=11)
        a = data_lib.get_dataset("lm_synthetic", **kw)
        b = data_lib.get_dataset("lm_synthetic", **kw)
        a0, a1 = next(a), next(a)
        np.testing.assert_array_equal(next(b)["tokens"], a0["tokens"])
        # start_batch=k replays batch k exactly (the resume seek).
        c = data_lib.get_dataset("lm_synthetic", start_batch=1, **kw)
        np.testing.assert_array_equal(next(c)["tokens"], a1["tokens"])
        # Different i → different batch (the stream moves).
        assert not np.array_equal(a0["tokens"], a1["tokens"])

    def test_lm_synthetic_range_and_zipf_skew(self):
        batch = next(data_lib.get_dataset(
            "lm_synthetic", batch_size=8, seq_len=256, vocab_size=32_000,
            seed=0))
        tok = batch["tokens"]
        assert tok.dtype == np.int32
        assert tok.min() >= 0 and tok.max() < 32_000
        # Zipf mass concentrates at low ranks: the bottom 1% of ids must
        # carry far more mass than the top half (≈55% vs ≈7% analytically).
        low = (tok < 320).mean()
        high = (tok >= 16_000).mean()
        assert low > 0.3 > high, (low, high)

    def test_lm_packed_synthetic_deterministic_and_structure(self):
        kw = dict(batch_size=4, seq_len=128, vocab_size=1000,
                  mean_doc_len=16, seed=9)
        a = data_lib.get_dataset("lm_packed_synthetic", **kw)
        a0, a1 = next(a), next(a)
        b = data_lib.get_dataset("lm_packed_synthetic", start_batch=1, **kw)
        b1 = next(b)
        np.testing.assert_array_equal(b1["tokens"], a1["tokens"])
        np.testing.assert_array_equal(b1["segments"], a1["segments"])
        seg, tok = a0["segments"], a0["tokens"]
        assert tok.min() >= 2 and tok.max() < 1000
        # Segment ids: start at 0, monotone, step by at most 1 (cumsum
        # over doc ends), and rows actually pack multiple documents.
        assert (seg[:, 0] == 0).all()
        d = np.diff(seg, axis=1)
        assert ((d == 0) | (d == 1)).all()
        assert (seg.max(axis=1) >= 2).all()

    def test_mean_doc_len_one_terminates(self):
        # Degenerate knob: doc length floor clamps to 1 instead of
        # sampling zero-length docs forever.
        batch = next(data_lib.get_dataset(
            "lm_packed_synthetic", batch_size=1, seq_len=16,
            vocab_size=100, mean_doc_len=1, seed=0))
        assert batch["segments"].shape == (1, 16)


class TestPrefetchIterator:
    def test_preserves_order_and_content(self):
        kw = dict(batch_size=2, seq_len=16, vocab_size=500, seed=4)
        sync = data_lib.get_dataset("lm_synthetic", **kw)
        pf = data_lib.PrefetchIterator(
            data_lib.get_dataset("lm_synthetic", **kw), depth=3)
        try:
            for _ in range(8):
                np.testing.assert_array_equal(next(pf)["tokens"],
                                              next(sync)["tokens"])
        finally:
            pf.close()
        assert not pf.alive

    def test_close_drains_and_joins(self):
        pf = data_lib.PrefetchIterator(
            data_lib.get_dataset("lm_synthetic", batch_size=2, seq_len=16),
            depth=2)
        next(pf)  # producer is certainly live
        pf.close()
        assert not pf.alive
        assert not _prefetch_threads()

    def test_producer_exception_propagates(self):
        def boom():
            yield {"x": np.zeros(1)}
            yield {"x": np.ones(1)}
            raise RuntimeError("generator exploded")

        pf = data_lib.PrefetchIterator(boom(), depth=2)
        assert next(pf)["x"][0] == 0
        assert next(pf)["x"][0] == 1
        with pytest.raises(RuntimeError, match="generator exploded"):
            next(pf)
        pf.close()
        assert not pf.alive

    def test_finite_iterator_stops(self):
        pf = data_lib.PrefetchIterator(iter(range(3)), depth=2)
        assert list(pf) == [0, 1, 2]
        pf.close()
        assert not pf.alive

    def test_rejects_zero_depth(self):
        with pytest.raises(ValueError, match="depth"):
            data_lib.PrefetchIterator(iter(()), depth=0)


class TestLoopPrefetch:
    def test_metrics_carry_input_wait_and_compile_time(self, cpu_devices):
        seen = []
        result = run_jaxjob(_job(steps=6, prefetch=2),
                            on_metrics=lambda s, m: seen.append(m))
        throughput_emissions = [m for m in seen if "tokens_per_sec" in m]
        assert throughput_emissions
        for m in throughput_emissions:
            assert m["input_wait_ms"] >= 0
        # compile_time_s is one-shot, on the first emission.
        assert "compile_time_s" in seen[0]
        assert sum("compile_time_s" in m for m in seen) == 1
        assert result.compile_time_s > 0
        assert result.input_wait_ms >= 0
        # The producer thread never outlives its run.
        assert not _prefetch_threads()

    def test_drain_on_should_stop_no_leaked_threads(self, cpu_devices):
        calls = {"n": 0}

        def should_stop():
            calls["n"] += 1
            return calls["n"] > 2

        result = run_jaxjob(_job(steps=50, prefetch=3),
                            should_stop=should_stop)
        assert result.steps < 50
        assert not _prefetch_threads()

    def test_exception_in_loop_drains_threads(self, cpu_devices):
        def bad_metrics(step, vals):
            raise RuntimeError("callback exploded")

        with pytest.raises(RuntimeError, match="callback exploded"):
            run_jaxjob(_job(steps=6, prefetch=2, log_every=1),
                       on_metrics=bad_metrics)
        assert not _prefetch_threads()

    def test_prefetch_resume_exact(self, cpu_devices, tmp_path):
        """Restore at step k yields the identical batch sequence (and so
        identical final loss) to a never-interrupted run — prefetched-
        but-unconsumed batches are regenerated, not replayed stale."""
        def spec(steps, prefetch):
            return V1JAXJob.from_dict({
                "kind": "jaxjob",
                "mesh": {"axes": {"dp": -1}},
                "checkpointing": {"enabled": True, "intervalSteps": 4,
                                  "asyncSave": False},
                "runtime": {"model": "llama_tiny", "steps": steps,
                            "batch_size": 2, "seq_len": 16,
                            "learning_rate": 1e-3, "prefetch": prefetch},
            })

        straight = run_jaxjob(spec(8, 2), artifacts_dir=str(tmp_path / "a"))
        run_jaxjob(spec(4, 2), artifacts_dir=str(tmp_path / "b"))
        resumed = run_jaxjob(spec(8, 2), artifacts_dir=str(tmp_path / "b"))
        assert resumed.restored_from_step == 4
        assert abs(straight.final_metrics["loss"]
                   - resumed.final_metrics["loss"]) < 1e-5
        # And the prefetched stream IS the synchronous stream: the same
        # run with prefetch off lands on the same loss.
        sync = run_jaxjob(spec(8, 0), artifacts_dir=str(tmp_path / "c"))
        assert abs(straight.final_metrics["loss"]
                   - sync.final_metrics["loss"]) < 1e-5
        assert not _prefetch_threads()


class TestCompileCacheResolution:
    """Dir resolution is pure env/config logic — no jax involved."""

    def test_precedence_and_kill_switch(self, monkeypatch):
        from polyaxon_tpu.runtime import compile_cache as cc

        monkeypatch.delenv(cc.ENV_CACHE, raising=False)
        monkeypatch.delenv(cc.ENV_CACHE_DIR, raising=False)
        assert cc.resolve_cache_dir(None) is None  # opt-in: off by default
        assert cc.resolve_cache_dir("/cfg") == "/cfg"
        monkeypatch.setenv(cc.ENV_CACHE_DIR, "/envdir")
        assert cc.resolve_cache_dir(None) == "/envdir"
        assert cc.resolve_cache_dir("/cfg") == "/cfg"  # config wins
        monkeypatch.setenv(cc.ENV_CACHE, "0")  # force-disable beats all
        assert cc.resolve_cache_dir("/cfg") is None

    def test_executor_resolves_shared_default(self, tmp_path, monkeypatch):
        """POLYAXON_TPU_COMPILE_CACHE=1 without an explicit dir: the
        executor points every gang (env-inherited) at ONE cache under
        the agent's artifacts root, so a preemption-requeued run finds
        the first attempt's executables."""
        from polyaxon_tpu.agent.executor import LocalExecutor
        from polyaxon_tpu.controlplane import ControlPlane
        from polyaxon_tpu.runtime import compile_cache as cc

        monkeypatch.setenv(cc.ENV_CACHE, "1")
        monkeypatch.delenv(cc.ENV_CACHE_DIR, raising=False)
        plane = ControlPlane(str(tmp_path / "home"))
        LocalExecutor(plane)
        assert os.environ[cc.ENV_CACHE_DIR] == os.path.join(
            plane.artifacts_root, cc.SHARED_CACHE_DIRNAME)


@pytest.mark.perf
@pytest.mark.slow
class TestOverlapPerf:
    """Relative-timing assertions; retried internally (host-load
    sensitive on this oversubscribed 1-core runner). `slow`: they burn
    ~80s of repeated jaxjob runs, so they live in the ci.sh input-
    pipeline stage (which runs this whole module) rather than tier-1."""

    def test_prefetch_throughput_not_worse_than_sync(self, cpu_devices):
        """`prefetch: 2` must not lose to `prefetch: 0` in the same
        process: with a spare core the overlap is a win; on this 1-core
        host the producer and device compete, so the honest bound is
        parity within scheduler noise."""
        def tps(prefetch):
            result = run_jaxjob(_job(
                steps=14, prefetch=prefetch, seq_len=64, batch_size=2,
                log_every=10**9))
            return result.throughput

        best_ratio = 0.0
        for _ in range(3):
            sync = tps(0)
            overlapped = tps(2)
            best_ratio = max(best_ratio, overlapped / sync)
            if best_ratio >= 1.0:
                break
        assert best_ratio >= 0.9, best_ratio
        assert not _prefetch_threads()

    def test_compile_cache_reuse_across_runs(self, cpu_devices, tmp_path):
        """Two identical run_jaxjob invocations against one persistent
        compile cache: the second's warm-up (compile_time_s) is a disk
        load, not an XLA compile. Single-device mesh on purpose — this
        host's XLA:CPU AOT reload of SHARDED executables is the known
        hazard tests/conftest.py documents."""
        import jax

        cache = str(tmp_path / "xla-cache")

        def run(tag):
            return run_jaxjob(
                _job(steps=2, mesh={"dp": 1}, log_every=1,
                     compile_cache_dir=cache),
                artifacts_dir=str(tmp_path / tag),
                devices=jax.devices()[:1])

        cold = run("cold")
        import os
        assert os.listdir(cache), "cache dir is empty after a cold run"
        warm = run("warm")
        assert warm.compile_time_s < cold.compile_time_s, (
            cold.compile_time_s, warm.compile_time_s)
        # Scoped config: the run restored the global jax setting.
        assert jax.config.jax_compilation_cache_dir is None
