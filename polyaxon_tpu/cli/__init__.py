"""``plx`` — the CLI (SURVEY.md §2 "CLI" [K]).

Mirrors the reference's command surface (run / ops / projects / config /
check / models) against the embedded control plane. State lives under
``$POLYAXON_TPU_HOME`` (default ``~/.polyaxon_tpu``).

Usage: ``python -m polyaxon_tpu.cli <command> ...``
"""

from polyaxon_tpu.cli.main import cli

__all__ = ["cli"]
