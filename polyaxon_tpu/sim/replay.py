"""Incident replay (ISSUE 13): captured evidence becomes a committed
regression scenario.

The flight recorder writes ``postmortem.json`` when a run dies and the
serving engine now dumps its request-timeline ring at shutdown
(``obs.reqtrace.dump_ring``) — both are write-only evidence until this
adapter turns them into :mod:`sim.traces` arrival traces that replay
through the REAL scheduler/admission/store (``FleetSim``) and are
judged by the telemetry oracle (``obs.oracle``), closing ROADMAP item
4's loop: every real incident can be committed under
``sim/scenarios/`` as a permanent regression.

Determinism is the contract: conversion is pure arithmetic over the
captured dump (timestamps rebased to t=0 and compressed into a fixed
horizon; no wall clock, no randomness beyond the scenario's own
seed), so the same scenario file yields byte-identical trace JSON
(:func:`trace_to_json`) and identical oracle verdicts across runs.

Scenario file shape::

    {
      "name": "preemption-storm",
      "description": "...",
      "source_kind": "postmortem" | "ring",
      "postmortem": {...}   # flight.dump payload  (source_kind one of)
      "ring": {...},        # reqtrace ring dump
      "horizon": 6.0,       # compressed seconds the incident maps onto
      "background": {"jobs": 40, "churn": 10, "seed": 13}
    }
"""

from __future__ import annotations

import json
import random
from typing import Any, Optional

from polyaxon_tpu.sim import traces
from polyaxon_tpu.sim.traces import TraceEvent, job_op

DEFAULT_HORIZON = 6.0

# Serving request class → scheduler queue, for ring-dump replays.
_CLASS_QUEUE = {"interactive": "prod", "batch": "batch",
                "best-effort": "best-effort"}

# Ring annotations that mark a disruption worth replaying as a
# preemption event (chaos.* matches by prefix).
_DISRUPTION_EVENTS = ("requeue", "preempted", "retry")


def _record_time(record: dict) -> Optional[float]:
    for key in ("start", "time"):
        value = record.get(key)
        if isinstance(value, (int, float)):
            return float(value)
    return None


def _rebaser(times: list[float], horizon: float):
    """start-of-incident → 0, end → ``horizon``; an instantaneous
    incident (or none) maps everything to 0."""
    if not times:
        return lambda t: 0.0
    t0, t1 = min(times), max(times)
    span = t1 - t0
    if span <= 0:
        return lambda t: 0.0
    scale = horizon / span
    return lambda t: round(min(max(t - t0, 0.0) * scale, horizon), 6)


def trace_from_postmortem(pm: dict, *, horizon: float = DEFAULT_HORIZON,
                          project: str = "platform") -> list[TraceEvent]:
    """A flight-recorder dump as an arrival trace: the incident run is
    resubmitted at t=0 (with restart churn when it died restartable),
    and every requeue/retry/chaos annotation in its ring replays as a
    preemption storm at its rebased offset — so the disruption pattern
    that killed the original run hits the replay fleet in the same
    relative rhythm."""
    ring = pm.get("ring") or []
    times = [t for t in (_record_time(r) for r in ring) if t is not None]
    rebase = _rebaser(times, horizon)
    uuid = str(pm.get("run_uuid") or "incident")
    status = str(pm.get("status") or "").lower()
    restarts = status in ("failed", "preempted", "retrying")
    events = [TraceEvent(
        0.0, "churn" if restarts else "job",
        job_op(queue="best-effort", restart=restarts,
               name=f"replay-{uuid[:8]}"),
        project)]
    storm_offsets: set[float] = set()
    for record in ring:
        t = _record_time(record)
        if t is None:
            continue
        for event in record.get("events") or []:
            name = str(event.get("name") or "")
            if name in _DISRUPTION_EVENTS or name.startswith("chaos."):
                offset = rebase(t)
                if offset in storm_offsets:
                    continue
                storm_offsets.add(offset)
                events.append(TraceEvent(offset, "storm", None,
                                         payload={"fraction": 0.5,
                                                  "source": name}))
    events.sort(key=lambda e: (e.at, e.kind))
    return events


def trace_from_ring_dump(dump: dict, *, horizon: float = DEFAULT_HORIZON,
                         project: str = "serving") -> list[TraceEvent]:
    """A serving request-timeline ring as an arrival trace: each
    captured request arrives at its rebased submit offset as a short
    job on the queue its class maps to, so the mixed-class arrival
    pattern (and any burst that overloaded admission) replays against
    the real queues."""
    requests = dump.get("requests") or []
    starts = []
    for req in requests:
        start = (req.get("summary") or {}).get("start")
        if isinstance(start, (int, float)):
            starts.append(float(start))
    rebase = _rebaser(starts, horizon)
    events: list[TraceEvent] = []
    for req in requests:
        summary = req.get("summary") or {}
        start = summary.get("start")
        if not isinstance(start, (int, float)):
            continue
        klass = str(summary.get("class") or "batch")
        queue = _CLASS_QUEUE.get(klass, "batch")
        rid = str(summary.get("request_id") or "req")
        events.append(TraceEvent(
            rebase(float(start)), "job",
            job_op(queue=queue, name=f"req-{rid[:8]}"),
            project))
    events.sort(key=lambda e: (e.at, e.kind))
    return events


# ----------------------------------------------------------- scenarios
def load_scenario(source: Any) -> dict:
    if isinstance(source, str):
        with open(source) as fh:
            source = json.load(fh)
    if not isinstance(source, dict):
        raise ValueError("scenario must be a JSON object")
    kind = source.get("source_kind")
    if kind not in ("postmortem", "ring"):
        raise ValueError(f"scenario source_kind must be postmortem|ring, "
                         f"got {kind!r}")
    if kind not in source:
        raise ValueError(f"scenario is missing its {kind!r} payload")
    return source


def scenario_trace(scenario: dict) -> list[TraceEvent]:
    """Scenario file → full arrival trace: the incident-derived events
    plus the scenario's seeded background fill (so the replay exercises
    contention, not an empty fleet). Pure function of the scenario."""
    horizon = float(scenario.get("horizon", DEFAULT_HORIZON))
    if scenario["source_kind"] == "postmortem":
        events = trace_from_postmortem(scenario["postmortem"],
                                       horizon=horizon)
    else:
        events = trace_from_ring_dump(scenario["ring"], horizon=horizon)
    background = scenario.get("background") or {}
    rng = random.Random(int(background.get("seed", 0)))
    for _ in range(int(background.get("jobs", 0))):
        queue = rng.choice(("batch", "best-effort", None))
        events.append(TraceEvent(round(rng.uniform(0, horizon), 6), "job",
                                 job_op(queue=queue),
                                 rng.choice(traces.PROJECTS)))
    for _ in range(int(background.get("churn", 0))):
        events.append(TraceEvent(round(rng.uniform(0, horizon), 6), "churn",
                                 job_op(queue="best-effort", restart=True),
                                 rng.choice(traces.PROJECTS)))
    events.sort(key=lambda e: (e.at, e.kind, e.project))
    return events


def trace_to_json(events: list[TraceEvent]) -> str:
    """Canonical bytes for a trace — the determinism witness the
    round-trip test compares (sorted keys, no whitespace, offsets
    rounded where they were built)."""
    rows = [{"at": event.at, "kind": event.kind, "spec": event.spec,
             "project": event.project, "payload": event.payload}
            for event in events]
    return json.dumps(rows, sort_keys=True, separators=(",", ":"))


def replay_scenario(source: Any, *, seed: int = 0, max_wall: float = 120.0,
                    capacity: int = 24,
                    oracle_source: Any = None) -> dict:
    """Replay one scenario through the real control plane and judge
    the end state with the oracle. A fresh ``AlertEngine`` (committed
    ruleset) watches every few ticks — its rate/burn windows read the
    shared metrics history, so the replayed incident produces the same
    fire→resolve arcs a live one would — and the whole episode is
    bracketed by a named ``replay`` window marker (storm events inside
    mark their own ``storm`` windows via the sim), so during-window
    invariants scope to the replayed phases exactly like live runs."""
    import time as _time

    from polyaxon_tpu.obs import history as obs_history
    from polyaxon_tpu.obs import metrics as obs_metrics
    from polyaxon_tpu.obs import oracle as obs_oracle
    from polyaxon_tpu.obs import rules as obs_rules
    from polyaxon_tpu.sim.fleet import FleetSim

    scenario = load_scenario(source)
    events = scenario_trace(scenario)
    invariants = obs_oracle.load_invariants(oracle_source)
    sim = FleetSim(seed=seed, capacity=capacity)
    clock_skew = [0.0]
    engine = obs_rules.AlertEngine(
        obs_rules.load_ruleset(),
        clock=lambda: _time.time() + clock_skew[0])
    history = obs_history.default_history()
    baseline = obs_metrics.REGISTRY.snapshot()
    try:
        orig_tick = sim.tick

        def tick_with_alerts() -> None:
            orig_tick()
            if len(sim.tick_seconds) % 5 == 0:
                engine.evaluate(plane=sim.plane)

        sim.tick = tick_with_alerts
        history.mark_window("replay", start=True)
        sim_result = sim.run_trace(events, max_wall=max_wall)
        history.mark_window("replay", end=True)
        # The fleet is drained: jump the engine clock past every rate/
        # burn window so firings the incident legitimately tripped
        # resolve, leaving the fire→resolve arc in history evidence.
        clock_skew[0] = 600.0
        engine.evaluate(plane=sim.plane)
        bundle = obs_oracle.TelemetryBundle.from_plane(
            sim.plane, engine=engine, baseline=baseline)
        oracle_result = obs_oracle.summarize(
            obs_oracle.evaluate(invariants, bundle))
    finally:
        sim.close()
    return {
        "scenario": scenario.get("name"),
        "source_kind": scenario["source_kind"],
        "trace_events": len(events),
        "sim": sim_result,
        "oracle": oracle_result,
    }
