"""Planted unbatched store writes (golden: invariant-store-batch).
The transaction-wrapped twin is the negative control."""


def promote(store, uuid):
    store.transition(uuid, "scheduled")
    store.transition(uuid, "starting")


def promote_batched(store, uuid):
    with store.transaction():
        store.transition(uuid, "scheduled")
        store.transition(uuid, "starting")
