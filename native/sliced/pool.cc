#include "pool.h"

#include <algorithm>

namespace sliced {

const char* GangStateName(GangState s) {
  switch (s) {
    case GangState::kPending: return "pending";
    case GangState::kRunning: return "running";
    case GangState::kRestarting: return "restarting";
    case GangState::kFailed: return "failed";
    case GangState::kPreempted: return "preempted";
    case GangState::kReleased: return "released";
  }
  return "unknown";
}

// ---------------------------------------------------------------- inventory
bool Pool::AddSlice(const std::string& name, const std::string& topology,
                    bool preemptible) {
  if (slices_.count(name)) return false;
  Slice slice;
  slice.name = name;
  slice.preemptible = preemptible;
  if (!ParseTopology(topology, &slice.topology)) return false;
  slice.owner.assign(slice.topology.chips(), -1);
  slices_[name] = std::move(slice);
  return true;
}

bool Pool::RemoveSlice(const std::string& name) {
  auto it = slices_.find(name);
  if (it == slices_.end()) return false;
  PreemptSlice(name);
  slices_.erase(it);
  return true;
}

int Pool::FreeChips(const std::string& name) const {
  auto it = slices_.find(name);
  if (it == slices_.end()) return -1;
  int free = 0;
  for (int64_t owner : it->second.owner) free += owner < 0 ? 1 : 0;
  return free;
}

std::vector<std::string> Pool::SliceNames() const {
  std::vector<std::string> names;
  for (const auto& [name, _] : slices_) names.push_back(name);
  return names;
}

// -------------------------------------------------------------- placement
namespace {

// All distinct orderings of `want` padded with 1s onto `ndims` axes.
std::vector<std::array<int, kMaxDims>> ShapePermutations(const Topology& want,
                                                         int ndims) {
  std::array<int, kMaxDims> base{1, 1, 1};
  for (int i = 0; i < want.ndims; ++i) base[i] = want.dims[i];
  std::sort(base.begin(), base.begin() + ndims);
  std::vector<std::array<int, kMaxDims>> perms;
  do {
    perms.push_back(base);
  } while (std::next_permutation(base.begin(), base.begin() + ndims));
  return perms;
}

}  // namespace

std::optional<Placement> Pool::FindPlacementOn(const Slice& slice,
                                               const Topology& want) const {
  if (want.chips() > slice.topology.chips()) return std::nullopt;
  // A request with more (non-trivial) dims than the slice torus cannot
  // be ICI-contiguous there; silently dropping axes would under-allocate.
  for (int d = slice.topology.ndims; d < want.ndims; ++d)
    if (want.dims[d] > 1) return std::nullopt;
  const Topology& topo = slice.topology;
  std::optional<Placement> best;
  int best_score = -1;
  int best_linear = 0;

  for (const auto& shape : ShapePermutations(want, topo.ndims)) {
    bool fits = true;
    for (int d = 0; d < topo.ndims; ++d) fits &= shape[d] <= topo.dims[d];
    if (!fits) continue;

    std::array<int, kMaxDims> offset{0, 0, 0};
    // Enumerate all offsets (wraparound keeps a sub-torus ICI-contiguous).
    auto advance = [&]() {
      for (int d = topo.ndims - 1; d >= 0; --d) {
        if (++offset[d] < topo.dims[d]) return true;
        offset[d] = 0;
      }
      return false;
    };
    do {
      // A full-ring dim only tiles once: skip duplicate rotations.
      bool redundant = false;
      for (int d = 0; d < topo.ndims; ++d)
        redundant |= shape[d] == topo.dims[d] && offset[d] != 0;
      if (redundant) continue;

      std::vector<int> chips;
      chips.reserve(want.chips());
      bool free = true;
      std::array<int, kMaxDims> rel{0, 0, 0};
      auto advance_rel = [&]() {
        for (int d = topo.ndims - 1; d >= 0; --d) {
          if (++rel[d] < shape[d]) return true;
          rel[d] = 0;
        }
        return false;
      };
      do {
        std::array<int, kMaxDims> coord{0, 0, 0};
        for (int d = 0; d < topo.ndims; ++d)
          coord[d] = (offset[d] + rel[d]) % topo.dims[d];
        int idx = CoordToIndex(topo, coord);
        if (slice.owner[idx] >= 0) {
          free = false;
          break;
        }
        chips.push_back(idx);
      } while (advance_rel());
      if (!free) continue;

      int score = 0;  // prefer shape-aligned offsets: less fragmentation
      for (int d = 0; d < topo.ndims; ++d)
        score += offset[d] % shape[d] == 0 ? 1 : 0;
      int linear = CoordToIndex(topo, offset);
      if (score > best_score || (score == best_score && linear < best_linear)) {
        Placement p;
        p.slice = slice.name;
        p.offset = offset;
        p.shape = shape;
        std::sort(chips.begin(), chips.end());
        p.chips = std::move(chips);
        best = std::move(p);
        best_score = score;
        best_linear = linear;
      }
    } while (advance());
  }
  return best;
}

std::optional<Placement> Pool::FindPlacement(const Topology& want) const {
  // Deterministic order; prefer the tightest fit (least leftover chips)
  // so small gangs don't fragment big slices.
  std::vector<const Slice*> order;
  for (const auto& [_, slice] : slices_) order.push_back(&slice);
  std::sort(order.begin(), order.end(), [](const Slice* a, const Slice* b) {
    if (a->topology.chips() != b->topology.chips())
      return a->topology.chips() < b->topology.chips();
    return a->name < b->name;
  });
  for (const Slice* slice : order) {
    auto p = FindPlacementOn(*slice, want);
    if (p) return p;
  }
  return std::nullopt;
}

bool Pool::CanEverFit(const Topology& want) const {
  for (const auto& [_, slice] : slices_) {
    Slice empty = slice;
    std::fill(empty.owner.begin(), empty.owner.end(), -1);
    if (FindPlacementOn(empty, want)) return true;
  }
  return false;
}

void Pool::Occupy(const Placement& p, int64_t gang_id) {
  Slice& slice = slices_.at(p.slice);
  for (int chip : p.chips) slice.owner[chip] = gang_id;
}

void Pool::Vacate(const Placement& p) {
  auto it = slices_.find(p.slice);
  if (it == slices_.end()) return;
  for (int chip : p.chips) it->second.owner[chip] = -1;
}

// ------------------------------------------------------------------ gangs
int64_t Pool::RequestGang(const std::string& run_uuid,
                          const std::string& topology, int priority,
                          int max_restarts) {
  Topology want;
  if (!ParseTopology(topology, &want)) return -1;
  if (!CanEverFit(want)) return -2;
  Gang gang;
  const int64_t id = next_id_++;
  gang.id = id;
  gang.run_uuid = run_uuid;
  gang.requested = want;
  gang.priority = priority;
  gang.max_restarts = max_restarts;
  gangs_[id] = std::move(gang);
  TryPlacePending(0.0);
  return id;
}

bool Pool::ReleaseGang(int64_t id) {
  auto it = gangs_.find(id);
  if (it == gangs_.end()) return false;
  Gang& gang = it->second;
  if (gang.state == GangState::kRunning || gang.state == GangState::kRestarting)
    Vacate(gang.placement);
  gangs_.erase(it);  // a long-lived agent must not accumulate dead gangs
  TryPlacePending(0.0);
  return true;
}

const Gang* Pool::GetGang(int64_t id) const {
  auto it = gangs_.find(id);
  return it == gangs_.end() ? nullptr : &it->second;
}

// ---------------------------------------------------------------- signals
bool Pool::Heartbeat(int64_t id, int proc, double now) {
  auto it = gangs_.find(id);
  if (it == gangs_.end()) return false;
  Gang& gang = it->second;
  if (gang.state != GangState::kRunning && gang.state != GangState::kRestarting)
    return false;
  gang.heartbeats[proc] = now;
  if (gang.state == GangState::kRestarting) gang.state = GangState::kRunning;
  return true;
}

int Pool::PreemptSlice(const std::string& name) {
  auto it = slices_.find(name);
  if (it == slices_.end()) return -1;
  int evicted = 0;
  for (auto& [id, gang] : gangs_) {
    if ((gang.state == GangState::kRunning ||
         gang.state == GangState::kRestarting) &&
        gang.placement.slice == name) {
      Vacate(gang.placement);
      gang.state = GangState::kPreempted;
      gang.heartbeats.clear();
      events_.push_back({id, "PREEMPTED", "slice " + name + " evicted"});
      ++evicted;
    }
  }
  return evicted;
}

// -------------------------------------------------------------- reconcile
bool Pool::TryEvictFor(const Gang& want) {
  // Cheapest eviction: the preemptible slice where removing the fewest
  // strictly-lower-priority gangs frees a placement.
  std::string best_slice;
  std::vector<int64_t> best_victims;
  std::optional<Placement> best_placement;

  for (const auto& [name, slice] : slices_) {
    if (!slice.preemptible) continue;
    std::vector<int64_t> victims;
    for (const auto& [id, gang] : gangs_) {
      if ((gang.state == GangState::kRunning ||
           gang.state == GangState::kRestarting) &&
          gang.placement.slice == name && gang.priority < want.priority)
        victims.push_back(id);
    }
    if (victims.empty()) continue;
    Slice trial = slice;
    for (int64_t v : victims)
      for (int chip : gangs_.at(v).placement.chips) trial.owner[chip] = -1;
    auto p = FindPlacementOn(trial, want.requested);
    if (!p) continue;
    // Minimal victim set: only gangs whose chips the placement actually
    // needs are evicted (greedy — a different offset might overlap even
    // fewer, but never evict a gang the chosen placement doesn't touch).
    std::vector<int64_t> needed;
    for (int64_t v : victims) {
      const auto& chips = gangs_.at(v).placement.chips;
      bool overlaps = false;
      for (int chip : p->chips)
        overlaps |= std::find(chips.begin(), chips.end(), chip) != chips.end();
      if (overlaps) needed.push_back(v);
    }
    if (best_slice.empty() || needed.size() < best_victims.size()) {
      best_slice = name;
      best_victims = needed;
      best_placement = p;
    }
  }
  if (!best_placement) return false;
  for (int64_t v : best_victims) {
    Gang& victim = gangs_.at(v);
    Vacate(victim.placement);
    victim.state = GangState::kPreempted;
    victim.heartbeats.clear();
    events_.push_back(
        {v, "PREEMPTED", "evicted for higher-priority gang " +
                             std::to_string(want.id)});
  }
  return true;
}

void Pool::TryPlacePending(double now) {
  (void)now;
  std::vector<Gang*> pending;
  for (auto& [_, gang] : gangs_)
    if (gang.state == GangState::kPending) pending.push_back(&gang);
  std::sort(pending.begin(), pending.end(), [](const Gang* a, const Gang* b) {
    if (a->priority != b->priority) return a->priority > b->priority;
    return a->id < b->id;
  });
  for (Gang* gang : pending) {
    auto p = FindPlacement(gang->requested);
    if (!p && TryEvictFor(*gang)) p = FindPlacement(gang->requested);
    if (!p) continue;
    gang->placement = *p;
    gang->state = GangState::kRunning;
    Occupy(*p, gang->id);
    events_.push_back({gang->id, "PLACED",
                       p->slice + " offset " +
                           std::to_string(CoordToIndex(
                               slices_.at(p->slice).topology, p->offset))});
  }
}

void Pool::Tick(double now, double heartbeat_timeout) {
  for (auto& [id, gang] : gangs_) {
    if (gang.state != GangState::kRunning || gang.heartbeats.empty()) continue;
    double oldest = now;
    for (const auto& [_, ts] : gang.heartbeats) oldest = std::min(oldest, ts);
    if (now - oldest <= heartbeat_timeout) continue;
    events_.push_back({id, "LOST", "heartbeat stale"});
    if (gang.restarts < gang.max_restarts) {
      ++gang.restarts;
      gang.state = GangState::kRestarting;  // chips stay reserved
      gang.heartbeats.clear();
      events_.push_back({id, "RESTART",
                         "attempt " + std::to_string(gang.restarts) + "/" +
                             std::to_string(gang.max_restarts)});
    } else {
      gang.state = GangState::kFailed;
      Vacate(gang.placement);
      events_.push_back({id, "FAILED", "restarts exhausted"});
    }
  }
  TryPlacePending(now);
}

std::vector<Event> Pool::DrainEvents() {
  std::vector<Event> out;
  out.swap(events_);
  return out;
}

}  // namespace sliced
