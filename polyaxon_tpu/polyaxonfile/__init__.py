from polyaxon_tpu.polyaxonfile.context import ContextError, default_globals, render_value
from polyaxon_tpu.polyaxonfile.patch import patch_dict
from polyaxon_tpu.polyaxonfile.reader import (
    PolyaxonfileError,
    apply_presets,
    check_polyaxonfile,
    get_component,
    get_operation,
    load_specs,
    resolve_operation_context,
    spec_kind,
)

__all__ = [
    "ContextError",
    "PolyaxonfileError",
    "apply_presets",
    "check_polyaxonfile",
    "default_globals",
    "get_component",
    "get_operation",
    "load_specs",
    "patch_dict",
    "render_value",
    "resolve_operation_context",
    "spec_kind",
]
