"""Page-pool allocator for paged-KV continuous batching.

Host-side bookkeeping for the device-side paged cache
(``models/llama.py`` paged surface): a fixed pool of KV pages shared by
all slots, per-slot block tables mapping position//page_size → page id.
Memory then scales with tokens actually held instead of the dense
engine's slots × max_len reservation, so `--kv-pages` can deliberately
oversubscribe (admission waits for pages; a live row that cannot
extend fails loudly rather than corrupting a neighbour).

Page 0 is scratch — never allocated; idle rows and masked holes write
there (see ``paged_coords``). The allocator is plain numpy/ints on the
host: allocation happens between decode steps at Python speed, never
inside the compiled program.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class PagePool:
    def __init__(self, slots: int, max_len: int, page_size: int,
                 n_pages: int, prefix_cache: bool = True):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = page_size
        self.max_pages_per_row = -(-max_len // page_size)
        # Page 0 is scratch: usable pages are 1..n_pages-1.
        if n_pages < 2:
            raise ValueError(f"kv pool needs >= 2 pages, got {n_pages}")
        self.n_pages = n_pages
        self._free = list(range(n_pages - 1, 0, -1))
        self.tables = np.full((slots, self.max_pages_per_row), -1, np.int32)
        # Prefix cache: prompt pages FULLY covered by prefill positions
        # are content-addressed by their token chain, shared via
        # refcounts, and kept resident after release (LRU-evicted only
        # under allocation pressure) — a repeated system prompt costs
        # its KV once. Decode pages are never shared: their content
        # diverges per request.
        self.prefix_cache = prefix_cache
        self._ref = np.zeros(n_pages, np.int32)
        self._by_key: dict = {}  # token-chain key -> page id
        self._key_of: dict = {}  # page id -> key
        self._cached: dict = {}  # retired-but-resident pages, LRU order
        # Pages whose prefix key THIS slot registered during its
        # current tenancy — the only keys a failed admission must
        # invalidate (hit pages hold content from completed prefills).
        self._fresh_keys: dict[int, set] = {}
        self.prefix_hits = 0
        self.prefix_misses = 0

    @classmethod
    def dense_equivalent(cls, slots: int, max_len: int, page_size: int,
                         prefix_cache: bool = True) -> "PagePool":
        """Pool sized to the dense engine's reservation (+ scratch)."""
        maxp = -(-max_len // page_size)
        return cls(slots, max_len, page_size, slots * maxp + 1,
                   prefix_cache=prefix_cache)

    @property
    def free_pages(self) -> int:
        """Allocatable pages: truly free + retired-but-resident cache."""
        return len(self._free) + len(self._cached)

    def pages_for(self, length: int) -> int:
        return -(-max(length, 1) // self.page_size)

    def utilization(self) -> dict:
        """Pool occupancy in the user's units (usable pages — the
        scratch page is internal): the engine-tick gauges and /v1/stats
        both read this one snapshot. `free` counts allocatable pages,
        so retired-but-resident prefix-cache pages land there."""
        total = self.n_pages - 1
        free = self.free_pages
        used = max(total - free, 0)
        return {"total": total, "used": used, "free": free,
                "fraction": round(used / total, 4) if total else 0.0}

    def _shareable(self, length: int, tokens) -> int:
        if not (self.prefix_cache and tokens is not None):
            return 0
        return min((length - 1) // self.page_size, self.pages_for(length))

    def _plan(self, length: int, tokens) -> int:
        """Allocatable units this admission actually consumes: prefix
        hits on LIVE pages (shared with another row) cost nothing;
        hits on resident pages and every miss/private page cost one."""
        need = self.pages_for(length)
        consume = 0
        shareable = self._shareable(length, tokens)
        for i in range(need):
            if i < shareable:
                page = self._by_key.get(
                    tuple(tokens[:(i + 1) * self.page_size]))
                if page is not None and self._ref[page] > 0:
                    continue  # live share: no new allocation
            consume += 1
        return consume

    def can_admit(self, length: int, tokens=None) -> bool:
        return self._plan(length, tokens) <= self.free_pages

    def _alloc_one(self):
        """One page: free list first, then evict the LRU resident
        prefix page. None = pool genuinely dry."""
        if self._free:
            return self._free.pop()
        if self._cached:
            page = next(iter(self._cached))
            del self._cached[page]
            key = self._key_of.pop(page, None)
            if key is not None:
                self._by_key.pop(key, None)
            return page
        return None

    def admit(self, slot: int, length: int,
              tokens: Optional[list] = None) -> bool:
        """Allocate pages covering positions 0..length-1 for ``slot``.
        With ``tokens`` (the full prompt) and prefix caching on, pages
        fully covered by the PREFILL positions (0..length-2) reuse
        pages whose token chain matches — their KV content is identical
        by construction, so the prefill's idempotent rewrite of shared
        pages is harmless. False = nothing allocated.

        Page i is shareable iff fully inside the prefill range: the
        decode write at length-1 (and everything after) must land on
        private pages."""
        need = self.pages_for(length)
        if self._plan(length, tokens) > self.free_pages:
            return False
        row = self.tables[slot]
        assert (row < 0).all(), f"slot {slot} admitted while still holding pages"
        ps = self.page_size
        shareable = self._shareable(length, tokens)
        fresh = self._fresh_keys.setdefault(slot, set())
        for i in range(need):
            page = None
            if i < shareable:
                key = tuple(tokens[:(i + 1) * ps])
                hit = self._by_key.get(key)
                if hit is not None:
                    page = hit
                    if page in self._cached:
                        del self._cached[page]  # claim the resident page
                    self.prefix_hits += 1
                else:
                    page = self._alloc_one()
                    if page is not None:
                        self._by_key[key] = page
                        self._key_of[page] = key
                        fresh.add(page)  # key valid only after prefill
                        self.prefix_misses += 1
            else:
                page = self._alloc_one()
            if page is None:
                # _plan said this fits, so this branch is belt-and-
                # braces against accounting drift: roll back cleanly
                # rather than corrupt the row.
                self.release(slot, invalidate_prefix=True)
                return False
            row[i] = page
            self._ref[page] += 1
        return True

    def ensure(self, slot: int, pos: int) -> bool:
        """Make position ``pos`` writable for ``slot`` (allocating its
        page if new). False = pool exhausted; the row keeps its pages."""
        idx = pos // self.page_size
        if idx >= self.max_pages_per_row:
            return False
        if self.tables[slot, idx] >= 0:
            return True
        page = self._alloc_one()
        if page is None:
            return False
        self.tables[slot, idx] = page
        self._ref[page] += 1
        return True

    def release(self, slot: int, invalidate_prefix: bool = False) -> None:
        """Drop the slot's references. A page at refcount 0 returns to
        the free list — unless it is a prefix page, which stays
        resident (LRU) so the next identical prompt hits it.

        ``invalidate_prefix``: the slot's admission failed before its
        prefill wrote the pages — only the keys THIS slot freshly
        registered are dropped; pages it merely hit carry content from
        completed prefills and stay shareable."""
        row = self.tables[slot]
        fresh = self._fresh_keys.pop(slot, set())
        for idx in np.flatnonzero(row >= 0):
            page = int(row[idx])
            self._ref[page] -= 1
            if self._ref[page] <= 0:
                self._ref[page] = 0
                key = self._key_of.get(page)
                if key is not None and invalidate_prefix and page in fresh:
                    del self._key_of[page]
                    self._by_key.pop(key, None)
                    key = None
                if key is not None:
                    self._cached.pop(page, None)
                    self._cached[page] = True  # to LRU tail
                else:
                    self._free.append(page)
        row[:] = -1

    def invalidate_prefix_cache(self) -> None:
        """Forget every resident prefix page (device cache rebuilt →
        their content is gone). Pages still referenced by live rows
        keep their allocation but lose their shareability."""
        for page in list(self._cached):
            del self._cached[page]
            self._free.append(page)
        self._by_key.clear()
        self._key_of.clear()

    def padded_row(self, slot: int) -> np.ndarray:
        """The slot's block-table row (fixed [max_pages_per_row])."""
        return self.tables[slot]
