"""Attention ops with selectable implementations.

``impl``:
- ``"xla"``   einsum attention with fp32 softmax — the always-correct
  reference; XLA fuses it well on TPU for moderate sequence lengths.
- ``"flash"`` Pallas blocked flash attention (TPU): O(S) memory, MXU
  tiled; falls back to xla off-TPU (ops/flash.py).
- ``"ring"``  context-parallel ring attention over the cp mesh axis:
  KV blocks rotate around the ICI ring via ppermute inside shard_map
  while queries stay resident (ops/ring.py). Net-new vs the reference
  (SURVEY.md §5.7: long-context is absent upstream).

All impls take [B, S, H, D] and GQA (n_kv_heads <= n_heads) layouts.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """[B, S, Hkv, D] -> [B, S, Hkv*n_rep, D] for grouped-query attention."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(b, s, h * n_rep, d)


def xla_attention_with_lse(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, Hkv, D]
    v: jax.Array,  # [B, Sk, Hkv, D]
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    window: Optional[int] = None,
) -> tuple[jax.Array, jax.Array]:
    """Einsum attention that also returns the row logsumexp
    ``[B, H, Sq]`` (f32) — the flash residual. Partial attentions over
    key shards merge exactly via (o, lse), which is what ring attention
    does with the per-block results. Plain differentiable jnp: no
    custom vjp needed. When jitted with the lse unused, XLA dead-code
    eliminates it, so ``xla_attention`` is this function's first half."""
    if window is not None:
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if not causal:
            raise ValueError("sliding window requires causal attention")
    n_rep = q.shape[2] // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scale = softmax_scale if softmax_scale is not None else q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        # Offset supports decode/extension where Sq < Sk.
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        if window is not None:
            # Sliding window: each query sees the last `window` keys
            # (its own position included).
            mask &= jnp.triu(jnp.ones((sq, sk), dtype=bool),
                             k=sk - sq - window + 1)
        logits = jnp.where(mask[None, None], logits, -1e30)
    if segment_ids is not None:
        seg_mask = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        logits = jnp.where(seg_mask, logits, -1e30)
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    unnorm = jnp.exp(logits - m)
    denom = jnp.sum(unnorm, axis=-1, keepdims=True)
    lse = (m + jnp.log(denom))[..., 0]  # [B, H, Sq]
    probs = (unnorm / denom).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v), lse


def xla_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, Hkv, D]
    v: jax.Array,  # [B, Sk, Hkv, D]
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    window: Optional[int] = None,
) -> jax.Array:
    return xla_attention_with_lse(
        q, k, v, causal=causal, segment_ids=segment_ids,
        softmax_scale=softmax_scale, window=window)[0]


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    impl: str = "xla",
    segment_ids: Optional[jax.Array] = None,
    axis_name: Optional[str] = None,
    window: Optional[int] = None,
    block_q: Optional[int] = None,   # flash tile tuning (None = default)
    block_k: Optional[int] = None,
    bwd_impl: Optional[str] = None,  # flash bwd: "pallas" | "xla"
) -> jax.Array:
    flash_kwargs = {k_: v_ for k_, v_ in (
        ("block_q", block_q), ("block_k", block_k),
        ("bwd_impl", bwd_impl)) if v_ is not None}
    if impl == "auto":
        # Flash on real TPU (it self-falls-back when shapes don't tile);
        # einsum reference elsewhere. Flash knobs are tolerated here —
        # they apply when flash is picked — so configs stay portable.
        impl = "flash" if jax.default_backend() == "tpu" else "xla"
    elif flash_kwargs and impl != "flash":
        # An explicitly non-flash impl with flash tuning knobs is a
        # config error, not something to ignore silently (a sweep
        # against the wrong impl measures nothing).
        raise ValueError(
            f"flash tuning knobs {sorted(flash_kwargs)} require "
            f"impl='flash' (or 'auto'), got `{impl}`")
    if impl == "xla":
        return xla_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                             window=window)
    if impl == "flash":
        from polyaxon_tpu.ops.flash import flash_attention

        return flash_attention(q, k, v, causal=causal, window=window,
                               segment_ids=segment_ids, **flash_kwargs)
    if segment_ids is not None:
        raise ValueError(
            f"segment_ids (packed sequences) only supported by "
            f"impl='xla'/'flash', got `{impl}`"
        )
    if window is not None:
        raise ValueError(
            f"sliding window is supported by impl='xla'/'flash', got `{impl}`")
    if impl == "ring":
        from polyaxon_tpu.ops.ring import ring_attention

        return ring_attention(q, k, v, causal=causal, axis_name=axis_name or "cp")
    if impl == "ulysses":
        from polyaxon_tpu.ops.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, causal=causal, axis_name=axis_name or "cp")
    raise ValueError(f"Unknown attention impl `{impl}`")
