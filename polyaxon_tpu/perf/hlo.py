"""Collective accounting over compiled HLO text.

The sharded program GSPMD emits makes every byte of inter-device
traffic explicit as a collective instruction; parsing the
post-optimization module therefore gives an exact op census and a
shape-derived traffic estimate without running a single step. Wire
bytes use the standard ring-algorithm costs **per participant**:

    all-reduce          2 * B * (g-1)/g     (reduce-scatter + all-gather)
    all-gather          B_out * (g-1)/g     (B_out = gathered result)
    reduce-scatter      B_out * (g-1)       (receives (g-1)/g of input)
    all-to-all          B * (g-1)/g         (keeps 1/g locally)
    collective-permute  B                   (one hop per pair)

where ``g`` is the replica-group size. These are estimates of traffic
*volume* — topology (ICI hop count, DCN crossings) is out of scope; the
budget gate cares about op counts and byte deltas, both of which these
formulas rank faithfully.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Optional

COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

# f8 variants first so "f8e4m3fn" doesn't half-match "f8".
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# `%name = <result-type> <op>(`. The result type is everything between
# `=` and the op token — matched that way because TPU HLO layouts embed
# colons and parens (`bf16[4,2048]{2,1,0:T(2,128)(2,1)S(1)}`) that
# defeat any character-class spelling. Async collectives appear as
# `-start`/`-done` pairs; only the `-start` carries the transfer (the
# `-done` result aliases it), so `-done` lines never match the op
# pattern (the kind token must be followed directly by `(`).
_ASSIGN_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w\.\-]+)\s*=\s*(?P<rest>.+)$")
_OP_RE = re.compile(
    r"(?:^|\s)(?P<op>"
    + "|".join(k + r"(?:-start)?" for k in COLLECTIVE_KINDS)
    + r")\(",
)
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_GROUPS_RE = re.compile(r"replica_groups=\{\{([^}]*)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[")
_PAIRS_RE = re.compile(r"source_target_pairs=\{\{")


@dataclasses.dataclass
class CollectiveOp:
    kind: str            # canonical kind (no -start suffix)
    name: str            # HLO instruction name
    result_bytes: int    # total bytes of the result shape(s)
    group_size: int      # replica-group participants
    wire_bytes: float    # estimated bytes on the wire per participant
    line: str            # the source line (diagnostics / report detail)


def _shape_bytes_list(type_str: str) -> list[int]:
    out = []
    for dtype, dims in _SHAPE_RE.findall(type_str):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue  # token[], opaque[] — carry no payload
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append(n * size)
    return out


def _result_bytes(type_str: str, async_start: bool) -> int:
    """Payload bytes of a collective's result type.

    Sync form: the (possibly tuple) result IS the payload — sum it.
    ``-start`` form: the result tuple aliases (source, destination,
    context scalars); summing would double-count the transfer, so take
    the largest member (the destination — equal to the sync form's
    result for every kind)."""
    sizes = _shape_bytes_list(type_str)
    if not sizes:
        return 0
    return max(sizes) if async_start else sum(sizes)


def _group_size(line: str, n_devices: Optional[int]) -> int:
    m = _GROUPS_RE.search(line)
    if m:
        first = [p for p in m.group(1).split(",") if p.strip()]
        return max(len(first), 1)
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return max(int(m.group(2)), 1)
    if _PAIRS_RE.search(line):
        return 2  # permute: pairwise
    return max(n_devices or 1, 1)


def _wire_bytes(kind: str, result_bytes: int, g: int) -> float:
    if g <= 1:
        return 0.0
    if kind == "all-reduce":
        return 2.0 * result_bytes * (g - 1) / g
    if kind == "all-gather":
        return result_bytes * (g - 1) / g
    if kind == "reduce-scatter":
        return float(result_bytes) * (g - 1)
    if kind == "all-to-all":
        return result_bytes * (g - 1) / g
    if kind == "collective-permute":
        return float(result_bytes)
    raise ValueError(f"unknown collective kind {kind!r}")


def parse_collectives(hlo_text: str,
                      n_devices: Optional[int] = None) -> list[CollectiveOp]:
    """All collective instructions in a post-optimization HLO module."""
    ops: list[CollectiveOp] = []
    for line in hlo_text.splitlines():
        assign = _ASSIGN_RE.match(line)
        if not assign:
            continue
        rest = assign.group("rest")
        m = _OP_RE.search(rest)
        if not m:
            continue
        op_token = m.group("op")
        async_start = op_token.endswith("-start")
        kind = op_token[: -len("-start")] if async_start else op_token
        # Result type = everything before the op token; operand shapes
        # (inside the call parens) stay out of the census.
        result_bytes = _result_bytes(rest[: m.start()], async_start)
        g = _group_size(line, n_devices)
        ops.append(CollectiveOp(
            kind=kind,
            name=assign.group("name"),
            result_bytes=result_bytes,
            group_size=g,
            wire_bytes=_wire_bytes(kind, result_bytes, g),
            line=line.strip(),
        ))
    return ops


def summarize_collectives(ops: list[CollectiveOp]) -> dict:
    """Aggregate an op list into the budget-comparable report shape."""
    counts: dict[str, int] = {}
    bytes_by_kind: dict[str, int] = {}
    for op in ops:
        counts[op.kind] = counts.get(op.kind, 0) + 1
        bytes_by_kind[op.kind] = (
            bytes_by_kind.get(op.kind, 0) + int(op.wire_bytes))
    return {
        "counts": dict(sorted(counts.items())),
        "wire_bytes_by_kind": dict(sorted(bytes_by_kind.items())),
        "est_wire_bytes_per_step": int(sum(o.wire_bytes for o in ops)),
        "n_collectives": len(ops),
    }
