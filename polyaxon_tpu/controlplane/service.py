"""The embedded control plane: run CRUD + lifecycle + compilation.

haupt's API layer collapsed into an in-process service (SURVEY.md §2
"API server", §7 step 4): same capability set — submit, compile, stop,
approve, restart/resume, statuses, metrics — without Django or a
network hop. An HTTP facade can wrap this class 1:1 later; the CLI and
tuner consume it directly.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Sequence, Union

from polyaxon_tpu.compiler import compile_operation
from polyaxon_tpu.controlplane.store import RunRecord, Store
from polyaxon_tpu.lifecycle import V1Statuses
from polyaxon_tpu.polyaxonfile import (
    check_polyaxonfile,
    get_operation,
    resolve_operation_context,
)
from polyaxon_tpu.polyflow.operation import V1Operation
from polyaxon_tpu.polyflow.runs import V1RunKind
from polyaxon_tpu.streams import StreamsService


class ControlPlane:
    def __init__(self, home: str):
        self.home = os.path.abspath(home)
        os.makedirs(self.home, exist_ok=True)
        self.store = Store(os.path.join(self.home, "plx.db"))
        self.artifacts_root = os.path.join(self.home, "artifacts")
        os.makedirs(self.artifacts_root, exist_ok=True)
        self.streams = StreamsService(self.artifacts_root)
        from polyaxon_tpu.connections import ConnectionCatalog

        self.connections = ConnectionCatalog(home=self.home)
        # The implicit default queue always exists so bare submits (no
        # `queue:` in the spec) validate and list like any other queue.
        from polyaxon_tpu.scheduling import DEFAULT_QUEUE

        if self.store.get_queue(DEFAULT_QUEUE) is None:
            self.store.upsert_queue(
                DEFAULT_QUEUE, priority=0,
                description="implicit default queue")

    # -- submission --------------------------------------------------------
    def submit(
        self,
        polyaxonfile: Union[str, dict, Sequence, None] = None,
        *,
        op: Optional[V1Operation] = None,
        project: str = "default",
        params: Optional[dict[str, Any]] = None,
        presets: Optional[Sequence[Union[str, dict]]] = None,
        name: Optional[str] = None,
        tags: Optional[list[str]] = None,
        meta: Optional[dict] = None,
        parent_uuid: Optional[str] = None,
        pipeline_uuid: Optional[str] = None,
        iteration: Optional[int] = None,
    ) -> RunRecord:
        if op is None:
            op = check_polyaxonfile(polyaxonfile, params=params, presets=presets)
        elif params or presets:
            op = check_polyaxonfile(op.to_dict(), params=params, presets=presets)
        is_pipeline = op.matrix is not None or (
            op.component is not None and op.component.run_kind == V1RunKind.DAG
        )
        if op.schedule is not None:
            kind = "schedule"  # a recurring parent that spawns child runs
            cron_expr = getattr(op.schedule, "cron", None)
            if cron_expr:
                from polyaxon_tpu.controlplane.cron import Cron

                Cron(cron_expr)  # fail fast at submit, not in the agent
        elif op.matrix is not None:
            kind = "matrix"
        elif is_pipeline:
            kind = V1RunKind.DAG
        else:
            kind = op.component.run_kind if op.component else "hub"
        if parent_uuid and not (meta or {}).get("owner"):
            # Child runs (matrix trials, DAG nodes, schedule fires)
            # inherit the submitting owner's stamp: API-level isolation
            # keys off meta["owner"], and a sweep's trials must stay
            # visible to the owner who submitted the sweep.
            parent_owner = (self.store.get_run(parent_uuid).meta
                            or {}).get("owner")
            if parent_owner:
                meta = {**(meta or {}), "owner": parent_owner}
        # Project row + run row land in one commit: a crash between
        # them would leave a project with no run (or, ordered the other
        # way, a run pointing at a missing project).
        with self.store.transaction():
            self.store.create_project(project)
            record = self.store.create_run(
                project=project,
                spec=op.to_dict(),
                name=name or op.name or (op.component.name if op.component else None),
                kind=kind,
                params={k: p.to_dict() for k, p in (op.params or {}).items()} or None,
                tags=tags or op.tags,
                meta=meta,
                parent_uuid=parent_uuid,
                pipeline_uuid=pipeline_uuid,
                iteration=iteration,
            )
        return record

    # -- compilation -------------------------------------------------------
    def resolve_hub_ref(self, ref: str):
        """Load a component from the local hub (<home>/hub/<name>.yaml).

        Upstream resolves hub refs against the component registry; the
        embedded plane's registry is a directory of component files.
        Version tags (``name:tag``) select ``<name>-<tag>.yaml`` first,
        then fall back to ``<name>.yaml``.
        """
        from polyaxon_tpu.polyaxonfile import get_component, load_specs

        name, _, tag = ref.partition(":")
        hub_dir = os.path.join(self.home, "hub")
        candidates = [f"{name}-{tag}" if tag else None, name]
        for candidate in candidates:
            if not candidate:
                continue
            for ext in (".yaml", ".yml", ".json"):
                path = os.path.join(hub_dir, candidate + ext)
                if os.path.exists(path):
                    return get_component(load_specs(path))
        raise ValueError(
            f"hub component `{ref}` not found under {hub_dir}")

    def compile_run(self, run_uuid: str) -> RunRecord:
        """created → compiled → queued (SURVEY §3.1 lifecycle tail).

        The whole resolution+compilation is one ``compile`` span on the
        run's lifecycle timeline (obs.trace): trace_id = run uuid, and
        a failed compile records an error span before the scheduler
        pins the FAILED condition.
        """
        import time as _time

        from polyaxon_tpu.obs import trace as obs_trace

        t0 = _time.time()
        try:
            record = self._compile_run(run_uuid)
        except Exception as exc:
            obs_trace.record_completed(
                self.run_artifacts_dir(run_uuid), run_uuid, "compile",
                start=t0, end=_time.time(), component="controlplane",
                status="error", error=f"{type(exc).__name__}: {exc}")
            raise
        obs_trace.record_completed(
            self.run_artifacts_dir(run_uuid), run_uuid, "compile",
            start=t0, end=_time.time(), component="controlplane",
            attributes={"kind": record.kind, "status": record.status.value,
                        "queue": ((record.meta or {}).get("scheduling")
                                  or {}).get("queue")})
        return record

    def _compile_run(self, run_uuid: str) -> RunRecord:
        record = self.store.get_run(run_uuid)
        op = get_operation(record.spec)
        if op.component is None and op.hub_ref:
            component = self.resolve_hub_ref(op.hub_ref)
            # exactly-one-source validation: swap hubRef → component via
            # a dict rebuild (validate_assignment rejects in-place edits).
            op_dict = op.to_dict()
            op_dict.pop("hubRef", None)
            op_dict["component"] = component.to_dict()
            op = get_operation(op_dict)
            # The resolved component may be a pipeline: recompute kind so
            # a hub DAG takes the pipeline path, not the job compiler.
            kind = component.run_kind or record.kind
            self.store.update_run(run_uuid, spec=op.to_dict(), kind=kind)
            record = self.store.get_run(run_uuid)
        if record.kind in ("matrix", V1RunKind.DAG, "schedule"):
            # Pipelines compile trivially: children are compiled per-trial.
            self.store.transition(run_uuid, V1Statuses.COMPILED, reason="PipelineCompiled")
            self.store.transition(run_uuid, V1Statuses.QUEUED)
            return self.store.get_run(run_uuid)
        trial_params = dict((record.meta or {}).get("trial_params") or {})
        if op.joins:
            from polyaxon_tpu.controlplane.joins import resolve_joins

            matched: list[str] = []
            joined = resolve_joins(
                self.store, self.streams,
                [j.to_dict() for j in op.joins], project=record.project,
                matched=matched)
            trial_params.update(joined)
            if matched:
                # Join upstreams are lineage edges (inputs → this run);
                # stamped here because the query result is not
                # re-derivable after the upstream set changes.
                meta = dict(record.meta or {})
                meta["upstream_runs"] = sorted(set(matched))
                self.store.update_run(run_uuid, meta=meta)
                record = self.store.get_run(run_uuid)
        resolved = resolve_operation_context(
            op,
            params=trial_params,
            run_uuid=record.uuid,
            run_name=record.name or "",
            project_name=record.project,
            iteration=record.iteration,
            artifacts_root=self.artifacts_root,
        )
        plan = compile_operation(
            resolved,
            run_uuid=record.uuid,
            artifacts_root=self.artifacts_root,
            project=record.project,
            catalog=self.connections,
            hub_resolver=self.resolve_hub_ref,
        )
        self.store.update_run(
            run_uuid, resolved_spec=resolved.to_dict(), launch_plan=plan.to_dict()
        )
        self._stamp_scheduling(run_uuid, resolved, plan)

        # Run memoization (upstream V1Cache lifecycle: created →
        # awaiting_cache → succeeded on hit / compiled on miss): an
        # identical resolved component+params that already succeeded
        # short-circuits and reuses the hit's outputs.
        cache = op.cache
        if cache is not None and not cache.disable:
            key = self._cache_key(resolved)
            self.store.transition(run_uuid, V1Statuses.AWAITING_CACHE)
            hit = self.store.find_cached(key, project=record.project, ttl=cache.ttl)
            if hit is not None and hit.uuid != run_uuid:
                self._adopt_outputs(hit, run_uuid)
                meta = dict(record.meta or {})
                meta["cache_hit_from"] = hit.uuid
                self.store.update_run(run_uuid, meta=meta, cache_key=key)
                self._index_lineage(run_uuid)
                self.store.transition(
                    run_uuid, V1Statuses.SUCCEEDED, reason="CacheHit",
                    message=f"reused outputs of {hit.uuid}")
                return self.store.get_run(run_uuid)
            self.store.update_run(run_uuid, cache_key=key)

        self._index_lineage(run_uuid)
        self.store.transition(run_uuid, V1Statuses.COMPILED, reason="Compiled")
        self.store.transition(run_uuid, V1Statuses.QUEUED)
        return self.store.get_run(run_uuid)

    def _stamp_scheduling(self, run_uuid: str, resolved: V1Operation,
                          plan) -> None:
        """Resolve queue + priority class against the catalog and stamp
        ``meta["scheduling"]`` so admission ticks never re-parse specs.

        Unknown queue/priority-class names raise ``SchedulingError``
        here — at compile, where the submitting user sees the failure —
        instead of silently landing at the back of the default queue.
        """
        from polyaxon_tpu.scheduling import (
            DEFAULT_QUEUE,
            RunSchedInfo,
            SchedulingError,
            resolve_priority_class,
        )

        queue_name = plan.queue or DEFAULT_QUEUE
        if self.store.get_queue(queue_name) is None:
            known = [q["name"] for q in self.store.list_queues()]
            raise SchedulingError(
                f"unknown queue `{queue_name}` (known: {known}); create it "
                "with `plx queue add`")
        run = resolved.component.run if resolved.component else None
        env = getattr(run, "environment", None)
        class_name = getattr(env, "priority_class_name", None) or None
        priority = resolve_priority_class(class_name)  # raises on unknown
        resources = plan.resources
        info = RunSchedInfo(
            queue=queue_name,
            priority_class=(str(class_name).lower() if class_name
                            else "default"),
            priority=priority,
            chips=int(getattr(resources, "chips", 0) or 0),
            preemptible=bool(getattr(resources, "preemptible", False)),
        )
        record = self.store.get_run(run_uuid)
        meta = dict(record.meta or {})
        meta["scheduling"] = info.to_meta()
        self.store.update_run(run_uuid, meta=meta)

    # -- scheduling catalog ------------------------------------------------
    def upsert_queue(self, name: str, **kwargs) -> dict:
        return self.store.upsert_queue(name, **kwargs)

    def delete_queue(self, name: str) -> bool:
        from polyaxon_tpu.scheduling import DEFAULT_QUEUE

        if name == DEFAULT_QUEUE:
            raise ValueError("the default queue cannot be deleted")
        return self.store.delete_queue(name)

    def set_quota(self, project: str, **kwargs) -> dict:
        return self.store.set_quota(project, **kwargs)

    def delete_quota(self, project: str) -> bool:
        return self.store.delete_quota(project)

    def scheduling_stats(self) -> dict:
        """Queue depth + quota usage, the operator view surfaced by
        ``GET /api/v1/queues|quotas`` and ``plx queue ls``."""
        from polyaxon_tpu.scheduling import LIVE_STATUSES, sched_info

        pipeline_kinds = {"matrix", V1RunKind.DAG, "schedule"}
        queued = [r for r in self.store.list_runs(statuses=[V1Statuses.QUEUED])
                  if r.kind not in pipeline_kinds]
        live = [r for r in self.store.list_runs(statuses=LIVE_STATUSES)
                if r.kind not in pipeline_kinds]
        depth: dict[str, int] = {}
        running: dict[str, int] = {}
        projects: dict[str, dict] = {}
        for record in queued:
            info = sched_info(record)
            depth[info.queue] = depth.get(info.queue, 0) + 1
            usage = projects.setdefault(
                record.project, {"runs": 0, "chips": 0, "queued": 0})
            usage["queued"] += 1
        for record in live:
            info = sched_info(record)
            running[info.queue] = running.get(info.queue, 0) + 1
            usage = projects.setdefault(
                record.project, {"runs": 0, "chips": 0, "queued": 0})
            usage["runs"] += 1
            usage["chips"] += info.chips
        queues = []
        for row in self.store.list_queues():
            queues.append({**row,
                           "depth": depth.get(row["name"], 0),
                           "running": running.get(row["name"], 0)})
        quotas = []
        for row in self.store.list_quotas():
            usage = projects.get(row["project"],
                                 {"runs": 0, "chips": 0, "queued": 0})
            quotas.append({**row, "used_runs": usage["runs"],
                           "used_chips": usage["chips"],
                           "queued": usage["queued"]})
        return {"queues": queues, "quotas": quotas, "projects": projects}

    @staticmethod
    def _cache_key(resolved: V1Operation) -> str:
        """Content hash of what determines a run's outputs: the resolved
        component spec + literal params."""
        import hashlib
        import json as _json

        payload = {
            "component": resolved.component.to_dict() if resolved.component else None,
            "params": {k: p.to_dict() for k, p in (resolved.params or {}).items()},
        }
        return hashlib.sha256(
            _json.dumps(payload, sort_keys=True, default=str).encode()
        ).hexdigest()

    def _adopt_outputs(self, hit: RunRecord, run_uuid: str) -> None:
        """Copy the cache hit's outputs manifest into the new run's dir."""
        import shutil

        src = os.path.join(self.artifacts_root, hit.uuid)
        dst = os.path.join(self.artifacts_root, run_uuid)
        os.makedirs(dst, exist_ok=True)
        for name in ("outputs.json",):
            path = os.path.join(src, name)
            if os.path.exists(path):
                shutil.copy2(path, os.path.join(dst, name))
        outputs_dir = os.path.join(src, "outputs")
        if os.path.isdir(outputs_dir):
            shutil.copytree(
                outputs_dir, os.path.join(dst, "outputs"), dirs_exist_ok=True)

    # -- lifecycle ops -----------------------------------------------------
    def stop(self, run_uuid: str, message: str = "") -> None:
        record = self.store.get_run(run_uuid)
        if record.is_done:
            return
        self.store.transition(run_uuid, V1Statuses.STOPPING, message=message)
        for child in self.store.list_runs(pipeline_uuid=run_uuid):
            if not child.is_done:
                self.stop(child.uuid, message="pipeline stopped")

    def restart(self, run_uuid: str, *, copy: bool = False) -> RunRecord:
        record = self.store.get_run(run_uuid)
        meta = dict(record.meta or {})
        meta["restarted_from"] = record.uuid
        if copy:
            meta["copy_artifacts_from"] = record.uuid
        return self.store.create_run(
            project=record.project,
            spec=record.spec,
            name=record.name,
            kind=record.kind,
            params=record.params,
            tags=record.tags,
            meta=meta,
            parent_uuid=record.parent_uuid,
        )

    def resume(self, run_uuid: str) -> RunRecord:
        """Requeue a stopped/failed/preempted run in place, keeping its
        artifacts dir so checkpoint restore continues from the last step
        (SURVEY §5.4: the build owns both halves of resume)."""
        record = self.store.get_run(run_uuid)
        if not record.is_done and record.status != V1Statuses.PREEMPTED:
            raise ValueError(f"Run `{run_uuid}` is not resumable from {record.status}")
        if record.launch_plan:
            # One commit for the whole requeue hop: a crash mid-chain
            # would otherwise strand the run in RESUMING/COMPILED where
            # neither the scheduler nor resume() would pick it back up.
            with self.store.transaction():
                self.store.transition(run_uuid, V1Statuses.RESUMING, force=True)
                self.store.transition(run_uuid, V1Statuses.COMPILED)
                self.store.transition(run_uuid, V1Statuses.QUEUED)
            return self.store.get_run(run_uuid)
        self.store.transition(run_uuid, V1Statuses.RESUMING, force=True)
        # Stopped before compilation: compile now (resolves + queues).
        return self.compile_run(run_uuid)

    # -- reads -------------------------------------------------------------
    def get_run(self, run_uuid: str) -> RunRecord:
        return self.store.get_run(run_uuid)

    def list_runs(self, **kwargs) -> list[RunRecord]:
        return self.store.list_runs(**kwargs)

    def get_statuses(self, run_uuid: str) -> list[dict]:
        return self.store.get_conditions(run_uuid)

    def get_metric(self, run_uuid: str, name: str) -> Optional[float]:
        value = self.streams.last_metric(run_uuid, name)
        if value is None:
            outputs = self.streams.get_outputs(run_uuid)
            for key in (name, f"final_{name}"):
                if key in outputs:
                    return float(outputs[key])
        return value

    def run_artifacts_dir(self, run_uuid: str) -> str:
        return os.path.join(self.artifacts_root, run_uuid)

    def timeline(self, run_uuid: str) -> dict:
        """The run's ordered lifecycle span tree (obs.trace):
        compile → admission → placement → execute(init) →
        runtime(jit_compile/step/checkpoint/...) → sync, with chaos and
        retry annotations attached to the phase they hit. Backs
        ``GET .../runs/<uuid>/timeline`` and ``plx ops timeline``."""
        from polyaxon_tpu.obs.trace import build_timeline, read_trace

        self.store.get_run(run_uuid)  # 404s unknown uuids at the API edge
        return build_timeline(read_trace(self.run_artifacts_dir(run_uuid)),
                              trace_id=run_uuid)

    def report(self, run_uuid: str) -> dict:
        """Performance attribution report (obs.analyze): the run's wall
        clock decomposed into phases, step-time trend with anomaly
        flags, and retry/chaos/requeue annotations per phase — plus the
        run's status and any alerts that fired on it. Backs
        ``GET .../runs/<uuid>/report`` and ``plx ops report``."""
        from polyaxon_tpu.obs.analyze import analyze_timeline

        record = self.store.get_run(run_uuid)
        report = analyze_timeline(self.timeline(run_uuid))
        report["status"] = record.status.value
        report["retries"] = record.retries
        report["alerts"] = (record.meta or {}).get("alerts") or []
        return report

    def verify(self, run_uuid: Optional[str] = None) -> dict:
        """Telemetry-oracle verdicts (obs.oracle): the committed
        invariant set judged against this plane's end state —
        scoped to one run when ``run_uuid`` is given, fleet-wide
        otherwise. Backs ``GET .../runs/<uuid>/verify`` and
        ``plx ops verify``."""
        from polyaxon_tpu.obs.oracle import verify_plane

        if run_uuid is not None:
            self.store.get_run(run_uuid)  # 404s unknown uuids
        return verify_plane(self, run_uuid=run_uuid)

    # -- cross-run lineage -------------------------------------------------
    def _upstream_edges(
        self, record: RunRecord,
        sibling_cache: Optional[dict] = None,
    ) -> list[tuple[str, str, Optional[str]]]:
        """(upstream_uuid, edge_kind, label) for every input edge the
        data model records: ``runs.<uuid>``/``ops.<name>`` param refs,
        DAG dependencies, join matches (meta.upstream_runs, stamped at
        compile), and cache adoption. ``sibling_cache`` (pipeline_uuid
        → {name: record}) is shared by the project-wide downstream scan
        so sibling listings run once per pipeline, not once per run."""
        out: list[tuple[str, str, Optional[str]]] = []
        cache = sibling_cache if sibling_cache is not None else {}

        def sibs() -> dict[str, RunRecord]:
            key = record.pipeline_uuid
            if not key:
                return {}
            if key not in cache:
                cache[key] = {c.name: c for c in self.store.list_runs(
                    pipeline_uuid=key)}
            return cache[key]

        # Param refs + DAG dependencies need only the raw spec dict —
        # no pydantic re-validation per scanned run.
        for name, param in (record.params or {}).items():
            ref = param.get("ref") if isinstance(param, dict) else None
            if not ref:
                continue
            if ref.startswith("runs."):
                out.append((ref.split(".")[1], "param", name))
            elif ref.startswith("ops."):
                sib = sibs().get(ref.split(".")[1])
                if sib is not None:
                    out.append((sib.uuid, "param", name))
        meta = record.meta or {}
        for uuid in meta.get("upstream_runs") or []:
            out.append((uuid, "join", None))
        if meta.get("cache_hit_from"):
            out.append((meta["cache_hit_from"], "cache", None))
        deps = (record.spec or {}).get("dependencies") or []
        if deps and record.pipeline_uuid:
            for dep in deps:
                sib = sibs().get(dep)
                if sib is not None:
                    out.append((sib.uuid, "dag", None))
        return out

    def _index_lineage(self, run_uuid: str) -> None:
        """Mirror this run's upstream edges onto each upstream's
        ``meta["downstream_runs"]`` at compile time (ADVICE r5: the
        per-request ``lineage_graph`` downstream scan re-derived edges
        for every run in the project — O(runs) store reads per call).
        Every edge kind the data model records (param refs, DAG deps,
        joins, cache adoption) is known by the time a run leaves
        compile, so submit time is the one place the index stays
        consistent. ``meta["lineage_indexed"]`` marks the run so the
        request-time scan skips re-deriving it."""
        record = self.store.get_run(run_uuid)
        # The mirrored edges and the indexed marker are one unit: a
        # crash after some edge writes but before the marker would look
        # indexed-enough to skip yet miss edges, so batch the lot.
        with self.store.transaction():
            for uuid, kind, label in self._upstream_edges(record):
                try:
                    up = self.store.get_run(uuid)
                except KeyError:  # deleted upstream: no edge
                    continue
                meta = dict(up.meta or {})
                edges = list(meta.get("downstream_runs") or [])
                entry = {"uuid": run_uuid, "kind": kind,
                         **({"label": label} if label else {})}
                if entry not in edges:
                    edges.append(entry)
                    meta["downstream_runs"] = edges
                    self.store.update_run(uuid, meta=meta)
            meta = dict(record.meta or {})
            meta["lineage_indexed"] = True
            self.store.update_run(run_uuid, meta=meta)

    def lineage_graph(self, run_uuid: str) -> dict:
        """Inputs → run → outputs across runs (SURVEY §2 "Tracking":
        upstream's artifact-lineage graph view): upstream runs feeding
        this one (param refs, DAG deps, joins, cache adoption),
        downstream runs consuming it, and the run's own artifact
        records + outputs as the terminal nodes."""
        record = self.store.get_run(run_uuid)
        nodes: dict[str, dict] = {}
        edges: list[dict] = []

        def node(r: RunRecord) -> None:
            # "owner" rides along so the API's scoped-token filter can
            # drop foreign nodes without an extra get_run per node.
            nodes.setdefault(r.uuid, {
                "uuid": r.uuid, "name": r.name, "kind": r.kind,
                "status": r.status.value,
                "owner": (r.meta or {}).get("owner"),
            })

        node(record)
        sibling_cache: dict = {}
        for uuid, kind, label in self._upstream_edges(record, sibling_cache):
            try:
                up = self.store.get_run(uuid)
            except KeyError:  # deleted upstream: drop edge
                continue
            node(up)
            edges.append({"from": uuid, "to": run_uuid, "kind": kind,
                          **({"label": label} if label else {})})
        # Downstream edges come from the submit-time index (mirrored
        # into meta["downstream_runs"] by _index_lineage); the
        # re-deriving scan survives ONLY for legacy records compiled
        # before the index existed (meta.lineage_indexed unset), so a
        # hot-path request costs one list query + O(edges) lookups
        # instead of O(runs) edge derivations (ADVICE r5).
        seen_down: set[tuple] = set()
        for entry in (record.meta or {}).get("downstream_runs") or []:
            try:
                down = self.store.get_run(entry["uuid"])
            except KeyError:  # deleted downstream
                continue
            node(down)
            edge = {"from": run_uuid, "to": down.uuid,
                    "kind": entry.get("kind"),
                    **({"label": entry["label"]}
                       if entry.get("label") else {})}
            seen_down.add((down.uuid, edge["kind"], entry.get("label")))
            edges.append(edge)
        for other in self.store.list_runs(project=record.project):
            if other.uuid == run_uuid or (other.meta or {}).get(
                    "lineage_indexed"):
                continue
            for uuid, kind, label in self._upstream_edges(
                    other, sibling_cache):
                if uuid == run_uuid and (other.uuid, kind,
                                         label) not in seen_down:
                    node(other)
                    edges.append({
                        "from": run_uuid, "to": other.uuid, "kind": kind,
                        **({"label": label} if label else {})})
        return {
            "run": run_uuid,
            "nodes": list(nodes.values()),
            "edges": edges,
            "artifacts": self.streams.get_lineage(run_uuid),
            "outputs": self.streams.get_outputs(run_uuid),
        }
