"""Planted unjoined daemon thread (golden: invariant-daemon-drain).
The joined twin is the negative control."""
import threading


def spawn():
    worker = threading.Thread(target=print, daemon=True)
    worker.start()
    return worker


def spawn_drained():
    drained = threading.Thread(target=print, daemon=True)
    drained.start()
    drained.join(timeout=1)
    return drained
